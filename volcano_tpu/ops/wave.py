"""Wave-batched allocate solver: W tasks per device iteration.

The sequential solver (``ops/allocate.py``) preserves Volcano's exact
per-task semantics but pays one device loop iteration per task — at
BASELINE's north-star shape (10k nodes x 100k pending pods) that is 100k
sequential steps and over ten seconds of device time.  This module trades a
small, documented amount of ordering fidelity for two orders of magnitude:
tasks are processed in *waves* of W (task order preserved across and within
waves), and each wave resolves with batched feasibility/score tensors plus
an O(W^2) prefix-acceptance pass that lands on the MXU as tiny matmuls.

**Profile dedup.** Pending pods are overwhelmingly replicas: a gang of 64
identical tasks shares one request vector, one node-selector bitset, one
affinity term set.  The expensive [*, N] tensors (resource fit, scores,
ports, affinity) are therefore computed once per *distinct task profile*
present in the wave (host-side ``np.unique`` over the per-task rows), and
every task just gathers its profile's row — the same collapse the array
schema performs on the reference's O(tasks x nodes x predicates) fan-out
(scheduler_helper.go:43-118), applied a second time within the solve.

Semantics relative to ``pkg/scheduler/actions/allocate/allocate.go:40-250``
(and to the sequential solver, which mirrors it step-for-step):

- predicates/scores for the tasks of one wave are evaluated against the
  cluster state at the start of the wave *attempt*, not after every single
  placement.  Within an attempt, capacity is still charged exactly, in task
  order, via per-node prefix sums: a task is only accepted if the requests
  of every earlier accepted wave-task on its chosen node still leave room.
  Tasks that lose the race re-enter the next attempt, where scores are
  recomputed on the updated state; each attempt is guaranteed to resolve at
  least the first unresolved task, so the attempt loop terminates.
- choice diversification: when many tasks of a wave argmax to the same
  node, the k-th contender is steered to its profile's k-th-best feasible
  node (scaled by how many replicas the best node can still hold).  The
  sequential reference reaches the same nodes one fill at a time (best node
  saturates, scores shift to the runner-up); the wave solver just gets
  there without serializing.  Tie-break stays lowest-node-index.
- gang discard (stmt.Discard, statement.go:324-367) is applied as one
  vectorized rollback after the scan instead of at each job boundary, so
  capacity held by a doomed job is not released to later jobs within the
  same solve call.  The allocate action re-runs the solver on the remaining
  pending tasks when any job was discarded (``actions/allocate.py``),
  which restores the freed capacity for the next pass — the same "later
  jobs see post-discard state" outcome, one round later.
- queue-overuse gating (proportion.go:217-229) is evaluated when the job's
  first task comes up in its wave, against live queue allocations at that
  attempt — the same point in task order where the reference evaluates it.
- a task with no feasible node marks its job fit-failed and aborts the
  job's remaining tasks (allocate.go:189-193): in-wave, later tasks of that
  job are masked from this attempt's acceptance and from every later
  attempt; tasks of the job accepted in earlier attempts stay (they are
  rolled back at the end unless the job still reached ready).

Everything else — epsilon resource semantics, pipeline (future-idle)
accounting surviving discard, port/pod-count/label/taint/inter-pod-affinity
predicates, additive scoring — is identical to the sequential solver, and
the two agree exactly on conflict-free workloads (tests/test_wave.py).

Bitset predicates (node selector / required+preferred node affinity /
taints / host ports) are evaluated as f32 matmuls over the unpacked bit
axis: "row bits all present in table row" == "popcount(row & ~table) == 0",
and the popcount of an AND is an inner product of 0/1 vectors — which puts
the predicate fan-out on the MXU instead of the vector units.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..arrays.affinity import AffinityArgs
from .allocate import (
    NEG,
    AllocResult,
    SolveJobs,
    SolveNodes,
    SolveQueues,
    SolveTasks,
)
from .nodeclass import NodeClasses
from .resreq import less_equal
from .scoring import ScoreWeights, node_score

import os as _os
import time as _time


def _env_int(name: str, default: int) -> int:
    try:
        return int(_os.environ.get(name, default))
    except ValueError:
        return default


DEFAULT_WAVE = _env_int("VOLCANO_TPU_WAVE", 2048)
# cnt0 tables above this element count ship as sparse entries and are
# scattered on device (tests lower it to force the sparse path).
CNT0_SPARSE_MIN = 4_000_000
# Same for each profile-term table ([U, Ep]): past this element count
# the four tables ship as one sparse entry list.
PROF_SPARSE_MIN = _env_int("VOLCANO_TPU_PROF_SPARSE_MIN", 1_000_000)
# diversification breadth: k-th contender takes its k-th best node
TOPK = _env_int("VOLCANO_TPU_TOPK", 256)
# In-attempt re-walk rounds for conflict losers.  Default 4: measured
# best at the north-star affinity mix in rounds 3 AND 4 (16 costs more
# per-attempt sub-round machinery than the attempt-count reduction it
# buys; acceptance stays exact either way — sub-rounds only change how
# much conflict retry happens inside one ranking).
SUBROUNDS = _env_int("VOLCANO_TPU_SUBROUNDS", 4)
# live affinity steering inside sub-rounds ([UM,EW]x[EW,N] matmuls per
# dirty sub-round).  Default OFF: measured at the north-star affinity
# shape (10k nodes x 100k pods, 5/5/10% affinity mix) the steering costs
# more per attempt than it saves in attempt count — identical placements
# land ~25% faster without it (see BASELINE.md affinity analysis).
# Re-enable with VOLCANO_TPU_AFF_STEER=1 for term-heavy small clusters.
AFF_STEER = _env_int("VOLCANO_TPU_AFF_STEER", 0)
# Attempt-level cache of the inter-pod affinity planes (required/anti
# feasibility + soft score): recompute only on term-count changes
# instead of every attempt.  Exact (same values); knob exists for A/B
# measurement.
AFF_ACACHE = _env_int("VOLCANO_TPU_AFF_ACACHE", 1)
# Flattened (term x domain) scatter keys index an [EW * D + 1] buffer
# with int32 device arithmetic (jax's default index width).  At the
# 100k-node x 1M-pod tier the PRODUCT crosses 2^31 while each axis
# stays far below it, so past this bound the conflict/count machinery
# switches to 2-D (term, domain) indexing — identical values,
# overflow-free.  Env-overridable so the 2-D form is exercised (and
# parity-tested) at small shapes.
def _keyspace_max() -> int:
    try:
        return int(
            _os.environ.get("VOLCANO_TPU_KEYSPACE_MAX", 2**31 - 2)
        )
    except ValueError:
        return 2**31 - 2


# Per-attempt count-window gathers cnt[e, node_dom[n, key(e)]] run as
# ~10 ns/element serialized gathers on TPU (21 ms per attempt at
# 10k x 100k); below this [D, N] f32 footprint they run instead as one
# MXU matmul against a domain-membership one-hot (exact: counts are
# zero outside a term's own key's domains, so each output element picks
# up exactly one product, and f32 represents the integer counts
# exactly).  Above it (hyperscale D ~ 50k) the gather path remains.
DOM_MM_MAX_MB = _env_int("VOLCANO_TPU_DOM_MM_MB", 1024)

# ---- two-phase device solve (node-class compaction + shortlists) -----
# Phase 1 (coarse) collapses the node table into node classes and
# evaluates the static predicate planes once per (profile x class) in
# bf16, then ranks every node ONCE per solve on the initial state and
# keeps each profile's top-S candidates as a shortlist.  Phase 2 (fine)
# runs the attempt/sub-round wave machinery on the [UM, S] shortlist
# planes instead of [UM, N]; a profile whose shortlist has no live
# feasible candidate falls back to a full-N rescore for that attempt
# (counted per reason), so binding is never lost to pruning — the
# TPU-native analog of the reference's percentageOfNodesToFind sampling
# (scheduler_helper.go:37-62).  Knobs are read per call so bench.py can
# A/B both modes inside one process.
def _two_phase_on() -> bool:
    return _os.environ.get("VOLCANO_TPU_TWOPHASE", "1") != "0"


def _nodeclass_on() -> bool:
    return _os.environ.get("VOLCANO_TPU_NODECLASS", "1") != "0"


def _fallback_cap() -> int:
    """Max shortlist-fallback rescores per solve (0 = unlimited)."""
    try:
        return max(0, int(_os.environ.get("VOLCANO_TPU_FB_CAP", 0)))
    except ValueError:
        return 0


def shortlist_size(n: int) -> int:
    """Phase-2 shortlist length per profile.  VOLCANO_TPU_TOPK pins it
    explicitly; the default mirrors the reference's adaptive
    percentageOfNodesToFind (50 - N/125 percent, floor 5%, at least 100
    nodes — scheduler_helper.go:37-62) and never drops below the walk
    ranking depth TOPK, so attempt-1 rankings keep their full prefix."""
    raw = _os.environ.get("VOLCANO_TPU_TOPK")
    if raw:
        try:
            return max(1, min(n, int(raw)))
        except ValueError:
            pass
    pct = max(5, 50 - n // 125)
    return min(n, max(100, TOPK, n * pct // 100))


# Coarse phase profile-chunk size: bounds the [chunk, N, R] fit
# broadcast (the only [*, N, R] tensor of the coarse pass) so hyperscale
# profile counts stream through lax.map instead of materializing
# [U, N, R] at once.
COARSE_CHUNK = _env_int("VOLCANO_TPU_COARSE_CHUNK", 256)

# Telemetry of the most recent two-phase solve on this host (the cycle
# driver folds it into the device_coarse/device_fine sub-lanes and the
# flight recorder; tests read the shortlist shape).  Keys: enabled,
# coarse_s, fine_s, shortlist ((U, S) or None), n_nodes,
# compacted_classes (bool: real class planes vs per-node identity),
# mesh_shards (effective node-axis shard count of the rankings; 1 off
# a mesh).
LAST_TWOPHASE: dict = {"enabled": False}


class SolveProfiles(NamedTuple):
    """Distinct task profiles ([U] rows): every per-task input that shapes
    the [*, N] feasibility/score tensors.  Tasks map to profiles via
    ``pid``; waves gather their present profiles via ``wave_prof``."""

    req: jnp.ndarray  # [U, R]
    init_req: jnp.ndarray  # [U, R]
    ports: jnp.ndarray  # [U, PW] uint32
    sel_bits: jnp.ndarray  # [U, LW]
    aff_bits: jnp.ndarray  # [U, A, LW]
    aff_terms: jnp.ndarray  # [U]
    tol_bits: jnp.ndarray  # [U, TW]
    pref_bits: jnp.ndarray  # [U, AP, LW]
    pref_w: jnp.ndarray  # [U, AP]
    t_req_aff: jnp.ndarray  # [U, E]
    t_req_anti: jnp.ndarray  # [U, E]
    t_matches: jnp.ndarray  # [U, E]
    t_soft: jnp.ndarray  # [U, E]


class GState(NamedTuple):
    """Cluster state threaded through waves and attempts."""

    idle: jnp.ndarray  # [N, R]
    pip_extra: jnp.ndarray  # [N, R]
    ntasks: jnp.ndarray  # [N] int32
    pip_ntasks: jnp.ndarray  # [N]
    nport_bits: jnp.ndarray  # [N, B] bool (unpacked, alloc side)
    pip_nport_bits: jnp.ndarray  # [N, B] bool
    cnt_alloc: jnp.ndarray  # [E, D] int32
    cnt_pip: jnp.ndarray  # [E, D] int32
    q_alloc: jnp.ndarray  # [Q, R]
    q_pip: jnp.ndarray  # [Q, R]
    alloc_cnt: jnp.ndarray  # [J] int32
    fit_failed: jnp.ndarray  # [J] bool
    job_skip: jnp.ndarray  # [J] bool (fit abort OR overuse skip)
    job_overskip: jnp.ndarray  # [J] bool (skipped for overuse only)
    assigned: jnp.ndarray  # [P] int32
    pipelined: jnp.ndarray  # [P] int32
    iters: jnp.ndarray  # [] int32 total attempt iterations
    fb_exhausted: jnp.ndarray  # [] int32 shortlist-fallback rescores
    fb_affinity: jnp.ndarray  # [] int32 ... for required-affinity profiles
    fb_rounds: jnp.ndarray  # [] int32 fallback rescore ROUNDS (cap unit)


def _unpack_bits(words):
    """[..., W] uint32 -> [..., W*32] bool, bit 0 of word 0 first."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[..., :, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*words.shape[:-1], -1).astype(bool)


def _subset_mm(rows_bits, table_missing_f):
    """rows ⊆ table per pair, as a matmul.

    rows_bits: [..., B] bool; table_missing_f: [N, B] f32 of ~table.
    Result [..., N] bool: no bit of the row falls on a missing table bit.
    """
    viol = jnp.matmul(rows_bits.astype(jnp.float32), table_missing_f.T)
    return viol == 0


def _subset_mm_bf(rows_bits, table_missing_bf):
    """bf16 variant of ``_subset_mm`` for the coarse class planes: the
    products are 0/1 and the verdict reads ==0 vs >=1 — a bf16-rounded
    sum of non-negative integers can never land in (0, 0.5), so the
    classification is exact (the _aff_parts indicator argument) at ~4x
    the MXU rate."""
    viol = jnp.matmul(
        rows_bits.astype(jnp.bfloat16), table_missing_bf.T
    )
    return viol < 0.5


def _class_static(cls: NodeClasses, sel_bits, aff_bits, aff_terms,
                  tol_bits, pref_bits, pref_w, naff_weight,
                  has_taints: bool):
    """Phase-1 coarse planes: static (label/taint/ready) feasibility and
    preferred-affinity score once per (profile-row x node CLASS).

    Inputs are packed word rows for ``Ub`` profiles; result is
    ``(ok [Ub, C] bool, score [Ub, C] f32)``.  Class members share the
    static node planes byte-for-byte (nodeclass.build_node_classes), so
    expanding through ``class_id`` reproduces the node-level masks
    exactly; the bf16 indicator matmuls are exact for the ==0 / >=1
    classification and the score sums the exact booleans in f32, so the
    expanded score matches the node-level computation bit-for-bit."""
    bf = jnp.bfloat16
    f32 = jnp.float32
    Ub = sel_bits.shape[0]
    A = aff_bits.shape[1]
    AP = pref_bits.shape[1]
    C = cls.ready.shape[0]
    missing_bf = (~_unpack_bits(cls.label_bits)).astype(bf)  # [C, B]
    ok = cls.ready[None, :] & _subset_mm_bf(
        _unpack_bits(sel_bits), missing_bf
    )
    term_ok = _subset_mm_bf(
        _unpack_bits(aff_bits).reshape(Ub * A, -1), missing_bf
    ).reshape(Ub, A, C)
    term_real = jnp.arange(A)[None, :] < aff_terms[:, None]  # [Ub, A]
    ok &= (
        jnp.any(term_ok & term_real[:, :, None], axis=1)
        | (aff_terms == 0)[:, None]
    )
    if has_taints:
        untol = jnp.matmul(
            _unpack_bits(cls.taint_bits).astype(bf),
            (~_unpack_bits(tol_bits)).astype(bf).T,
        )  # [C, Ub]
        ok &= untol.T < 0.5
    pref_match = _subset_mm_bf(
        _unpack_bits(pref_bits).reshape(Ub * AP, -1), missing_bf
    ).reshape(Ub, AP, C)
    score = naff_weight * jnp.sum(
        pref_match.astype(f32) * pref_w[:, :, None], axis=1
    )
    return ok, score


def _identity_classes(nodes: SolveNodes) -> NodeClasses:
    """Per-node identity classes derived from the node planes (the
    automatic path when no compacted class planes were supplied): every
    node is its own class, so the class-axis machinery applies with the
    static matmuls staying at node granularity."""
    N = nodes.idle.shape[0]
    return NodeClasses(
        class_id=jnp.arange(N, dtype=jnp.int32),
        label_bits=nodes.label_bits,
        taint_bits=nodes.taint_bits,
        ready=nodes.ready,
    )


@partial(jax.jit, static_argnames=("chunk", "has_taints",
                                   "cls_identity"))
def _static_planes(nodes: SolveNodes, prof: SolveProfiles,
                   cls: NodeClasses, naff_weight, chunk: int,
                   has_taints: bool, cls_identity: bool):
    """Separately-jitted producer of the [U, C] static planes (ISSUE 9
    persistent statics): ``_class_static`` over the WHOLE padded profile
    table, cached across solves by ``ops/devincr.DeviceIncremental``
    keyed on (class-table content sig, profile content generation,
    epoch-relevant bits) — steady-state solves then skip static
    evaluation entirely, both in the coarse pass and per wave.

    Rows are computed independently (the matmuls contract over the bit
    axis only), so gathering rows of this result is bit-identical to
    calling ``_class_static`` on the gathered rows in-kernel — the
    property the DEVINCR=0 parity contract rests on.  Profiles stream
    through ``lax.map`` in ``chunk`` rows like the coarse pass."""
    if cls_identity:
        cls = _identity_classes(nodes)
    U = prof.sel_bits.shape[0]

    def body(rowset):
        sel_bits, aff_bits, aff_terms, tol_bits, pref_bits, pref_w = \
            rowset
        return _class_static(
            cls, sel_bits, aff_bits, aff_terms, tol_bits, pref_bits,
            pref_w, naff_weight, has_taints,
        )

    cols = (prof.sel_bits, prof.aff_bits, prof.aff_terms,
            prof.tol_bits, prof.pref_bits, prof.pref_w)
    if chunk >= U:
        return body(cols)
    resh = tuple(
        a.reshape(U // chunk, chunk, *a.shape[1:]) for a in cols
    )
    ok, sc = jax.lax.map(body, resh)
    C = ok.shape[-1]
    return ok.reshape(U, C), sc.reshape(U, C)


def _hier_pin() -> int:
    """The pinned ``VOLCANO_TPU_TOPK_BLOCKS`` value (0 = adaptive).
    Read OUTSIDE the jits — ``solve_wave`` resolves it per call and
    threads it through as a static argument, so flipping the knob
    in-process actually re-specializes the kernels (an env read at
    trace time would silently hit the jit cache instead)."""
    try:
        return max(0, int(_os.environ.get("VOLCANO_TPU_TOPK_BLOCKS",
                                          "0")))
    except ValueError:
        return 0


def _hier_blocks(n: int, k: int, n_shards: int = 1,
                 pin: Optional[int] = None) -> int:
    """Block count of the hierarchical block->shard->global top-k for
    an [*, n] ranking (trace-static; n, k, n_shards are static inside
    every caller's jit).

    ``pin`` is the resolved ``VOLCANO_TPU_TOPK_BLOCKS`` (0 = adaptive;
    ``None`` reads the env — only sound for EAGER callers, jitted
    callers must thread ``solve_wave``'s static through).  A pinned
    count is pow2-clamped to a divisor of ``n`` (1 disables the block
    stage).  The adaptive default engages the block stage only when
    each shard's node slice is large and the ranking depth is a small
    fraction of it — one top_k over [*, n] at 100k+ nodes sorts the
    whole plane, while per-block top_k + the winner merge sorts ~k
    rows per block.  Blocks are sized toward TOPK_BLOCK_ROWS (pow2,
    floor 4 * k so the merged candidate set stays well under n)."""
    n_sh = max(1, n_shards)
    if pin is None:
        pin = _hier_pin()
    if pin:
        p = 1
        while p * 2 <= pin:
            p *= 2
        nb = max(p, n_sh)
        while nb > n_sh and n % nb:
            nb //= 2
        if n % nb:
            # The pinned count (and the shard count) do not divide the
            # node axis: the global form is both correct and what GSPMD
            # would fall back to anyway.
            return 1
        return max(nb, 1)
    if n < TOPK_HIER_MIN or k * 4 > n // max(n_sh, 1):
        return max(n_sh, 1)
    rows = TOPK_BLOCK_ROWS
    while rows < 4 * k:
        rows *= 2
    nb = max(n_sh, 1)
    while n % (nb * 2) == 0 and n // nb > rows:
        nb *= 2
    return nb


# Node-axis thresholds of the adaptive hierarchical selection (see
# _hier_blocks): below TOPK_HIER_MIN nodes a single top_k wins; above,
# blocks aim at TOPK_BLOCK_ROWS rows each.
TOPK_HIER_MIN = _env_int("VOLCANO_TPU_TOPK_HIER_MIN", 65536)
TOPK_BLOCK_ROWS = _env_int("VOLCANO_TPU_TOPK_BLOCK_ROWS", 8192)


def _merge_block_cands(cand_s, cand_i, k: int, n_shards: int = 1):
    """Merge per-block (score, global node id) candidate lists into the
    global top-``k`` id set — the shard->global tail of the
    block->shard->global hierarchy (arxiv 2002.07062's tiling, applied
    to the selection reduce).

    ``cand_s``/``cand_i`` are [U, B, klb] with blocks ascending-id node
    ranges and each block's list in local rank order.  When the blocks
    subdivide ``n_shards`` mesh shards evenly, the merge runs in two
    stages: a SHARD-LOCAL reduce of each shard's blocks (zero
    cross-chip traffic), then the cross-chip winner reduction over the
    [U, n_shards * min(k, ...)] survivors — communication stays at the
    two-stage form's volume no matter how many blocks subdivide a
    shard.  Otherwise one flat reduce over [U, B * klb].

    The result is EXACTLY the top-k of the blocks' union with
    ``jax.lax.top_k`` tie-breaking (lower node id first): within a
    block, equal-score candidates sit in ascending-id order (top_k's
    own tie-break); blocks (and shards) concatenate in ascending-id
    range order; every merge stage's top_k prefers the earlier
    position — so within any score class, position order is ascending
    node id order at every stage."""
    U, B, klb = cand_s.shape
    if n_shards > 1 and B > n_shards and B % n_shards == 0:
        bps = B // n_shards
        ksh = min(k, bps * klb)
        sh_s = cand_s.reshape(U, n_shards, bps * klb)
        sh_i = cand_i.reshape(U, n_shards, bps * klb)
        ms, pos = jax.lax.top_k(sh_s, ksh)  # shard-local block merge
        mi = jnp.take_along_axis(sh_i, pos, axis=2)
        flat_s = ms.reshape(U, n_shards * ksh)
        flat_i = mi.reshape(U, n_shards * ksh)
    else:
        flat_s = cand_s.reshape(U, B * klb)
        flat_i = cand_i.reshape(U, B * klb)
    kf = min(k, flat_s.shape[1])
    _s, pos = jax.lax.top_k(flat_s, kf)  # cross-chip winner reduction
    out = jnp.take_along_axis(flat_i, pos, axis=1)
    if kf < k:
        # Degenerate: fewer candidates than k (tiny blocks).  Pad by
        # repeating the last winner — callers either never hit this
        # (klb == min(k, nlb) keeps B*klb >= k whenever N >= k) or
        # tolerate duplicate trailing ids.
        out = jnp.concatenate(
            [out, jnp.broadcast_to(out[:, -1:], (U, k - kf))], axis=1
        )
    return out


def _topk_nodes(scores, k: int, n_shards: int = 1,
                pin: Optional[int] = None):
    """Top-``k`` node ids per profile row — hierarchical
    block->shard->global under a mesh and/or at large node counts.
    ``pin`` threads the resolved TOPK_BLOCKS static from jitted
    callers (see ``_hier_pin``); eager callers may leave it None.

    ``scores`` is [U, N] with the node axis optionally sharded over
    ``n_shards`` mesh devices.  The selection runs in up to three
    stages (each optional, all exact):

    1. per-BLOCK top_k inside each shard's slice (``_hier_blocks``
       picks the block count; blocks are ascending-id node ranges, so
       the reshape keeps every block within its owning shard and the
       stage runs with zero communication) — at the 100k-node tier this
       replaces one full-plane sort with ~k-deep sorts per block;
    2. a shard-local merge of each shard's block candidates;
    3. the cross-chip winner reduction over (score, global node id)
       pairs — the only cross-device communication (arxiv 2002.07062).

    The result is EXACTLY ``jax.lax.top_k(scores, k)``: a global top-k
    element is necessarily a top-k element of its own block (a block
    can contribute at most min(k, block_rows) winners), and the
    tie-break matches because candidate positions order by (block,
    local rank) — ascending node id within any score class at every
    stage (see ``_merge_block_cands``).
    """
    U, N = scores.shape
    if n_shards > 1 and N % n_shards:
        n_shards = 1
    nb = _hier_blocks(N, k, n_shards, pin)
    if nb <= 1 or N % nb:
        _s, idx = jax.lax.top_k(scores, k)
        return idx.astype(jnp.int32)
    nlb = N // nb
    klb = min(k, nlb)
    loc = scores.reshape(U, nb, nlb)
    loc_s, loc_i = jax.lax.top_k(loc, klb)  # block-local ranking
    gid = loc_i.astype(jnp.int32) + (
        jnp.arange(nb, dtype=jnp.int32) * nlb
    )[None, :, None]
    return _merge_block_cands(loc_s, gid, k, n_shards)


@partial(jax.jit, static_argnames=("sl_k", "chunk", "features",
                                   "cnt0_any", "cls_identity",
                                   "mesh_shards", "n_blocks",
                                   "with_cand", "static_ext",
                                   "hier_pin"))
def _coarse_shortlist(nodes: SolveNodes, prof: SolveProfiles, extra_prof,
                      score_prof, cls: NodeClasses, aff: AffinityArgs,
                      weights: ScoreWeights, eps, scalar_slot,
                      sl_k: int, chunk: int, features: tuple,
                      cnt0_any: bool, cls_identity: bool,
                      mesh_shards: int = 1, n_blocks: int = 1,
                      with_cand: bool = False, static_ext: bool = False,
                      stat_ok=None, stat_score=None, hier_pin: int = 0):
    """Phase 1 + shortlist selection of the two-phase solve.

    Evaluates the wave-0-attempt-1 live mask + score for every profile
    row over all N nodes ONCE (class-compacted statics, initial dynamic
    state) and keeps each profile's top-``sl_k`` candidates, returned as
    ``[U, sl_k]`` int32 node ids sorted ASCENDING — in-shortlist
    rankings then break score ties by node index exactly like the full
    path's top_k.  The masks are evaluated at solve-start state, which
    within a solve only loses capacity/ports/pod slots and only gains
    affinity counts — so a node pruned here stays infeasible for every
    non-required-affinity feature, and required-affinity drift is what
    the fine phase's fallback rescore exists for.

    When ``cnt0_any`` is False the inter-pod planes are skipped: with
    all-zero counts both the required/anti verdicts and the soft score
    are uniform per profile, and per-profile-uniform components cannot
    change top-k membership (a uniformly infeasible profile exhausts its
    shortlist on attempt 1 and resolves through the fallback rescore,
    reaching the identical no-node outcome).

    Profiles stream through ``lax.map`` in ``chunk`` rows so the
    [chunk, N, R] fit broadcast — the pass's only [*, N, R] tensor —
    bounds device memory at hyperscale profile counts.

    ``mesh_shards`` > 1 (the node axis is sharded over that many mesh
    devices) makes the candidate selection shard-local: each chip ranks
    only its own node slice and the per-profile winners reduce across
    chips as (score, global node id) pairs (``_topk_nodes``) — the
    shortlist membership is bit-identical to the single-device pass.

    ``with_cand`` (the device-incremental lane, ISSUE 9) restructures
    the selection into per-block top-k + winner merge over ``n_blocks``
    ascending-id node blocks and ALSO returns the per-block candidate
    lists ``(cand_s [U, B, klb], cand_i [U, B, klb])`` — the warm-start
    state ``_warm_shortlist`` patches on later solves.  The selected
    SET is identical to the direct top-k (a global top-k element is a
    top-k element of its own block, and candidate positions order by
    (block, local rank) — ascending node id within any score class, the
    ``_topk_nodes`` argument), and the returned shortlist sorts
    ascending, so the array is bit-identical either way.  ``static_ext``
    takes the (profile x class) static planes as PARAMS (``stat_ok`` /
    ``stat_score`` [U, C], chunk rows threaded through the profile
    stream) instead of evaluating ``_class_static`` in-kernel.
    """
    (has_ports, has_aff, has_taints, has_future, _has_overuse,
     has_extra, has_extra_score) = features
    f32 = jnp.float32
    bf = jnp.bfloat16
    N = nodes.idle.shape[0]
    U = prof.req.shape[0]
    if cls_identity:
        cls = _identity_classes(nodes)
    # Initial dynamic node state, shared by every chunk.
    if has_future:
        fi0 = nodes.idle + nodes.releasing - nodes.pipelined
    else:
        fi0 = nodes.idle
    pods_ok0 = (nodes.max_tasks <= 0) | (nodes.ntasks < nodes.max_tasks)
    if has_ports:
        nport_bf = _unpack_bits(nodes.ports).astype(bf)  # [N, B]
    if has_aff and cnt0_any:
        E = aff.cnt0.shape[0]
        nd_e = jnp.take(aff.node_dom, aff.term_key, axis=1)  # [N, E]
        cv0 = aff.cnt0[jnp.arange(E)[None, :], jnp.maximum(nd_e, 0)]
        cv0 = jnp.where(nd_e >= 0, cv0, 0)  # [N, E]
        total0 = jnp.sum(aff.cnt0, axis=-1)  # [E]
        cv0_zero_bf = (cv0 == 0).astype(bf)
        cv0_pos_bf = (cv0 > 0).astype(bf)
        cv0_f = cv0.astype(f32)

    def body(rowset):
        (req, init_req, ports, sel_bits, aff_bits, aff_terms, tol_bits,
         pref_bits, pref_w, t_req_aff, t_req_anti, t_matches, t_soft,
         e_ok, e_score) = rowset[:15]
        if static_ext:
            # Persistent static planes (ISSUE 9): the chunk's rows of
            # the externally-produced [U, C] planes — bit-identical to
            # the in-kernel evaluation (rows are computed
            # independently; see _static_planes).
            ok_c, score_c = rowset[15], rowset[16]
        else:
            ok_c, score_c = _class_static(
                cls, sel_bits, aff_bits, aff_terms, tol_bits, pref_bits,
                pref_w, weights.node_affinity_weight, has_taints,
            )
        feas = ok_c[:, cls.class_id]  # [u, N] expand
        static_score = score_c[:, cls.class_id]
        if has_extra:
            feas &= e_ok
        if has_extra_score:
            static_score = static_score + e_score
        fit = less_equal(
            init_req[:, None, :], fi0[None, :, :], eps, scalar_slot
        )
        feas &= fit & pods_ok0[None, :]
        if has_ports:
            p_bits = _unpack_bits(ports)
            clash = jnp.matmul(p_bits.astype(bf), nport_bf.T)
            feas &= ~jnp.any(p_bits, axis=-1)[:, None] | (clash < 0.5)
        score = jax.vmap(node_score, in_axes=(0, None, None, None))(
            req, nodes.allocatable, nodes.idle, weights
        ) + static_score
        if has_aff and cnt0_any:
            selfok = (total0 == 0)[None, :] & t_matches  # [u, E]
            need = (t_req_aff & ~selfok).astype(bf)
            aff_viol = jnp.matmul(need, cv0_zero_bf.T)
            anti_viol = jnp.matmul(t_req_anti.astype(bf), cv0_pos_bf.T)
            feas &= (aff_viol < 0.5) & (anti_viol < 0.5)
            score = score + jnp.matmul(t_soft, cv0_f.T)
        masked = jnp.where(feas, score, NEG)
        if with_cand:
            # Per-block top-k + hierarchical winner merge (ISSUE 9 +
            # the 100k-node tier): identical membership to the direct
            # top-k (see the docstring), the block candidates become
            # the warm-start state, and under a mesh the merge reduces
            # shard-local before the cross-chip winner reduction
            # (_merge_block_cands — blocks subdivide shards because
            # the caller keeps n_blocks a multiple of the shard
            # count).
            u_ = masked.shape[0]
            nlb = N // n_blocks
            klb = min(sl_k, nlb)
            loc_s, loc_i = jax.lax.top_k(
                masked.reshape(u_, n_blocks, nlb), klb
            )
            gid = loc_i.astype(jnp.int32) + (
                jnp.arange(n_blocks, dtype=jnp.int32) * nlb
            )[None, :, None]
            idx = _merge_block_cands(loc_s, gid, sl_k, mesh_shards)
            return (jnp.sort(idx, axis=1).astype(jnp.int32), loc_s, gid)
        # Shard-local ranking + cross-chip winner reduction under a
        # mesh; identical membership to a global top_k (see _topk_nodes).
        idx = _topk_nodes(masked, sl_k, mesh_shards, hier_pin)
        return jnp.sort(idx, axis=1).astype(jnp.int32)

    ones_u = jnp.ones((U, 1), bool)
    zeros_u = jnp.zeros((U, 1), f32)
    cols = (
        prof.req, prof.init_req, prof.ports, prof.sel_bits,
        prof.aff_bits, prof.aff_terms, prof.tol_bits, prof.pref_bits,
        prof.pref_w, prof.t_req_aff, prof.t_req_anti, prof.t_matches,
        prof.t_soft,
        extra_prof if has_extra else ones_u,
        score_prof if has_extra_score else zeros_u,
    )
    if static_ext:
        cols = cols + (stat_ok, stat_score)
    if chunk >= U:
        return body(cols)
    resh = tuple(
        a.reshape(U // chunk, chunk, *a.shape[1:]) for a in cols
    )
    out = jax.lax.map(body, resh)
    if with_cand:
        sl, cand_s, cand_i = out
        klb = cand_s.shape[-1]
        return (sl.reshape(U, sl_k),
                cand_s.reshape(U, n_blocks, klb),
                cand_i.reshape(U, n_blocks, klb))
    return out.reshape(U, sl_k)


@partial(jax.jit, static_argnames=("sl_k", "klb", "nlb", "chunk",
                                   "features", "cnt0_any",
                                   "cls_identity", "static_ext",
                                   "mesh_shards"))
def _warm_shortlist(nodes: SolveNodes, prof: SolveProfiles, extra_prof,
                    score_prof, cls: NodeClasses, aff: AffinityArgs,
                    weights: ScoreWeights, eps, scalar_slot,
                    stat_ok, stat_score, db_rows, cand_s, cand_i,
                    sl_k: int, klb: int, nlb: int, chunk: int,
                    features: tuple, cnt0_any: bool, cls_identity: bool,
                    static_ext: bool, mesh_shards: int = 1):
    """Warm-started shortlist selection (ISSUE 9): re-rank ONLY the node
    blocks whose rows are in the cycle's dirty set, patch their
    candidates into the carried per-block lists, and merge winners.

    ``db_rows`` is the [ndb] list of dirty block ids (padded with
    duplicates of the first — the scatter rewrites identical values, so
    padding is idempotent); ``cand_s``/``cand_i`` are the previous
    solve's per-block candidates ([U, B, klb], produced by
    ``_coarse_shortlist`` with ``with_cand`` or by an earlier warm
    pass).  The caller (``ops/devincr.DeviceIncremental``) proves every
    node OUTSIDE the dirty blocks has byte-identical solve inputs to the
    previous solve, so its retained candidates equal what a fresh
    ranking would produce and the merged shortlist is bit-identical to
    a full ``_coarse_shortlist`` over today's state.  Same formulas as
    the coarse body, evaluated on the gathered dirty-block node rows
    ([U, ndb*nlb] instead of [U, N]).

    Returns ``(shortlists [U, sl_k], cand_s, cand_i)`` — the updated
    candidates are the next solve's warm state."""
    (has_ports, has_aff, has_taints, has_future, _has_overuse,
     _has_extra, _has_extra_score) = features
    f32 = jnp.float32
    bf = jnp.bfloat16
    N = nodes.idle.shape[0]
    U = prof.req.shape[0]
    if cls_identity:
        cls = _identity_classes(nodes)
    ndb = db_rows.shape[0]
    rows = (
        db_rows[:, None] * nlb
        + jnp.arange(nlb, dtype=jnp.int32)[None, :]
    ).reshape(-1)  # [M] global node ids of the dirty blocks
    # Gathered node-side solve-start state (row subsets of the same
    # planes the coarse pass reads — values bitwise equal per node).
    idle_r = nodes.idle[rows]
    if has_future:
        rel = nodes.releasing
        rel_r = rel[rows] if rel.shape[0] == N else rel
        pip = nodes.pipelined
        pip_r = pip[rows] if pip.shape[0] == N else pip
        fi0_r = idle_r + rel_r - pip_r
    else:
        fi0_r = idle_r
    mt_r = nodes.max_tasks[rows]
    pods_ok0_r = (mt_r <= 0) | (nodes.ntasks[rows] < mt_r)
    cid_r = cls.class_id[rows]
    alloc_r = nodes.allocatable[rows]
    if has_ports:
        nport_bf_r = _unpack_bits(nodes.ports[rows]).astype(bf)
    if has_aff and cnt0_any:
        E = aff.cnt0.shape[0]
        nd_e_r = jnp.take(aff.node_dom[rows], aff.term_key,
                          axis=1)  # [M, E]
        cv0_r = aff.cnt0[jnp.arange(E)[None, :], jnp.maximum(nd_e_r, 0)]
        cv0_r = jnp.where(nd_e_r >= 0, cv0_r, 0)
        total0 = jnp.sum(aff.cnt0, axis=-1)
        cv0_zero_bf = (cv0_r == 0).astype(bf)
        cv0_pos_bf = (cv0_r > 0).astype(bf)
        cv0_f = cv0_r.astype(f32)

    def body(rowset):
        (req, init_req, ports, sel_bits, aff_bits, aff_terms, tol_bits,
         pref_bits, pref_w, t_req_aff, t_req_anti, t_matches,
         t_soft) = rowset[:13]
        if static_ext:
            ok_c, score_c = rowset[13], rowset[14]
        else:
            ok_c, score_c = _class_static(
                cls, sel_bits, aff_bits, aff_terms, tol_bits, pref_bits,
                pref_w, weights.node_affinity_weight, has_taints,
            )
        feas = ok_c[:, cid_r]  # [u, M] expand at the dirty rows
        static_score = score_c[:, cid_r]
        fit = less_equal(
            init_req[:, None, :], fi0_r[None, :, :], eps, scalar_slot
        )
        feas &= fit & pods_ok0_r[None, :]
        if has_ports:
            p_bits = _unpack_bits(ports)
            clash = jnp.matmul(p_bits.astype(bf), nport_bf_r.T)
            feas &= ~jnp.any(p_bits, axis=-1)[:, None] | (clash < 0.5)
        score = jax.vmap(node_score, in_axes=(0, None, None, None))(
            req, alloc_r, idle_r, weights
        ) + static_score
        if has_aff and cnt0_any:
            selfok = (total0 == 0)[None, :] & t_matches
            need = (t_req_aff & ~selfok).astype(bf)
            aff_viol = jnp.matmul(need, cv0_zero_bf.T)
            anti_viol = jnp.matmul(t_req_anti.astype(bf), cv0_pos_bf.T)
            feas &= (aff_viol < 0.5) & (anti_viol < 0.5)
            score = score + jnp.matmul(t_soft, cv0_f.T)
        masked = jnp.where(feas, score, NEG)
        u_ = masked.shape[0]
        loc_s, loc_i = jax.lax.top_k(
            masked.reshape(u_, ndb, nlb), klb
        )
        gid = loc_i.astype(jnp.int32) + db_rows[None, :, None] * nlb
        return loc_s, gid

    cols = (
        prof.req, prof.init_req, prof.ports, prof.sel_bits,
        prof.aff_bits, prof.aff_terms, prof.tol_bits, prof.pref_bits,
        prof.pref_w, prof.t_req_aff, prof.t_req_anti, prof.t_matches,
        prof.t_soft,
    )
    if static_ext:
        cols = cols + (stat_ok, stat_score)
    if chunk >= U:
        s_new, i_new = body(cols)
    else:
        resh = tuple(
            a.reshape(U // chunk, chunk, *a.shape[1:]) for a in cols
        )
        s_new, i_new = jax.lax.map(body, resh)
        s_new = s_new.reshape(U, ndb, klb)
        i_new = i_new.reshape(U, ndb, klb)
    # Patch the dirty blocks' candidates (duplicate padded block ids
    # rewrite identical values — idempotent) and merge winners exactly
    # like the coarse pass's with_cand tail: block->shard->global under
    # a mesh, one flat reduce otherwise (_merge_block_cands).
    cand_s = cand_s.at[:, db_rows].set(s_new)
    cand_i = cand_i.at[:, db_rows].set(i_new)
    idx = _merge_block_cands(cand_s, cand_i, sl_k, mesh_shards)
    sl = jnp.sort(idx, axis=1).astype(jnp.int32)
    return sl, cand_s, cand_i


@partial(jax.jit, static_argnames=("wave", "n_waves", "ew", "features",
                                   "terms_disjoint", "two_phase",
                                   "cls_identity", "fb_cap",
                                   "mesh_shards", "static_ext",
                                   "hier_pin", "flat_keys", "has_bias"))
def _solve_wave(
    nodes: SolveNodes,
    tasks: SolveTasks,
    jobs: SolveJobs,
    queues: SolveQueues,
    weights: ScoreWeights,
    eps,
    scalar_slot,
    aff: AffinityArgs,
    prof: SolveProfiles,
    extra_prof: jnp.ndarray,  # [U, N] bool custom verdicts ([1,1] if unused)
    score_prof: jnp.ndarray,  # [U, N] f32 custom scores ([1,1] if unused)
    pid: jnp.ndarray,  # [P] int32 global profile id per task
    wave_prof: jnp.ndarray,  # [NW, U_MAX] int32 profile ids present per wave
    wave_terms: jnp.ndarray,  # [NW, EW] int32 term ids per wave (pad=dummy)
    cls: NodeClasses,  # class planes ([1]-dummies unless compacted)
    shortlists: jnp.ndarray,  # [U, S] int32 ([1, 1] unless two_phase)
    wave: int,
    n_waves: int,
    ew: int,
    features: tuple = (True, True, True, True, True, False, False),
    terms_disjoint: bool = False,
    two_phase: bool = False,
    cls_identity: bool = False,
    fb_cap: int = 0,
    mesh_shards: int = 1,
    static_ext: bool = False,
    stat_ok=None,  # [U, C] bool persistent static planes (ISSUE 9)
    stat_score=None,  # [U, C] f32
    hier_pin: int = 0,  # resolved TOPK_BLOCKS (0 = adaptive)
    flat_keys: bool = True,  # (term x domain) key space fits int32
    node_bias=None,  # [N] f32 additive node-order bias (topology)
    has_bias: bool = False,  # static: bias add traced only when real
) -> AllocResult:
    # Static feature flags let XLA drop whole subsystems from the program
    # when the snapshot provably cannot exercise them (no host ports
    # anywhere, no affinity terms, no taints, no releasing capacity =>
    # no pipelining, no finite queue deserved => no overuse gating).
    (has_ports, has_aff, has_taints, has_future, has_overuse,
     has_extra, has_extra_score) = features

    # Per-task solver state lives in job/real/pid only; req/init_req are
    # gathered from the profile rows on device (tasks sharing a pid have
    # identical inputs by contract), so callers ship [1, ...] dummies for
    # every other SolveTasks field — at the north-star shape the ~5 MB of
    # per-task arrays cost ~150 ms of upload through the remote-TPU
    # tunnel (~35 MB/s into an execution).
    P = tasks.job.shape[0]
    R = prof.req.shape[1]
    pid = pid.astype(jnp.int32)
    N = nodes.idle.shape[0]
    J = jobs.min_available.shape[0]
    A = prof.aff_bits.shape[1]
    AP = prof.pref_bits.shape[1]
    E, D = aff.cnt0.shape
    Q = queues.deserved.shape[0]
    W = wave
    NW = n_waves
    UM = wave_prof.shape[1]
    EW = ew
    S = shortlists.shape[1] if two_phase else N
    K = min(TOPK, S)
    # int32 index audit (the 100k x 1M tier): flattened (term, domain)
    # keys are only sound while EW * D + 1 fits the int32 device index
    # space; past the gate every keyed scatter/gather below runs in
    # its 2-D form.  The verdict arrives as the ``flat_keys`` STATIC —
    # resolved by solve_wave outside the jit (_keyspace_max is an env
    # read; reading it at trace time would pin the first verdict into
    # the jit cache).
    flat_keys_ok = flat_keys
    JP = J + W  # job axis padded so any wave's window slice stays in range
    f32 = jnp.float32
    BIG = jnp.float32(1.0e9)

    # The device inner loop avoids every large sort and every wide
    # scatter/gather it can:
    #  - nodes are *ranked once per wave* (argsort of the per-profile score
    #    rows); attempts walk down the fixed ranking by live cumulative
    #    capacity instead of re-sorting (TPU TopK/sort is millisecond-slow
    #    at [U, 16k]);
    #  - job- and queue-indexed state reads/writes are [W, W]/[W, Q]
    #    one-hot matmuls over the wave's contiguous job window (TPU
    #    scatters serialize per row);
    #  - a stalled attempt (no placement and no new skip) leaves the state
    #    bit-identical, so the loop exits; the unresolved tasks stay
    #    Pending for the cycle (see attempt_cond).

    node_ready = nodes.ready
    if two_phase:
        if cls_identity:
            # No compacted classes supplied (knob off, or device-resident
            # nodes without caller-built planes): every node is its own
            # class — the shortlist machinery still applies, the static
            # matmuls just stay at node granularity.
            cls = _identity_classes(nodes)
    else:
        # Unpacked-bit tables (f32 complements feed the matmul subset
        # checks) — the two-phase path evaluates these per CLASS instead.
        label_missing_f = (~_unpack_bits(nodes.label_bits)).astype(f32)
        node_taint_bits_f = _unpack_bits(nodes.taint_bits).astype(f32)

    # Padded-row job sentinel J keeps wave windows ([jlo, jlo+W)) in the
    # padded job range without branching.
    tjob = jnp.where(tasks.real, tasks.job.astype(jnp.int32), J)
    prev_job = jnp.concatenate([jnp.int32([-1]), tjob[:-1]])
    is_first = tasks.real & (tjob != prev_job)
    queue_p = jnp.pad(jobs.queue, (0, W))

    job_seen = jnp.zeros((JP,), bool).at[tjob].max(tasks.real)

    # With wave-disjoint term sets the global count tables are
    # loop-INVARIANT (no wave reads another wave's writes, so the
    # write-back is skipped); carrying the 164 MB-at-scale tables
    # through the fori_loop makes XLA rematerialize them from the
    # sparse cnt0 entries inside the loop (measured ~0.4 s/cycle).
    # Keep them out of the carry and gather windows straight from the
    # input instead.
    cnt0_i32 = aff.cnt0.astype(jnp.int32)
    state = GState(
        idle=nodes.idle,
        pip_extra=jnp.zeros_like(nodes.idle),
        ntasks=nodes.ntasks,
        pip_ntasks=jnp.zeros_like(nodes.ntasks),
        nport_bits=_unpack_bits(nodes.ports),
        pip_nport_bits=jnp.zeros_like(_unpack_bits(nodes.ports)),
        cnt_alloc=(jnp.zeros((1, 1), jnp.int32) if terms_disjoint
                   else cnt0_i32),
        cnt_pip=(jnp.zeros((1, 1), jnp.int32) if terms_disjoint
                 else jnp.zeros_like(cnt0_i32)),
        q_alloc=queues.allocated,
        q_pip=jnp.zeros_like(queues.allocated),
        alloc_cnt=jnp.zeros((JP,), jnp.int32),
        fit_failed=jnp.zeros((JP,), bool),
        job_skip=jnp.zeros((JP,), bool),
        job_overskip=jnp.zeros((JP,), bool),
        assigned=jnp.full((P,), -1, jnp.int32),
        pipelined=jnp.full((P,), -1, jnp.int32),
        iters=jnp.int32(0),
        fb_exhausted=jnp.int32(0),
        fb_affinity=jnp.int32(0),
        fb_rounds=jnp.int32(0),
    )

    tril = jnp.tril(jnp.ones((W, W), bool), k=-1)  # strictly-earlier mask

    # Domain-membership one-hot for the count-window matmul (see
    # DOM_MM_MAX_MB): dom_oh[d, n] = 1 iff node n belongs to global
    # domain d under SOME topology key.  Counts are zero outside a
    # term's own key's domains, so cnt @ dom_oh picks up exactly
    # cnt[e, node_dom[n, key(e)]] — the per-attempt gather as one MXU
    # pass.  Built once per solve; trace-static size gate.
    dom_mm = has_aff and (D * N * 4 <= DOM_MM_MAX_MB * 1_000_000)
    if dom_mm:
        # Stored [N, D] (node-major): contractions read it transposed
        # for free via dot_general, while the sub-round filter can
        # ROW-gather the choice nodes' membership (contiguous rows)
        # instead of multiplying against all N columns.
        K_keys = aff.node_dom.shape[1]
        dom_ohT = jnp.zeros((N, D), f32)
        for k in range(K_keys):
            nd_k = aff.node_dom[:, k]  # [N] domain id or -1
            dom_ohT = dom_ohT.at[
                jnp.arange(N), jnp.where(nd_k >= 0, nd_k, D)
            ].max(jnp.where(nd_k >= 0, 1.0, 0.0),
                  mode="drop")
    else:
        dom_ohT = None

    def run_wave(w, state: GState) -> GState:
        off = w * W
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, off, W, axis=0)

        jraw = sl(tjob)
        real_w = sl(tasks.real)
        is_first_w = sl(is_first)
        # Index of each task's profile in this wave's presence list,
        # recomputed on device: every pid in the wave appears in
        # wave_prof[w] by construction, so the equality argmax is exact
        # — and a [W, UM] compare beats shipping a [P] vector through
        # the tunnel.
        pid_w = sl(pid)
        pid_l = jnp.argmax(
            pid_w[:, None] == wave_prof[w][None, :], axis=1
        ).astype(jnp.int32)

        # Job window: job ids of a wave form a contiguous range (tasks are
        # job-contiguous), so job state lives in [W]-sized locals.
        jlo = jnp.min(jnp.where(real_w, jraw, J))
        jw = jnp.clip(jraw - jlo, 0, W - 1)
        onehot_j = (
            (jw[:, None] == jnp.arange(W)[None, :]) & real_w[:, None]
        ).astype(f32)  # [W_task, W_job]
        queue_l = jax.lax.dynamic_slice_in_dim(queue_p, jlo, W)
        onehot_ql = (queue_l[:, None] == jnp.arange(Q)[None, :]).astype(f32)
        onehot_jq = jnp.matmul(onehot_j, onehot_ql)  # [W_task, Q]
        onehot_u = (pid_l[:, None] == jnp.arange(UM)[None, :]).astype(f32)
        same_pid = pid_l[:, None] == pid_l[None, :]
        jsl = lambda a: jax.lax.dynamic_slice_in_dim(a, jlo, W, axis=0)

        # Profiles present in this wave ([UM] global rows).
        pids = wave_prof[w]  # [UM]
        p_req = prof.req[pids]
        p_init_req = prof.init_req[pids]
        p_req_pos = p_req > 0
        # Per-task requests, reconstructed from the wave's profile rows
        # ([W] gather from [UM, R]) instead of a shipped [P, R] table.
        req_w = p_req[pid_l]
        init_req_w = p_init_req[pid_l]
        if has_ports:
            p_ports = _unpack_bits(prof.ports[pids])  # [UM, B]
            p_has_ports = jnp.any(p_ports, axis=-1)
            ports_w = p_ports[pid_l]  # [W, B] per-task view
        if has_aff:
            # Term window: gather this wave's referenced terms (tasks are
            # job-contiguous, terms per-jobish), so every [*, E] tensor
            # below is bounded by terms-per-wave — the tiling that keeps
            # the affinity machinery scalable to 50k x 500k (SURVEY.md
            # section 7 hard parts).
            wterms = wave_terms[w]  # [EW], padded with the dummy row
            # Waves whose window is entirely dummy padding neither consult
            # nor change any term count (matched tasks put their terms in
            # the window too); the per-attempt [N, EW] gather and the
            # [UM, EW] x [EW, N] violation/score matmuls are lax.cond-
            # skipped for them — with sparse affinity, most waves.
            # E here includes the appended dummy row, whose index (the
            # wave_terms pad value) is E - 1.
            wave_live = jnp.any(wterms != E - 1)
            tk_w = aff.term_key[wterms]
            node_dom_t = jnp.take(aff.node_dom, tk_w, axis=1)  # [N, EW]
            term_arange = jnp.arange(EW)
            esl = lambda a: jnp.take(a, wterms, axis=1)
            p_t_req_aff = esl(prof.t_req_aff[pids])  # [UM, EW]
            p_t_req_anti = esl(prof.t_req_anti[pids])
            p_t_matches = esl(prof.t_matches[pids])
            p_t_soft = esl(prof.t_soft[pids])
            t_matches_w = p_t_matches[pid_l]  # [W, EW]
            # Terms some wave profile REQUIRES (affinity or anti): the
            # conflict machinery and the dirty tracking both key off
            # this set (soft-only spread terms never feed either).
            term_req_w = jnp.any(p_t_req_aff | p_t_req_anti, axis=0)


        # ---- static predicate masks, hoisted out of the attempt loop ----
        if two_phase:
            # Phase-1 coarse: one bf16 evaluation per (profile x CLASS),
            # expanded to nodes through the class_id gather.  Class
            # members share the static planes byte-for-byte, so the
            # expanded masks/scores equal the node-level computation
            # exactly; the [UM, B] x [B, C] matmuls replace [UM, B] x
            # [B, N] — the N/C compaction of the static fan-out.
            if static_ext:
                # Persistent static planes (ISSUE 9): the wave's rows
                # of the externally-produced [U, C] planes replace the
                # per-wave _class_static evaluation entirely — the
                # steady-state win of the device-incremental lane (rows
                # compute independently, so the gather is bit-identical
                # to the in-kernel evaluation).
                cls_ok = stat_ok[pids]
                cls_pref = stat_score[pids]
            else:
                cls_ok, cls_pref = _class_static(
                    cls, prof.sel_bits[pids], prof.aff_bits[pids],
                    prof.aff_terms[pids], prof.tol_bits[pids],
                    prof.pref_bits[pids], prof.pref_w[pids],
                    weights.node_affinity_weight, has_taints,
                )
            p_ok = cls_ok[:, cls.class_id]  # [UM, N]
            if has_extra:
                p_ok &= extra_prof[pids]
            p_static_score = cls_pref[:, cls.class_id]
            if has_extra_score:
                p_static_score = p_static_score + score_prof[pids]
        else:
            p_ok = node_ready[None, :] & _subset_mm(
                _unpack_bits(prof.sel_bits[pids]), label_missing_f
            )
            if has_extra:
                # Custom-plugin verdicts, per profile (tasks sharing a
                # profile share a mask row by construction).
                p_ok &= extra_prof[pids]
            aff_bits_p = _unpack_bits(prof.aff_bits[pids])  # [UM, A, B]
            term_ok = _subset_mm(
                aff_bits_p.reshape(UM * A, -1), label_missing_f
            ).reshape(UM, A, N)
            n_terms = prof.aff_terms[pids]
            term_real = jnp.arange(A)[None, :] < n_terms[:, None]  # [UM, A]
            p_ok &= (
                jnp.any(term_ok & term_real[:, :, None], axis=1)
                | (n_terms == 0)[:, None]
            )
            if has_taints:
                # Taints: any node taint bit not tolerated kills the pair.
                untol = jnp.matmul(
                    node_taint_bits_f,
                    (~_unpack_bits(prof.tol_bits[pids])).astype(f32).T,
                )  # [N, UM]
                p_ok &= untol.T == 0

            pref_bits_p = _unpack_bits(prof.pref_bits[pids])  # [UM, AP, B]
            pref_match = _subset_mm(
                pref_bits_p.reshape(UM * AP, -1), label_missing_f
            ).reshape(UM, AP, N)
            p_static_score = weights.node_affinity_weight * jnp.sum(
                pref_match * prof.pref_w[pids][:, :, None], axis=1
            )  # [UM, N]
            if has_extra_score:
                # Attempt-invariant: hoisted out of the attempt loop (XLA
                # does not hoist out of while_loops).
                p_static_score = p_static_score + score_prof[pids]

        if has_bias:
            # Topology node-order bias (ops/topology.contig_bias): an
            # additive plane over nodes, identical for every profile.
            # Folding it here covers the full-N ranking, the two-phase
            # shortlist gather (static_sl below), and the fb-counted
            # full-N fallback rescore in one place.  Gated by the
            # STATIC flag — not a `+ 0.0` — so biasless solves trace
            # the exact pre-topology program (bitwise: -0.0 + 0.0
            # flips a sign bit).
            p_static_score = p_static_score + node_bias[None, :].astype(f32)

        if two_phase:
            # Phase-2 hoists: the wave's shortlist window and every
            # static plane gathered down to it.  sl rows are ascending
            # node ids, so in-shortlist top_k tie-breaks by node index
            # exactly like the full path.
            sl_w = shortlists[pids]  # [UM, S]
            p_ok_sl = jnp.take_along_axis(p_ok, sl_w, axis=1)
            static_sl = jnp.take_along_axis(p_static_score, sl_w, axis=1)
            mt_sl = nodes.max_tasks[sl_w]  # [UM, S]
            alloc_sl = nodes.allocatable[sl_w]  # [UM, S, R]

        def live_parts(s: GState, cw_a, cw_p, aff_ok_c, aff_soft_c,
                       aff_dirty_a):
            """Per-attempt dynamic feasibility [UM, N].

            The inter-pod affinity planes (required/anti feasibility +
            soft-term score) depend ONLY on the wave's term counts, so
            they are carried across attempts and recomputed solely when
            a sub-round actually changed a count (aff_dirty_a): the
            [N, EW] domain gather over cnt[EW, D~N] and the
            [UM, EW] x [EW, N] matmuls — the dominant per-attempt cost
            at the affinity-mix north-star shape — run once per count
            change instead of once per attempt.  Exact: same values,
            fewer recomputes."""
            if has_future:
                future_idle = (
                    s.idle + nodes.releasing - nodes.pipelined - s.pip_extra
                )
                walk_idle = future_idle
            else:
                future_idle = s.idle
                walk_idle = s.idle
            fit_future = less_equal(
                p_init_req[:, None, :], future_idle[None, :, :],
                eps, scalar_slot,
            )
            total_ntasks = s.ntasks + s.pip_ntasks
            pods_ok = (
                (nodes.max_tasks <= 0) | (total_ntasks < nodes.max_tasks)
            )[None, :]
            p_feasible = p_ok & fit_future & pods_ok
            if has_ports:
                used_port_f = (s.nport_bits | s.pip_nport_bits).astype(f32)
                port_clash = jnp.matmul(
                    p_ports.astype(f32), used_port_f.T
                )
                p_feasible &= ~p_has_ports[:, None] | (port_clash == 0)
            aff_ok, aff_soft = aff_ok_c, aff_soft_c
            if has_aff:
                def _aff_parts(cnt):
                    if dom_mm:
                        # One MXU pass replaces the [N, EW] serialized
                        # gather (21 ms/attempt at 10k x 100k).  f32 is
                        # exact: integer counts, one product per output.
                        cv = jax.lax.dot_general(
                            cnt.astype(f32), dom_ohT,
                            (((1,), (1,)), ((), ())),
                        ).T
                    else:
                        cv = cnt[
                            term_arange[None, :],
                            jnp.maximum(node_dom_t, 0)
                        ]
                        cv = jnp.where(node_dom_t >= 0, cv, 0)  # [N, EW]
                    total = jnp.sum(cnt, axis=-1)  # [EW]
                    # Required affinity: every required term needs a
                    # resident match in the node's domain (or the
                    # self-match rule).
                    selfok = (total == 0)[None, :] & p_t_matches  # [UM, E]
                    # 0/1 indicator products feeding a zero/nonzero
                    # decision: bf16 is exact for the classification
                    # (true sums are integers; a bf16-rounded value >= 1
                    # can never land below 0.5, and true 0 stays 0) and
                    # runs ~4x faster on the MXU than f32.
                    bf = jnp.bfloat16
                    need = (p_t_req_aff & ~selfok).astype(bf)
                    aff_viol = jnp.matmul(need, (cv == 0).astype(bf).T)
                    anti_viol = jnp.matmul(
                        p_t_req_anti.astype(bf), (cv > 0).astype(bf).T
                    )
                    soft = jnp.matmul(p_t_soft, cv.T.astype(f32))
                    return (aff_viol < 0.5) & (anti_viol < 0.5), soft

                # Cache init is (all-true, zeros) and aff_dirty_a starts
                # at wave_live, so term-free waves never enter the
                # compute branch (the old _aff_skip case).  With the
                # cache disabled, every attempt of a live wave
                # recomputes (the pre-cache behavior).
                gate = aff_dirty_a if AFF_ACACHE else wave_live
                aff_ok, aff_soft = jax.lax.cond(
                    gate, _aff_parts,
                    lambda cnt: (aff_ok_c, aff_soft_c), cw_a + cw_p
                )
                p_feasible &= aff_ok
            return p_feasible, future_idle, walk_idle, aff_ok, aff_soft

        def rank_nodes(s: GState, p_feasible, aff_soft):
            """Per-profile node ranking by live score ([UM, K] ids).

            One argsort per attempt.  Because infeasible nodes rank last
            (NEG-masked) and every live-feasible node holds at least one
            copy, the first unresolved candidate always lands on a node
            that accepts it — the attempt loop's progress guarantee.
            """
            p_score = jax.vmap(node_score, in_axes=(0, None, None, None))(
                p_req, nodes.allocatable, s.idle, weights
            )
            p_score = p_score + p_static_score
            if has_aff:
                # Soft-term component rides the attempt cache (zeros for
                # term-free waves).
                p_score = p_score + aff_soft
            p_score = jnp.where(p_feasible, p_score, NEG)
            # top_k is the partial sort: ties prefer lower node index,
            # matching the stable argsort it replaces.  Under a mesh the
            # ranking runs shard-local with only the (score, node id)
            # winner reduction crossing chips (_topk_nodes) — this is
            # the full-N path, so it also keeps the two-phase fallback
            # rescore shard-local.
            return _topk_nodes(p_score, K, mesh_shards, hier_pin)

        def live_parts_sl(s: GState, cw_a, cw_p, aff_ok_c, aff_soft_c,
                          aff_dirty_a):
            """Phase-2 fine ``live_parts``: per-attempt dynamic
            feasibility on the [UM, S] shortlist planes.

            Same formulas as ``live_parts`` evaluated only at each
            profile's candidate nodes — the fit broadcast, the port
            clash, and the affinity violation contractions all shrink by
            N/S.  The count-vector gather/matmul over [N, EW] stays
            shared (it is profile-independent); only the per-profile
            planes compact.  Values at shortlist nodes are bit-identical
            to the full computation's."""
            if has_future:
                future_idle = (
                    s.idle + nodes.releasing - nodes.pipelined - s.pip_extra
                )
                walk_idle = future_idle
            else:
                future_idle = s.idle
                walk_idle = s.idle
            fi_sl = future_idle[sl_w]  # [UM, S, R] row gather
            fit_sl = less_equal(
                p_init_req[:, None, :], fi_sl, eps, scalar_slot
            )
            nt_sl = (s.ntasks + s.pip_ntasks)[sl_w]
            pods_ok = (mt_sl <= 0) | (nt_sl < mt_sl)
            feas = p_ok_sl & fit_sl & pods_ok
            if has_ports:
                used = (s.nport_bits | s.pip_nport_bits)[sl_w]  # [UM,S,B]
                clash = jnp.einsum(
                    "ub,usb->us", p_ports.astype(f32), used.astype(f32)
                )
                feas &= ~p_has_ports[:, None] | (clash == 0)
            aff_ok, aff_soft = aff_ok_c, aff_soft_c
            if has_aff:
                def _aff_parts_sl(cnt):
                    if dom_mm:
                        cv = jax.lax.dot_general(
                            cnt.astype(f32), dom_ohT,
                            (((1,), (1,)), ((), ())),
                        ).T
                    else:
                        cv = cnt[
                            term_arange[None, :],
                            jnp.maximum(node_dom_t, 0)
                        ]
                        cv = jnp.where(node_dom_t >= 0, cv, 0)  # [N, EW]
                    cv_sl = cv[sl_w]  # [UM, S, EW] row gather
                    total = jnp.sum(cnt, axis=-1)  # [EW]
                    selfok = (total == 0)[None, :] & p_t_matches
                    bfl = jnp.bfloat16
                    need = (p_t_req_aff & ~selfok).astype(bfl)
                    aff_viol = jnp.einsum(
                        "ue,use->us", need, (cv_sl == 0).astype(bfl)
                    )
                    anti_viol = jnp.einsum(
                        "ue,use->us", p_t_req_anti.astype(bfl),
                        (cv_sl > 0).astype(bfl),
                    )
                    soft = jnp.einsum(
                        "ue,use->us", p_t_soft, cv_sl.astype(f32)
                    )
                    return (
                        (aff_viol < 0.5) & (anti_viol < 0.5), soft
                    )

                gate = aff_dirty_a if AFF_ACACHE else wave_live
                aff_ok, aff_soft = jax.lax.cond(
                    gate, _aff_parts_sl,
                    lambda cnt: (aff_ok_c, aff_soft_c), cw_a + cw_p
                )
                feas &= aff_ok
            return feas, future_idle, walk_idle, aff_ok, aff_soft

        def rank_shortlist(s: GState, feas_sl, aff_soft):
            """In-shortlist ranking: [UM, K] global node ids + their
            feasibility.  sl rows are ascending node ids, so top_k ties
            resolve to the lowest node index — the full path's
            tie-break."""
            p_score = jax.vmap(node_score, in_axes=(0, 0, 0, None))(
                p_req, alloc_sl, s.idle[sl_w], weights
            )
            p_score = p_score + static_sl
            if has_aff:
                p_score = p_score + aff_soft
            p_score = jnp.where(feas_sl, p_score, NEG)
            _scores, pos = jax.lax.top_k(p_score, K)
            ranked = jnp.take_along_axis(sl_w, pos, axis=1).astype(
                jnp.int32
            )
            feas_k = jnp.take_along_axis(feas_sl, pos, axis=1)
            return ranked, feas_k

        done0 = ~real_w

        def attempt_cond(carry):
            (_s, _cwa, _cwp, done, _al, _ff, skip_l, _ov, _aw, _pw, it,
             stalled, _aok, _asoft, _adirty, _fbe, _fba, _fbr) = carry
            skip_t = (
                jnp.matmul(onehot_j, skip_l.astype(f32)[:, None])[:, 0] > 0
            )
            # An attempt that resolves nothing leaves the state
            # bit-identical, so the next attempt would stall the same way:
            # exit on stall.  (Stall happens when every unresolved task's
            # feasible nodes sit beyond the top-K ranking prefix while the
            # prefix keeps live capacity claimed by earlier candidates —
            # those tasks stay Pending this cycle, the same outcome as the
            # reference's percentage-of-nodes-to-score cutoff,
            # scheduler_helper.go:43-62.)  The iteration bound is a
            # belt-and-braces guard on top.
            return jnp.any(~done & ~skip_t) & ~stalled & (it < 2 * W + 64)

        def attempt_body(carry):
            (s, cw_a, cw_p, done, alloc_l, fitf_l, skip_l, over_l,
             assigned_w, pipelined_w, it, _stalled,
             aff_ok_c, aff_soft_c, aff_dirty_a, fb_e, fb_a,
             fb_r) = carry
            skip_l0 = skip_l

            if has_overuse:
                # Queue-overuse gating at each job's first task (live q).
                gate = is_first_w & ~done
                q_tot_w = jnp.matmul(onehot_jq, s.q_alloc + s.q_pip)
                des_w = jnp.matmul(onehot_jq, queues.deserved)
                overused = ~less_equal(q_tot_w, des_w, eps, scalar_slot)
                gate_over = gate & overused & real_w
                gated = (
                    jnp.matmul(
                        onehot_j.T, gate_over.astype(f32)[:, None]
                    )[:, 0] > 0
                )
                skip_l = skip_l | gated
                over_l = over_l | gated

            skip_t = (
                jnp.matmul(onehot_j, skip_l.astype(f32)[:, None])[:, 0] > 0
            )
            cand = ~done & ~skip_t

            if two_phase:
                (feas_sl, future_idle, walk_idle, aff_ok_c,
                 aff_soft_c) = live_parts_sl(
                    s, cw_a, cw_p, aff_ok_c, aff_soft_c, aff_dirty_a
                )
                ranked, feas_k_att = rank_shortlist(s, feas_sl,
                                                    aff_soft_c)
                p_any = jnp.any(feas_sl, axis=1)
                # Shortlist exhaustion -> full-N rescore for the affected
                # profiles only (lax.cond: the [UM, N] planes are only
                # materialized when a live profile actually ran dry), so
                # binding is never lost to pruning.  Counted per reason:
                # required-affinity profiles exhaust when the live
                # domain landscape drifted from the solve-start counts
                # the shortlist was built on; everything else exhausts
                # when earlier waves claimed all S candidates.
                cand_u = (
                    jnp.matmul(
                        onehot_u.T, cand.astype(f32)[:, None]
                    )[:, 0] > 0
                )
                exhausted = cand_u & ~p_any
                if has_aff:
                    prof_req_terms = jnp.any(
                        p_t_req_aff | p_t_req_anti, axis=1
                    )
                else:
                    prof_req_terms = jnp.zeros((UM,), bool)
                need_fb = jnp.any(exhausted)
                if fb_cap:
                    # The cap counts rescore ROUNDS (one per attempt
                    # that fired); a round rescores every profile
                    # exhausting in that attempt, and the per-reason
                    # counters tally those profiles.
                    need_fb &= (s.fb_rounds + fb_r) < fb_cap

                def _fb_rescore(_):
                    # Fresh [UM, N] planes (the attempt-level affinity
                    # cache stays shortlist-shaped; the fallback
                    # recomputes — exact, just uncached).
                    aff_ok_d = jnp.ones((UM, N), bool)
                    aff_soft_d = jnp.zeros((UM, N), f32)
                    dirty = wave_live if has_aff else jnp.bool_(False)
                    p_full, _fi, _wi, _ao, soft_full = live_parts(
                        s, cw_a, cw_p, aff_ok_d, aff_soft_d, dirty
                    )
                    ranked_f = rank_nodes(s, p_full, soft_full)
                    feask_f = jnp.take_along_axis(p_full, ranked_f,
                                                  axis=1)
                    pany_f = jnp.any(p_full, axis=1)
                    mex = exhausted
                    return (
                        jnp.where(mex[:, None], ranked_f, ranked),
                        jnp.where(mex[:, None], feask_f, feas_k_att),
                        jnp.where(mex, pany_f, p_any),
                        jnp.sum(
                            (mex & ~prof_req_terms).astype(jnp.int32)
                        ),
                        jnp.sum(
                            (mex & prof_req_terms).astype(jnp.int32)
                        ),
                    )

                def _fb_skip(_):
                    return (ranked, feas_k_att, p_any, jnp.int32(0),
                            jnp.int32(0))

                ranked, feas_k_att, p_any, fbe_i, fba_i = jax.lax.cond(
                    need_fb, _fb_rescore, _fb_skip, None
                )
                fb_e = fb_e + fbe_i
                fb_a = fb_a + fba_i
                fb_r = fb_r + need_fb.astype(jnp.int32)
            else:
                (p_feasible, future_idle, walk_idle, aff_ok_c,
                 aff_soft_c) = live_parts(
                    s, cw_a, cw_p, aff_ok_c, aff_soft_c, aff_dirty_a
                )
                ranked = rank_nodes(s, p_feasible, aff_soft_c)
                p_any = jnp.any(p_feasible, axis=1)
                feas_k_att = jnp.take_along_axis(p_feasible, ranked,
                                                 axis=1)

            any_feasible = (
                jnp.matmul(onehot_u, p_any.astype(f32)[:, None])[:, 0] > 0
            )
            no_node = cand & ~any_feasible

            # Abort-in-order: a no-node task masks later tasks of its job
            # from this attempt's acceptance (allocate.go:189-193).
            same_job = jw[:, None] == jw[None, :]
            aborted = jnp.any(same_job & tril & no_node[None, :], axis=1)

            # Hoisted per-attempt constants for the sub-round loop.
            mt_k = nodes.max_tasks[ranked]
            rows_rk = jnp.matmul(onehot_u, ranked.astype(f32))  # [W, K]

            # Contention groups: profiles whose rankings share most of
            # their top nodes compete for the same capacity; rank their
            # candidates jointly so the combined demand spreads over
            # enough nodes in one pass instead of one profile per
            # sub-round.  (Profiles with disjoint rankings keep
            # per-profile ranks — joint ranking would over-spread them.)
            TOPOV = min(16, K)
            top = ranked[:, :TOPOV]  # [UM, TOPOV]
            ov = jnp.sum(
                (top[:, None, :, None] == top[None, :, None, :]),
                axis=(-1, -2),
            )  # [UM, UM] shared-top-node counts
            grp = ov >= (TOPOV + 1) // 2
            grp_pair = (
                jnp.matmul(
                    jnp.matmul(onehot_u, grp.astype(f32)), onehot_u.T
                ) > 0
            )  # [W, W] same-contention-group mask
            if has_aff:
                # Only REQUIRED terms gate pair-wise conflicts: soft
                # (preferred/spread) terms influence scores, never
                # feasibility, so same-domain soft interactions place in
                # one pass with attempt-start scores.
                p_involved = p_t_req_aff | p_t_req_anti
                # Per-task activity masks for the sub-round lax.cond
                # gates: the [EW*D] scatter-min / count scatters only
                # matter while a candidate carries required terms (filter)
                # or an accepted task matches any windowed term (counts).
                involved_any_t = jnp.any(p_involved[pid_l], axis=1)  # [W]
                matches_any_t = jnp.any(t_matches_w, axis=1)  # [W]

            # ---- sub-rounds: rejected tasks re-walk against live capacity
            # within the attempt, reusing this attempt's feasibility and
            # ranking.  Capacity counts (c) and the fit checks always read
            # the LIVE state, so acceptance stays exact; only the node
            # *steering* uses attempt-start scores (the steering is already
            # a documented heuristic).  This collapses the cross-profile
            # conflict retries that previously cost one full attempt
            # (predicates + scoring + ranking) each.  Tasks with inter-pod
            # affinity terms only resolve in the first sub-round: their
            # feasibility depends on count state that live_parts refreshes
            # per attempt.
            def sub_cond(sc):
                (_s, _cwa, _cwp, _fk, _dirty, done_sub, _al, _aw, _pw, si,
                 progressed, _cch) = sc
                return progressed & (si < SUBROUNDS) & jnp.any(
                    cand & ~done_sub & ~aborted
                )

            def sub_body(sc):
                (s_, cw_a_, cw_p_, feas_k_c, aff_dirty, done_sub, alloc_l_,
                 assigned_w_, pipelined_w_, si, _progressed,
                 cnt_changed) = sc
                cand_s = cand & ~done_sub & ~aborted

                if has_aff:
                    # Live affinity steering: after an affinity-relevant
                    # acceptance, recompute the profile-level required-
                    # (anti)affinity feasibility against the sub-round
                    # count window, so once a sibling claims a domain the
                    # rest of the gang walks only nodes of that domain
                    # instead of re-discovering it one attempt at a time.
                    # Gated on a dirty flag: waves without affinity
                    # activity skip the [N, EW] work entirely.
                    def steer(_):
                        cnt_live_n = cw_a_ + cw_p_  # [EW, D]
                        total_live_n = jnp.sum(cnt_live_n, axis=-1)
                        selfok_p = (
                            (total_live_n == 0)[None, :] & p_t_matches
                        )  # [UM, EW]
                        # bf16 indicator matmuls: see _aff_parts.
                        bf_ = jnp.bfloat16
                        need_l = (p_t_req_aff & ~selfok_p).astype(bf_)
                        if two_phase:
                            # Steer directly at the ranked candidates:
                            # [UM, K, EW] window instead of [UM, N].
                            dw_r = node_dom_t[ranked]  # [UM, K, EW]
                            cval_r = cnt_live_n[
                                term_arange[None, None, :],
                                jnp.maximum(dw_r, 0),
                            ]
                            cval_r = jnp.where(dw_r >= 0, cval_r, 0)
                            aff_viol_l = jnp.einsum(
                                "ue,uke->uk", need_l,
                                (cval_r == 0).astype(bf_),
                            )
                            anti_viol_l = jnp.einsum(
                                "ue,uke->uk", p_t_req_anti.astype(bf_),
                                (cval_r > 0).astype(bf_),
                            )
                            return feas_k_att & (aff_viol_l < 0.5) & (
                                anti_viol_l < 0.5
                            )
                        cval_live = cnt_live_n[
                            term_arange[None, :], jnp.maximum(node_dom_t, 0)
                        ]
                        cval_live = jnp.where(node_dom_t >= 0, cval_live, 0)
                        aff_viol_l = jnp.matmul(
                            need_l, (cval_live == 0).astype(bf_).T
                        )
                        anti_viol_l = jnp.matmul(
                            p_t_req_anti.astype(bf_),
                            (cval_live > 0).astype(bf_).T,
                        )
                        p_feas_sub = p_feasible & (aff_viol_l < 0.5) & (
                            anti_viol_l < 0.5
                        )
                        return jnp.take_along_axis(
                            p_feas_sub, ranked, axis=1
                        )

                    if AFF_STEER:
                        feas_k = jax.lax.cond(
                            aff_dirty, steer, lambda _: feas_k_c, None
                        )
                    else:
                        feas_k = feas_k_c
                else:
                    feas_k = feas_k_c

                # Live capacity walk (copies of the profile per ranked node).
                if has_future:
                    walk_idle_ = (
                        s_.idle + nodes.releasing - nodes.pipelined
                        - s_.pip_extra
                    )
                else:
                    walk_idle_ = s_.idle
                walk_k = walk_idle_[ranked]  # [UM, K, R] small gather
                per = jnp.where(
                    p_req_pos[:, None, :],
                    walk_k / jnp.maximum(p_req[:, None, :], 1e-9),
                    jnp.inf,
                )
                c_res = jnp.clip(jnp.min(per, axis=-1), 0.0, BIG)
                nt_k = (s_.ntasks + s_.pip_ntasks)[ranked]
                c_pods = jnp.where(
                    mt_k > 0, (mt_k - nt_k).astype(f32), BIG
                )
                c = jnp.where(
                    feas_k, jnp.minimum(jnp.floor(c_res), c_pods), 0.0
                )
                if has_aff:
                    # A profile that anti-affines against its own labels
                    # holds at most one copy per domain; cap the walk at
                    # one per node so siblings spread instead of stacking
                    # on one node and serializing through reject/retry.
                    self_anti = jnp.any(p_t_req_anti & p_t_matches, axis=1)
                    c = jnp.where(self_anti[:, None], jnp.minimum(c, 1.0), c)
                cumcap = jnp.cumsum(c, axis=1)  # [UM, K]

                # m = my rank among the remaining candidates of my
                # contention group (>= my profile's own candidates).
                m = jnp.sum(
                    grp_pair & tril & cand_s[None, :], axis=1
                ).astype(f32)
                rows_cc = jnp.matmul(onehot_u, cumcap)  # [W, K]
                j = jnp.sum(
                    (rows_cc <= m[:, None]).astype(jnp.int32), axis=1
                )
                overflow = cand_s & any_feasible & (j >= K)
                j = jnp.clip(j, 0, K - 1)
                j1h = (j[:, None] == jnp.arange(K)[None, :]).astype(f32)
                choice = jnp.round(jnp.sum(rows_rk * j1h, axis=1)).astype(
                    jnp.int32
                )
                choice = jnp.clip(choice, 0, N - 1)
                live = cand_s & any_feasible & ~overflow

                # ---- prefix acceptance in task order -----------------------
                same_node = (choice[:, None] == choice[None, :]) & tril
                pre = (same_node & live[None, :]).astype(f32)
                cum_req = jnp.matmul(pre, req_w)  # [W, R]
                cum_cnt = jnp.sum(pre, axis=1).astype(jnp.int32)

                # One fused node gather for every per-choice read.
                cols = [
                    s_.idle,
                    (s_.ntasks + s_.pip_ntasks)[:, None].astype(f32),
                    nodes.max_tasks[:, None].astype(f32),
                ]
                if has_future:
                    cols.append(
                        s_.idle + nodes.releasing - nodes.pipelined
                        - s_.pip_extra
                    )
                g = jnp.concatenate(cols, axis=1)[choice]  # [W, C]
                idle_c = g[:, :R]
                ntasks_c = jnp.round(g[:, R]).astype(jnp.int32)
                maxt_c = jnp.round(g[:, R + 1]).astype(jnp.int32)

                fits_idle = less_equal(
                    init_req_w + cum_req, idle_c, eps, scalar_slot
                )
                tot_c = ntasks_c + cum_cnt
                pods_fit = (maxt_c <= 0) | (tot_c < maxt_c)
                clean = live & pods_fit
                if has_ports:
                    # Pair clash within this sub-round + live clash against
                    # everything already applied to the state.
                    pair_port = jnp.matmul(
                        ports_w.astype(f32), ports_w.astype(f32).T
                    )
                    port_conf = jnp.any(
                        same_node & live[None, :] & (pair_port > 0), axis=1
                    )
                    used_bits_c = (
                        s_.nport_bits | s_.pip_nport_bits
                    )[choice]  # [W, B]
                    port_live = jnp.any(ports_w & used_bits_c, axis=1)
                    clean &= ~port_conf & ~port_live
                if has_aff:
                    # Shared row-compaction machinery (TPU scatters and
                    # gathers serialize per element, so update count is
                    # the cost; the participants are few).
                    jidx_w = jnp.arange(W, dtype=jnp.int32)
                    GCAP = min(256, W)

                    def _earliest_rows(mask):
                        """Indices of the earliest <=GCAP rows in
                        ``mask`` (+ validity): top_k on the
                        descending-index score picks the smallest
                        indices first."""
                        score = jnp.where(mask, W - jidx_w, 0)
                        sc, idx_ = jax.lax.top_k(score, GCAP)
                        return idx_, sc > 0

                    # Live per-task recheck + pair-conflict filter, both
                    # lax.cond-skipped for waves with no real terms (the
                    # scatter-min runs over EW*D keys — millions of
                    # entries at hyperscale).
                    def _aff_filter(op):
                        clean_in, cwa, cwp = op
                        # A sibling placed in an earlier sub-round already
                        # satisfies (or violates) required terms here, so
                        # involved tasks resolve within the attempt
                        # instead of one per attempt.
                        dw = node_dom_t[choice]  # [W, EW]
                        cnt_live = cwa + cwp  # [EW, D]
                        total_live = jnp.sum(cnt_live, axis=-1)  # [EW]
                        if dom_mm:
                            # Row-gather the choice nodes' membership
                            # (contiguous [W, D] rows), then one small
                            # MXU pass — 8x fewer FLOPs than
                            # multiplying against all N columns.
                            cval_t = jax.lax.dot_general(
                                cnt_live.astype(f32), dom_ohT[choice],
                                (((1,), (1,)), ((), ())),
                            ).T  # [W, EW]
                        else:
                            cval_t = cnt_live[
                                term_arange[None, :], jnp.maximum(dw, 0)
                            ]
                            cval_t = jnp.where(dw >= 0, cval_t, 0)
                        req_aff_t = p_t_req_aff[pid_l]  # [W, EW]
                        selfok_t = (total_live == 0)[None, :] & t_matches_w
                        aff_ok = ~jnp.any(
                            req_aff_t & ~selfok_t & (cval_t == 0), axis=1
                        )
                        anti_ok = ~jnp.any(
                            p_t_req_anti[pid_l] & (cval_t > 0), axis=1
                        )
                        out = clean_in & aff_ok & anti_ok
                        # Same-domain interaction with earlier tasks of
                        # THIS sub-round: only ANTI terms serialize (an
                        # earlier giver in my domain would violate my
                        # anti constraint once committed).  Required
                        # AFFINITY siblings landing in the same domain
                        # are mutually consistent — the earlier giver
                        # satisfies the later one, exactly what the
                        # sequential walk would produce — so they place
                        # in one pass.  A task relying on the self-match
                        # rule conflicts only with an earlier giver in a
                        # DIFFERENT domain (two "firsts" splitting the
                        # gang); an earlier same-domain giver makes its
                        # placement consistent.
                        anti_inv = (
                            p_t_req_anti[pid_l] & (dw >= 0)
                        )  # [W, EW]
                        gives = t_matches_w & (dw >= 0)
                        uses_selfok = (
                            req_aff_t & selfok_t & (cval_t == 0)
                        )  # [W, EW]
                        # Pair conflicts via scatter-min over (term,
                        # domain) keys instead of an O(W^2 * EW) pair
                        # tensor: the minimum live-giver index per key
                        # identifies the earliest giver in each domain;
                        # its per-term min (gt) the earliest giver in any
                        # domain.
                        jidx = jidx_w
                        # Only REQUIRED terms' givers feed the conflict
                        # reads (anti_inv / uses_selfok mask every
                        # consumer), so soft-only spread terms drop out
                        # of the scatter key space — exact.
                        gmask = (gives & live[:, None]
                                 & term_req_w[None, :])  # [W, EW]
                        grow = jnp.any(gmask, axis=1)  # [W]

                        # TPU scatters serialize per update: the full
                        # [W, EW] key scatter costs ~2 ms/sub-round at
                        # the north-star shape.  Giver rows are few, so
                        # compact to the earliest <=GCAP of them (min
                        # over a superset of rows with no giver entries
                        # is unchanged); overflow falls back exactly.
                        # Two address forms, identical values: the
                        # flattened [EW * D + 1] buffer (scratch slot
                        # EW * D for masked entries) while the key
                        # space fits int32, the 2-D [EW, D + 1] buffer
                        # (scratch COLUMN D) past it — the scale-tier
                        # int32 audit.
                        if flat_keys_ok:
                            keyv = (
                                term_arange[None, :] * D
                                + jnp.maximum(dw, 0)
                            )
                            scratch = EW * D

                            def _gm_full(_):
                                keys_g = jnp.where(gmask, keyv, scratch)
                                return (
                                    jnp.full((EW * D + 1,), W, jnp.int32)
                                    .at[keys_g.reshape(-1)]
                                    .min(jnp.broadcast_to(
                                        jidx[:, None], (W, EW)
                                    ).reshape(-1))
                                )

                            def _gm_compact(_):
                                gidx, gvalid = _earliest_rows(grow)
                                keys_c = jnp.where(
                                    gmask[gidx] & gvalid[:, None],
                                    keyv[gidx], scratch,
                                )
                                return (
                                    jnp.full((EW * D + 1,), W, jnp.int32)
                                    .at[keys_c.reshape(-1)]
                                    .min(jnp.broadcast_to(
                                        jidx[gidx][:, None], (GCAP, EW)
                                    ).reshape(-1))
                                )

                            def _gm_at(dwv):
                                kv = (
                                    term_arange[None, :] * D
                                    + jnp.maximum(dwv, 0)
                                )
                                return gm[kv]
                        else:
                            def _gm_full(_):
                                cols = jnp.where(
                                    gmask, jnp.maximum(dw, 0), D
                                )
                                return (
                                    jnp.full((EW, D + 1), W, jnp.int32)
                                    .at[jnp.broadcast_to(
                                        term_arange[None, :], (W, EW)
                                    ), cols]
                                    .min(jnp.broadcast_to(
                                        jidx[:, None], (W, EW)
                                    ))
                                )

                            def _gm_compact(_):
                                gidx, gvalid = _earliest_rows(grow)
                                cols = jnp.where(
                                    gmask[gidx] & gvalid[:, None],
                                    jnp.maximum(dw[gidx], 0), D,
                                )
                                return (
                                    jnp.full((EW, D + 1), W, jnp.int32)
                                    .at[jnp.broadcast_to(
                                        term_arange[None, :], (GCAP, EW)
                                    ), cols]
                                    .min(jnp.broadcast_to(
                                        jidx[gidx][:, None], (GCAP, EW)
                                    ))
                                )

                            def _gm_at(dwv):
                                return gm[term_arange[None, :],
                                          jnp.maximum(dwv, 0)]

                        gm = jax.lax.cond(
                            jnp.sum(grow) > GCAP, _gm_full, _gm_compact,
                            None,
                        )
                        # Earliest giver of each term in ANY domain:
                        # directly from the giver rows — identical to
                        # min-reducing gm over the [EW, D] key space,
                        # without touching the 1.28M-entry buffer.
                        jb = jnp.broadcast_to(jidx[:, None], (W, EW))
                        gt = jnp.min(jnp.where(gmask, jb, W), axis=0)

                        # Conflict reads compacted the same way: only
                        # rows carrying anti/selfok terms consult gm,
                        # so gather gm at <=GCAP involved rows instead
                        # of the full [W, EW] element gather.
                        # live-masked (like gmask): conflict is only
                        # consumed as `out & ~conflict` and out is
                        # already false for non-live rows, so dead
                        # involved rows must not inflate the count past
                        # the compaction cap.
                        inv_rows = live & jnp.any(
                            anti_inv | uses_selfok, axis=1
                        )  # [W]

                        def _conf_full(_):
                            gm_my = _gm_at(dw)  # [W, EW]
                            c_anti = jnp.any(
                                anti_inv & (gm_my < jidx[:, None]),
                                axis=1,
                            )
                            gm_my_self = jnp.where(dw >= 0, gm_my, W)
                            c_self = jnp.any(
                                uses_selfok
                                & (gt[None, :] < jidx[:, None])
                                & (gm_my_self > gt[None, :]), axis=1,
                            )
                            return c_anti | c_self

                        def _conf_compact(_):
                            ci, cvalid = _earliest_rows(inv_rows)
                            gm_my_c = _gm_at(dw[ci])  # [GCAP, EW]
                            ji_c = jidx[ci]
                            c_anti = jnp.any(
                                anti_inv[ci]
                                & (gm_my_c < ji_c[:, None]), axis=1,
                            )
                            gm_self_c = jnp.where(dw[ci] >= 0, gm_my_c,
                                                  W)
                            c_self = jnp.any(
                                uses_selfok[ci]
                                & (gt[None, :] < ji_c[:, None])
                                & (gm_self_c > gt[None, :]), axis=1,
                            )
                            return (
                                jnp.zeros((W,), bool)
                                .at[ci]
                                .set((c_anti | c_self) & cvalid)
                            )

                        # Domain-less nodes (dw < 0) have no "my
                        # domain": a selfok user there conflicts with
                        # ANY earlier giver (the committed count kills
                        # its selfok on the next attempt, as the
                        # sequential walk would) — gm_my_self = W keeps
                        # that rule in both branches.
                        conflict = jax.lax.cond(
                            jnp.sum(inv_rows) > GCAP,
                            _conf_full, _conf_compact, None,
                        )
                        return out & ~conflict

                    # The filter only modifies bits of tasks that carry
                    # required terms: with none of them in `clean` it is
                    # the identity, so the gate checks CLEAN (tasks
                    # actually placing this sub-round), not candidacy —
                    # unresolved affinity stragglers stop re-running the
                    # scatter-min machinery every sub-round.
                    clean = jax.lax.cond(
                        wave_live & jnp.any(clean & involved_any_t),
                        _aff_filter, lambda op: op[0],
                        (clean, cw_a_, cw_p_),
                    )

                acc_alloc = clean & fits_idle
                if has_future:
                    fut_c = g[:, R + 2:2 * R + 2]
                    fits_fut = less_equal(
                        init_req_w + cum_req, fut_c, eps, scalar_slot
                    )
                    acc_pipe = clean & ~fits_idle & fits_fut
                else:
                    acc_pipe = jnp.zeros_like(acc_alloc)

                # ---- apply --------------------------------------------------
                radd = req_w * acc_alloc[:, None]
                s_ = s_._replace(
                    idle=s_.idle.at[choice].add(-radd),
                    ntasks=s_.ntasks.at[choice].add(
                        acc_alloc.astype(jnp.int32)
                    ),
                    q_alloc=s_.q_alloc + jnp.matmul(onehot_jq.T, radd),
                )
                if has_future:
                    padd = req_w * acc_pipe[:, None]
                    s_ = s_._replace(
                        pip_extra=s_.pip_extra.at[choice].add(padd),
                        pip_ntasks=s_.pip_ntasks.at[choice].add(
                            acc_pipe.astype(jnp.int32)
                        ),
                        q_pip=s_.q_pip + jnp.matmul(onehot_jq.T, padd),
                    )
                if has_ports:
                    s_ = s_._replace(
                        nport_bits=s_.nport_bits.at[choice].max(
                            ports_w & acc_alloc[:, None]
                        )
                    )
                    if has_future:
                        s_ = s_._replace(
                            pip_nport_bits=s_.pip_nport_bits.at[choice].max(
                                ports_w & acc_pipe[:, None]
                            )
                        )
                if has_aff:
                    # Window-local count update: the wave only touches its
                    # own term rows, so updates stay on the [EW, D] window
                    # carried through the loops; the global state is
                    # written back once per wave.  lax.cond-skipped for
                    # waves with no real terms (nothing to count).
                    def _cnt_update(op):
                        cwa, cwp = op
                        dw = node_dom_t[choice]  # [W, EW]
                        inc_base = t_matches_w & (dw >= 0)

                        # Count-scatter address forms (the scale-tier
                        # int32 audit, see _gm_full): flattened keys
                        # while EW * D fits int32, 2-D (term, domain)
                        # indices past it.  Masked rows carry value 0
                        # and land on domain 0 — a no-op either way.
                        if flat_keys_ok:
                            def _cnt_add(cw, dwv, vals):
                                fd = (
                                    term_arange[None, :] * D
                                    + jnp.maximum(dwv, 0)
                                )
                                return (
                                    cw.reshape(-1)
                                    .at[fd.reshape(-1)]
                                    .add(vals.reshape(-1))
                                    .reshape(EW, D)
                                )
                        else:
                            def _cnt_add(cw, dwv, vals):
                                rows = vals.shape[0]
                                return cw.at[
                                    jnp.broadcast_to(
                                        term_arange[None, :], (rows, EW)
                                    ),
                                    jnp.maximum(dwv, 0),
                                ].add(vals)

                        def cnt_apply(cw, acc):
                            # Accepted matching tasks are few per
                            # sub-round: scatter-add from the earliest
                            # <=GCAP of them (value-0 masking for the
                            # padding) instead of all W x EW keys —
                            # exact, with the full scatter as overflow
                            # fallback.
                            rows_m = jnp.any(inc_base, axis=1) & acc

                            def _full(_):
                                return _cnt_add(
                                    cw, dw,
                                    (inc_base & acc[:, None])
                                    .astype(jnp.int32),
                                )

                            def _compact(_):
                                ci, cval = _earliest_rows(rows_m)
                                vals = (
                                    inc_base[ci]
                                    & acc[ci][:, None]
                                    & cval[:, None]
                                ).astype(jnp.int32)
                                return _cnt_add(cw, dw[ci], vals)

                            return jax.lax.cond(
                                jnp.sum(rows_m) > GCAP, _full, _compact,
                                None,
                            )

                        cwa = cnt_apply(cwa, acc_alloc)
                        if has_future:
                            cwp = cnt_apply(cwp, acc_pipe)
                        return cwa, cwp

                    did_cnt = wave_live & jnp.any(
                        (acc_alloc | acc_pipe) & matches_any_t
                    )
                    cw_a_, cw_p_ = jax.lax.cond(
                        did_cnt, _cnt_update, lambda op: op,
                        (cw_a_, cw_p_),
                    )
                    cnt_changed = cnt_changed | did_cnt

                alloc_l_ = alloc_l_ + jnp.round(
                    jnp.matmul(
                        onehot_j.T, acc_alloc.astype(f32)[:, None]
                    )[:, 0]
                ).astype(jnp.int32)
                assigned_w_ = jnp.where(acc_alloc, choice, assigned_w_)
                pipelined_w_ = jnp.where(acc_pipe, choice, pipelined_w_)
                resolved = acc_alloc | acc_pipe
                if has_aff:
                    giver_rel = jnp.any(
                        t_matches_w & term_req_w[None, :], axis=1
                    )
                    dirty_next = jnp.any(
                        resolved & (involved_any_t | giver_rel)
                    )
                else:
                    dirty_next = jnp.bool_(False)
                return (
                    s_, cw_a_, cw_p_, feas_k, dirty_next,
                    done_sub | resolved, alloc_l_,
                    assigned_w_, pipelined_w_, si + 1, jnp.any(resolved),
                    cnt_changed,
                )

            (s, cw_a, cw_p, _fk, _dirty, done_sub, alloc_l, assigned_w,
             pipelined_w, subs, _prog, cnt_changed_out) = (
                jax.lax.while_loop(
                    sub_cond, sub_body,
                    (s, cw_a, cw_p, feas_k_att, jnp.bool_(False), done,
                     alloc_l, assigned_w, pipelined_w, jnp.int32(0),
                     jnp.bool_(True), jnp.bool_(False)),
                )
            )

            # Attempt-level job bookkeeping for fit failures.
            fit_upd = (
                jnp.matmul(
                    onehot_j.T, no_node.astype(f32)[:, None]
                )[:, 0] > 0
            )
            fitf_l = fitf_l | fit_upd
            skip_l = skip_l | fit_upd

            new_done = done_sub | no_node
            stalled = ~jnp.any(new_done & ~done) & jnp.all(
                skip_l == skip_l0
            )
            done = done | new_done

            return (
                s, cw_a, cw_p, done, alloc_l, fitf_l, skip_l, over_l,
                assigned_w, pipelined_w, it + jnp.maximum(subs, 1), stalled,
                aff_ok_c, aff_soft_c, cnt_changed_out, fb_e, fb_a, fb_r,
            )

        # Per-wave count windows (the wave only touches its own term rows).
        if has_aff:
            if terms_disjoint:
                cw_a0 = cnt0_i32[wterms]
                cw_p0 = jnp.zeros_like(cw_a0)
            else:
                cw_a0 = state.cnt_alloc[wterms]
                cw_p0 = state.cnt_pip[wterms]
            # Affinity attempt-cache init: all-feasible/zero-score with
            # the dirty flag at wave_live, so live waves compute on the
            # first attempt and term-free waves never do.  Two-phase
            # carries the cache at shortlist width.
            aff_ok0 = jnp.ones((UM, S if two_phase else N), bool)
            aff_soft0 = jnp.zeros((UM, S if two_phase else N), f32)
            aff_dirty0 = wave_live
        else:
            cw_a0 = jnp.zeros((1, 1), jnp.int32)
            cw_p0 = jnp.zeros((1, 1), jnp.int32)
            aff_ok0 = jnp.ones((1, 1), bool)
            aff_soft0 = jnp.zeros((1, 1), f32)
            aff_dirty0 = jnp.bool_(False)

        init = (
            state,
            cw_a0,
            cw_p0,
            done0,
            jsl(state.alloc_cnt),
            jsl(state.fit_failed),
            jsl(state.job_skip),
            jsl(state.job_overskip),
            jnp.full((W,), -1, jnp.int32),
            jnp.full((W,), -1, jnp.int32),
            jnp.int32(0),
            jnp.bool_(False),
            aff_ok0,
            aff_soft0,
            aff_dirty0,
            jnp.int32(0),
            jnp.int32(0),
            jnp.int32(0),
        )
        (s, cw_a, cw_p, _done, alloc_l, fitf_l, skip_l, over_l, assigned_w,
         pipelined_w, _it, _stalled, _aok, _asoft, _adirty, _fbe, _fba,
         _fbr) = (
            jax.lax.while_loop(attempt_cond, attempt_body, init)
        )
        if has_aff and not terms_disjoint:
            # Real rows are unique in wterms; duplicate writes only hit
            # the dummy scratch row.  With wave-disjoint term sets (the
            # static flag) no later wave reads these counts and the
            # write-back — a full [E, D]-table rewrite per wave under
            # XLA's scatter lowering — is skipped.
            s = s._replace(
                cnt_alloc=s.cnt_alloc.at[wterms].set(cw_a),
                cnt_pip=s.cnt_pip.at[wterms].set(cw_p),
            )

        jupd_back = lambda g, l: jax.lax.dynamic_update_slice_in_dim(
            g, l, jlo, axis=0
        )
        s = s._replace(
            iters=s.iters + _it,
            fb_exhausted=s.fb_exhausted + _fbe,
            fb_affinity=s.fb_affinity + _fba,
            fb_rounds=s.fb_rounds + _fbr,
        )
        return s._replace(
            alloc_cnt=jupd_back(s.alloc_cnt, alloc_l),
            fit_failed=jupd_back(s.fit_failed, fitf_l),
            job_skip=jupd_back(s.job_skip, skip_l),
            job_overskip=jupd_back(s.job_overskip, over_l),
            assigned=jax.lax.dynamic_update_slice_in_dim(
                s.assigned, assigned_w, off, axis=0
            ),
            pipelined=jax.lax.dynamic_update_slice_in_dim(
                s.pipelined, pipelined_w, off, axis=0
            ),
        )

    state = jax.lax.fori_loop(0, NW, run_wave, state)

    # ---- gang commit/discard, vectorized (stmt.Discard) --------------------
    min_av_p = jnp.pad(jobs.min_available, (0, W), constant_values=1 << 30)
    ready_base_p = jnp.pad(jobs.ready_base, (0, W))
    job_ready = ready_base_p + state.alloc_cnt >= min_av_p
    never_ready_p = job_seen & ~state.job_overskip & ~job_ready  # [JP]
    discard_t = never_ready_p[tjob] & tasks.real & (state.assigned >= 0)
    n_c = jnp.maximum(state.assigned, 0)
    rsub = jnp.take(prof.req, pid, axis=0) * discard_t[:, None]
    idle = state.idle.at[n_c].add(rsub)
    q_alloc = state.q_alloc.at[queue_p[tjob]].add(-rsub)
    assigned = jnp.where(discard_t, -1, state.assigned)

    pipelined = state.pipelined
    if N <= 32000:
        # Narrow the [P] result vectors on device: the device->host fetch
        # of `assigned` dominates transfer time at north-star scale
        # (100k x 4B through a ~3.5 MB/s tunnel), and node indices fit
        # int16 whenever N does.  Hosts consume them as indices, where
        # numpy upcasts transparently.
        assigned = assigned.astype(jnp.int16)
        pipelined = pipelined.astype(jnp.int16)
    return AllocResult(
        assigned=assigned,
        pipelined=pipelined,
        never_ready=never_ready_p[:J],
        fit_failed=state.fit_failed[:J],
        idle=idle,
        q_alloc=q_alloc + state.q_pip,
        iters=state.iters,
        fb_exhausted=state.fb_exhausted,
        fb_affinity=state.fb_affinity,
    )


@partial(jax.jit, static_argnames=("e", "d"))
def _scatter_cnt0(rows, cols, vals, e, d):
    return jnp.zeros((e, d), jnp.int32).at[rows, cols].add(vals)


@partial(jax.jit, static_argnames=("u", "e"))
def _scatter_profile_tables(rows, cols, flags, soft, u, e):
    """Rebuild the dense [U, E] profile-term tables from their sparse
    entries on device (see solve_wave: shipping ~tens of MB of mostly-
    zero bool/f32 tables through a remote-TPU tunnel costs seconds;
    the entries are tiny).  Padded entries carry flags/soft of 0 at
    (0, 0) — add is a no-op there; real (u, e) pairs are unique."""
    zb = jnp.zeros((u, e), jnp.int8)
    aff = zb.at[rows, cols].add(flags & 1) > 0
    anti = zb.at[rows, cols].add((flags >> 1) & 1) > 0
    match = zb.at[rows, cols].add((flags >> 2) & 1) > 0
    soft_t = jnp.zeros((u, e), jnp.float32).at[rows, cols].add(soft)
    return aff, anti, match, soft_t


def _np(a):
    # ascontiguousarray: no-op for the usual numpy inputs; jax arrays
    # fetched from a sharded placement can materialize non-contiguous,
    # which breaks the profile-hash .view(uint8) reinterpret.
    return np.ascontiguousarray(a)


_HASH_SEED = np.random.RandomState(0x5EED)


def _profile_tasks(tasks: SolveTasks, aff: AffinityArgs, extra_ok=None,
                   extra_score=None):
    """Group tasks into distinct profiles (host, numpy).

    Returns (profiles, pid[P]) where profiles hold one row per distinct
    combination of every per-task solver input except job identity, and
    pid is ordered by first occurrence (so job-contiguous task order keeps
    per-wave profile ranges narrow).

    Grouping hashes each row with a random linear map and verifies the
    result exactly (every row compared against its representative); on the
    astronomically unlikely hash collision it falls back to exact grouping.
    """
    P = tasks.req.shape[0]
    cols = [
        _np(tasks.req).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(tasks.init_req).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(tasks.ports).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(tasks.sel_bits).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(tasks.aff_bits).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(tasks.aff_terms).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(tasks.tol_bits).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(tasks.pref_bits).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(tasks.pref_w).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(aff.t_req_aff).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(aff.t_req_anti).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(aff.t_matches).reshape(P, -1).view(np.uint8).reshape(P, -1),
        _np(aff.t_soft).reshape(P, -1).view(np.uint8).reshape(P, -1),
    ]
    if extra_ok is not None:
        # Custom per-task node masks split profiles: tasks of one profile
        # must share a mask row (the kernel applies it per profile).
        cols.append(np.packbits(_np(extra_ok), axis=1))
    if extra_score is not None:
        cols.append(
            _np(extra_score).astype(np.float32)
            .reshape(P, -1).view(np.uint8).reshape(P, -1)
        )
    raw = np.concatenate(cols, axis=1)  # [P, C] uint8
    # Three independent linear hashes with small coefficients: every dot
    # product stays below 2^33, so the float64 BLAS matmul is exact and two
    # distinct rows collide in one column with probability ~2^-20 (the
    # coefficients are random); across three columns ~2^-60 per pair.
    rnd = _HASH_SEED.randint(1, 1 << 20, size=(raw.shape[1], 3))
    h = (raw.astype(np.float64) @ rnd.astype(np.float64)).astype(np.int64)
    p1 = np.uint64(0x9E3779B97F4A7C15).astype(np.int64)
    p2 = np.uint64(0xC2B2AE3D27D4EB4F).astype(np.int64)
    with np.errstate(over="ignore"):
        hv = h[:, 0] + h[:, 1] * p1 + h[:, 2] * p2
    _, first_idx, inv = np.unique(
        hv, return_index=True, return_inverse=True
    )
    # Renumber profiles by first occurrence so pid follows task order.
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order))
    pid = rank[inv].astype(np.int32)
    u = first_idx[order]

    if not np.array_equal(raw, raw[u][pid]):  # hash collision: exact path
        key = np.ascontiguousarray(raw)
        _, first_idx, inv = np.unique(
            key.view([("", np.uint8)] * key.shape[1]).ravel(),
            return_index=True,
            return_inverse=True,
        )
        order = np.argsort(first_idx, kind="stable")
        rank = np.empty(len(order), np.int64)
        rank[order] = np.arange(len(order))
        pid = rank[inv].astype(np.int32)
        u = first_idx[order]

    profiles = SolveProfiles(
        req=_np(tasks.req)[u],
        init_req=_np(tasks.init_req)[u],
        ports=_np(tasks.ports)[u],
        sel_bits=_np(tasks.sel_bits)[u],
        aff_bits=_np(tasks.aff_bits)[u],
        aff_terms=_np(tasks.aff_terms)[u],
        tol_bits=_np(tasks.tol_bits)[u],
        pref_bits=_np(tasks.pref_bits)[u],
        pref_w=_np(tasks.pref_w)[u],
        t_req_aff=_np(aff.t_req_aff)[u],
        t_req_anti=_np(aff.t_req_anti)[u],
        t_matches=_np(aff.t_matches)[u],
        t_soft=_np(aff.t_soft)[u],
    )
    extra_prof = _np(extra_ok)[u] if extra_ok is not None else None
    score_prof = (
        _np(extra_score).astype(np.float32)[u]
        if extra_score is not None else None
    )
    return profiles, pid, extra_prof, score_prof


def _renumber_pid(pid: np.ndarray):
    """Renumber profile ids by first occurrence; return (pid2, u_rows) where
    u_rows[k] is the first task row of profile k."""
    _, first_idx, inv = np.unique(pid, return_index=True, return_inverse=True)
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order))
    return rank[inv].astype(np.int32), first_idx[order]


def _profiles_from_pid(tasks: SolveTasks, aff: AffinityArgs,
                       pid: np.ndarray):
    """Build SolveProfiles from caller-supplied profile ids (the store
    mirror interns them at pod-add time, so no per-cycle hashing)."""
    pid, u = _renumber_pid(pid)
    profiles = SolveProfiles(
        req=_np(tasks.req)[u],
        init_req=_np(tasks.init_req)[u],
        ports=_np(tasks.ports)[u],
        sel_bits=_np(tasks.sel_bits)[u],
        aff_bits=_np(tasks.aff_bits)[u],
        aff_terms=_np(tasks.aff_terms)[u],
        tol_bits=_np(tasks.tol_bits)[u],
        pref_bits=_np(tasks.pref_bits)[u],
        pref_w=_np(tasks.pref_w)[u],
        t_req_aff=_np(aff.t_req_aff)[u],
        t_req_anti=_np(aff.t_req_anti)[u],
        t_matches=_np(aff.t_matches)[u],
        t_soft=_np(aff.t_soft)[u],
    )
    return profiles, pid


def bucket_pow2(n: int, floor: int, min_pad: int = 8) -> int:
    """Anti-recompile shape bucket: next power of two >= n plus 25%
    headroom (raw counts clustering at a power of two must not flip
    buckets cycle-to-cycle — each flip is a multi-second XLA recompile).
    ``floor`` bounds the smallest bucket per axis."""
    target = n + max(n // 4, min_pad)
    b = max(floor, 1)
    while b < target:
        b *= 2
    return b


def _pad_profiles_rows(profiles: SolveProfiles) -> SolveProfiles:
    """Pad the profile table's row axis to a power of two (min 64) with
    inert zero rows.  The row count is data-dependent (distinct task
    profiles this cycle); unpadded it changes shape almost every cycle
    and forces an XLA recompile of the wave solver — ~7s per new shape,
    dwarfing the solve itself.  Padded rows are never referenced: pid and
    wave_prof only index real rows."""
    U = int(_np(profiles.req).shape[0])
    pad = bucket_pow2(U, floor=64) - U
    if pad == 0:
        return profiles
    def z(a):
        a = _np(a)
        return np.concatenate(
            [a, np.zeros((pad, *a.shape[1:]), a.dtype)]
        )

    return SolveProfiles(*[z(a) for a in profiles])


def _term_windows(profiles: SolveProfiles, aff: AffinityArgs,
                  pid: np.ndarray, wave_prof: np.ndarray, n_waves: int,
                  skip_cnt0: bool = False, skip_prof: bool = False):
    """Per-wave lists of the affinity terms the wave's profiles reference.

    Every [*, E] tensor in the kernel is gathered down to the wave's term
    list, bounding the affinity machinery by terms-per-wave instead of
    total terms.  One dummy scratch row is appended to the term axis and
    used as list padding, so the windowed count write-back scatters to
    unique real rows (duplicates only hit the dummy).
    Returns (profiles, aff, wave_terms [NW, EW], EW, iom) — iom being
    the [U, E] nonzero union of the four profile-term tables (pre-dummy
    columns; the sparse-shipping path reuses it).  ``skip_prof``: leave
    the profile tables without the dummy column (the caller rebuilds
    them on device at the dummy-extended width — skips four ~dense host
    copies).
    """
    t_req_aff = _np(profiles.t_req_aff)
    E = t_req_aff.shape[1]
    iom = (
        t_req_aff | _np(profiles.t_req_anti) | _np(profiles.t_matches)
        | (_np(profiles.t_soft) != 0)
    )
    # Append the dummy scratch term row E.
    def zc(a):
        a = _np(a)
        return np.concatenate(
            [a, np.zeros((*a.shape[:-1], 1), a.dtype)], axis=-1
        )

    if not skip_prof:
        profiles = profiles._replace(
            t_req_aff=zc(profiles.t_req_aff),
            t_req_anti=zc(profiles.t_req_anti),
            t_matches=zc(profiles.t_matches),
            t_soft=zc(profiles.t_soft),
        )
    repl = {
        "term_key": np.concatenate(
            [_np(aff.term_key), np.zeros(1, np.int32)]
        ),
    }
    if not skip_cnt0:
        # skip_cnt0: the caller rebuilds cnt0 on device with the dummy
        # row included — skip the dense [Ep, D] host copy here.
        repl["cnt0"] = np.concatenate(
            [_np(aff.cnt0),
             np.zeros((1, _np(aff.cnt0).shape[1]), _np(aff.cnt0).dtype)]
        )
    aff = aff._replace(**repl)
    wp = _np(wave_prof)
    U = iom.shape[0]
    term_lists = []
    ew = 1
    for w in range(n_waves):
        pids = np.unique(np.clip(wp[w], 0, U - 1))
        terms = np.flatnonzero(iom[pids].any(axis=0))
        term_lists.append(terms)
        ew = max(ew, len(terms))
    EW = bucket_pow2(ew, floor=16, min_pad=4)
    wave_terms = np.full((n_waves, EW), E, np.int32)  # pad = dummy row
    for w, terms in enumerate(term_lists):
        wave_terms[w, :len(terms)] = terms
    # Term sets are usually wave-disjoint (terms select a job's own app
    # label and jobs never split across waves): no wave then reads a
    # count another wave wrote, and the per-wave window write-back into
    # the global [E, D] tables — a full-table rewrite per wave under
    # XLA's scatter lowering, ~2 s/cycle at the north-star affinity
    # shape — can be skipped wholesale.
    if term_lists:
        all_terms = np.concatenate(term_lists)
        terms_disjoint = bool(
            len(all_terms) == len(np.unique(all_terms))
        )
    else:
        terms_disjoint = True
    # iom's dummy column is all-zero; callers reuse it as the nonzero
    # union of the four tables (the sparse-shipping path).
    return profiles, aff, wave_terms, int(EW), iom, terms_disjoint


def _wave_profiles(pid: np.ndarray, n_waves: int, wave: int):
    """Per-wave lists of the profiles actually PRESENT in each wave.

    Shared profiles recur across the whole task list, so id *ranges* per
    wave degenerate to the full profile table at scale; explicit presence
    lists keep UM at (distinct profiles per wave), padded to a power of
    two across waves to bound recompilation.  Padding repeats the wave's
    first profile (read-only duplication).  Returns wave_prof [NW, UM];
    the per-task index into its wave's list is recomputed on device (a
    [W, UM] equality argmax per wave beats shipping a [P] vector through
    the tunnel).
    """
    seg = pid.reshape(n_waves, wave)
    lists = []
    um = 1
    for w in range(n_waves):
        u = np.unique(seg[w])
        lists.append(u)
        um = max(um, len(u))
    UM = 1
    while UM < um:
        UM *= 2
    wave_prof = np.zeros((n_waves, UM), np.int32)
    for w, u in enumerate(lists):
        wave_prof[w, :len(u)] = u
        wave_prof[w, len(u):] = u[0]
    return wave_prof


def _pad_tasks(tasks: SolveTasks, pad: int) -> SolveTasks:
    def z(a):
        a = _np(a)
        return np.concatenate([a, np.zeros((pad, *a.shape[1:]), a.dtype)])

    return SolveTasks(
        req=z(tasks.req),
        init_req=z(tasks.init_req),
        job=np.concatenate(
            [_np(tasks.job), np.full((pad,), -1, np.int32)]
        ),
        real=np.concatenate([_np(tasks.real), np.zeros((pad,), bool)]),
        ports=z(tasks.ports),
        sel_bits=z(tasks.sel_bits),
        aff_bits=z(tasks.aff_bits),
        aff_terms=z(tasks.aff_terms),
        tol_bits=z(tasks.tol_bits),
        pref_bits=z(tasks.pref_bits),
        pref_w=z(tasks.pref_w),
    )


def _pad_aff(aff: AffinityArgs, pad: int) -> AffinityArgs:
    def z(a):
        a = _np(a)
        return np.concatenate([a, np.zeros((pad, *a.shape[1:]), a.dtype)])

    return AffinityArgs(
        node_dom=aff.node_dom,
        term_key=aff.term_key,
        cnt0=aff.cnt0,
        t_req_aff=z(aff.t_req_aff),
        t_req_anti=z(aff.t_req_anti),
        t_matches=z(aff.t_matches),
        t_soft=z(aff.t_soft),
    )


def _host_node_classes(nodes: SolveNodes):
    """Compact the node table into classes from HOST arrays.

    Only called when ``nodes.label_bits`` is numpy (direct callers, the
    remote solver child); device-resident callers (devsnap, mesh) build
    classes from their own host copies and pass ``node_classes`` in —
    this helper is deliberately outside the vclint hot registry because
    by contract it never sees a device array.

    The grouping is memoized on a content digest of the static planes
    (one entry): the remote solver child has no mirror epoch to key on,
    but its node table is just as epoch-stable cycle-to-cycle, and the
    digest (a linear byte hash) is an order of magnitude cheaper than
    re-running the structured-row unique sort every solve."""
    import hashlib

    from .nodeclass import build_node_classes

    h = hashlib.blake2b(digest_size=16)
    planes = (
        nodes.label_bits, nodes.taint_bits, np.asarray(nodes.ready),
        np.asarray(nodes.allocatable, np.float32),
        np.asarray(nodes.max_tasks, np.int32),
    )
    for a in planes:
        a = np.ascontiguousarray(a)
        h.update(repr((a.shape, a.dtype.str)).encode())
        h.update(memoryview(a).cast("B"))
    key = h.hexdigest()
    cached = _host_node_classes._cache
    if cached is not None and cached[0] == key:
        return cached[1]
    classes, _n, _sig = build_node_classes(*planes)
    _host_node_classes._cache = (key, classes)
    return classes


_host_node_classes._cache = None


def solve_wave(
    nodes: SolveNodes,
    tasks: SolveTasks,
    jobs: SolveJobs,
    queues: SolveQueues,
    weights: ScoreWeights,
    eps,
    scalar_slot,
    aff: AffinityArgs,
    node_bias=None,
    wave: int = DEFAULT_WAVE,
    pid=None,
    profiles: SolveProfiles = None,
    extra_ok=None,
    extra_score=None,
    taint_any=None,
    node_classes: NodeClasses = None,
    mesh_shards: int = 1,
    devincr=None,
) -> AllocResult:
    """Wave-batched solve; same signature/result as ``allocate.solve``.

    Pads the task axis to a multiple of ``wave`` (padded rows are inert),
    deduplicates tasks into profiles host-side, and truncates the result
    back to the caller's task count.  ``pid`` (optional [P] int32) supplies
    precomputed profile ids — tasks with equal ids must have identical
    per-task solver inputs — and skips the feature-hashing pass.  With
    ``profiles`` also given (rows aligned to the pid numbering, which must
    be by first occurrence), nothing per-task is recomputed here and
    ``aff``'s task-level fields may be dummies.

    ``node_bias`` (optional [N] f32, ops/topology.contig_bias) is an
    additive node-order bias folded into every profile's static score —
    the 9th element of the fast path's solve_args tuple, so remote
    frames and mesh sharding carry it like any other node plane, and
    the solver wire stays byte-identical when absent.

    ``extra_ok`` (optional [P, N] bool) carries custom-plugin predicate
    verdicts (session add_predicate_fn / add_device_mask_fn); it folds
    into the profile grouping so tasks sharing a profile share a mask
    row, and is only supported when profiles are computed in-call
    (custom plugins make a configuration fast-path-ineligible).

    ``mesh_shards`` (mesh callers: the device count the node axis is
    sharded over) restructures every node-axis ranking — the coarse
    shortlist selection, the per-attempt walk ranking, and the full-N
    fallback rescore — into the shard-local + winner-reduction form
    (``_topk_nodes``), keeping the per-profile (score, node id)
    all-reduce as the only cross-chip communication of the selection
    step.  Results are bit-identical to ``mesh_shards=1``; a node axis
    the shard count does not divide falls back to the global form.

    ``devincr`` (optional ``ops.devincr.DeviceIncremental``, ISSUE 9)
    makes the two-phase coarse machinery incremental ACROSS solves:
    persistent [U, C] static planes keyed on content versions replace
    the in-kernel ``_class_static`` passes, and the coarse shortlist
    warm-starts from the previous solve's per-block candidates when the
    caller proved (``begin_solve``) which node rows may have changed.
    Results are bit-for-bit equal to ``devincr=None``; custom-plugin
    solves (``extra_ok``/``extra_score``) and non-two-phase solves
    ignore the context.
    """
    P = int(tasks.job.shape[0])
    if (extra_ok is not None or extra_score is not None) and (
            pid is not None or profiles is not None):
        raise ValueError(
            "extra_ok/extra_score require in-call profile computation"
        )
    wave = int(min(wave, max(1, P)))
    pad = (-P) % wave
    if pad:
        tasks = _pad_tasks(tasks, pad)
        if profiles is None:
            aff = _pad_aff(aff, pad)
        if extra_ok is not None:
            extra_ok = np.concatenate([
                _np(extra_ok),
                np.ones((pad, _np(extra_ok).shape[1]), bool),
            ])
        if extra_score is not None:
            extra_score = np.concatenate([
                _np(extra_score).astype(np.float32),
                np.zeros((pad, _np(extra_score).shape[1]), np.float32),
            ])
    n_waves = (P + pad) // wave
    if profiles is not None and pid is not None:
        pid = np.asarray(pid, np.int64)
        if pad:
            # Padded rows are all-zero features: append a fresh profile.
            fresh = int(pid.max() + 1) if len(pid) else 0
            pid = np.concatenate([pid, np.full(pad, fresh, np.int64)])
            profiles = SolveProfiles(*[
                np.concatenate(
                    [_np(a), np.zeros((1, *np.asarray(a).shape[1:]),
                                      np.asarray(a).dtype)]
                )
                for a in profiles
            ])
        pid = pid.astype(np.int32)
    elif pid is not None:
        pid = np.asarray(pid, np.int64)
        if pad:
            fresh = (pid.max() + 1) if len(pid) else 0
            pid = np.concatenate([pid, np.full(pad, fresh, np.int64)])
        profiles, pid = _profiles_from_pid(tasks, aff, pid)
    else:
        profiles, pid, extra_prof, score_prof = _profile_tasks(
            tasks, aff, extra_ok, extra_score
        )
    u_before = int(_np(profiles.req).shape[0])
    profiles = _pad_profiles_rows(profiles)
    u_pad = int(_np(profiles.req).shape[0]) - u_before
    if extra_ok is not None:
        if u_pad:
            extra_prof = np.concatenate([
                extra_prof, np.ones((u_pad, extra_prof.shape[1]), bool),
            ])
    else:
        extra_prof = np.ones((1, 1), bool)
    if extra_score is not None:
        if u_pad:
            score_prof = np.concatenate([
                score_prof,
                np.zeros((u_pad, score_prof.shape[1]), np.float32),
            ])
    else:
        score_prof = np.zeros((1, 1), np.float32)
    wave_prof = _wave_profiles(pid, n_waves, wave)
    # Input diet for the device call: the kernel reads only job/real
    # per-task (req/init_req come from profile gathers), so every other
    # per-task field ships as a [1, ...] dummy, and the three [P] id
    # vectors narrow to int16 when their value ranges allow — at
    # 10k x 100k this cuts the per-solve upload ~6 MB -> ~0.7 MB
    # (~35 MB/s effective into-execution tunnel bandwidth).
    R_ = int(profiles.req.shape[1])
    job_in = tasks.job
    job_sh = getattr(job_in, "sharding", None)
    if job_sh is not None and not isinstance(job_in, np.ndarray):
        # Mesh / committed-array callers: dummies and narrowed ids must
        # land on the same device set or the jit sees incompatible
        # committed arguments (the cnt0 rebuild below has the same rule).
        _put = lambda x: jax.device_put(x, job_sh)
    else:
        _put = lambda x: x
    z1 = lambda shape, dt: _put(np.zeros(shape, dt))
    tasks = tasks._replace(
        req=z1((1, R_), np.float32),
        init_req=z1((1, R_), np.float32),
        ports=z1((1, 1), np.uint32),
        sel_bits=z1((1, 1), np.uint32),
        aff_bits=z1((1, 1, 1), np.uint32),
        aff_terms=z1((1,), np.int32),
        tol_bits=z1((1, 1), np.uint32),
        pref_bits=z1((1, 1, 1), np.uint32),
        pref_w=z1((1, 1), np.float32),
    )
    if int(profiles.req.shape[0]) < 32767:
        pid = _put(np.asarray(pid).astype(np.int16))
    if int(jobs.min_available.shape[0]) < 32767:
        job_h = _np(job_in)
        if job_h.dtype != np.int16:
            tasks = tasks._replace(job=_put(job_h.astype(np.int16)))
    cnt0_in = aff.cnt0
    cnt0_host = _np(cnt0_in)
    cnt0_sparse = cnt0_host.size > CNT0_SPARSE_MIN
    if cnt0_sparse:
        # One scan serves both the feature bit and the sparse extraction
        # (cnt0 is the largest host array on this path).
        rows_nz, cols_nz = np.nonzero(cnt0_host)
        cnt0_any = bool(len(rows_nz))
    else:
        cnt0_any = bool(cnt0_host.any())
    features = (
        bool(_np(profiles.ports).any()),
        bool(
            _np(profiles.t_req_aff).any()
            or _np(profiles.t_req_anti).any()
            or _np(profiles.t_soft).any()
            or cnt0_any
        ),
        # Device-resident callers (ops/devsnap.py, the mesh plane cache)
        # pass the taint feature as a host-computed hint — fetching a
        # persistent device plane back just to .any() it would put a
        # tunnel round trip on every dispatch.
        (bool(taint_any) if taint_any is not None
         # vclint: disable=VCL201 -- numpy fallback; taint_any skips it
         # (device-resident callers always pass the host-computed hint)
         else bool(_np(nodes.taint_bits).any())),
        bool(_np(nodes.releasing).any() or _np(nodes.pipelined).any()),
        bool((_np(queues.deserved) < 1.0e38).any()),
        extra_ok is not None,
        extra_score is not None,
    )
    prof_sparse = (
        _np(profiles.t_req_aff).size > PROF_SPARSE_MIN
    )
    profiles, aff, wave_terms, ew, prof_iom, terms_disjoint = (
        _term_windows(
            profiles, aff, pid, wave_prof, n_waves,
            skip_cnt0=cnt0_sparse, skip_prof=prof_sparse,
        )
    )
    # Profile-term tables ([U, Ep] bool x3 + f32) reach ~75 MB at the
    # north-star affinity shape but are overwhelmingly zero (a profile
    # references only its own job's terms).  Past the threshold, ship
    # the sparse entries and rebuild dense on device — measured ~2 s of
    # per-cycle upload through the remote-TPU tunnel otherwise.
    if prof_sparse:
        # The tables stayed at the pre-dummy width (skip_prof): gather
        # flags at prof_iom's nonzeros and rebuild on device at the
        # dummy-extended width — the dummy column is all-zero, so the
        # entry set is identical.
        t_aff_h = _np(profiles.t_req_aff)
        t_anti_h = _np(profiles.t_req_anti)
        t_mat_h = _np(profiles.t_matches)
        t_soft_h = _np(profiles.t_soft)
        ur, ec = np.nonzero(prof_iom)
        flags = (
            t_aff_h[ur, ec].astype(np.int8)
            | (t_anti_h[ur, ec].astype(np.int8) << 1)
            | (t_mat_h[ur, ec].astype(np.int8) << 2)
        )
        soft_vals = t_soft_h[ur, ec].astype(np.float32)
        k = bucket_pow2(len(ur), floor=16)
        ppad = k - len(ur)
        if ppad:
            ur = np.concatenate([ur, np.zeros(ppad, np.int64)])
            ec = np.concatenate([ec, np.zeros(ppad, np.int64)])
            flags = np.concatenate([flags, np.zeros(ppad, np.int8)])
            soft_vals = np.concatenate(
                [soft_vals, np.zeros(ppad, np.float32)]
            )
        d_aff, d_anti, d_mat, d_soft = _scatter_profile_tables(
            ur.astype(np.int32), ec.astype(np.int32), flags, soft_vals,
            t_aff_h.shape[0], t_aff_h.shape[1] + 1,
        )
        in_sh = getattr(cnt0_in, "sharding", None)
        if in_sh is not None and not isinstance(cnt0_in, np.ndarray):
            try:
                d_aff, d_anti, d_mat, d_soft = tuple(
                    jax.device_put(x, in_sh)
                    for x in (d_aff, d_anti, d_mat, d_soft)
                )
            except ValueError:
                # A partitioned in_sh whose axis does not divide the
                # rebuilt [U, Ep+1] tables (mesh callers sharding the
                # term axis): replicate them instead — the [E, D] count
                # pair is the memory wall, not these.
                rep = jax.sharding.NamedSharding(
                    in_sh.mesh, jax.sharding.PartitionSpec()
                )
                d_aff, d_anti, d_mat, d_soft = tuple(
                    jax.device_put(x, rep)
                    for x in (d_aff, d_anti, d_mat, d_soft)
                )
        profiles = profiles._replace(
            t_req_aff=d_aff, t_req_anti=d_anti, t_matches=d_mat,
            t_soft=d_soft,
        )
    if cnt0_sparse:
        # Hyperscale [Ep, D] count tables reach hundreds of MB; ship the
        # sparse resident entries (typically none on a fresh cycle) and
        # scatter them on device — into the dummy-row-extended shape —
        # instead of uploading (and host-copying) the dense zeros.
        vals_nz = cnt0_host[rows_nz, cols_nz].astype(np.int32)
        k = bucket_pow2(len(rows_nz), floor=16)
        cpad = k - len(rows_nz)
        if cpad:
            # Padded entries add 0 to cell (0, 0): a no-op.
            rows_nz = np.concatenate([rows_nz, np.zeros(cpad, np.int64)])
            cols_nz = np.concatenate([cols_nz, np.zeros(cpad, np.int64)])
            vals_nz = np.concatenate([vals_nz, np.zeros(cpad, np.int32)])
        cnt0_dev = _scatter_cnt0(
            rows_nz.astype(np.int32), cols_nz.astype(np.int32), vals_nz,
            cnt0_host.shape[0] + 1, cnt0_host.shape[1],
        )
        in_sharding = getattr(cnt0_in, "sharding", None)
        if in_sharding is not None and not isinstance(cnt0_in, np.ndarray):
            # Mesh callers pass cnt0 replicated over their devices; the
            # rebuilt table must match, or the jit below sees committed
            # arrays on incompatible device sets.
            cnt0_dev = jax.device_put(cnt0_dev, in_sharding)
        aff = aff._replace(cnt0=cnt0_dev)
    # ---- two-phase solve prep (node classes + shortlists) ------------
    N_in = int(nodes.idle.shape[0])
    two_phase = _two_phase_on() and N_in > 0
    if two_phase and node_classes is None and _nodeclass_on() \
            and isinstance(nodes.label_bits, np.ndarray):
        node_classes = _host_node_classes(nodes)
    cls_identity = node_classes is None
    if two_phase and not cls_identity:
        cls_arg = node_classes
    else:
        # Inert dummies; the kernel derives identity classes from the
        # node planes when two_phase & cls_identity.
        cls_arg = NodeClasses(
            class_id=z1((1,), np.int32),
            label_bits=z1((1, 1), np.uint32),
            taint_bits=z1((1, 1), np.uint32),
            ready=z1((1,), bool),
        )
    sl_k = shortlist_size(N_in) if two_phase else 1
    # Effective shard count for the node-axis rankings: only when the
    # (padded) node axis divides evenly — otherwise the global form is
    # both correct and what GSPMD would fall back to anyway.
    n_sh = int(mesh_shards) if mesh_shards else 1
    if n_sh > 1 and (N_in % n_sh):
        n_sh = 1
    U_rows = int(profiles.req.shape[0])
    # Largest power of two <= COARSE_CHUNK: the profile axis is
    # pow2-padded, so a pow2 chunk always divides it (lax.map needs an
    # exact reshape).
    chunk = 1
    while chunk * 2 <= max(1, min(COARSE_CHUNK, U_rows)):
        chunk *= 2
    # Trace-static knob verdicts resolved OUTSIDE the jits (an env read
    # at trace time would pin the first verdict into the jit cache and
    # make in-process knob flips no-ops): the hierarchical-selection
    # pin, and the int32 key-space verdict for the kernel's windowed
    # [EW, D] (term x domain) scatters — ``ew`` and the domain width
    # are exactly the kernel's EW and D.
    hier_pin = _hier_pin()
    flat_keys = (ew * int(cnt0_host.shape[1]) + 1) <= _keyspace_max()
    # Device-incremental context (ISSUE 9): only the two-phase slim
    # path qualifies — custom-plugin solves carry per-solve [U, N]
    # planes the cache keys cannot cover.
    dv = devincr
    if dv is not None and (not two_phase or features[5] or features[6]):
        dv = None
    # Exact f32 matmuls are load-bearing: the one-hot matmuls carry node
    # indices, resource sums, and 0/1 predicate counts that are compared
    # with == / <=; the TPU default (bf16 MXU passes) rounds node ids above
    # 256 and capacity sums, mis-routing placements and stalling the
    # attempt loop.
    t_coarse = 0.0
    stat = None
    with jax.default_matmul_precision("float32"):
        if two_phase:
            t0 = _time.perf_counter()
            if dv is not None:
                stat = dv.static_planes(
                    nodes, profiles, cls_arg,
                    weights.node_affinity_weight, chunk,
                    has_taints=features[2], cls_identity=cls_identity,
                )
                sl = dv.shortlist(
                    nodes, profiles, extra_prof, score_prof, cls_arg,
                    aff, weights, eps, scalar_slot,
                    sl_k=sl_k, chunk=chunk, features=features,
                    cnt0_any=bool(cnt0_any), cls_identity=cls_identity,
                    mesh_shards=n_sh, stat=stat,
                )
            else:
                sl = _coarse_shortlist(
                    nodes, profiles, extra_prof, score_prof, cls_arg,
                    aff, weights, eps, scalar_slot,
                    sl_k=sl_k, chunk=chunk,
                    features=features, cnt0_any=bool(cnt0_any),
                    cls_identity=cls_identity, mesh_shards=n_sh,
                    hier_pin=hier_pin,
                )
            t_coarse = _time.perf_counter() - t0
        else:
            sl = z1((1, 1), np.int32)
        t0 = _time.perf_counter()
        res = _solve_wave(
            nodes, tasks, jobs, queues, weights, eps, scalar_slot, aff,
            profiles, extra_prof, score_prof, pid, wave_prof,
            wave_terms, cls_arg, sl,
            wave=wave, n_waves=n_waves, ew=ew, features=features,
            terms_disjoint=terms_disjoint, two_phase=two_phase,
            cls_identity=cls_identity, fb_cap=_fallback_cap(),
            mesh_shards=n_sh,
            static_ext=stat is not None,
            stat_ok=stat[0] if stat is not None else None,
            stat_score=stat[1] if stat is not None else None,
            hier_pin=hier_pin,
            flat_keys=flat_keys,
            node_bias=node_bias,
            has_bias=node_bias is not None,
        )
        t_fine = _time.perf_counter() - t0
    # Dispatch-side sub-lane telemetry (the cycle driver folds it into
    # the device_coarse/device_fine lanes; with async device dispatch
    # these measure the host-side dispatch legs, the residual device
    # wait stays on the caller's fetch).
    LAST_TWOPHASE.clear()
    LAST_TWOPHASE.update({
        "enabled": two_phase,
        "coarse_s": t_coarse,
        "fine_s": t_fine,
        "shortlist": (U_rows, sl_k) if two_phase else None,
        "n_nodes": N_in,
        "compacted_classes": two_phase and not cls_identity,
        "mesh_shards": n_sh,
        "devincr": dv.solve_info() if dv is not None else None,
    })
    if dv is not None:
        dv.end_solve()
    if pad:
        res = res._replace(
            assigned=res.assigned[:P], pipelined=res.pipelined[:P]
        )
    return res
