"""Device kernels: fit predicates, scoring, and the allocate solver."""

from .allocate import (
    AllocResult,
    SolveJobs,
    SolveNodes,
    SolveQueues,
    SolveTasks,
    solve,
    solve_inputs,
)
from .predicates import static_predicate_mask
from .resreq import is_empty, less, less_equal, less_equal_strict
from .scoring import ScoreWeights, default_weights, node_score

__all__ = [
    "AllocResult",
    "SolveJobs",
    "SolveNodes",
    "SolveQueues",
    "SolveTasks",
    "solve",
    "solve_inputs",
    "static_predicate_mask",
    "is_empty",
    "less",
    "less_equal",
    "less_equal_strict",
    "ScoreWeights",
    "default_weights",
    "node_score",
]
