"""Topology-aware gang placement: fabric planes + contiguous blocks.

Every scorer in the tree so far is topology-blind: a 32-task training
gang scattered across racks binds "correctly" but trains slowly — the
exact scenario the paper's workload (distributed training on
accelerator fabrics, SURVEY §1) cares about most, and one quantity-based
policies provably leave on the table (Gavel, arXiv:2008.09213).  This
module adds the fabric as a solver *dimension*, not a new solver:

- **fabric model** — nodes carry fabric coordinates from labels
  (``fabric.volcano-tpu/rack`` / ``slice`` / ``host``).  The mirror
  interns the values append-only (``StoreMirror._fabric_vals`` /
  ``_fabric_blocks``, compaction-carried) and this module derives two
  epoch-cached host planes: ``fabric_coords`` ``[N, 3]`` int32 (the
  wire plane — ``arrays.NodeArrays.fabric`` carries the same layout
  over snapwire protocol v2) and ``block_ids`` ``[N]`` int32, where a
  *block* is one contiguous placement domain — an interned
  ``(rack, slice)`` pair (an ICI slice / NVLink island within a rack).
  Unlabeled nodes get coordinate/block ``-1`` and never join a block.

- **contiguous-block gang scoring** — ``gang_block_fit`` is one jitted
  pass over the node planes (the block-granular sibling of
  ``ops/wave._coarse_shortlist``'s two-phase pattern): per-node task
  capacity per gang profile, segment-summed per block, reduced to
  per-block *whole-gang* feasibility and a partial-fit score.
  ``select_block`` is the deterministic host-side pick (max score, tie
  lowest block id).  Per-gang constraints (``PodGroup.topology`` /
  the ``scheduling.volcano-tpu/topology`` annotation):

  - ``require-contiguous`` — allocate pre-gates the gang (drops it
    from the solve with the exclusive drop reason
    ``topology-infeasible`` when no block can host the whole gang) and
    post-gates the result (a scattered assignment is vetoed before
    commit, never bound);
  - ``prefer-contiguous`` — the selected block's nodes get an additive
    node-order bias (``contig_bias``) folded into the wave solver's
    static score plane; the solver's existing full-N fallback
    guarantees binding is never lost to the preference.

- **fabric defragmentation** — ``fabric_frag`` scores stranded partial
  slices per block; ``FastCycle._plan_rebalance`` uses the per-block
  fit planes to concentrate a require-gang's migration plan on one
  target block, proven and committed through the existing what-if
  engine under the same disruption budgets and staleness guards.

Kill switch ``VOLCANO_TPU_TOPOLOGY=0``: every hook gates on
``topology_on()`` *and* the presence of fabric labels, so an unlabeled
cluster — or the switch — keeps the solve inputs (and therefore the
remote-solver wire frames) byte-identical to the pre-topology build.

``oracle.oracle_topology`` is the deliberately naive Go-shaped
re-implementation of the scoring + selection; tests require exact
agreement on seeded fragmented fabrics.
"""

from __future__ import annotations

import os
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

F = np.float32
I = np.int32

# Fabric coordinate label keys (canonical definitions in api.spec so
# the wire schema can share them without an arrays -> ops cycle).
from ..api.spec import (  # noqa: E402  (re-export)
    FABRIC_HOST,
    FABRIC_L,
    FABRIC_LEVELS,
    FABRIC_RACK,
    FABRIC_SLICE,
)

# Per-node fit counts are clipped here before the int32 cast (a node
# with no requested slot would otherwise divide to inf).
_FIT_MAX = float(2 ** 30)


def topology_on() -> bool:
    """Master switch (``VOLCANO_TPU_TOPOLOGY``, default on).  Read per
    decision, not at import — in-process flips must take effect."""
    return os.environ.get("VOLCANO_TPU_TOPOLOGY", "1") != "0"


def topo_weight() -> float:
    """Additive node-order bias for the selected block's nodes
    (``VOLCANO_TPU_TOPO_WEIGHT``, default 1.0)."""
    raw = os.environ.get("VOLCANO_TPU_TOPO_WEIGHT", "1.0")
    try:
        return float(raw)
    except ValueError:
        return 1.0


# ------------------------------------------------------------ mirror planes

def _fabric_interners(m) -> Tuple[dict, dict]:
    """The mirror's append-only fabric interners, created on first use
    for stores older than this module.  ``_fabric_vals`` maps
    ``(level, label value) -> code``; ``_fabric_blocks`` maps
    ``(rack code, slice code) -> block id``.  Both are carried across
    compaction (cache/mirror.py ``maybe_compact``), so codes and block
    ids are stable for the life of the store."""
    vals = getattr(m, "_fabric_vals", None)
    if vals is None:
        vals = m._fabric_vals = {}
    blocks = getattr(m, "_fabric_blocks", None)
    if blocks is None:
        blocks = m._fabric_blocks = {}
    return vals, blocks


def fabric_planes(m) -> Tuple[np.ndarray, np.ndarray, int]:
    """``(coords [Nrows, FABRIC_L] int32, block_id [Nrows] int32,
    n_blocks)`` for the mirror's node table; ``-1`` marks a missing
    coordinate / blockless node.

    Epoch-cached on the mirror: coordinates are a pure function of the
    node table (every node add/update bumps ``m.epoch``), and the
    interners are append-only, so per-row values are stable across
    epochs — the same property that lets the label/taint bit planes
    ride the devsnap row-delta machinery."""
    N = len(m.n_name)
    cache = getattr(m, "_fabric_cache", None)
    key = (m.epoch, N)
    if cache is not None and cache[0] == key:
        return cache[1], cache[2], cache[3]
    vals, blocks = _fabric_interners(m)
    coords = np.full((N, FABRIC_L), -1, I)
    block = np.full((N,), -1, I)
    for ni in range(N):
        if not m.n_alive[ni]:
            continue
        node = m.node_objs[ni]
        labels = getattr(node, "labels", None) if node is not None else None
        if not labels:
            continue
        for li, lkey in enumerate(FABRIC_LEVELS):
            v = labels.get(lkey)
            if v is None:
                continue
            code = vals.get((li, v))
            if code is None:
                code = vals[(li, v)] = len(vals)
            coords[ni, li] = code
        if coords[ni, 0] >= 0 and coords[ni, 1] >= 0:
            bkey = (int(coords[ni, 0]), int(coords[ni, 1]))
            bid = blocks.get(bkey)
            if bid is None:
                bid = blocks[bkey] = len(blocks)
            block[ni] = bid
    n_blocks = len(blocks)
    m._fabric_cache = (key, coords, block, n_blocks)
    return coords, block, n_blocks


def has_fabric(m) -> bool:
    """True when at least one live node carries a complete block
    coordinate (the cheap gate every fast-path hook checks first)."""
    _, block, n_blocks = fabric_planes(m)
    return n_blocks > 0 and bool((block >= 0).any())


# --------------------------------------------------------------- kernels

class BlockFit(NamedTuple):
    """Per-block gang-fit planes (device arrays until fetched)."""

    cfit: jnp.ndarray   # [B, U] i32 gang tasks of profile u the block holds
    whole: jnp.ndarray  # [B] bool block can host the WHOLE gang
    score: jnp.ndarray  # [B] f32 partial-fit score (sum of min(cfit, cnt))


@partial(jax.jit, static_argnames=("n_blocks",))
def gang_block_fit(idle, ready, ntasks, max_tasks, block_id, prof_req,
                   prof_cnt, eps, *, n_blocks: int):
    """Whole-gang fit per fabric block, one kernel dispatch.

    ``idle`` [N, R] f32, ``ready`` [N] bool, ``ntasks``/``max_tasks``
    [N] i32 (``max_tasks`` 0 = unlimited), ``block_id`` [N] i32 (-1 =
    blockless), ``prof_req`` [U, R] f32 per-profile init requests of the
    gang's pending tasks (all-zero rows inert), ``prof_cnt`` [U] i32
    pending tasks per profile (0 for padding), ``eps`` [R] f32.
    ``n_blocks`` is static (pow2-bucketed by callers); blockless nodes
    collapse into a trash row that is sliced off.

    Definitions (mirrored exactly by ``oracle.oracle_topology``):

    - per (node, profile) capacity = min over requested slots of
      ``floor((idle + eps) / req)``, 0 for profiles with no requested
      slot, 0 on not-ready nodes, capped by the node's remaining pod
      slots when ``max_tasks > 0``;
    - ``cfit[b, u]`` = sum of the capacity over the block's nodes;
    - ``whole[b]`` = all profiles: ``cfit[b, u] >= prof_cnt[u]``;
    - ``score[b]`` = sum over profiles of ``min(cfit[b, u], cnt[u])``.

    The per-profile independence makes ``whole`` an upper bound when
    profiles share capacity — it is a pre-filter; the post-solve
    topology gate (fastpath) is the exact enforcer.
    """
    idle = idle.astype(jnp.float32)
    req = prof_req.astype(jnp.float32)
    eps = eps.astype(jnp.float32)
    cnt = prof_cnt.astype(jnp.int32)

    requested = req > eps[None, :]  # [U, R]
    per = jnp.floor(
        (idle[:, None, :] + eps[None, None, :])
        / jnp.maximum(req[None, :, :], 1e-9)
    )
    per = jnp.where(requested[None, :, :], per, jnp.float32(_FIT_MAX))
    cap = jnp.min(per, axis=-1)  # [N, U]
    cap = jnp.where(jnp.any(requested, axis=-1)[None, :], cap, 0.0)
    cap = jnp.clip(cap, 0.0, _FIT_MAX)
    slots_left = jnp.where(
        max_tasks > 0,
        jnp.maximum(max_tasks - ntasks, 0).astype(jnp.float32),
        jnp.float32(_FIT_MAX),
    )
    cap = jnp.minimum(cap, slots_left[:, None])
    cap = jnp.where(ready[:, None], cap, 0.0).astype(jnp.int32)

    # Segment-sum into blocks; -1 rows land in the trash row n_blocks.
    seg = jnp.where(block_id >= 0, block_id, n_blocks)
    cfit = jnp.zeros((n_blocks + 1, cap.shape[1]), jnp.int32)
    cfit = cfit.at[seg].add(cap)
    cfit = cfit[:n_blocks]
    whole = jnp.all(cfit >= cnt[None, :], axis=-1)
    score = jnp.sum(
        jnp.minimum(cfit, cnt[None, :]).astype(jnp.float32), axis=-1
    )
    return BlockFit(cfit=cfit, whole=whole, score=score)


@jax.jit
def fabric_frag(cfit, whole, prof_cnt):
    """Stranded-partial-slice score per block, in [0, 1].

    A block holding gang capacity it cannot complete (``whole`` false
    but ``score > 0``) strands that capacity for contiguous placement:
    ``frag[b] = (1 - whole[b]) * score[b] / total_need``.  The mean
    over blocks is the ``volcano_topology_frag_score`` gauge the
    defragmentation lane drives toward zero."""
    cnt = prof_cnt.astype(jnp.float32)
    need = jnp.maximum(jnp.sum(cnt), 1.0)
    partial = jnp.sum(
        jnp.minimum(cfit.astype(jnp.float32), cnt[None, :]), axis=-1
    )
    return jnp.where(whole, 0.0, partial / need)


# ------------------------------------------------------------- host side

def select_block(whole: np.ndarray, score: np.ndarray,
                 require: bool) -> int:
    """Deterministic target-block pick over fetched planes: the
    max-score block (tie: lowest block id), restricted to whole-gang
    blocks when ``require``.  Returns -1 when no candidate exists."""
    whole = np.asarray(whole, bool)
    score = np.asarray(score, np.float32)
    cand = whole if require else np.ones(len(score), bool)
    if not cand.any():
        return -1
    masked = np.where(cand, score, -np.inf)
    return int(np.argmax(masked))  # argmax ties -> lowest index


def contig_bias(block_id: np.ndarray, target_block: int, n_pad: int,
                weight: Optional[float] = None) -> np.ndarray:
    """``[n_pad]`` f32 additive node-order bias: ``weight`` on the
    target block's nodes, 0 elsewhere (padding rows included).  Folded
    into the wave solver's static score plane (BatchNodeOrder), so the
    preference can never outrank feasibility — infeasible nodes stay
    NEG-masked after the add."""
    if weight is None:
        weight = topo_weight()
    bias = np.zeros((n_pad,), F)
    if target_block >= 0 and weight != 0.0:
        n = min(len(block_id), n_pad)
        bias[:n][np.asarray(block_id[:n]) == target_block] = F(weight)
    return bias
