"""Typed plugin/action argument helpers (framework/arguments.go)."""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

log = logging.getLogger(__name__)


class Arguments(dict):
    """String->string argument map with lenient typed getters."""

    def get_int(self, key: str, default: int) -> int:
        raw = self.get(key)
        if raw in (None, ""):
            return default
        try:
            return int(raw)
        except (TypeError, ValueError):
            log.warning("Could not parse argument %r for key %s", raw, key)
            return default

    def get_float(self, key: str, default: float) -> float:
        raw = self.get(key)
        if raw in (None, ""):
            return default
        try:
            return float(raw)
        except (TypeError, ValueError):
            log.warning("Could not parse argument %r for key %s", raw, key)
            return default

    def get_str(self, key: str, default: str) -> str:
        raw = self.get(key)
        if raw in (None, ""):
            return default
        return str(raw)

    def get_bool(self, key: str, default: bool) -> bool:
        raw = self.get(key)
        if raw in (None, ""):
            return default
        if isinstance(raw, bool):
            return raw
        s = str(raw).strip().lower()
        if s in ("true", "1", "yes"):
            return True
        if s in ("false", "0", "no"):
            return False
        log.warning("Could not parse argument %r for key %s", raw, key)
        return default


def get_action_args(configurations: List["Configuration"], action: str) -> Optional[Arguments]:
    """Per-action configuration lookup (GetArgOfActionFromConf)."""
    for c in configurations:
        if c.name == action:
            return Arguments(c.arguments)
    return None


# Late import type for annotation only.
from .conf import Configuration  # noqa: E402
