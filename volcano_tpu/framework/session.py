"""Session: the per-cycle scheduling context and plugin host.

Mirrors ``pkg/scheduler/framework/session.go`` + ``session_plugins.go``: a
Session is built from a deep-copied store snapshot, plugins register
callbacks into tiered registries, and actions dispatch through the tier
semantics (victim-set intersection for Preemptable/Reclaimable, veto chains
for JobReady/JobPipelined/JobValid/JobEnqueueable, first-nonzero comparator
chains for orderings, additive node scores).

TPU-native additions: plugins also contribute *device-level* state the
allocate/preempt kernels consume — additive ``ScoreWeights``, per-queue
``deserved`` shares, and extra [P, N] mask factories — so one jitted solver
call replaces the per-(task, node) callback fan-out.  Host callbacks remain
the semantic reference and serve the preempt/reclaim victim logic.
"""

from __future__ import annotations

import itertools
import logging
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..api import (
    ClusterInfo,
    JobInfo,
    NamespaceInfo,
    NodeInfo,
    PodGroupCondition,
    PodGroupPhase,
    QueueInfo,
    Resource,
    TaskInfo,
    TaskStatus,
    ValidateResult,
)
from .conf import Configuration, Tier

log = logging.getLogger(__name__)

_session_counter = itertools.count(1)


@dataclass
class Event:
    task: TaskInfo


@dataclass
class EventHandler:
    allocate_func: Optional[Callable[[Event], None]] = None
    deallocate_func: Optional[Callable[[Event], None]] = None


class Session:
    """One scheduling cycle's world view + plugin registries."""

    def __init__(self, cache, tiers: Sequence[Tier],
                 configurations: Sequence[Configuration] = ()):
        self.uid = f"ssn-{next(_session_counter)}"
        self.cache = cache
        self.tiers: List[Tier] = list(tiers)
        self.configurations: List[Configuration] = list(configurations)

        # Observability (obs/, ISSUE 3): the store's span tracer, so the
        # object path's snapshot / action / plugin boundaries land in
        # the same per-cycle trace the fast path records (a cache object
        # without one — bare test doubles — gets the shared no-op).
        from ..obs.trace import tracer_of

        self.tracer = tracer_of(cache)
        with self.tracer.span("snapshot", cat="object",
                              args={"session": self.uid}):
            snapshot: ClusterInfo = cache.snapshot()
        self.jobs: Dict[str, JobInfo] = snapshot.jobs
        self.nodes: Dict[str, NodeInfo] = snapshot.nodes
        self.queues: Dict[str, QueueInfo] = snapshot.queues
        self.namespace_info: Dict[str, NamespaceInfo] = snapshot.namespace_info

        self.plugins: Dict[str, object] = {}
        self.event_handlers: List[EventHandler] = []

        # Tiered callback registries (17 families, session.go:36-71).
        self.job_order_fns: Dict[str, Callable] = {}
        self.queue_order_fns: Dict[str, Callable] = {}
        self.task_order_fns: Dict[str, Callable] = {}
        self.namespace_order_fns: Dict[str, Callable] = {}
        self.preemptable_fns: Dict[str, Callable] = {}
        self.reclaimable_fns: Dict[str, Callable] = {}
        self.job_ready_fns: Dict[str, Callable] = {}
        self.job_pipelined_fns: Dict[str, Callable] = {}
        self.predicate_fns: Dict[str, Callable] = {}
        self.best_node_fns: Dict[str, Callable] = {}
        self.node_order_fns: Dict[str, Callable] = {}
        self.batch_node_order_fns: Dict[str, Callable] = {}
        self.node_map_fns: Dict[str, Callable] = {}
        self.node_reduce_fns: Dict[str, Callable] = {}
        self.overused_fns: Dict[str, Callable] = {}
        self.job_valid_fns: Dict[str, Callable] = {}
        self.job_enqueueable_fns: Dict[str, Callable] = {}

        # Device-level contributions (TPU-native).
        self.score_weight_fns: Dict[str, Callable[[], Dict[str, float]]] = {}
        self.device_mask_fns: Dict[str, Callable] = {}
        self.queue_deserved: Dict[str, Resource] = {}
        self.queue_allocated_open: Dict[str, Resource] = {}

        # PodGroup statuses at open, for change detection at close.
        self.pod_group_status: Dict[str, object] = {}

    # ------------------------------------------------------------ add_* API

    def add_job_order_fn(self, name, fn):
        self.job_order_fns[name] = fn

    def add_queue_order_fn(self, name, fn):
        self.queue_order_fns[name] = fn

    def add_task_order_fn(self, name, fn):
        self.task_order_fns[name] = fn

    def add_namespace_order_fn(self, name, fn):
        self.namespace_order_fns[name] = fn

    def add_preemptable_fn(self, name, fn):
        self.preemptable_fns[name] = fn

    def add_reclaimable_fn(self, name, fn):
        self.reclaimable_fns[name] = fn

    def add_job_ready_fn(self, name, fn):
        self.job_ready_fns[name] = fn

    def add_job_pipelined_fn(self, name, fn):
        self.job_pipelined_fns[name] = fn

    def add_predicate_fn(self, name, fn):
        self.predicate_fns[name] = fn

    def add_best_node_fn(self, name, fn):
        self.best_node_fns[name] = fn

    def add_node_order_fn(self, name, fn):
        self.node_order_fns[name] = fn

    def add_batch_node_order_fn(self, name, fn):
        self.batch_node_order_fns[name] = fn

    def add_node_map_fn(self, name, fn):
        self.node_map_fns[name] = fn

    def add_node_reduce_fn(self, name, fn):
        self.node_reduce_fns[name] = fn

    def add_overused_fn(self, name, fn):
        self.overused_fns[name] = fn

    def add_job_valid_fn(self, name, fn):
        self.job_valid_fns[name] = fn

    def add_job_enqueueable_fn(self, name, fn):
        self.job_enqueueable_fns[name] = fn

    def add_event_handler(self, handler: EventHandler):
        self.event_handlers.append(handler)

    def add_score_weight_fn(self, name, fn):
        """Contribute additive device score weights (TPU-native)."""
        self.score_weight_fns[name] = fn

    def add_device_mask_fn(self, name, fn):
        """Contribute an extra [P, N] predicate mask factory (TPU-native
        custom-plugin extension; cheaper than per-(task, node) host
        callbacks).  Contract: ``fn(cluster, pending_tasks, node_names)
        -> [len(pending), len(node_names)] bool or None``; the allocate
        action ANDs the result into the solver's feasibility."""
        self.device_mask_fns[name] = fn

    # ------------------------------------------------------ tier iteration

    def _tier_plugins(self, flag_attr: str):
        """(tier_index, PluginOption) list for plugins with a flag on.
        Memoized: this sits inside every heap comparison of the job/task
        orderings (tiers never change within a session)."""
        cache = getattr(self, "_tier_plugin_cache", None)
        if cache is None:
            cache = self._tier_plugin_cache = {}
        hit = cache.get(flag_attr)
        if hit is None:
            hit = cache[flag_attr] = [
                (ti, opt)
                for ti, tier in enumerate(self.tiers)
                for opt in tier.plugins
                if getattr(opt, flag_attr, None)
            ]
        return hit

    # ------------------------------------------------------------ dispatch

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        """First non-zero comparator across tiers wins
        (session_plugins.go:292-316)."""
        for _, opt in self._tier_plugins("enabled_job_order"):
            fn = self.job_order_fns.get(opt.name)
            if fn is None:
                continue
            j = fn(l, r)
            if j != 0:
                return j < 0
        if l.creation_timestamp == r.creation_timestamp:
            return l.uid < r.uid
        return l.creation_timestamp < r.creation_timestamp

    def namespace_order_fn(self, l: str, r: str) -> bool:
        for _, opt in self._tier_plugins("enabled_namespace_order"):
            fn = self.namespace_order_fns.get(opt.name)
            if fn is None:
                continue
            j = fn(l, r)
            if j != 0:
                return j < 0
        return l < r

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        for _, opt in self._tier_plugins("enabled_queue_order"):
            fn = self.queue_order_fns.get(opt.name)
            if fn is None:
                continue
            j = fn(l, r)
            if j != 0:
                return j < 0
        if l.queue.creation_timestamp == r.queue.creation_timestamp:
            return l.uid < r.uid
        return l.queue.creation_timestamp < r.queue.creation_timestamp

    def task_compare_fns(self, l: TaskInfo, r: TaskInfo) -> int:
        for _, opt in self._tier_plugins("enabled_task_order"):
            fn = self.task_order_fns.get(opt.name)
            if fn is None:
                continue
            j = fn(l, r)
            if j != 0:
                return j
        return 0

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        res = self.task_compare_fns(l, r)
        if res != 0:
            return res < 0
        if l.pod.creation_timestamp == r.pod.creation_timestamp:
            return l.uid < r.uid
        return l.pod.creation_timestamp < r.pod.creation_timestamp

    def job_valid(self, obj) -> Optional[ValidateResult]:
        """First failing validator wins (session_plugins.go:255-271);
        JobValid has no enable flag."""
        for tier in self.tiers:
            for opt in tier.plugins:
                fn = self.job_valid_fns.get(opt.name)
                if fn is None:
                    continue
                vr = fn(obj)
                if vr is not None and not vr.pass_:
                    return vr
        return None

    def job_ready(self, obj) -> bool:
        for _, opt in self._tier_plugins("enabled_job_ready"):
            fn = self.job_ready_fns.get(opt.name)
            if fn is None:
                continue
            if not fn(obj):
                return False
        return True

    def job_pipelined(self, obj) -> bool:
        for _, opt in self._tier_plugins("enabled_job_pipelined"):
            fn = self.job_pipelined_fns.get(opt.name)
            if fn is None:
                continue
            if not fn(obj):
                return False
        return True

    def job_enqueueable(self, obj) -> bool:
        """Veto chain; no enable flag (session_plugins.go:274-289)."""
        for tier in self.tiers:
            for opt in tier.plugins:
                fn = self.job_enqueueable_fns.get(opt.name)
                if fn is None:
                    continue
                if not fn(obj):
                    return False
        return True

    def overused(self, queue: QueueInfo) -> bool:
        """Any overused verdict wins; no enable flag
        (session_plugins.go:196-210)."""
        for tier in self.tiers:
            for opt in tier.plugins:
                fn = self.overused_fns.get(opt.name)
                if fn is None:
                    continue
                if fn(queue):
                    return True
        return False

    def _victims(self, registry, flag_attr, arg, candidates) -> List[TaskInfo]:
        """Tier semantics for victim selection (session_plugins.go:110-193):
        the victim set and its initialized flag persist ACROSS tiers — every
        enabled plugin intersects the carried set — and the walk stops at the
        first tier boundary where the set is non-empty.  (Go's empty slices
        are nil, so `victims != nil` only fires on a populated set, and an
        earlier tier's empty result keeps poisoning later intersections.)"""
        victims: List[TaskInfo] = []
        init = False
        for tier in self.tiers:
            for opt in tier.plugins:
                if not getattr(opt, flag_attr, None):
                    continue
                fn = registry.get(opt.name)
                if fn is None:
                    continue
                cand = fn(arg, candidates) or []
                if not init:
                    victims = list(cand)
                    init = True
                else:
                    cand_uids = {c.uid for c in cand}
                    victims = [v for v in victims if v.uid in cand_uids]
            if victims:
                return victims
            if init:
                # The carried set is empty and can only shrink under further
                # intersection — short-circuit the remaining tiers.
                return victims
        return victims

    def preemptable(self, preemptor: TaskInfo, preemptees) -> List[TaskInfo]:
        return self._victims(
            self.preemptable_fns, "enabled_preemptable", preemptor, preemptees
        )

    def reclaimable(self, reclaimer: TaskInfo, reclaimees) -> List[TaskInfo]:
        return self._victims(
            self.reclaimable_fns, "enabled_reclaimable", reclaimer, reclaimees
        )

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """Raise FitError on the first failing predicate
        (session_plugins.go:408-425)."""
        for _, opt in self._tier_plugins("enabled_predicate"):
            fn = self.predicate_fns.get(opt.name)
            if fn is None:
                continue
            fn(task, node)  # raises on failure

    def node_order_fn(self, task: TaskInfo, node: NodeInfo) -> float:
        score = 0.0
        for _, opt in self._tier_plugins("enabled_node_order"):
            fn = self.node_order_fns.get(opt.name)
            if fn is None:
                continue
            score += fn(task, node)
        return score

    def batch_node_order_fn(self, task: TaskInfo, nodes) -> Dict[str, float]:
        scores: Dict[str, float] = {}
        for _, opt in self._tier_plugins("enabled_node_order"):
            fn = self.batch_node_order_fns.get(opt.name)
            if fn is None:
                continue
            for node_name, s in fn(task, nodes).items():
                scores[node_name] = scores.get(node_name, 0.0) + s
        return scores

    def best_node_fn(self, task: TaskInfo, node_scores) -> Optional[NodeInfo]:
        for _, opt in self._tier_plugins("enabled_best_node"):
            fn = self.best_node_fns.get(opt.name)
            if fn is None:
                continue
            best = fn(task, node_scores)
            if best is not None:
                return best
        return None

    def score_weights(self, slots):
        """Assemble the additive device ScoreWeights from enabled plugins.

        ``slots`` is the session's ResourceSlots layout; binpack's named
        per-resource weights are resolved to dense slot vectors here.
        """
        import jax.numpy as jnp

        from ..ops.scoring import ScoreWeights

        width = slots.width
        merged = {
            "binpack_weight": 0.0,
            "binpack_res": [1.0] * width,
            "least_req_weight": 0.0,
            "most_req_weight": 0.0,
            "balanced_weight": 0.0,
            "node_affinity_weight": 0.0,
        }
        for _, opt in self._tier_plugins("enabled_node_order"):
            fn = self.score_weight_fns.get(opt.name)
            if fn is None:
                continue
            for k, v in fn().items():
                if k == "binpack_res":
                    dense = [0.0] * width
                    for name, w in v.items():
                        idx = slots.index.get(name)
                        if idx is not None:
                            dense[idx] = float(w)
                    merged[k] = dense
                else:
                    merged[k] = merged[k] + v
        return ScoreWeights(
            binpack_weight=float(merged["binpack_weight"]),
            binpack_res=jnp.asarray(merged["binpack_res"], jnp.float32),
            least_req_weight=float(merged["least_req_weight"]),
            most_req_weight=float(merged["most_req_weight"]),
            balanced_weight=float(merged["balanced_weight"]),
            node_affinity_weight=float(merged["node_affinity_weight"]),
        )

    # --------------------------------------------------- mutation operations

    def _dispatch_events(self, task: TaskInfo, allocate: bool):
        for eh in self.event_handlers:
            fn = eh.allocate_func if allocate else eh.deallocate_func
            if fn is not None:
                fn(Event(task=task))

    def allocate_task(self, task: TaskInfo, hostname: str) -> None:
        """Session-level Allocate (session.go:250-305): update status, add to
        node, fire events; once the job is ready, every Allocated task is
        dispatched (bound) immediately."""
        self.cache.allocate_volumes(task, hostname)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"job {task.job} not in session")
        job.update_task_status(task, TaskStatus.Allocated)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"node {hostname} not in session")
        node.add_task(task)
        self._dispatch_events(task, allocate=True)
        if self.job_ready(job):
            for t in list(
                job.task_status_index.get(TaskStatus.Allocated, {}).values()
            ):
                self.dispatch_bind(t)

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Session-level Pipeline (session.go:207-249): NOT transactional —
        survives Statement.discard."""
        job = self.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.Pipelined)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self._dispatch_events(task, allocate=True)

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Session-level Evict (session.go:334-380): immediate cache evict."""
        self.cache.evict(reclaimee, reason)
        job = self.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.Releasing)
        node = self.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self._dispatch_events(reclaimee, allocate=False)

    def dispatch_bind(self, task: TaskInfo) -> None:
        """Send the bind to the cache (session.go:307-330 dispatch:
        BindVolumes then Bind)."""
        self.cache.bind_volumes(task)
        self.cache.bind(task, task.node_name)
        job = self.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.Binding)

    def update_job_condition(self, job: JobInfo, condition: PodGroupCondition):
        self.cache.record_job_condition(job, condition)

    def statement(self) -> "Statement":
        from .statement import Statement

        return Statement(self)
