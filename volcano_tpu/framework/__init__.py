"""Scheduling framework: session, statement, plugin host, configuration."""

from .arguments import Arguments, get_action_args
from .conf import (
    DEFAULT_SCHEDULER_CONF,
    DEPLOYED_SCHEDULER_CONF,
    REBALANCE_SCHEDULER_CONF,
    Configuration,
    PluginOption,
    SchedulerConfiguration,
    Tier,
    parse_scheduler_conf,
)
from .framework import close_session, open_session
from .plugins import (
    get_action,
    get_plugin_builder,
    register_action,
    register_plugin_builder,
)
from .session import Event, EventHandler, Session
from .statement import Statement

__all__ = [
    "Arguments",
    "get_action_args",
    "DEFAULT_SCHEDULER_CONF",
    "DEPLOYED_SCHEDULER_CONF",
    "REBALANCE_SCHEDULER_CONF",
    "Configuration",
    "PluginOption",
    "SchedulerConfiguration",
    "Tier",
    "parse_scheduler_conf",
    "close_session",
    "open_session",
    "get_action",
    "get_plugin_builder",
    "register_action",
    "register_plugin_builder",
    "Event",
    "EventHandler",
    "Session",
    "Statement",
]
