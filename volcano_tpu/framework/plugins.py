"""Plugin and action registries (pkg/scheduler/framework/plugins.go)."""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

_lock = threading.Lock()
_plugin_builders: Dict[str, Callable] = {}
_actions: Dict[str, object] = {}


def register_plugin_builder(name: str, builder: Callable) -> None:
    with _lock:
        _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Optional[Callable]:
    with _lock:
        return _plugin_builders.get(name)


def register_action(action) -> None:
    with _lock:
        _actions[action.name] = action


def get_action(name: str):
    with _lock:
        return _actions.get(name)


def list_actions():
    with _lock:
        return dict(_actions)
