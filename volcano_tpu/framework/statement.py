"""Statement: the gang-transactional operation buffer.

Mirrors ``pkg/scheduler/framework/statement.go``: Evict/Pipeline/Allocate
apply immediately to session state and are recorded; ``commit`` flushes the
side effects to the cache (evictions + binds), ``discard`` undoes the session
state in reverse order (unevict/unpipeline/unallocate).  Used by allocate
(commit iff JobReady, allocate.go:241-245) and preempt (commit iff
JobPipelined, preempt.go:131-137).
"""

from __future__ import annotations

import logging
from typing import List, Tuple

from ..api import TaskInfo, TaskStatus

log = logging.getLogger(__name__)


class Statement:
    def __init__(self, ssn):
        self.ssn = ssn
        self.operations: List[Tuple[str, tuple]] = []

    # ------------------------------------------------------------ recording

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Tentative evict: session state only (statement.go:40-77)."""
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.Releasing)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._dispatch_events(reclaimee, allocate=False)
        self.operations.append(("evict", (reclaimee, reason)))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Tentative pipeline (statement.go:126-166)."""
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.Pipelined)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self.ssn._dispatch_events(task, allocate=True)
        self.operations.append(("pipeline", (task, hostname)))

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        """Tentative allocate (statement.go:210-262)."""
        self.ssn.cache.allocate_volumes(task, hostname)
        job = self.ssn.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.Allocated)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        self.ssn._dispatch_events(task, allocate=True)
        self.operations.append(("allocate", (task, hostname)))

    # -------------------------------------------------------------- undo ops

    def _unevict(self, reclaimee: TaskInfo) -> None:
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.Running)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._dispatch_events(reclaimee, allocate=True)

    def _unpipeline(self, task: TaskInfo) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.Pending)
        hostname = task.node_name
        task.node_name = ""
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            node.remove_task(task)
        self.ssn._dispatch_events(task, allocate=False)

    def _unallocate(self, task: TaskInfo) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.Pending)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        task.node_name = ""
        self.ssn._dispatch_events(task, allocate=False)

    # ------------------------------------------------------- commit/discard

    def discard(self) -> None:
        """Undo in reverse order (statement.go:324-346)."""
        for name, args in reversed(self.operations):
            try:
                if name == "evict":
                    self._unevict(args[0])
                elif name == "pipeline":
                    self._unpipeline(args[0])
                elif name == "allocate":
                    self._unallocate(args[0])
            except Exception:  # mirror Go: log and continue
                log.exception("Failed to undo %s", name)
        self.operations.clear()

    def commit(self) -> None:
        """Flush side effects (statement.go:349-367): evict -> cache.evict,
        allocate -> bind volumes + cache.bind (task becomes Binding)."""
        for name, args in self.operations:
            try:
                if name == "evict":
                    self.ssn.cache.evict(args[0], args[1])
                elif name == "pipeline":
                    pass  # no cache side effect
                elif name == "allocate":
                    task = args[0]
                    self.ssn.cache.bind_volumes(task)
                    self.ssn.cache.bind(task, task.node_name)
                    job = self.ssn.jobs.get(task.job)
                    if job is not None:
                        job.update_task_status(task, TaskStatus.Binding)
            except Exception:
                log.exception("Failed to commit %s", name)
        self.operations.clear()
