"""Scheduler YAML configuration schema.

Same YAML shape as the reference (``pkg/scheduler/conf/scheduler_conf.go``)
so existing ``volcano-scheduler.conf`` files work unchanged: an ``actions``
string, plugin ``tiers`` with 11 per-plugin enable flags and free-form
``arguments``, and per-action ``configurations``.  Defaults mirror
``pkg/scheduler/plugins/defaults.go:20-55`` (every flag defaults to enabled
except ``enableBestNode``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import yaml


@dataclass
class PluginOption:
    name: str
    enabled_job_order: Optional[bool] = None
    enabled_namespace_order: Optional[bool] = None
    enabled_job_ready: Optional[bool] = None
    enabled_job_pipelined: Optional[bool] = None
    enabled_task_order: Optional[bool] = None
    enabled_preemptable: Optional[bool] = None
    enabled_reclaimable: Optional[bool] = None
    enabled_queue_order: Optional[bool] = None
    enabled_predicate: Optional[bool] = None
    enabled_best_node: Optional[bool] = None
    enabled_node_order: Optional[bool] = None
    arguments: Dict[str, str] = field(default_factory=dict)

    def apply_defaults(self) -> None:
        """Nil flags default to enabled (defaults.go:20-55); best-node
        stays opt-in."""
        for f in (
            "enabled_job_order",
            "enabled_namespace_order",
            "enabled_job_ready",
            "enabled_job_pipelined",
            "enabled_task_order",
            "enabled_preemptable",
            "enabled_reclaimable",
            "enabled_queue_order",
            "enabled_predicate",
            "enabled_node_order",
        ):
            if getattr(self, f) is None:
                setattr(self, f, True)


@dataclass
class Tier:
    plugins: List[PluginOption] = field(default_factory=list)


@dataclass
class Configuration:
    name: str
    arguments: Dict[str, str] = field(default_factory=dict)


@dataclass
class SchedulerConfiguration:
    actions: str = ""
    tiers: List[Tier] = field(default_factory=list)
    configurations: List[Configuration] = field(default_factory=list)


_YAML_FLAGS = {
    "enableJobOrder": "enabled_job_order",
    "enableNamespaceOrder": "enabled_namespace_order",
    "enableJobReady": "enabled_job_ready",
    "enableJobPipelined": "enabled_job_pipelined",
    "enableTaskOrder": "enabled_task_order",
    "enablePreemptable": "enabled_preemptable",
    "enableReclaimable": "enabled_reclaimable",
    "enableQueueOrder": "enabled_queue_order",
    "enablePredicate": "enabled_predicate",
    "enableBestNode": "enabled_best_node",
    "enableNodeOrder": "enabled_node_order",
}


def parse_scheduler_conf(conf_str: str) -> SchedulerConfiguration:
    """Parse the YAML config and apply plugin defaults
    (pkg/scheduler/util.go loadSchedulerConf)."""
    raw = yaml.safe_load(conf_str) or {}
    conf = SchedulerConfiguration(actions=raw.get("actions", ""))
    for tier_raw in raw.get("tiers") or []:
        tier = Tier()
        for p in tier_raw.get("plugins") or []:
            opt = PluginOption(name=p["name"])
            for yaml_key, attr in _YAML_FLAGS.items():
                if yaml_key in p:
                    setattr(opt, attr, bool(p[yaml_key]))
            opt.arguments = {
                str(k): str(v) for k, v in (p.get("arguments") or {}).items()
            }
            opt.apply_defaults()
            tier.plugins.append(opt)
        conf.tiers.append(tier)
    for c in raw.get("configurations") or []:
        conf.configurations.append(
            Configuration(
                name=c.get("name", ""),
                arguments={
                    str(k): str(v)
                    for k, v in (c.get("arguments") or {}).items()
                },
            )
        )
    return conf


# In-binary default configuration (pkg/scheduler/util.go:31-42).
DEFAULT_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

# Deployed default plus the device-native rebalance lane (ISSUE 5,
# docs/rebalance.md): gang-aware defragmentation with disruption
# budgets.  Separate from DEPLOYED_SCHEDULER_CONF because rebalance
# evicts running pods — an operator opt-in, as the reference family's
# descheduler is a separate deployment.
REBALANCE_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill, rebalance"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""

# Shipped deployment default (installer helm chart config
# volcano-scheduler.conf: adds conformance + binpack).
DEPLOYED_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""
