"""OpenSession / CloseSession (pkg/scheduler/framework/framework.go).

Open: snapshot -> Session, instantiate plugins from the config tiers, run
OnSessionOpen, and evict invalid jobs (writing Unschedulable conditions,
session.go:104-131).  Close: run OnSessionClose, then write job statuses
back to the store (jobUpdater semantics, job_updater.go + session.go
jobStatus).
"""

from __future__ import annotations

import logging
from typing import List, Sequence

from ..api import (
    JobInfo,
    PodGroupCondition,
    PodGroupPhase,
    TaskStatus,
    allocated_status,
)
from ..metrics import metrics
from .arguments import Arguments
from .conf import Configuration, Tier
from .plugins import get_plugin_builder
from .session import Session

log = logging.getLogger(__name__)

POD_GROUP_UNSCHEDULABLE = "Unschedulable"


def open_session(cache, tiers: Sequence[Tier],
                 configurations: Sequence[Configuration] = ()) -> Session:
    ssn = Session(cache, tiers, configurations)

    # Session-open job validation sweep (session.go:107-131).  NOTE: this
    # runs BEFORE plugins register their validators — exactly like the
    # reference, where openSession() precedes plugin.OnSessionOpen — so
    # plugin JobValid checks only gate actions (allocate/preempt/...), not
    # session membership.  Enqueue deliberately sees pod-less Pending
    # PodGroups (delay-pod-creation design).
    for job in list(ssn.jobs.values()):
        if job.pod_group is not None and job.pod_group.status.conditions:
            ssn.pod_group_status[job.uid] = job.pod_group.status
        vr = ssn.job_valid(job)
        if vr is not None:
            if not vr.pass_:
                ssn.update_job_condition(
                    job,
                    PodGroupCondition(
                        type=POD_GROUP_UNSCHEDULABLE,
                        status="True",
                        transition_id=ssn.uid,
                        reason=vr.reason,
                        message=vr.message,
                    ),
                )
            del ssn.jobs[job.uid]

    # Instantiate + open plugins (framework.go:36-50).
    for tier in ssn.tiers:
        for opt in tier.plugins:
            builder = get_plugin_builder(opt.name)
            if builder is None:
                log.warning("Failed to get plugin %s", opt.name)
                continue
            if opt.name not in ssn.plugins:
                plugin = builder(Arguments(opt.arguments))
                ssn.plugins[opt.name] = plugin
    for name, plugin in ssn.plugins.items():
        with metrics.plugin_timer(name, "OnSessionOpen"), \
                ssn.tracer.span(f"plugin:{name}", cat="plugin",
                                args={"phase": "OnSessionOpen"}):
            plugin.on_session_open(ssn)

    log.debug(
        "Open session %s with %d jobs and %d queues",
        ssn.uid, len(ssn.jobs), len(ssn.queues),
    )
    return ssn


def _job_status(ssn: Session, job: JobInfo):
    """Derive the PodGroup status to write back (session.go jobStatus)."""
    status = job.pod_group.status
    unschedulable = any(
        c.type == POD_GROUP_UNSCHEDULABLE
        and c.status == "True"
        and c.transition_id == ssn.uid
        for c in status.conditions
    )
    running_tasks = len(job.task_status_index.get(TaskStatus.Running, {}))
    if running_tasks != 0 and unschedulable:
        status.phase = PodGroupPhase.Unknown.value
    else:
        allocated = 0
        for st, tasks in job.task_status_index.items():
            if allocated_status(st) or st == TaskStatus.Succeeded:
                allocated += len(tasks)
        if allocated >= job.min_available:
            status.phase = PodGroupPhase.Running.value
        elif job.pod_group.status.phase != PodGroupPhase.Inqueue.value:
            status.phase = PodGroupPhase.Pending.value
    status.running = running_tasks
    status.failed = len(job.task_status_index.get(TaskStatus.Failed, {}))
    status.succeeded = len(job.task_status_index.get(TaskStatus.Succeeded, {}))
    return status


def close_session(ssn: Session) -> None:
    for name, plugin in ssn.plugins.items():
        with metrics.plugin_timer(name, "OnSessionClose"), \
                ssn.tracer.span(f"plugin:{name}", cat="plugin",
                                args={"phase": "OnSessionClose"}):
            plugin.on_session_close(ssn)

    # jobUpdater.UpdateAll: push PodGroup statuses back to the store.
    for job in ssn.jobs.values():
        if job.pod_group is None:
            continue
        job.pod_group.status = _job_status(ssn, job)
        ssn.cache.update_job_status(job)

    ssn.jobs = {}
    ssn.nodes = {}
    ssn.plugins = {}
    ssn.event_handlers = []
    log.debug("Close session %s", ssn.uid)
