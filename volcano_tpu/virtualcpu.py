"""Virtual CPU platform override, shared by tests/conftest.py and
``__graft_entry__.dryrun_multichip``.

Multi-chip sharding is validated on a virtual N-device CPU mesh
(``xla_force_host_platform_device_count``), matching how the driver
dry-runs the multi-chip path without N real chips.  The environment's TPU
plugin pins ``jax_platforms`` at interpreter startup — before any of our
code runs — so setting the env vars is not enough: the live jax config
must also be overridden after import.

This module intentionally imports jax only inside the function, so callers
can set the env vars before jax's first import when they are early enough
(conftest is; a driver calling ``dryrun_multichip`` may not be — the
post-import config update covers that case, and the final device-count
check catches the one unrecoverable ordering: jax already *initialized*
with too few devices).
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def force_virtual_cpu_platform(n_devices: int = 8) -> None:
    """Pin JAX to the virtual-CPU platform with >= ``n_devices`` devices.

    Raises RuntimeError if jax was already initialized with fewer virtual
    CPU devices than requested (the override can then no longer take
    effect in this process).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"--{_FLAG}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (flags + f" --{_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = re.sub(
            rf"--{_FLAG}=\d+", f"--{_FLAG}={n_devices}", flags
        )
    os.environ.setdefault("JAX_ENABLE_X64", "0")

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        cpus = jax.devices("cpu")
    except RuntimeError as e:
        # Backends already initialized TPU-only: jax raises its own
        # "Unknown backend cpu" with no hint at the real problem.
        raise RuntimeError(
            "jax backends were initialized before the virtual-CPU "
            "platform override could take effect — call "
            "force_virtual_cpu_platform (or dryrun_multichip) in a "
            f"fresh process (underlying error: {e})"
        ) from e
    if len(cpus) < n_devices:
        raise RuntimeError(
            f"virtual CPU platform has {len(cpus)} devices, need "
            f"{n_devices}; jax was initialized before the platform "
            "override could take effect — call force_virtual_cpu_platform "
            "(or dryrun_multichip) in a fresh process"
        )
