"""Job state machine (pkg/controllers/job/state/): 8 states, each mapping a
bus Action to SyncJob/KillJob plus a phase-transition closure.

Pod-retain semantics (state/factory.go): ``PodRetainPhaseNone`` kills every
pod; ``PodRetainPhaseSoft`` retains Succeeded/Failed pods.
"""

from __future__ import annotations

from typing import Callable, Optional, Set

from .apis import Action, DEFAULT_MAX_RETRY, Job, JobPhase, JobStatus

POD_RETAIN_PHASE_NONE: Set[str] = set()
POD_RETAIN_PHASE_SOFT: Set[str] = {"Succeeded", "Failed"}

UpdateStatusFn = Optional[Callable[[JobStatus], bool]]


class State:
    """Base: execute(action) drives sync_job/kill_job on the controller."""

    def __init__(self, ctrl, job: Job):
        self.ctrl = ctrl
        self.job = job

    def execute(self, action: str) -> None:
        raise NotImplementedError


def _phase(status: JobStatus, phase: JobPhase) -> bool:
    status.state.phase = phase.value
    return True


class PendingState(State):
    def execute(self, action: str) -> None:
        job = self.job
        if action == Action.RestartJob.value:
            def f(s):
                s.retry_count += 1
                return _phase(s, JobPhase.Restarting)
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_NONE, f)
        elif action == Action.AbortJob.value:
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT,
                               lambda s: _phase(s, JobPhase.Aborting))
        elif action == Action.CompleteJob.value:
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT,
                               lambda s: _phase(s, JobPhase.Completing))
        elif action == Action.TerminateJob.value:
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT,
                               lambda s: _phase(s, JobPhase.Terminating))
        else:
            def f(s):
                if job.min_available <= s.running + s.succeeded + s.failed:
                    return _phase(s, JobPhase.Running)
                return False
            self.ctrl.sync_job(job, f)


class RunningState(State):
    def execute(self, action: str) -> None:
        job = self.job
        if action == Action.RestartJob.value:
            def f(s):
                s.retry_count += 1
                return _phase(s, JobPhase.Restarting)
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_NONE, f)
        elif action == Action.AbortJob.value:
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT,
                               lambda s: _phase(s, JobPhase.Aborting))
        elif action == Action.TerminateJob.value:
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT,
                               lambda s: _phase(s, JobPhase.Terminating))
        elif action == Action.CompleteJob.value:
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT,
                               lambda s: _phase(s, JobPhase.Completing))
        else:
            def f(s):
                total = job.total_tasks()
                if s.succeeded + s.failed == total:
                    if s.succeeded >= job.min_available:
                        return _phase(s, JobPhase.Completed)
                    return _phase(s, JobPhase.Failed)
                return False
            self.ctrl.sync_job(job, f)


class RestartingState(State):
    def execute(self, action: str) -> None:
        job = self.job

        def f(s):
            max_retry = job.max_retry or DEFAULT_MAX_RETRY
            if s.retry_count >= max_retry:
                return _phase(s, JobPhase.Failed)
            total = job.total_tasks()
            if total - s.terminating >= s.min_available:
                return _phase(s, JobPhase.Pending)
            return False

        self.ctrl.kill_job(job, POD_RETAIN_PHASE_NONE, f)


class AbortingState(State):
    def execute(self, action: str) -> None:
        job = self.job
        if action == Action.ResumeJob.value:
            def f(s):
                s.retry_count += 1
                return _phase(s, JobPhase.Restarting)
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT, f)
        else:
            def f(s):
                if s.terminating or s.pending or s.running:
                    return False
                return _phase(s, JobPhase.Aborted)
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT, f)


class AbortedState(State):
    def execute(self, action: str) -> None:
        job = self.job
        if action == Action.ResumeJob.value:
            def f(s):
                s.retry_count += 1
                return _phase(s, JobPhase.Restarting)
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT, f)
        else:
            self.ctrl.kill_job(job, POD_RETAIN_PHASE_SOFT, None)


class TerminatingState(State):
    def execute(self, action: str) -> None:
        def f(s):
            if s.terminating or s.pending or s.running:
                return False
            return _phase(s, JobPhase.Terminated)

        self.ctrl.kill_job(self.job, POD_RETAIN_PHASE_SOFT, f)


class CompletingState(State):
    def execute(self, action: str) -> None:
        def f(s):
            if s.terminating or s.pending or s.running:
                return False
            return _phase(s, JobPhase.Completed)

        self.ctrl.kill_job(self.job, POD_RETAIN_PHASE_SOFT, f)


class FinishedState(State):
    """Completed/Failed/Terminated: only ensure lingering pods are gone
    (state/finished.go)."""

    def execute(self, action: str) -> None:
        self.ctrl.kill_job(self.job, POD_RETAIN_PHASE_SOFT, None)


def new_state(ctrl, job: Job) -> State:
    """state/factory.go NewState."""
    phase = job.status.state.phase
    if phase in (JobPhase.Pending.value, ""):
        return PendingState(ctrl, job)
    if phase == JobPhase.Running.value:
        return RunningState(ctrl, job)
    if phase == JobPhase.Restarting.value:
        return RestartingState(ctrl, job)
    if phase == JobPhase.Aborting.value:
        return AbortingState(ctrl, job)
    if phase == JobPhase.Aborted.value:
        return AbortedState(ctrl, job)
    if phase == JobPhase.Terminating.value:
        return TerminatingState(ctrl, job)
    if phase == JobPhase.Completing.value:
        return CompletingState(ctrl, job)
    return FinishedState(ctrl, job)
