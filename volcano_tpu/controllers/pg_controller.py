"""PodGroup controller (pkg/controllers/podgroup).

Auto-creates a gang-of-1 PodGroup for plain pods lacking one and
back-annotates the pod (pg_controller_handler.go:50,72-105), so bare pods
still flow through gang scheduling.
"""

from __future__ import annotations

import copy
import logging
from collections import deque

from ..api import GROUP_NAME_ANNOTATION, Pod, PodGroup
from ..cache import ClusterStore

log = logging.getLogger(__name__)


class PodGroupController:
    def __init__(self, store: ClusterStore):
        self.store = store
        self.queue = deque()
        store.watch(self._on_store_event)

    def _on_store_event(self, kind: str, event: str, obj) -> None:
        if kind == "Pod" and event == "add":
            if not obj.annotations.get(GROUP_NAME_ANNOTATION):
                self.queue.append(obj.uid)

    def process_all(self) -> None:
        while self.queue:
            uid = self.queue.popleft()
            pod = self.store.pods.get(uid)
            if pod is None or pod.annotations.get(GROUP_NAME_ANNOTATION):
                continue
            pg_name = f"podgroup-{pod.uid}"
            if f"{pod.namespace}/{pg_name}" not in self.store.pod_groups:
                self.store.add_pod_group(
                    PodGroup(
                        name=pg_name,
                        namespace=pod.namespace,
                        min_member=1,
                        priority_class=pod.priority_class,
                    )
                )
            updated = copy.copy(pod)
            updated.annotations = dict(pod.annotations)
            updated.annotations[GROUP_NAME_ANNOTATION] = pg_name
            self.store.update_pod(updated)
