"""Controller plane: job lifecycle, podgroup wrapping, queues, GC.

``ControllerManager`` aggregates the controllers the reference's
vc-controller-manager starts (cmd/controller-manager/app/server.go).
"""

from __future__ import annotations

from ..cache import ClusterStore
from .apis import (
    Action,
    Command,
    DEFAULT_MAX_RETRY,
    Event,
    Job,
    JobPhase,
    JobState,
    JobStatus,
    LifecyclePolicy,
    Request,
    TaskSpec,
    VolumeSpec,
)
from .gc import GarbageCollector
from .job_controller import JobController, apply_policies
from .pg_controller import PodGroupController
from .queue_controller import QueueController


class ControllerManager:
    """All controllers wired to one store; process() runs each to
    quiescence (one reconcile pump)."""

    def __init__(self, store: ClusterStore):
        self.store = store
        self.job_controller = JobController(store)
        self.pg_controller = PodGroupController(store)
        self.queue_controller = QueueController(store)
        self.gc = GarbageCollector(store)

    def process(self) -> None:
        self.pg_controller.process_all()
        self.job_controller.process_all()
        self.queue_controller.process_all()
        self.gc.sweep()


__all__ = [
    "Action",
    "Command",
    "ControllerManager",
    "DEFAULT_MAX_RETRY",
    "Event",
    "GarbageCollector",
    "Job",
    "JobController",
    "JobPhase",
    "JobState",
    "JobStatus",
    "LifecyclePolicy",
    "PodGroupController",
    "QueueController",
    "Request",
    "TaskSpec",
    "VolumeSpec",
    "apply_policies",
]
