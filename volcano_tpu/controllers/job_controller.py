"""Job controller: reconciles batch Jobs into PodGroups + Pods.

Mirrors ``pkg/controllers/job``: store events become Requests
(job_controller_handler.go), ``applyPolicies`` maps request events through
task- then job-level lifecycle policies (job_controller_util.go:110-184),
and the state machine (``state.py``) drives ``sync_job``/``kill_job``
(job_controller_actions.go):

- initiate: create the PodGroup (with MinResources aggregated from the
  highest-priority MinAvailable tasks, job_controller_actions.go:545) and
  run job plugins (svc/ssh/env rendezvous wiring)
- GATE: pods are only created once the PodGroup leaves Pending
  (job_controller_actions.go:227-231) — i.e. after the scheduler's enqueue
  action admits the job
- sync: diff desired vs actual pods per task (create/delete for scale
  up/down), classify pod phases into status counters
- kill: delete non-retained pods, bump job version, delete the PodGroup

The controller is synchronous against the store: ``process_all()`` drains
the request queue (the reference's sharded worker loop collapses to this in
a single-process store-of-record design).
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Set

from ..api import (
    GROUP_NAME_ANNOTATION,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodPhase,
    Resource,
)
from ..cache import ClusterStore
from .apis import Action, Event, Job, JobPhase, JobStatus, Request
from .job_plugins import get_job_plugin
from .state import new_state

log = logging.getLogger(__name__)

FINISHED_PHASES = (
    JobPhase.Completed.value,
    JobPhase.Failed.value,
    JobPhase.Terminated.value,
)


def apply_policies(job: Job, req: Request) -> str:
    """job_controller_util.go:110-184."""
    if req.action:
        return req.action
    if req.event == Event.OutOfSync.value:
        return Action.SyncJob.value
    if req.job_version < job.status.version:
        return Action.SyncJob.value

    def match(policies) -> Optional[str]:
        for policy in policies:
            events = policy.event_list()
            if events and req.event:
                if req.event in events or Event.Any.value in events:
                    return policy.action
            if policy.exit_code is not None and policy.exit_code == req.exit_code:
                return policy.action
        return None

    if req.task_name:
        for task in job.tasks:
            if task.name == req.task_name:
                action = match(task.policies)
                if action:
                    return action
                break
    action = match(job.policies)
    if action:
        return action
    return Action.SyncJob.value


class JobController:
    def __init__(self, store: ClusterStore):
        self.store = store
        self.queue: Deque[Request] = deque()
        # Jobs whose sync failed on missing IO (named PVC not yet
        # created): retried at the next reconcile pump — the analog of
        # the reference's rate-limited workqueue requeue on syncJob error.
        self._retry_keys: set = set()
        store.watch(self._on_store_event)

    # ------------------------------------------------------------- watchers

    def _on_store_event(self, kind: str, event: str, obj) -> None:
        if kind == "Job":
            if event in ("add", "update"):
                self.queue.append(
                    Request(namespace=obj.namespace, job_name=obj.name,
                            event=Event.OutOfSync.value)
                )
            elif event == "delete":
                self._cleanup_job(obj)
        elif kind == "Pod":
            pod = obj
            if not pod.owner_job:
                return
            ns, name = pod.owner_job.split("/", 1)
            # The pod carries the job version it was created under
            # (job_controller_handler.go:154-178), so stale-generation pod
            # events degrade to sync instead of firing policies.
            version = int(pod.annotations.get("volcano-tpu/job-version", "0"))
            if event == "update":
                if pod.phase == PodPhase.Failed:
                    self.queue.append(
                        Request(namespace=ns, job_name=name,
                                task_name=pod.task_name,
                                event=Event.PodFailed.value,
                                exit_code=pod.exit_code,
                                job_version=version)
                    )
                elif pod.phase == PodPhase.Succeeded:
                    self.queue.append(
                        Request(namespace=ns, job_name=name,
                                task_name=pod.task_name,
                                event=Event.TaskCompleted.value,
                                job_version=version)
                    )
                else:
                    self.queue.append(
                        Request(namespace=ns, job_name=name,
                                event=Event.OutOfSync.value)
                    )
            elif event == "evict":
                self.queue.append(
                    Request(namespace=ns, job_name=name,
                            task_name=pod.task_name,
                            event=Event.PodEvicted.value,
                            job_version=version)
                )
            elif event == "delete":
                self.queue.append(
                    Request(namespace=ns, job_name=name,
                            event=Event.OutOfSync.value)
                )
        elif kind == "Node" and event == "update":
            # Device/node health: a node going NotReady raises
            # DeviceUnhealthy for every job with pods on it (TPU-native
            # failure event, SURVEY.md 5.3).
            node_info = self.store.nodes.get(obj.name)
            if obj.ready or node_info is None:
                return
            for resident in node_info.tasks.values():
                pod = resident.pod
                if not pod.owner_job:
                    continue
                ns, name = pod.owner_job.split("/", 1)
                self.queue.append(
                    Request(
                        namespace=ns, job_name=name,
                        task_name=pod.task_name,
                        event=Event.DeviceUnhealthy.value,
                        job_version=int(
                            pod.annotations.get("volcano-tpu/job-version", "0")
                        ),
                    )
                )
        elif kind == "PodGroup" and event == "status":
            if obj.owner_job:
                ns, name = obj.owner_job.split("/", 1)
                self.queue.append(
                    Request(namespace=ns, job_name=name,
                            event=Event.OutOfSync.value)
                )
        elif kind == "Command" and event == "add":
            if obj.target_kind == "Job":
                self.store.delete_command(obj.name)
                self.queue.append(
                    Request(
                        namespace=obj.target_namespace,
                        job_name=obj.target_name,
                        event=Event.CommandIssued.value,
                        action=obj.action,
                    )
                )

    # ------------------------------------------------------------- requests

    def process_all(self, max_iters: int = 10000) -> None:
        if self._retry_keys:
            retry, self._retry_keys = self._retry_keys, set()
            for key in retry:
                ns, name = key.split("/", 1)
                self.queue.append(
                    Request(namespace=ns, job_name=name,
                            event=Event.OutOfSync.value)
                )
        iters = 0
        while self.queue and iters < max_iters:
            req = self.queue.popleft()
            iters += 1
            try:
                self._process(req)
            except Exception:
                log.exception("Failed to process request %s", req)

    def _process(self, req: Request) -> None:
        key = f"{req.namespace}/{req.job_name}"
        job = self.store.batch_jobs.get(key)
        if job is None:
            return
        action = apply_policies(job, req)
        phase_before = job.status.state.phase
        state = new_state(self, job)
        state.execute(action)
        if job.status.state.phase != phase_before:
            # A phase transition re-queues the job (the reference's status
            # update round-trips through the informer into a new request).
            self.queue.append(
                Request(namespace=req.namespace, job_name=req.job_name,
                        event=Event.OutOfSync.value)
            )

    # --------------------------------------------------------------- helpers

    def _job_pods(self, job: Job) -> List[Pod]:
        return [
            p for p in self.store.pods.values() if p.owner_job == job.key
        ]

    def _classify(self, pods: List[Pod]) -> Dict[str, int]:
        counts = {"pending": 0, "running": 0, "succeeded": 0, "failed": 0,
                  "terminating": 0, "unknown": 0}
        for pod in pods:
            if pod.deleting:
                counts["terminating"] += 1
            elif pod.phase == PodPhase.Pending:
                counts["pending"] += 1
            elif pod.phase == PodPhase.Running:
                counts["running"] += 1
            elif pod.phase == PodPhase.Succeeded:
                counts["succeeded"] += 1
            elif pod.phase == PodPhase.Failed:
                counts["failed"] += 1
            else:
                counts["unknown"] += 1
        return counts

    def _plugins(self, job: Job):
        out = []
        for name, args in job.plugins.items():
            plugin = get_job_plugin(name, args)
            if plugin is not None:
                out.append(plugin)
        return out

    def _calc_pg_min_resources(self, job: Job) -> Dict[str, object]:
        """Sum requests of the MinAvailable highest-priority task replicas
        (job_controller_actions.go calcPGMinResources, simplified: spec
        order stands in for priority-class ordering)."""
        total = Resource.empty()
        remaining = job.min_available
        for task in job.tasks:
            per_replica = Resource.empty()
            for c in task.containers:
                per_replica.add(Resource.from_resource_list(c))
            n = min(task.replicas, max(remaining, 0))
            for _ in range(n):
                total.add(per_replica)
            remaining -= n
            if remaining <= 0:
                break
        out = {
            "cpu": f"{int(total.milli_cpu)}m",
            "memory": total.memory,
        }
        # Extended/scalar resources (TPUs etc.) must survive into
        # MinResources or the enqueue gate can't see the demand.
        if total.scalars:
            for name, quant in total.scalars.items():
                out[name] = f"{int(quant)}m"
        return out

    def _create_job_io(self, job: Job) -> bool:
        """PVC creation for the job's volumes (createJobIOIfNotExist,
        job_controller_actions.go:394-460).  Returns False when a named
        claim is missing — the job stays Pending (no PodGroup, no pods)
        until the claim appears, exactly the reference's behavior."""
        for vol in job.volumes:
            name = vol.volume_claim_name
            if not name and vol.volume_claim is None:
                # Unvalidated submission path (raw store.add_batch_job
                # bypasses admission): flag instead of generating a name
                # for a claim that can never exist.
                self.store.record_event(
                    f"Job/{job.key}", "InvalidVolume",
                    "either volumeClaim or volumeClaimName must be "
                    "specified",
                )
                return False
            if not name:
                # Generate a unique claim name and persist it on the
                # spec (GenPVCName + spec update, :404-420).
                from ..api import new_uid

                while True:
                    name = f"{job.name}-volume-{new_uid('pvc')[-12:]}"
                    if f"{job.namespace}/{name}" not in self.store.pvcs:
                        break
                vol.volume_claim_name = name
            if f"{job.namespace}/{name}" not in self.store.pvcs:
                if vol.volume_claim is not None:
                    # Controller-owned claim: create it — including
                    # recreating one that vanished after a restart or
                    # out-of-band delete (we still hold the spec).
                    self.store.put_pvc(job.namespace, name,
                                       vol.volume_claim,
                                       owner_job=job.key)
                else:
                    self.store.record_event(
                        f"Job/{job.key}", "PVCNotFound",
                        f"pvc {name} is not found, the job will be in "
                        "the Pending state until the PVC is created",
                    )
                    return False
            job.status.controlled_resources[f"volume-pvc-{name}"] = name
        return True

    def _initiate_job(self, job: Job) -> bool:
        """+finalizer, phase Pending, PVCs, PodGroup, plugins
        (job_controller_actions.go:144-176,394-531).  Returns False when
        job IO isn't ready yet (missing claim): the sync is retried."""
        if "volcano-tpu/job-cleanup" not in job.finalizers:
            job.finalizers.append("volcano-tpu/job-cleanup")
        if not job.status.state.phase:
            job.status.state.phase = JobPhase.Pending.value
        job.status.min_available = job.min_available

        if not self._create_job_io(job):
            return False

        pg_uid = f"{job.namespace}/{job.name}"
        if pg_uid not in self.store.pod_groups:
            pg = PodGroup(
                name=job.name,
                namespace=job.namespace,
                min_member=job.min_available,
                queue=job.queue,
                priority_class=job.priority_class,
                min_resources=self._calc_pg_min_resources(job),
                owner_job=job.key,
            )
            self.store.add_pod_group(pg)
        for plugin in self._plugins(job):
            # Run each plugin's job-add hook once per job generation
            # (the reference guards via Status.ControlledResources,
            # svc/svc.go:128) — re-running would e.g. rotate ssh keys.
            marker = f"plugin-{plugin.name}"
            if marker in job.status.controlled_resources:
                continue
            plugin.on_job_add(job, self.store)
            job.status.controlled_resources[marker] = plugin.name
        return True

    def _pod_name(self, job: Job, task, index: int) -> str:
        return f"{job.name}-{task.name}-{index}"

    def _create_pod(self, job: Job, task, index: int, global_index: int) -> Pod:
        pod = Pod(
            name=self._pod_name(job, task, index),
            namespace=job.namespace,
            containers=[dict(c) for c in task.containers],
            init_containers=[dict(c) for c in task.init_containers],
            labels={
                **task.labels,
                "volcano-tpu/job-name": job.name,
                "volcano-tpu/job-namespace": job.namespace,
                "volcano-tpu/task-spec": task.name,
            },
            annotations={
                GROUP_NAME_ANNOTATION: job.name,
                "volcano-tpu/task-index": str(index),
                "volcano-tpu/global-index": str(global_index),
                "volcano-tpu/job-version": str(job.status.version),
            },
            node_selector=dict(task.node_selector),
            tolerations=list(task.tolerations),
            host_ports=list(task.host_ports),
            env=dict(task.env),
            priority_class=job.priority_class,
            owner_job=job.key,
            task_name=task.name,
        )
        # Mount the job's volumes, one entry per claim (duplicate claim
        # names collapse to the first mount, job_controller_util.go:56-78).
        seen_claims = set()
        for vol in job.volumes:
            cn = vol.volume_claim_name
            if not cn or cn in seen_claims:
                continue
            seen_claims.add(cn)
            pod.volumes.append((cn, vol.mount_path))
        for plugin in self._plugins(job):
            plugin.on_pod_create(pod, job)
        return pod

    # ---------------------------------------------------------- sync / kill

    def sync_job(self, job: Job, update_status) -> None:
        if job.deleting:
            return
        if not self._initiate_job(job):
            # Missing claim: job stays Pending, re-synced next reconcile
            # (initiateJob error return, job_controller_actions.go:144).
            self._retry_keys.add(job.key)
            self.store.batch_jobs[job.key] = job
            return

        pods = self._job_pods(job)
        pg = self.store.pod_groups.get(f"{job.namespace}/{job.name}")
        # Pod creation gate (job_controller_actions.go:227-231).
        gate_open = pg is not None and pg.status.phase not in (
            "", PodGroupPhase.Pending.value
        )
        if gate_open:
            existing: Dict[str, Pod] = {p.name: p for p in pods}
            desired: Set[str] = set()
            global_index = 0
            for task in job.tasks:
                for i in range(task.replicas):
                    name = self._pod_name(job, task, i)
                    desired.add(name)
                    if name not in existing:
                        self.store.add_pod(
                            self._create_pod(job, task, i, global_index)
                        )
                    global_index += 1
            # Scale down: delete pods beyond desired replicas.
            for pod in pods:
                if pod.name not in desired and not pod.deleting:
                    self._delete_pod(pod)
            pods = self._job_pods(job)

        counts = self._classify(pods)
        job.status.pending = counts["pending"]
        job.status.running = counts["running"]
        job.status.succeeded = counts["succeeded"]
        job.status.failed = counts["failed"]
        job.status.terminating = counts["terminating"]
        job.status.unknown = counts["unknown"]
        job.status.min_available = job.min_available
        if update_status is not None and update_status(job.status):
            job.status.state.last_transition = time.time()
        self.store.batch_jobs[job.key] = job

    def kill_job(self, job: Job, retain_phases: Set[str], update_status) -> None:
        if job.deleting:
            return
        pods = self._job_pods(job)
        for pod in pods:
            if pod.deleting:
                continue
            if pod.phase in retain_phases:
                continue
            self._delete_pod(pod)
        counts = self._classify(self._job_pods(job))
        job.status = JobStatus(
            state=job.status.state,
            pending=counts["pending"],
            running=counts["running"],
            succeeded=counts["succeeded"],
            failed=counts["failed"],
            terminating=counts["terminating"],
            unknown=counts["unknown"],
            version=job.status.version + 1,
            min_available=job.min_available,
            retry_count=job.status.retry_count,
            controlled_resources=job.status.controlled_resources,
        )
        if update_status is not None and update_status(job.status):
            job.status.state.last_transition = time.time()
        # Delete the PodGroup (kill path).
        self.store.delete_pod_group(f"{job.namespace}/{job.name}")
        for plugin in self._plugins(job):
            plugin.on_job_delete(job, self.store)
        self.store.batch_jobs[job.key] = job

    def _delete_pod(self, pod: Pod) -> None:
        """Mark the pod terminating (the simulated kubelet finishes the
        deletion), mirroring the async pod Delete."""
        import copy as _copy

        updated = _copy.copy(pod)
        updated.deleting = True
        self.store.update_pod(updated)

    def _cleanup_job(self, job: Job) -> None:
        for pod in self._job_pods(job):
            self._delete_pod(pod)
        self.store.delete_pod_group(f"{job.namespace}/{job.name}")
        # Controller-created claims carry the job as owner and die with
        # it (owner refs on createPVC, job_controller_actions.go:512-531).
        self.store.delete_pvcs_owned_by(job.key)
        for plugin in self._plugins(job):
            plugin.on_job_delete(job, self.store)
