"""Queue controller (pkg/controllers/queue).

Reconciles Queue status (PodGroup phase counts,
queue_controller_action.go:34-82) and the open/close lifecycle driven by
commands (queue_controller.go:268-330; 5-state machine in queue/state/):
Open/Closed/Closing with CloseQueue draining to Closed once no PodGroups
remain, OpenQueue reopening.
"""

from __future__ import annotations

import logging
from collections import deque
from dataclasses import dataclass, field
from typing import Dict

from ..api import PodGroupPhase, QueueState
from ..cache import ClusterStore
from .apis import Action

log = logging.getLogger(__name__)


@dataclass
class QueueStatus:
    state: str = QueueState.Open.value
    pending: int = 0
    running: int = 0
    unknown: int = 0
    inqueue: int = 0


class QueueController:
    def __init__(self, store: ClusterStore):
        self.store = store
        self.queue = deque()
        self.status: Dict[str, QueueStatus] = {}
        store.watch(self._on_store_event)

    def _on_store_event(self, kind: str, event: str, obj) -> None:
        if kind == "Queue":
            name = obj if isinstance(obj, str) else obj.name
            self.queue.append((Action.SyncQueue.value, name))
        elif kind == "PodGroup":
            pg = obj
            if hasattr(pg, "queue"):
                self.queue.append((Action.SyncQueue.value, pg.queue))
        elif kind == "Command" and event == "add":
            if obj.target_kind == "Queue":
                self.store.delete_command(obj.name)
                action = (
                    Action.OpenQueue.value
                    if obj.action == Action.OpenQueue.value
                    else Action.CloseQueue.value
                    if obj.action == Action.CloseQueue.value
                    else Action.SyncQueue.value
                )
                self.queue.append((action, obj.target_name))

    # ------------------------------------------------------------- process

    def process_all(self) -> None:
        while self.queue:
            action, name = self.queue.popleft()
            queue = self.store.raw_queues.get(name)
            if queue is None:
                self.status.pop(name, None)
                continue
            status = self.status.setdefault(name, QueueStatus(state=queue.state))
            if action == Action.OpenQueue.value:
                queue.state = QueueState.Open.value
            elif action == Action.CloseQueue.value:
                queue.state = QueueState.Closing.value
            self._sync(queue, status)

    def _sync(self, queue, status: QueueStatus) -> None:
        counts = {"Pending": 0, "Running": 0, "Unknown": 0, "Inqueue": 0}
        total = 0
        for pg in self.store.pod_groups.values():
            if pg.queue != queue.name:
                continue
            total += 1
            counts[pg.status.phase] = counts.get(pg.status.phase, 0) + 1
        status.pending = counts["Pending"]
        status.running = counts["Running"]
        status.unknown = counts["Unknown"]
        status.inqueue = counts["Inqueue"]
        # Closing drains to Closed once empty (queue/state machine).
        if queue.state == QueueState.Closing.value and total == 0:
            queue.state = QueueState.Closed.value
        status.state = queue.state
