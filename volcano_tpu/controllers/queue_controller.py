"""Queue controller (pkg/controllers/queue).

Reconciles Queue status — PodGroup phase counts
(queue_controller_action.go:34-82) — and the open/close lifecycle driven
by Commands (queue_controller.go:268-330) through the reference's 5-state
machine (queue/state/{factory,open,closed,closing,unknown}.go; "" is
treated as Open, factory.go NewState).

Parity notes (each anchored to the reference):

- The PodGroup set per queue is an incrementally-maintained index
  (queue_controller.go ``podGroups`` map + handler updates,
  queue_controller_handler.go addPodGroup/deletePodGroup), not a scan
  over every PodGroup per sync; phase-only updates re-enqueue a sync
  (updatePodGroup: "if oldPG.Status.Phase != newPG.Status.Phase").
- Open/Close transitions record events on the queue: Normal
  "Open queue succeed"/"Close queue succeed" on an actual state change,
  Warning with the failure on error (queue_controller_action.go
  openQueue/closeQueue recorder.Event calls).
- Status write-back is skipped when nothing changed
  (queue_controller_action.go:70 "ignore update when status does not
  change").
- Failed requests retry up to ``MAX_RETRIES`` (=15, queue_controller.go
  maxRetries) and are then dropped with a Warning event naming the
  action (queue_controller.go handleQueueErr → recordEventsForQueue).
- State-machine quirk reproduced verbatim: a plain Sync on a *Closing*
  queue lands in **Unknown** — closing.go's default branch reads the
  status state ("Closing"), which is neither Open nor Closed, and falls
  through to QueueStateUnknown.  Draining Closing→Closed happens through
  an explicit CloseQueue action when the queue has emptied (closing.go
  CloseQueueAction branch), not through passive syncs.
"""

from __future__ import annotations

import logging
from collections import deque
from dataclasses import dataclass
from typing import Dict, Set

from ..api import PodGroupPhase, QueueState
from ..cache import ClusterStore
from .apis import Action

log = logging.getLogger(__name__)

# queue_controller.go:50-55 maxRetries.
MAX_RETRIES = 15

_OPEN = QueueState.Open.value
_CLOSED = QueueState.Closed.value
_CLOSING = QueueState.Closing.value
_UNKNOWN = QueueState.Unknown.value


@dataclass
class QueueStatus:
    """v1beta1.QueueStatus: state + per-phase PodGroup counts."""

    state: str = _OPEN
    pending: int = 0
    running: int = 0
    unknown: int = 0
    inqueue: int = 0


class QueueController:
    """Poll-driven analog of the reference's queue controller workers."""

    def __init__(self, store: ClusterStore):
        self.store = store
        self.queue = deque()
        self.status: Dict[str, QueueStatus] = {}
        # queue name -> set of PodGroup uids (queue_controller.go podGroups)
        # plus the reverse map, so a PodGroup that moves queues (or is
        # deleted by uid) is removed from its OLD queue's set.
        self.pod_groups: Dict[str, Set[str]] = {}
        self._pg_queue: Dict[str, str] = {}
        # Last-seen PodGroup phase, so updates re-enqueue a sync only on
        # an actual phase change (updatePodGroup's
        # "oldPG.Status.Phase != newPG.Status.Phase" gate) — the store
        # passes only the new object, so the old phase is tracked here.
        self._pg_phase: Dict[str, str] = {}
        self._retries: Dict[tuple, int] = {}
        store.watch(self._on_store_event)

    # ------------------------------------------------------------- handlers

    def _enqueue(self, action: str, name: str) -> None:
        self.queue.append((action, name))

    def _on_store_event(self, kind: str, event: str, obj) -> None:
        if kind == "Queue":
            name = obj if isinstance(obj, str) else obj.name
            if event == "delete":
                # deleteQueue handler: drop the PodGroup index entry.
                self.pod_groups.pop(name, None)
                self.status.pop(name, None)
                return
            # addQueue → SyncQueue.  updateQueue is an explicit no-op in
            # the reference ("currently do not care about queue update",
            # queue_controller_handler.go) — and must be here too: this
            # controller's own write-backs arrive as update events, and
            # reacting to them would self-drive a Closing queue into
            # Unknown with no external cause (Sync-on-Closing derives
            # Unknown, closing.go default branch).
            if event == "add":
                self._enqueue(Action.SyncQueue.value, name)
        elif kind == "PodGroup":
            if event == "delete":
                # The store notifies deletes by uid (the object is gone);
                # the reference recovers the queue from the informer
                # tombstone — here the reverse map is the tombstone.
                uid = obj if isinstance(obj, str) else obj.uid
                old = self._pg_queue.pop(uid, None)
                self._pg_phase.pop(uid, None)
                if old is not None:
                    members = self.pod_groups.get(old)
                    if members is not None:
                        members.discard(uid)
                    self._enqueue(Action.SyncQueue.value, old)
                return
            pg = obj
            qname = getattr(pg, "queue", None)
            if qname is None:
                return
            uid = getattr(pg, "uid", None) or getattr(pg, "name", "")
            old = self._pg_queue.get(uid)
            moved = old is not None and old != qname
            if moved:
                # Queue move: drop from the old set so the group is not
                # double-counted and the old queue can drain.
                members = self.pod_groups.get(old)
                if members is not None:
                    members.discard(uid)
                self._enqueue(Action.SyncQueue.value, old)
            first_seen = old is None
            self._pg_queue[uid] = qname
            self.pod_groups.setdefault(qname, set()).add(uid)
            phase = getattr(getattr(pg, "status", None), "phase", "")
            phase_changed = self._pg_phase.get(uid) != phase
            self._pg_phase[uid] = phase
            # addPodGroup always syncs; updatePodGroup only on a phase
            # change ("if oldPG.Status.Phase != newPG.Status.Phase",
            # queue_controller_handler.go) or a queue move — a spec-only
            # update must NOT re-sync (a Sync on a Closing queue derives
            # Unknown, so a no-op update would corrupt the state).
            if event == "add" or first_seen or moved or phase_changed:
                self._enqueue(Action.SyncQueue.value, qname)
        elif kind == "Command" and event == "add":
            if obj.target_kind == "Queue":
                # handleCommand: delete the Command, enqueue the request.
                self.store.delete_command(obj.name)
                action = (
                    obj.action
                    if obj.action in (Action.OpenQueue.value,
                                      Action.CloseQueue.value)
                    else Action.SyncQueue.value
                )
                self._enqueue(action, obj.target_name)

    # ------------------------------------------------------------- process

    def process_all(self) -> None:
        # Requeued items append to the tail; bound the walk to the items
        # present now so a persistently-failing request cannot spin this
        # call forever (the reference's rate limiter provides the same
        # backpressure through delays).
        for _ in range(len(self.queue)):
            if not self.queue:
                break
            action, name = self.queue.popleft()
            try:
                self._handle_queue(action, name)
            except Exception as e:  # handleQueueErr
                key = (action, name)
                n = self._retries.get(key, 0)
                if n < MAX_RETRIES:
                    self._retries[key] = n + 1
                    self.queue.append((action, name))
                else:
                    self._retries.pop(key, None)
                    self.store.record_event(
                        f"Queue/{name}", action,
                        f"{action} queue failed for {e}",
                    )
                    log.warning("Dropping queue request %s/%s: %s",
                                action, name, e)
            else:
                self._retries.pop((action, name), None)

    def _handle_queue(self, action: str, name: str) -> None:
        queue = self.store.raw_queues.get(name)
        if queue is None:
            # handleQueue: NotFound → "Queue %s has been deleted", done.
            # The PodGroup index is NOT dropped here (the reference's
            # handleQueue touches neither podGroups nor queueStatus):
            # a sync can race ahead of the queue's own add event — e.g.
            # PodGroup-before-Queue watch ordering — and wiping the
            # incrementally-built index would leave a late-created
            # queue permanently reporting zero PodGroups.  Cleanup of
            # both maps belongs to the Queue delete handler.
            self.status.pop(name, None)
            return
        state = queue.state or _OPEN
        if state not in (_OPEN, _CLOSED, _CLOSING, _UNKNOWN):
            raise ValueError(f"queue {name} state {state} is invalid")
        # state.Execute(action): per-state action dispatch
        # (queue/state/*.go).  Each cell is (fn, update_state_fn).
        if action == Action.OpenQueue.value:
            if state == _OPEN:
                # open.go OpenQueueAction → SyncQueue(state=Open).
                self._sync_queue(queue, lambda n_pgs: _OPEN)
            else:
                # closed/closing/unknown.go → OpenQueue(state=Open).
                self._open_queue(queue)
        elif action == Action.CloseQueue.value:
            if state == _CLOSED:
                # closed.go CloseQueueAction → SyncQueue(state=Closed).
                self._sync_queue(queue, lambda n_pgs: _CLOSED)
            elif state == _CLOSING:
                # closing.go CloseQueueAction → SyncQueue(drain).
                self._sync_queue(
                    queue,
                    lambda n_pgs: _CLOSED if n_pgs == 0 else _CLOSING,
                )
            else:
                # open/unknown.go → CloseQueue (event + drain).
                self._close_queue(queue)
        else:
            # SyncQueue: every state's default branch re-derives from
            # the recorded state through the same closure shape
            # (open.go/closed.go/closing.go/unknown.go default cases):
            # Open/"" → Open; Closed → Closed (empty-check only from a
            # non-closed state, closed.go omits it); Closing/Unknown →
            # Unknown (the v0.4 quirk documented in the module
            # docstring).
            def derive(n_pgs: int) -> str:
                if state == _OPEN:
                    return _OPEN
                if state == _CLOSED:
                    return _CLOSED
                return _UNKNOWN

            self._sync_queue(queue, derive)

    # ------------------------------------------------------------- actions

    def _pg_list(self, qname: str) -> Set[str]:
        return self.pod_groups.get(qname, set())

    def _sync_queue(self, queue, update_state_fn) -> None:
        """queue_controller_action.go syncQueue: counts + state closure +
        skip-unchanged write-back."""
        counts = {"Pending": 0, "Running": 0, "Unknown": 0, "Inqueue": 0}
        stale = []
        for uid in self._pg_list(queue.name):
            pg = self.store.pod_groups.get(uid)
            if pg is None:
                # Parity: the reference's syncQueue Get()s each member
                # and, on a NotFound error, deletes it from its local
                # podGroups cache before counting on
                # (queue_controller_action.go:44-56 — the code behind
                # its "check NotFound error and sync local cache"
                # comment).  A store miss IS our NotFound, and the
                # compaction below is that cache delete: the stale uid
                # leaves the index, the counts exclude it, and the
                # post-compaction member count feeds the state closure
                # exactly as n_pgs does there.  Pinned by
                # tests/test_controllers.py
                # test_sync_queue_compacts_stale_podgroups; PARITY.md
                # "Queue controller" row.
                stale.append(uid)
                continue
            phase = pg.status.phase
            if phase in counts:
                counts[phase] += 1
        if stale:
            members = self.pod_groups.get(queue.name)
            if members:
                members.difference_update(stale)
        n_pgs = len(self._pg_list(queue.name))
        new = QueueStatus(
            state=update_state_fn(n_pgs),
            pending=counts["Pending"],
            running=counts["Running"],
            unknown=counts["Unknown"],
            inqueue=counts["Inqueue"],
        )
        old = self.status.get(queue.name)
        if old == new and queue.state == new.state:
            return  # ignore update when status does not change
        self.status[queue.name] = new
        if queue.state != new.state:
            queue.state = new.state
            # UpdateStatus analog: refresh the store's QueueInfo wrapper
            # (what the scheduler session reads) and notify watchers.
            self.store.update_queue(queue)

    def _open_queue(self, queue) -> None:
        """queue_controller_action.go openQueue: state write + event,
        then status refinement."""
        if queue.state == _OPEN:
            return  # openQueue early return: nothing to change
        queue.state = _OPEN
        self.store.record_event(
            f"Queue/{queue.name}", Action.OpenQueue.value,
            "Open queue succeed",
        )
        self.store.update_queue(queue)
        self._sync_queue(queue, lambda n_pgs: _OPEN)

    def _close_queue(self, queue) -> None:
        """queue_controller_action.go closeQueue: state write + event,
        then drain refinement (Closed when empty, else Closing)."""
        if queue.state == _CLOSED:
            return  # closeQueue early return: nothing to change
        # Two-phase write, as the reference does it: the state lands as
        # Closed first (Update + event), then the status refinement
        # downgrades to Closing when PodGroups remain (UpdateStatus after
        # a re-Get).  The transient Closed IS reference behavior — its
        # informers observe the same intermediate write.
        queue.state = _CLOSED
        self.store.record_event(
            f"Queue/{queue.name}", Action.CloseQueue.value,
            "Close queue succeed",
        )
        self.store.update_queue(queue)
        self._sync_queue(
            queue, lambda n_pgs: _CLOSED if n_pgs == 0 else _CLOSING
        )
