"""Job plugins: distributed-workload rendezvous injection.

The reference's entire "distributed training support" is pod discovery
wiring (SURVEY.md 2.4 item 2): the **svc** plugin publishes a headless
service + per-task hosts ConfigMap mounted at /etc/volcano and
``<TASK>_HOSTS``/``<TASK>_NUM`` env (svc/svc.go:306-340), **ssh** generates a
per-job RSA keypair secret for passwordless MPI (ssh/ssh.go:76-199), and
**env** injects the task index (env/env.go:45).

The TPU-native analog adds JAX distributed bootstrap info: every pod gets
``VC_COORDINATOR_ADDRESS`` (task-0's stable DNS name), ``VC_PROCESS_COUNT``
and ``VC_PROCESS_ID`` — exactly what ``jax.distributed.initialize`` needs —
so a multi-host JAX workload scheduled by this framework can rendezvous over
ICI/DCN the way MPI jobs rendezvous via the reference's hostfiles.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List

from ..api import Pod

log = logging.getLogger(__name__)

CONFIG_MAP_MOUNT = "/etc/volcano"  # svc/const.go:28
TASK_INDEX_ENV = "VK_TASK_INDEX"  # env/env.go
SSH_SECRET_SUFFIX = "-ssh"


def _host_name(job, task_name: str, index: int) -> str:
    # Stable per-pod DNS-style name under the job's headless service.
    return f"{job.name}-{task_name}-{index}.{job.name}"


class EnvPlugin:
    """Task index env injection (plugins/env)."""

    name = "env"

    def __init__(self, arguments: List[str]):
        self.arguments = arguments

    def on_pod_create(self, pod: Pod, job) -> None:
        idx = pod.annotations.get("volcano-tpu/task-index", "0")
        pod.env[TASK_INDEX_ENV] = idx

    def on_job_add(self, job, store) -> None:
        pass

    def on_job_delete(self, job, store) -> None:
        pass


class SvcPlugin:
    """Headless service + hosts ConfigMap + rendezvous env (plugins/svc)."""

    name = "svc"

    def __init__(self, arguments: List[str]):
        self.arguments = arguments
        # Reference flag parity (svc.go:63-73): the plugin accepts
        # "--disable-network-policy" in its argument list.
        self.disable_network_policy = (
            "--disable-network-policy" in arguments
            or "--disable-network-policy=true" in arguments
        )

    def _hosts(self, job) -> Dict[str, str]:
        data = {}
        for task in job.tasks:
            hosts = [
                _host_name(job, task.name, i) for i in range(task.replicas)
            ]
            data[f"{task.name}.host"] = "\n".join(hosts)
        return data

    def on_job_add(self, job, store) -> None:
        store.put_config_map(job.namespace, f"{job.name}-svc", self._hosts(job))
        store.put_service(
            job.namespace,
            job.name,
            {"headless": True, "selector": {"volcano-tpu/job-name": job.name}},
        )
        if not self.disable_network_policy:
            # Pods of the job accept ingress only from pods of the same
            # job (svc.go:252-299: PodSelector = job labels, one Ingress
            # rule from the same selector, PolicyTypes=[Ingress]).
            selector = {"volcano-tpu/job-name": job.name,
                        "volcano-tpu/job-namespace": job.namespace}
            store.put_network_policy(
                job.namespace,
                job.name,
                {"pod_selector": selector,
                 "ingress_from": [selector],
                 "policy_types": ["Ingress"]},
            )
        job.status.controlled_resources["plugin-svc"] = "svc"

    def on_job_delete(self, job, store) -> None:
        store.delete_config_map(job.namespace, f"{job.name}-svc")
        store.delete_service(job.namespace, job.name)
        store.delete_network_policy(job.namespace, job.name)

    def on_pod_create(self, pod: Pod, job) -> None:
        total = job.total_tasks()
        # <TASK>_HOSTS / <TASK>_NUM for every task group (svc.go:306-340).
        for task in job.tasks:
            env_name = task.name.upper().replace("-", "_")
            pod.env[f"{env_name}_HOSTS"] = ",".join(
                _host_name(job, task.name, i) for i in range(task.replicas)
            )
            pod.env[f"{env_name}_NUM"] = str(task.replicas)
        # TPU-native rendezvous: jax.distributed.initialize inputs.
        if job.tasks:
            first = job.tasks[0]
            pod.env["VC_COORDINATOR_ADDRESS"] = (
                _host_name(job, first.name, 0) + ":8476"
            )
        pod.env["VC_PROCESS_COUNT"] = str(total)
        # Process id = global index across task groups in spec order.
        idx = int(pod.annotations.get("volcano-tpu/global-index", "0"))
        pod.env["VC_PROCESS_ID"] = str(idx)


class SshPlugin:
    """Per-job SSH keypair secret for passwordless MPI (plugins/ssh)."""

    name = "ssh"

    def __init__(self, arguments: List[str]):
        self.arguments = arguments

    def on_job_add(self, job, store) -> None:
        try:
            from cryptography.hazmat.primitives import serialization
            from cryptography.hazmat.primitives.asymmetric import rsa

            key = rsa.generate_private_key(
                public_exponent=65537, key_size=2048
            )
            private = key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            )
            public = key.public_key().public_bytes(
                serialization.Encoding.OpenSSH,
                serialization.PublicFormat.OpenSSH,
            )
        except Exception:  # pragma: no cover - crypto unavailable
            import secrets as pysecrets

            private = pysecrets.token_bytes(32)
            public = pysecrets.token_bytes(32)
        store.put_secret(
            job.namespace,
            job.name + SSH_SECRET_SUFFIX,
            {
                "id_rsa": private,
                "id_rsa.pub": public,
                "authorized_keys": public,
            },
        )
        job.status.controlled_resources["plugin-ssh"] = "ssh"

    def on_job_delete(self, job, store) -> None:
        store.delete_secret(job.namespace, job.name + SSH_SECRET_SUFFIX)

    def on_pod_create(self, pod: Pod, job) -> None:
        # Mount marker: the runtime mounts the secret at ~/.ssh.
        pod.annotations["volcano-tpu/ssh-secret"] = job.name + SSH_SECRET_SUFFIX


TPU_SLICE_KEY = "volcano-tpu/slice"


class TpuSlicePlugin:
    """TPU-native job plugin (SURVEY.md section 2.4 item 4): pack a job's
    tasks onto nodes of the same TPU slice so the gang's collectives ride
    ICI instead of DCN.

    Nodes advertise slice membership via ``Node.topology["volcano-tpu/
    slice"]`` (topology coordinates fold into node labels); every pod of
    the job gets a soft self-affinity term over that key, so the wave
    solver's (term, domain) count tensors pull siblings toward the slice
    an earlier sibling picked — the TPU analog of the reference's wiring
    of workload placement hints through pod templates.

    Argument: ``--weight=<int>`` (default 10, the score weight of the
    injected term)."""

    name = "tpuslice"

    def __init__(self, arguments: List[str]):
        self.weight = 10
        for arg in arguments:
            if arg.startswith("--weight="):
                try:
                    self.weight = max(int(arg.split("=", 1)[1]), 1)
                except ValueError:
                    pass

    def on_job_add(self, job, store) -> None:
        pass

    def on_job_delete(self, job, store) -> None:
        pass

    def on_pod_create(self, pod: Pod, job) -> None:
        from ..api.spec import AffinityTerm

        pod.preferred_affinity.append((
            AffinityTerm(
                match_labels={"volcano-tpu/job-name": job.name},
                topology_key=TPU_SLICE_KEY,
            ),
            self.weight,
        ))


PLUGIN_BUILDERS: Dict[str, Callable] = {
    "env": EnvPlugin,
    "svc": SvcPlugin,
    "ssh": SshPlugin,
    "tpuslice": TpuSlicePlugin,
}


def get_job_plugin(name: str, arguments: List[str]):
    builder = PLUGIN_BUILDERS.get(name)
    if builder is None:
        log.warning("Unknown job plugin %s", name)
        return None
    return builder(arguments)
