"""Controller-plane API types: the batch Job spec, lifecycle policies, the
command bus, and reconcile requests.

Mirrors ``pkg/apis/batch/v1alpha1/job.go`` (Job/TaskSpec/LifecyclePolicy/
JobStatus, 10 JobPhases), ``pkg/apis/bus/v1alpha1`` (Action/Event enums +
Command), and ``pkg/controllers/apis`` (Request).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import new_timestamp, new_uid

DEFAULT_MAX_RETRY = 3  # state/util.go:24


class Action(str, enum.Enum):
    """bus/v1alpha1/actions.go:22-60."""

    AbortJob = "AbortJob"
    RestartJob = "RestartJob"
    RestartTask = "RestartTask"
    TerminateJob = "TerminateJob"
    CompleteJob = "CompleteJob"
    ResumeJob = "ResumeJob"
    SyncJob = "SyncJob"
    Enqueue = "EnqueueJob"
    SyncQueue = "SyncQueue"
    OpenQueue = "OpenQueue"
    CloseQueue = "CloseQueue"


class Event(str, enum.Enum):
    """bus/v1alpha1/events.go:22-50."""

    Any = "*"
    PodFailed = "PodFailed"
    PodEvicted = "PodEvicted"
    Unknown = "Unknown"
    TaskCompleted = "TaskCompleted"
    OutOfSync = "OutOfSync"
    CommandIssued = "CommandIssued"
    JobUpdated = "JobUpdated"
    # TPU-native addition (SURVEY.md 5.3): device health is a first-class
    # failure event so lifecycle policies can react to chip/ICI degradation.
    DeviceUnhealthy = "DeviceUnhealthy"


class JobPhase(str, enum.Enum):
    """batch/v1alpha1/job.go:181-202."""

    Pending = "Pending"
    Aborting = "Aborting"
    Aborted = "Aborted"
    Running = "Running"
    Restarting = "Restarting"
    Completing = "Completing"
    Completed = "Completed"
    Terminating = "Terminating"
    Terminated = "Terminated"
    Failed = "Failed"


@dataclass
class LifecyclePolicy:
    """Event/ExitCode -> Action mapping (job.go:129-156)."""

    action: str = ""
    event: str = ""
    events: List[str] = field(default_factory=list)
    exit_code: Optional[int] = None
    timeout_seconds: Optional[float] = None

    def event_list(self) -> List[str]:
        events = list(self.events)
        if self.event:
            events.append(self.event)
        return events


@dataclass
class VolumeSpec:
    """A volume the job's pods mount (job.go:95-108 VolumeSpec).

    Exactly one of ``volume_claim_name`` (use an existing claim) or
    ``volume_claim`` (a claim spec the controller creates, e.g.
    ``{"storage": "10Gi"}``) should be set — the admission validator
    enforces the exclusivity (admit_job.go validateIO)."""

    mount_path: str
    volume_claim_name: str = ""
    volume_claim: Optional[Dict[str, object]] = None


@dataclass
class TaskSpec:
    """One task group of a Job (job.go:163-178)."""

    name: str
    replicas: int = 1
    # Pod template fields (subset of the framework Pod spec):
    containers: List[Dict[str, object]] = field(default_factory=list)
    init_containers: List[Dict[str, object]] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: list = field(default_factory=list)
    host_ports: List[int] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    policies: List[LifecyclePolicy] = field(default_factory=list)


@dataclass
class JobState:
    phase: str = JobPhase.Pending.value
    reason: str = ""
    message: str = ""
    last_transition: float = 0.0


@dataclass
class JobStatus:
    """job.go:224-268."""

    state: JobState = field(default_factory=JobState)
    min_available: int = 0
    pending: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    terminating: int = 0
    unknown: int = 0
    version: int = 0
    retry_count: int = 0
    controlled_resources: Dict[str, str] = field(default_factory=dict)


@dataclass
class Job:
    """The batch Job record (job.go:46-93)."""

    name: str
    namespace: str = "default"
    uid: str = ""
    min_available: int = 0
    tasks: List[TaskSpec] = field(default_factory=list)
    volumes: List[VolumeSpec] = field(default_factory=list)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    plugins: Dict[str, List[str]] = field(default_factory=dict)
    queue: str = "default"
    max_retry: int = DEFAULT_MAX_RETRY
    ttl_seconds_after_finished: Optional[float] = None
    priority_class: str = ""
    scheduler_name: str = "volcano-tpu"
    status: JobStatus = field(default_factory=JobStatus)
    creation_timestamp: float = 0.0
    deleting: bool = False
    finalizers: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.uid:
            self.uid = new_uid("job")
        if not self.creation_timestamp:
            self.creation_timestamp = new_timestamp()

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def total_tasks(self) -> int:
        return sum(t.replicas for t in self.tasks)


@dataclass
class Command:
    """Command bus record (bus/v1alpha1): user-issued action on a job/queue,
    owned by the target object."""

    action: str
    target_kind: str  # "Job" | "Queue"
    target_name: str
    target_namespace: str = "default"
    name: str = ""
    reason: str = ""
    message: str = ""

    def __post_init__(self):
        if not self.name:
            self.name = new_uid("cmd")


@dataclass
class Request:
    """Reconcile request (pkg/controllers/apis/request.go:25-35)."""

    namespace: str = ""
    job_name: str = ""
    task_name: str = ""
    queue_name: str = ""
    event: str = ""
    exit_code: int = 0
    action: str = ""
    job_version: int = 0
