"""TTL garbage collector for finished Jobs (pkg/controllers/garbagecollector).

Jobs with ``ttl_seconds_after_finished`` set are deleted (with cascading
pod/PodGroup cleanup) once the TTL elapses after they finish
(garbagecollector.go:166-287, with the requeue-at-expiry loop collapsed to
a sweep over the store).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

from ..cache import ClusterStore
from .apis import JobPhase

log = logging.getLogger(__name__)

FINISHED = (
    JobPhase.Completed.value,
    JobPhase.Failed.value,
    JobPhase.Terminated.value,
)


class GarbageCollector:
    def __init__(self, store: ClusterStore,
                 clock: Optional[Callable[[], float]] = None):
        self.store = store
        self.clock = clock or time.time
        # job key -> finish time observed
        self._finish_times = {}

    def sweep(self) -> int:
        """Delete expired finished jobs; returns number collected."""
        now = self.clock()
        collected = 0
        for key, job in list(self.store.batch_jobs.items()):
            if job.ttl_seconds_after_finished is None:
                continue
            if job.status.state.phase not in FINISHED:
                self._finish_times.pop(key, None)
                continue
            finish = self._finish_times.setdefault(
                key, job.status.state.last_transition or now
            )
            if now - finish >= job.ttl_seconds_after_finished:
                log.info("TTL expired for job %s; deleting", key)
                self.store.delete_batch_job(key)
                self._finish_times.pop(key, None)
                collected += 1
        return collected
