"""Incremental host lanes: persistent cycle aggregates + dirty-set derive.

ISSUE 8.  With the device solve sharded (mesh, PR 6) and pipelined
(PR 1), the cycle floor at north star moved to the HOST lanes — and
every one of them was a from-scratch full-table rebuild:
``FastCycle.derive()`` re-ran ``np.add.at``/``bincount`` reductions over
all 100k pod rows each cycle even when a steady-state cycle mutated a
few hundred.  This module makes the host side incremental the way the
device side already is (``ops/devsnap.py`` delta scatters):

- The store mirror records a per-cycle **dirty set** of pod rows whose
  dynamic state (status / node / job / alive) changed since the last
  derive (``StoreMirror.mark_pods_dirty``), driven by the same writers
  that already bump ``mutation_seq``.
- ``CycleAggregates`` keeps the cycle's aggregate planes **persistent**
  — ``n_used``/``n_releasing``/``n_ntasks``, the per-(job x status)
  count table behind the eight job counters, ``j_alloc_res``/
  ``j_pending_res``, and the resident mask — and refreshes them with
  **subtract-old / add-new delta scatters** over only the dirty rows.
  The shadow columns snapshot the dynamic state as of the last derive,
  so "old" contributions are recomputed exactly, and rows whose shadow
  equals their live state (the steady-state bench's bind-then-re-pend
  churn) contribute nothing and cost nothing beyond a vector compare.
- A **proven full-rebuild fallback** covers everything the delta path
  cannot: node-table epoch churn (node liveness participates in the
  resident predicate), mirror compaction (rows renumber), dirty-set
  overflow past ``VOLCANO_TPU_DIRTY_CAP``, bulk resyncs, and
  ``VOLCANO_TPU_INCREMENTAL=0``.

Exactness: the aggregate planes accumulate in float64.  Resource
quantities are integral (milli-CPU, bytes — the Kubernetes model), and
per-node / per-job sums stay far below 2^53, so every add/subtract is
exact integer arithmetic in the float64 domain — the delta-refreshed
planes are **bit-for-bit equal** to a from-scratch rebuild, which is
what the randomized-churn harness (tests/test_incremental.py) asserts
and ``VOLCANO_TPU_INCR_VERIFY=1`` re-checks on every delta derive.

Agreement with the pipelined staleness guard (``pipeline.py``): every
mark event advances ``mirror.dirty_seq`` and every writer that marks
also bumps ``mutation_seq`` (or ``epoch``/``compact_gen``), so a guard
that sees an unchanged ``mutation_seq`` is guaranteed the dirty set
recorded no pod-state change during the overlap — the two mechanisms
can never disagree on what "changed" means.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Tuple

import numpy as np

from .api import TaskStatus

log = logging.getLogger(__name__)

F64 = np.float64
I = np.int32

# ---------------------------------------------------------------- status

# Compact status-class columns: one per TaskStatus flag value, in enum
# order, plus a trailing "unmapped" bucket (never populated by
# construction — p_status only ever holds ``int(pod.task_status())`` —
# but a defensive landing spot beats silent aliasing).
STATUS_VALUES: Tuple[int, ...] = tuple(int(s) for s in TaskStatus)
N_STATUS = len(STATUS_VALUES)
_LUT_SIZE = 1024
_STATUS_CODE = np.full(_LUT_SIZE, N_STATUS, np.int64)
for _i, _v in enumerate(STATUS_VALUES):
    _STATUS_CODE[_v] = _i

_ST_PENDING = int(TaskStatus.Pending)
_ST_RELEASING = int(TaskStatus.Releasing)
_ALLOCATED = (TaskStatus.Bound, TaskStatus.Binding, TaskStatus.Running,
              TaskStatus.Allocated)
_IS_ALLOC = np.zeros(_LUT_SIZE, bool)
for _v in _ALLOCATED:
    _IS_ALLOC[int(_v)] = True
_IS_TERM = np.zeros(_LUT_SIZE, bool)
_IS_TERM[int(TaskStatus.Succeeded)] = True
_IS_TERM[int(TaskStatus.Failed)] = True

COL = {int(s): i for i, s in enumerate(TaskStatus)}
ALLOC_COLS = [COL[int(v)] for v in _ALLOCATED]


def _codes(status: np.ndarray) -> np.ndarray:
    return _STATUS_CODE[np.clip(status.astype(np.int64), 0, _LUT_SIZE - 1)]


def incremental_on() -> bool:
    return os.environ.get("VOLCANO_TPU_INCREMENTAL", "1") != "0"


def verify_on() -> bool:
    return os.environ.get("VOLCANO_TPU_INCR_VERIFY", "0") == "1"


def _grow2(a: np.ndarray, n: int) -> np.ndarray:
    """Grow the leading axis to ``n`` with zero fill (exact shape — the
    job/pod axes are compared against table sizes, not capacities)."""
    if n <= len(a):
        return a
    out = np.zeros((n, *a.shape[1:]), a.dtype)
    out[:len(a)] = a
    return out


class CycleAggregates:
    """Persistent derive-time aggregates over the store mirror.

    One instance per mirror (``aggregates_of``); every method runs on
    the cycle thread under the store lock (``FastCycle`` class-holds).
    The cycle works on COPIES of these planes — its in-cycle mutations
    (commit, unbind, evictions) reach the mirror's dynamic columns and
    mark rows dirty, and the next ``refresh`` reconciles them here.
    """

    # Reads/writes mirror dirty state; the cycle entry point holds the
    # store lock for the whole cycle.
    # vclint: class-holds: _lock

    __slots__ = (
        "key", "Pn", "Jn",
        "n_used", "n_releasing", "n_ntasks", "resident",
        "js_counts", "j_empty_pending", "j_alloc_res", "j_pending_res",
        "sh_status", "sh_node", "sh_job", "sh_alive",
        "last_mode", "delta_rows", "full_reason", "last_dirty_nodes",
    )

    def __init__(self):
        # key = (node_liveness_gen, compact_gen, Nn, R): any component
        # moving voids the delta path — node LIVENESS participates in
        # the resident predicate (and is the only node property the
        # aggregates read, so label/capacity edits and content-identical
        # node re-syncs keep the delta path alive), compaction renumbers
        # rows (compact_gen), and the plane shapes bind Nn/R.
        self.key: Optional[tuple] = None
        self.Pn = 0
        self.Jn = 0
        self.n_used: Optional[np.ndarray] = None
        self.n_releasing: Optional[np.ndarray] = None
        self.n_ntasks: Optional[np.ndarray] = None
        self.resident: Optional[np.ndarray] = None
        self.js_counts: Optional[np.ndarray] = None
        self.j_empty_pending: Optional[np.ndarray] = None
        self.j_alloc_res: Optional[np.ndarray] = None
        self.j_pending_res: Optional[np.ndarray] = None
        # Dynamic pod columns as of the last refresh (the "old" side of
        # subtract-old/add-new).
        self.sh_status = np.zeros(0, np.int16)
        self.sh_node = np.zeros(0, I)
        self.sh_job = np.zeros(0, I)
        self.sh_alive = np.zeros(0, bool)
        self.last_mode = ""
        self.delta_rows = 0
        self.full_reason = ""
        # Node rows whose derive-visible dynamic state changed in the
        # LAST delta refresh (old + new node of every truly-changed
        # dirty row), or None after a full rebuild — the device-lane
        # warm-shortlist diff (ops/devincr.py) accumulates these
        # between solves.
        self.last_dirty_nodes: Optional[np.ndarray] = None

    # ------------------------------------------------------------ refresh

    def refresh(self, m, Pn: int, Nn: int, R: int,
                n_alive: np.ndarray) -> str:
        """Bring the persistent planes up to the mirror's current state.
        Returns the mode taken: ``"delta"`` or ``"full"``."""
        from .metrics import metrics

        key = (m.node_liveness_gen, m.compact_gen, Nn, R)
        mode = "full"
        rows = None
        if not incremental_on():
            self.full_reason = "disabled"
            m.consume_pod_dirty(Pn)
        elif self.key != key or self.n_used is None:
            self.full_reason = "key-churn" if self.key is not None \
                else "first-derive"
            m.consume_pod_dirty(Pn)
        else:
            rows = m.consume_pod_dirty(Pn)
            if rows is None:
                self.full_reason = "dirty-overflow"
            else:
                mode = "delta"
        if mode == "delta":
            self._apply_delta(m, Pn, Nn, R, n_alive, rows)
            self.full_reason = ""
            if verify_on():
                self._verify(m, Pn, Nn, R, n_alive)
        else:
            self._rebuild(m, Pn, Nn, R, n_alive)
            self.key = key
        self.last_mode = mode
        metrics.host_incremental_derives.inc(mode=mode)
        return mode

    # ------------------------------------------------------- full rebuild

    def _rebuild(self, m, Pn: int, Nn: int, R: int,
                 n_alive: np.ndarray) -> None:
        (self.resident, self.n_used, self.n_releasing, self.n_ntasks,
         self.js_counts, self.j_empty_pending, self.j_alloc_res,
         self.j_pending_res) = _build_aggregates(m, Pn, Nn, R, n_alive)
        self.Pn = Pn
        self.Jn = len(m.j_uid)
        self.sh_status = m.p_status[:Pn].copy()
        self.sh_node = m.p_node[:Pn].copy()
        self.sh_job = m.p_job[:Pn].copy()
        self.sh_alive = m.p_alive[:Pn].copy()
        self.delta_rows = 0
        self.last_dirty_nodes = None

    # --------------------------------------------------------- delta path

    def _apply_delta(self, m, Pn: int, Nn: int, R: int,
                     n_alive: np.ndarray, rows: np.ndarray) -> None:
        """Subtract each truly-changed dirty row's old contribution
        (from the shadow columns) and add its new one (from the live
        columns), then re-anchor the shadow for those rows."""
        Jn = len(m.j_uid)
        if Jn > self.Jn:
            self.js_counts = _grow2(self.js_counts, Jn)
            self.j_empty_pending = _grow2(self.j_empty_pending, Jn)
            self.j_alloc_res = _grow2(self.j_alloc_res, Jn)
            self.j_pending_res = _grow2(self.j_pending_res, Jn)
        if Pn > self.Pn:
            self.resident = _grow2(self.resident, Pn)
            self.sh_status = _grow2(self.sh_status, Pn)
            self.sh_node = _grow2(self.sh_node, Pn)
            self.sh_job = _grow2(self.sh_job, Pn)
            self.sh_alive = _grow2(self.sh_alive, Pn)
            # New rows: "no row" semantics — alive False, node/job -1.
            self.sh_node[self.Pn:Pn] = -1
            self.sh_job[self.Pn:Pn] = -1
        self.Pn, self.Jn = Pn, Jn
        if not len(rows):
            self.delta_rows = 0
            self.last_dirty_nodes = np.zeros(0, np.int64)
            return
        st_o = self.sh_status[rows]
        nd_o = self.sh_node[rows]
        jb_o = self.sh_job[rows]
        al_o = self.sh_alive[rows]
        st_n = m.p_status[rows]
        nd_n = m.p_node[rows]
        jb_n = m.p_job[rows]
        al_n = m.p_alive[rows]
        ch = ((st_o != st_n) | (nd_o != nd_n) | (jb_o != jb_n)
              | (al_o != al_n))
        self.delta_rows = int(np.count_nonzero(ch))
        if not ch.any():
            self.last_dirty_nodes = np.zeros(0, np.int64)
            return
        # Old + new node of every truly-changed row: exactly the node
        # rows whose n_used/n_releasing/n_ntasks/ports contributions
        # moved this refresh (the warm-shortlist diff set).
        nds = np.concatenate(
            [nd_o[ch].astype(np.int64), nd_n[ch].astype(np.int64)]
        )
        self.last_dirty_nodes = np.unique(nds[nds >= 0])
        rows_c = rows[ch]
        be = m.p_be[rows_c]
        # One static-spec request gather serves both sides (specs are
        # immutable per row — a spec change tombstones and re-adds).
        er, si, v = m.c_req.gather(rows_c)
        v = v.astype(F64)
        self._scatter_side(Nn, n_alive, st_o[ch], nd_o[ch], jb_o[ch],
                           al_o[ch], be, er, si, v, -1)
        res_n = self._scatter_side(Nn, n_alive, st_n[ch], nd_n[ch],
                                   jb_n[ch], al_n[ch], be, er, si, v, +1)
        self.resident[rows_c] = res_n
        self.sh_status[rows_c] = st_n[ch]
        self.sh_node[rows_c] = nd_n[ch]
        self.sh_job[rows_c] = jb_n[ch]
        self.sh_alive[rows_c] = al_n[ch]

    def _scatter_side(self, Nn: int, n_alive: np.ndarray,
                      st: np.ndarray, nd: np.ndarray, jb: np.ndarray,
                      al: np.ndarray, be: np.ndarray, er: np.ndarray,
                      si: np.ndarray, v: np.ndarray,
                      sign: int) -> np.ndarray:
        """Apply one side (old = -1, new = +1) of the delta scatters.
        Returns the side's resident mask (the caller persists the new
        side's).

        All scatters are bincounts over flattened indices: np.add.at at
        large changed-row counts costs ~1 us/element, and the f64 sums
        stay exact (integral quantities), so the bincount matrices add
        the identical values."""
        R = self.n_used.shape[1]
        node_ok = nd >= 0
        if Nn:
            node_ok &= np.where(
                nd >= 0, n_alive[np.clip(nd, 0, Nn - 1)], False
            )
        term = _IS_TERM[np.clip(st.astype(np.int64), 0, _LUT_SIZE - 1)]
        res = al & node_ok & ~term
        rel = res & (st == _ST_RELEASING)

        def plane(mask_rows):
            sel = mask_rows[er]
            if not sel.any():
                return None
            return np.bincount(
                nd[er][sel].astype(np.int64) * R + si[sel],
                weights=v[sel], minlength=Nn * R,
            ).reshape(Nn, R)

        if res.any():
            add = plane(res)
            if add is not None:
                self.n_used += sign * add
            add = plane(rel)
            if add is not None:
                self.n_releasing += sign * add
            self.n_ntasks += sign * np.bincount(
                nd[res], minlength=Nn
            )[:Nn]
        valid = al & (jb >= 0)
        if valid.any():
            Jn = len(self.js_counts)
            W = self.js_counts.shape[1]
            codes = _codes(st[valid])
            self.js_counts += sign * np.bincount(
                jb[valid].astype(np.int64) * W + codes,
                minlength=Jn * W,
            ).reshape(Jn, W)
            pend = valid & (st == _ST_PENDING)
            pb = pend & be
            if pb.any():
                self.j_empty_pending += sign * np.bincount(
                    jb[pb], minlength=Jn
                )[:Jn]
            alloc = valid & _IS_ALLOC[
                np.clip(st.astype(np.int64), 0, _LUT_SIZE - 1)
            ]

            def jplane(mask_rows):
                sel = mask_rows[er]
                if not sel.any():
                    return None
                return np.bincount(
                    jb[er][sel].astype(np.int64) * R + si[sel],
                    weights=v[sel], minlength=Jn * R,
                ).reshape(Jn, R)

            add = jplane(alloc)
            if add is not None:
                self.j_alloc_res += sign * add
            add = jplane(pend)
            if add is not None:
                self.j_pending_res += sign * add
        return res

    # ----------------------------------------------------- close-time view

    def live_status_counts(self, m, Pn: int) -> np.ndarray:
        """The per-(job x status-class) count table adjusted to LIVE
        mirror state: the derive-time table plus deltas for rows the
        cycle itself has dirtied since (commit binds, evictions) — read
        WITHOUT consuming the dirty set.  Falls back to a full scan when
        tracking overflowed mid-cycle."""
        if (self.js_counts is None or m._pod_dirty_overflow
                or Pn > self.Pn or len(m.j_uid) > self.Jn):
            return _scan_status_counts(m, Pn, len(m.j_uid))
        counts = self.js_counts.copy()
        rows = np.flatnonzero(m._pod_dirty_mask[:Pn])
        if not len(rows):
            return counts
        Jn, W = counts.shape
        st_o, jb_o, al_o = (self.sh_status[rows], self.sh_job[rows],
                            self.sh_alive[rows])
        st_n, jb_n, al_n = (m.p_status[rows], m.p_job[rows],
                            m.p_alive[rows])
        for st, jb, al, sign in ((st_o, jb_o, al_o, -1),
                                 (st_n, jb_n, al_n, +1)):
            valid = al & (jb >= 0)
            if valid.any():
                counts += sign * np.bincount(
                    jb[valid].astype(np.int64) * W + _codes(st[valid]),
                    minlength=Jn * W,
                ).reshape(Jn, W)
        return counts

    # ----------------------------------------------------------- verifier

    def _verify(self, m, Pn: int, Nn: int, R: int,
                n_alive: np.ndarray) -> None:
        """VOLCANO_TPU_INCR_VERIFY=1: assert the delta-refreshed planes
        are bit-for-bit equal to a from-scratch rebuild (the churn
        harness's runtime guard)."""
        (resident, used, rel, ntasks, counts, empty, alloc,
         pending) = _build_aggregates(m, Pn, Nn, R, n_alive)
        pairs = (
            ("resident", resident, self.resident[:Pn]),
            ("n_used", used, self.n_used),
            ("n_releasing", rel, self.n_releasing),
            ("n_ntasks", ntasks, self.n_ntasks),
            ("js_counts", counts, self.js_counts),
            ("j_empty_pending", empty, self.j_empty_pending),
            ("j_alloc_res", alloc, self.j_alloc_res),
            ("j_pending_res", pending, self.j_pending_res),
        )
        for name, want, got in pairs:
            if not np.array_equal(want, got):
                bad = int(np.count_nonzero(
                    np.asarray(want) != np.asarray(got)))
                raise AssertionError(
                    f"incremental derive diverged from full rebuild: "
                    f"{name} differs in {bad} cells "
                    f"(delta_rows={self.delta_rows})"
                )


def _build_aggregates(m, Pn: int, Nn: int, R: int, n_alive: np.ndarray):
    """From-scratch aggregate build — the single source of truth both
    the full-rebuild refresh and the verifier use, so "fallback" and
    "reference" can never diverge from each other."""
    status = m.p_status[:Pn]
    alive = m.p_alive[:Pn]
    node = m.p_node[:Pn]
    job = m.p_job[:Pn]
    Jn = len(m.j_uid)
    node_ok = node >= 0
    if Nn:
        node_ok &= np.where(
            node >= 0, n_alive[np.clip(node, 0, Nn - 1)], False
        )
    term = _IS_TERM[np.clip(status.astype(np.int64), 0, _LUT_SIZE - 1)]
    resident = alive & node_ok & ~term
    releasing_m = resident & (status == _ST_RELEASING)
    def req_scatter(rows, targets, n_t):
        """[n_t, R] f64 bincount of the rows' requests grouped by
        ``targets[row]`` (node or job axis); exact for the integral
        quantities and far cheaper than np.add.at at 100k rows."""
        if not len(rows):
            return np.zeros((n_t, R), F64)
        er, si, v = m.c_req.gather(rows)
        return np.bincount(
            targets[rows][er].astype(np.int64) * R + si,
            weights=v.astype(F64), minlength=n_t * R,
        ).reshape(n_t, R)

    rows_res = np.flatnonzero(resident)
    used = req_scatter(rows_res, node, Nn)
    rel = req_scatter(np.flatnonzero(releasing_m), node, Nn)
    ntasks = (np.bincount(node[rows_res], minlength=Nn)[:Nn]
              if len(rows_res) else np.zeros(Nn, np.int64))
    counts = _scan_status_counts(m, Pn, Jn)
    valid = alive & (job >= 0)
    pend = valid & (status == _ST_PENDING)
    pb = np.flatnonzero(pend & m.p_be[:Pn])
    empty = (np.bincount(job[pb], minlength=Jn).astype(np.int64)
             if len(pb) else np.zeros(Jn, np.int64))
    alloc_res = req_scatter(
        np.flatnonzero(valid & _IS_ALLOC[
            np.clip(status.astype(np.int64), 0, _LUT_SIZE - 1)
        ]), job, Jn)
    pending_res = req_scatter(np.flatnonzero(pend), job, Jn)
    return (resident, used, rel, ntasks, counts, empty, alloc_res,
            pending_res)


def _scan_status_counts(m, Pn: int, Jn: int) -> np.ndarray:
    """[Jn, N_STATUS + 1] per-(job x status-class) counts over live rows
    with a job link — the compact replacement for derive's combined
    (job, raw-status) bincount AND close's ``_ensure_status_counts``
    scan (one table serves both)."""
    status = m.p_status[:Pn]
    valid = np.flatnonzero(m.p_alive[:Pn] & (m.p_job[:Pn] >= 0))
    W = N_STATUS + 1
    if not len(valid):
        return np.zeros((Jn, W), np.int64)
    job = m.p_job[:Pn][valid].astype(np.int64)
    codes = _codes(status[valid])
    return np.bincount(job * W + codes,
                       minlength=Jn * W).reshape(Jn, W)


def aggregates_of(m) -> CycleAggregates:
    """The mirror's persistent aggregates (created on first use)."""
    aggr = getattr(m, "_cycle_aggr", None)
    if aggr is None:
        aggr = m._cycle_aggr = CycleAggregates()
    return aggr


# ===================================================== ordering merge

def rank_from_cols(cols_primary_first: List[np.ndarray],
                   cache: Optional[tuple], max_merge_frac: float = 0.25):
    """[n] rank array for the total order the key columns define
    (primary first; the LAST column must be a unique tie-break so the
    order is total), re-lexsorting only rows whose key columns changed
    vs the cached order and MERGING them back in (ISSUE 8 order lane).

    Returns ``(rank, cache')`` where ``cache'`` is passed back next
    call.  With an intact cache and no changed rows this costs a few
    vector compares; with ``k`` changed rows it costs one k-row lexsort
    plus a vectorized lexicographic binary search (log2(n) passes over
    the column set); past ``max_merge_frac`` it falls back to the full
    lexsort.  The produced rank is IDENTICAL to the full lexsort's in
    every case — keys are unique, so the total order does not depend on
    how it was computed (asserted by the churn harness)."""
    n = len(cols_primary_first[0])
    if cache is not None:
        c_cols, c_order, c_rank = cache
        if (len(c_cols) != len(cols_primary_first)
                or len(c_order) != n):
            cache = None
    if cache is None:
        return _full_rank(cols_primary_first)
    changed = np.zeros(n, bool)
    for a, b in zip(c_cols, cols_primary_first):
        if a.dtype != b.dtype:
            return _full_rank(cols_primary_first)
        changed |= a != b
    k = int(np.count_nonzero(changed))
    if k == 0:
        return c_rank, (cols_primary_first, c_order, c_rank)
    if k > max(8, int(n * max_merge_frac)):
        return _full_rank(cols_primary_first)
    base_seq = c_order[~changed[c_order]]
    ins_rows = np.flatnonzero(changed)
    # Sort the changed rows by their NEW keys (small lexsort; lexsort
    # wants the primary key LAST).
    ins_order = np.lexsort(tuple(
        col[ins_rows] for col in reversed(cols_primary_first)
    ))
    ins_rows = ins_rows[ins_order]
    pos = _lex_searchsorted(cols_primary_first, base_seq, ins_rows)
    order = np.insert(base_seq, pos, ins_rows)
    rank = np.empty(n, np.int64)
    rank[order] = np.arange(n)
    return rank, (cols_primary_first, order, rank)


def _full_rank(cols_primary_first: List[np.ndarray]):
    order = np.lexsort(tuple(reversed(cols_primary_first)))
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order))
    return rank, (cols_primary_first, order, rank)


def _lex_searchsorted(cols: List[np.ndarray], base_seq: np.ndarray,
                      ins_rows: np.ndarray) -> np.ndarray:
    """Insertion positions of ``ins_rows`` into the key-sorted
    ``base_seq`` under the primary-first lexicographic key — a
    vectorized binary search (keys are unique across rows, so left/right
    bisection are the same position)."""
    m = len(ins_rows)
    lo = np.zeros(m, np.int64)
    hi = np.full(m, len(base_seq), np.int64)
    if not len(base_seq):
        return lo
    while True:
        active = lo < hi
        if not active.any():
            return lo
        mid = (lo + hi) // 2
        probe = base_seq[np.clip(mid, 0, len(base_seq) - 1)]
        less = np.zeros(m, bool)      # key(probe) < key(ins)
        decided = np.zeros(m, bool)
        for col in cols:
            a = col[probe]
            b = col[ins_rows]
            less |= ~decided & (a < b)
            decided |= a != b
        lo = np.where(active & less, mid + 1, lo)
        hi = np.where(active & ~less, mid, hi)
