"""Inter-pod (anti)affinity + topology-spread encoding: per-(term, domain)
count tensors.

This is the "hard predicate" of SURVEY.md (pod affinity is quadratic in pods
if done naively, ``predicates.go:272-291``): instead of a pods x pods match
matrix, every distinct (selector, topology-key, namespaces) term becomes a
row of a count tensor ``cnt[E, D]`` — how many resident pods matching term
``e`` live in topology domain ``d``.  The allocate solver then checks
required affinity (count > 0) / anti-affinity (count == 0) with one gather
per term, adds soft preferred/spread scores, and *updates the counts* as it
places tasks — mirroring how the reference's predicates plugin keeps its
nodeMap current through session Allocate events (predicates.go:111-136).

Domain interning: every topology key used by any term gets a column of
``node_dom[N, K]``; ``kubernetes.io/hostname`` domains are the node rows
themselves, other keys intern their observed label values.  Nodes missing
the label get domain -1 (they can never satisfy affinity there and never
violate anti-affinity — matching the host predicate's None handling).

The self-match rule of the upstream k8s predicate is reproduced: a required
affinity term with *no* matching pod anywhere is satisfied iff the incoming
pod itself matches the term's selector (this is what lets the first pod of a
self-affine gang schedule at all).
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Sequence, Tuple

import numpy as np

from ..api import AffinityTerm, TaskInfo

HOSTNAME_KEY = "kubernetes.io/hostname"

# Pseudo-selector marker for topology-spread terms: matches pods of the
# given job (PodGroup) instead of a label selector.
JOB_SELECTOR = "__job__"

I = np.int32
F = np.float32


class AffinityArgs(NamedTuple):
    """Device inputs for the affinity/spread machinery ([E]=terms,
    [D]=domains, [K]=topology keys).  E >= 1 always (padded all-false row)
    so shapes stay static when no affinity exists."""

    node_dom: np.ndarray  # [N, K] int32 domain id or -1
    term_key: np.ndarray  # [E] int32 -> key column of node_dom
    cnt0: np.ndarray  # [E, D] int32 resident pods matching term per domain
    t_req_aff: np.ndarray  # [P, E] bool task requires affinity term
    t_req_anti: np.ndarray  # [P, E] bool task requires anti-affinity term
    t_matches: np.ndarray  # [P, E] bool task's own labels match the term
    t_soft: np.ndarray  # [P, E] float32 soft weight (+prefer, -spread)


def empty_affinity(n_nodes: int, n_tasks: int) -> AffinityArgs:
    return AffinityArgs(
        node_dom=np.full((n_nodes, 1), -1, I),
        term_key=np.zeros((1,), I),
        cnt0=np.zeros((1, 1), I),
        t_req_aff=np.zeros((n_tasks, 1), bool),
        t_req_anti=np.zeros((n_tasks, 1), bool),
        t_matches=np.zeros((n_tasks, 1), bool),
        t_soft=np.zeros((n_tasks, 1), F),
    )


def _labels_match(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class _TermTable:
    """Interns (selector, topology_key, namespaces) triples."""

    def __init__(self):
        self.index: Dict[tuple, int] = {}
        self.terms: List[tuple] = []  # (sel_items, key, namespaces)

    def intern(self, term: AffinityTerm, task_ns: str) -> int:
        ns = tuple(sorted(term.namespaces)) if term.namespaces else (task_ns,)
        key = (tuple(sorted(term.match_labels.items())), term.topology_key, ns)
        if key not in self.index:
            self.index[key] = len(self.terms)
            self.terms.append(key)
        return self.index[key]

    def intern_job(self, job_id: str, topology_key: str) -> int:
        key = (((JOB_SELECTOR, job_id),), topology_key, None)
        if key not in self.index:
            self.index[key] = len(self.terms)
            self.terms.append(key)
        return self.index[key]


def _term_matches_pod(term: tuple, namespace: str, labels: Dict[str, str],
                      job_id: str) -> bool:
    sel_items, _key, ns = term
    sel = dict(sel_items)
    if JOB_SELECTOR in sel:
        return job_id == sel[JOB_SELECTOR]
    if ns is not None and namespace not in ns:
        return False
    return _labels_match(sel, labels)


def encode_affinity(
    cluster,
    pending_tasks: Sequence[TaskInfo],
    node_names: Sequence[str],
    n_pad: int,
    p_pad: int,
) -> AffinityArgs:
    """Build AffinityArgs from the snapshot.

    ``n_pad``/``p_pad`` are the padded node/task dims of the ClusterArrays.
    Resident-pod counting is O(residents x terms); terms are the distinct
    (selector, key, namespaces) triples across pending tasks, typically a
    handful.
    """
    table = _TermTable()
    per_task: List[Tuple[int, List[int], List[int], List[Tuple[int, float]]]] = []
    any_terms = False
    for i, ti in enumerate(pending_tasks):
        req_aff = [table.intern(t, ti.namespace) for t in ti.pod.affinity]
        req_anti = [table.intern(t, ti.namespace) for t in ti.pod.anti_affinity]
        soft: List[Tuple[int, float]] = []
        for term, w in getattr(ti.pod, "preferred_affinity", []):
            soft.append((table.intern(term, ti.namespace), float(w)))
        for term, w in getattr(ti.pod, "preferred_anti_affinity", []):
            soft.append((table.intern(term, ti.namespace), -float(w)))
        for key, w in getattr(ti.pod, "topology_spread", []):
            soft.append((table.intern_job(ti.job, key), -float(w)))
        if req_aff or req_anti or soft:
            any_terms = True
        per_task.append((i, req_aff, req_anti, soft))

    if not any_terms:
        return empty_affinity(n_pad, p_pad)

    E = len(table.terms)

    # ---- topology keys and node domains --------------------------------
    keys: List[str] = []
    key_index: Dict[str, int] = {}
    for (_sel, key, _ns) in table.terms:
        if key not in key_index:
            key_index[key] = len(keys)
            keys.append(key)
    K = len(keys)

    node_dom = np.full((n_pad, K), -1, I)
    next_dom = 0
    value_dom: Dict[Tuple[int, str], int] = {}
    node_list = [cluster.nodes[n] for n in node_names]
    for k, key in enumerate(keys):
        if key == HOSTNAME_KEY:
            for ni in range(len(node_list)):
                node_dom[ni, k] = next_dom + ni
            next_dom += len(node_list)
            continue
        for ni, node in enumerate(node_list):
            labels = node.node.labels if node.node else {}
            val = labels.get(key)
            if val is None:
                continue
            dk = (k, val)
            if dk not in value_dom:
                value_dom[dk] = next_dom
                next_dom += 1
            node_dom[ni, k] = value_dom[dk]
    D = max(1, next_dom)

    term_key = np.array(
        [key_index[key] for (_sel, key, _ns) in table.terms], I
    )

    # ---- resident counts ------------------------------------------------
    cnt0 = np.zeros((E, D), I)
    for ni, node in enumerate(node_list):
        for resident in node.tasks.values():
            for e, term in enumerate(table.terms):
                if not _term_matches_pod(
                    term, resident.namespace, resident.pod.labels,
                    resident.job,
                ):
                    continue
                d = node_dom[ni, term_key[e]]
                if d >= 0:
                    cnt0[e, d] += 1

    # ---- per-task vectors ----------------------------------------------
    t_req_aff = np.zeros((p_pad, E), bool)
    t_req_anti = np.zeros((p_pad, E), bool)
    t_matches = np.zeros((p_pad, E), bool)
    t_soft = np.zeros((p_pad, E), F)
    for i, req_aff, req_anti, soft in per_task:
        ti = pending_tasks[i]
        for e in req_aff:
            t_req_aff[i, e] = True
        for e in req_anti:
            t_req_anti[i, e] = True
        for e, w in soft:
            t_soft[i, e] += w
        for e, term in enumerate(table.terms):
            t_matches[i, e] = _term_matches_pod(
                term, ti.namespace, ti.pod.labels, ti.job
            )

    return AffinityArgs(
        node_dom=node_dom,
        term_key=term_key,
        cnt0=cnt0,
        t_req_aff=t_req_aff,
        t_req_anti=t_req_anti,
        t_matches=t_matches,
        t_soft=t_soft,
    )
