"""Dense array schema + snapshot encoder for the device-side data plane."""

from .affinity import AffinityArgs, empty_affinity, encode_affinity
from .schema import (
    ClusterArrays,
    IndexMaps,
    JobArrays,
    NodeArrays,
    QueueArrays,
    ResourceSlots,
    TaskArrays,
    encode_cluster,
    pad_dim,
)

__all__ = [
    "AffinityArgs",
    "empty_affinity",
    "encode_affinity",
    "ClusterArrays",
    "IndexMaps",
    "JobArrays",
    "NodeArrays",
    "QueueArrays",
    "ResourceSlots",
    "TaskArrays",
    "encode_cluster",
    "pad_dim",
]
