"""Dense array schema + snapshot encoder for the device-side data plane."""

from .schema import (
    ClusterArrays,
    IndexMaps,
    JobArrays,
    NodeArrays,
    QueueArrays,
    ResourceSlots,
    TaskArrays,
    encode_cluster,
    pad_dim,
)

__all__ = [
    "ClusterArrays",
    "IndexMaps",
    "JobArrays",
    "NodeArrays",
    "QueueArrays",
    "ResourceSlots",
    "TaskArrays",
    "encode_cluster",
    "pad_dim",
]
