"""Dense array schema: the device-side mirror of the cluster snapshot.

This is the TPU-native replacement for the reference's per-object data model
(``pkg/scheduler/api``): the Session snapshot (pending Tasks x Nodes x Queues)
is flattened into fixed-width struct-of-arrays so predicates, scorers, and the
assignment solver run as vmapped/jitted XLA programs.

Layout decisions (SURVEY.md section 7 array schema):
- Resources are fixed-width float32 vectors: slot 0 = milli-CPU,
  slot 1 = memory bytes, slots 2.. = extended scalar resources in
  milli-units.  The epsilon quanta of ``resource_info.go:70-72`` become a
  per-slot EPS vector so the fit kernels reproduce ``LessEqual``
  (resource_info.go:286-320) exactly.
- Label selectors / taints+tolerations / host ports are bitsets over
  session-scoped dictionaries (built per snapshot from the values that
  actually occur), so the predicate kernels are pure boolean algebra.
- Tasks are pre-sorted host-side into processing order with each job's tasks
  contiguous; ``task_job`` maps task row -> job row.  Shapes are padded to
  buckets to avoid XLA recompilation storms across cycles.

Host string<->index maps live in ``IndexMaps``; the authoritative object
store stays on host (``volcano_tpu.cache``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .. import native
from ..api import (
    CPU,
    FABRIC_LEVELS,
    MEMORY,
    MIN_MEMORY,
    MIN_MILLI_CPU,
    MIN_MILLI_SCALAR,
    ClusterInfo,
    JobInfo,
    NodeInfo,
    Resource,
    TaskInfo,
    TaskStatus,
)

F = np.float32
I = np.int32


class ResourceSlots:
    """Session-scoped mapping of resource names to vector slots."""

    def __init__(self, scalar_names: Sequence[str] = ()):  # noqa: D401
        self.scalar_names: List[str] = list(scalar_names)
        self.names: List[str] = [CPU, MEMORY] + self.scalar_names
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}

    @property
    def width(self) -> int:
        return len(self.names)

    def eps(self) -> np.ndarray:
        """Per-slot minimum quanta (resource_info.go:70-72)."""
        e = np.full((self.width,), MIN_MILLI_SCALAR, dtype=F)
        e[0] = MIN_MILLI_CPU
        e[1] = MIN_MEMORY
        return e

    def is_scalar_slot(self) -> np.ndarray:
        """Mask of extended-resource slots (the ones LessEqual may skip)."""
        m = np.ones((self.width,), dtype=bool)
        m[0] = False
        m[1] = False
        return m

    def vec(self, r: Resource) -> np.ndarray:
        v = np.zeros((self.width,), dtype=F)
        v[0] = r.milli_cpu
        v[1] = r.memory
        if r.scalars:
            for name, quant in r.scalars.items():
                idx = self.index.get(name)
                if idx is not None:
                    v[idx] = quant
        return v

    def csr_append(self, r: Resource, slot_buf: list, val_buf: list) -> None:
        """Append the (slot, value) pairs of ``r`` to CSR buffers (consumed
        by the native scatter kernel, csrc/vcsnap.cc)."""
        if r.milli_cpu:
            slot_buf.append(0)
            val_buf.append(r.milli_cpu)
        if r.memory:
            slot_buf.append(1)
            val_buf.append(r.memory)
        if r.scalars:
            index = self.index
            for name, quant in r.scalars.items():
                idx = index.get(name)
                if idx is not None and quant:
                    slot_buf.append(idx)
                    val_buf.append(quant)

    @classmethod
    def for_cluster(cls, cluster: ClusterInfo) -> "ResourceSlots":
        names = set()
        for node in cluster.nodes.values():
            if node.allocatable.scalars:
                names.update(node.allocatable.scalars.keys())
        for job in cluster.jobs.values():
            for task in job.tasks.values():
                if task.resreq.scalars:
                    names.update(task.resreq.scalars.keys())
                if task.init_resreq.scalars:
                    names.update(task.init_resreq.scalars.keys())
        return cls(sorted(names))


def pad_dim(n: int, minimum: int = 8) -> int:
    """Bucket a dimension to limit distinct compiled shapes."""
    b = minimum
    while b < n:
        b *= 2
    return b


class NodeArrays(NamedTuple):
    """Struct-of-arrays over nodes.  All [N, R] float32 unless noted."""

    allocatable: np.ndarray  # [N, R]
    idle: np.ndarray  # [N, R]
    used: np.ndarray  # [N, R]
    releasing: np.ndarray  # [N, R]
    pipelined: np.ndarray  # [N, R]
    ready: np.ndarray  # [N] bool: Ready phase and schedulable
    real: np.ndarray  # [N] bool: row is a real node (not padding)
    max_tasks: np.ndarray  # [N] int32 (pods capacity; 0 = unlimited)
    num_tasks: np.ndarray  # [N] int32 resident task count
    label_bits: np.ndarray  # [N, LW] uint32 packed label-pair bitset
    taint_bits: np.ndarray  # [N, TW] uint32 packed NoSchedule/NoExecute taints
    port_bits: np.ndarray  # [N, PW] uint32 packed used host ports
    # Fabric coordinates (rack/slice/host codes from the
    # fabric.volcano-tpu/* labels, ops/FABRIC_LEVELS order);
    # -1 = coordinate absent.  Interned per encode in first-seen order
    # over the sorted node names, so identical clusters encode
    # identically.
    fabric: np.ndarray  # [N, FL] int32


class TaskArrays(NamedTuple):
    """Struct-of-arrays over the tasks handed to the solver (usually the
    pending tasks of schedulable jobs, in processing order)."""

    req: np.ndarray  # [P, R] Resreq
    init_req: np.ndarray  # [P, R] InitResreq
    job: np.ndarray  # [P] int32 -> job row
    priority: np.ndarray  # [P] int32
    real: np.ndarray  # [P] bool
    sel_bits: np.ndarray  # [P, LW] required node-label pairs (AND)
    has_selector: np.ndarray  # [P] bool
    # Required node-affinity: up to MAX_AFFINITY_TERMS OR-alternative label
    # bitsets per task (k8s nodeSelectorTerms are alternatives).
    aff_bits: np.ndarray  # [P, A, LW]
    aff_terms: np.ndarray  # [P] int32 number of alternatives (0 = none)
    tol_bits: np.ndarray  # [P, TW] tolerated taints
    port_bits: np.ndarray  # [P, PW] requested host ports
    # Preferred node affinity (soft): per-term label bitsets and scores
    # pre-normalized to [0, 10] (CalculateNodeAffinityPriority semantics).
    pref_bits: np.ndarray  # [P, AP, LW]
    pref_w: np.ndarray  # [P, AP] float32


class JobArrays(NamedTuple):
    min_available: np.ndarray  # [J] int32
    queue: np.ndarray  # [J] int32 -> queue row
    priority: np.ndarray  # [J] int32
    ready_base: np.ndarray  # [J] int32 ReadyTaskNum before this cycle
    real: np.ndarray  # [J] bool


class QueueArrays(NamedTuple):
    weight: np.ndarray  # [Q] float32
    capability: np.ndarray  # [Q, R]
    has_capability: np.ndarray  # [Q] bool
    reclaimable: np.ndarray  # [Q] bool
    deserved: np.ndarray  # [Q, R] (filled by the proportion plugin)
    allocated: np.ndarray  # [Q, R] allocated at session open
    real: np.ndarray  # [Q] bool


class ClusterArrays(NamedTuple):
    """The full device-side snapshot."""

    nodes: NodeArrays
    tasks: TaskArrays
    jobs: JobArrays
    queues: QueueArrays
    eps: np.ndarray  # [R] per-slot epsilon quanta
    scalar_slot: np.ndarray  # [R] bool mask of extended-resource slots


# Declarative wire schema of the ClusterArrays leaves: (group, field,
# dtype, ndim) in declaration order.  tools/vclint's schema
# cross-checker (VCL304) verifies this table 1:1 against the NamedTuple
# classes above — same fields, same order — and that every dtype is
# wire-transportable (cache/snapwire._DTYPES <-> csrc/vcsnap.cc
# kVcsnapDtypes), so the frame codec can never silently drift from the
# mirror's column layout.  encode_cluster() is the producing authority;
# change it and this table together.
WIRE_COLUMNS: Tuple[Tuple[str, str, str, int], ...] = (
    ("NodeArrays", "allocatable", "float32", 2),
    ("NodeArrays", "idle", "float32", 2),
    ("NodeArrays", "used", "float32", 2),
    ("NodeArrays", "releasing", "float32", 2),
    ("NodeArrays", "pipelined", "float32", 2),
    ("NodeArrays", "ready", "bool", 1),
    ("NodeArrays", "real", "bool", 1),
    ("NodeArrays", "max_tasks", "int32", 1),
    ("NodeArrays", "num_tasks", "int32", 1),
    ("NodeArrays", "label_bits", "uint32", 2),
    ("NodeArrays", "taint_bits", "uint32", 2),
    ("NodeArrays", "port_bits", "uint32", 2),
    ("NodeArrays", "fabric", "int32", 2),
    ("TaskArrays", "req", "float32", 2),
    ("TaskArrays", "init_req", "float32", 2),
    ("TaskArrays", "job", "int32", 1),
    ("TaskArrays", "priority", "int32", 1),
    ("TaskArrays", "real", "bool", 1),
    ("TaskArrays", "sel_bits", "uint32", 2),
    ("TaskArrays", "has_selector", "bool", 1),
    ("TaskArrays", "aff_bits", "uint32", 3),
    ("TaskArrays", "aff_terms", "int32", 1),
    ("TaskArrays", "tol_bits", "uint32", 2),
    ("TaskArrays", "port_bits", "uint32", 2),
    ("TaskArrays", "pref_bits", "uint32", 3),
    ("TaskArrays", "pref_w", "float32", 2),
    ("JobArrays", "min_available", "int32", 1),
    ("JobArrays", "queue", "int32", 1),
    ("JobArrays", "priority", "int32", 1),
    ("JobArrays", "ready_base", "int32", 1),
    ("JobArrays", "real", "bool", 1),
    ("QueueArrays", "weight", "float32", 1),
    ("QueueArrays", "capability", "float32", 2),
    ("QueueArrays", "has_capability", "bool", 1),
    ("QueueArrays", "reclaimable", "bool", 1),
    ("QueueArrays", "deserved", "float32", 2),
    ("QueueArrays", "allocated", "float32", 2),
    ("QueueArrays", "real", "bool", 1),
)


@dataclass
class IndexMaps:
    """Host-side string<->index maps for one encoded snapshot."""

    slots: ResourceSlots
    node_names: List[str] = field(default_factory=list)
    node_index: Dict[str, int] = field(default_factory=dict)
    task_uids: List[str] = field(default_factory=list)
    task_infos: List[TaskInfo] = field(default_factory=list)
    job_ids: List[str] = field(default_factory=list)
    job_index: Dict[str, int] = field(default_factory=dict)
    queue_names: List[str] = field(default_factory=list)
    queue_index: Dict[str, int] = field(default_factory=dict)
    label_dict: Dict[Tuple[str, str], int] = field(default_factory=dict)
    taint_dict: Dict[Tuple[str, str, str], int] = field(default_factory=dict)
    port_dict: Dict[int, int] = field(default_factory=dict)

    @property
    def n_nodes(self) -> int:
        return len(self.node_names)

    @property
    def n_tasks(self) -> int:
        return len(self.task_uids)

    @property
    def n_jobs(self) -> int:
        return len(self.job_ids)


def _pack_bits(indices: Sequence[int], words: int) -> np.ndarray:
    out = np.zeros((words,), dtype=np.uint32)
    for i in indices:
        out[i // 32] |= np.uint32(1 << (i % 32))
    return out


def encode_cluster(
    cluster: ClusterInfo,
    pending_tasks: Sequence[TaskInfo],
    job_order: Sequence[str],
    slots: Optional[ResourceSlots] = None,
) -> Tuple[ClusterArrays, IndexMaps]:
    """Flatten a snapshot into ClusterArrays.

    ``pending_tasks`` must already be in processing order with each job's
    tasks contiguous; ``job_order`` lists job ids in that same order.
    """
    slots = slots or ResourceSlots.for_cluster(cluster)
    maps = IndexMaps(slots=slots)
    R = slots.width

    # ---------------------------------------------------------------- dicts
    # Label-pair dictionary: every (k, v) appearing in a node label or a task
    # selector; taint dictionary from node taints; port dictionary from all
    # used/requested host ports.
    for node in cluster.nodes.values():
        if node.node is not None:
            for kv in node.node.labels.items():
                maps.label_dict.setdefault(kv, len(maps.label_dict))
            for t in node.node.taints:
                key = (t.key, t.value, t.effect)
                maps.taint_dict.setdefault(key, len(maps.taint_dict))
        for ti in node.tasks.values():
            for port in ti.pod.host_ports:
                maps.port_dict.setdefault(port, len(maps.port_dict))
    for ti in pending_tasks:
        for kv in ti.pod.node_selector.items():
            maps.label_dict.setdefault(kv, len(maps.label_dict))
        for req in ti.pod.required_node_affinity:
            for kv in req.items():
                maps.label_dict.setdefault(kv, len(maps.label_dict))
        for sel, _w in ti.pod.preferred_node_affinity:
            for kv in sel.items():
                maps.label_dict.setdefault(kv, len(maps.label_dict))
        for port in ti.pod.host_ports:
            maps.port_dict.setdefault(port, len(maps.port_dict))

    LW = max(1, (len(maps.label_dict) + 31) // 32)
    TW = max(1, (len(maps.taint_dict) + 31) // 32)
    PW = max(1, (len(maps.port_dict) + 31) // 32)

    # ---------------------------------------------------------------- queues
    queue_names = sorted(cluster.queues.keys())
    maps.queue_names = queue_names
    maps.queue_index = {n: i for i, n in enumerate(queue_names)}
    Q = pad_dim(len(queue_names), 4)
    q_weight = np.zeros((Q,), F)
    q_cap = np.zeros((Q, R), F)
    q_hascap = np.zeros((Q,), bool)
    q_reclaim = np.zeros((Q,), bool)
    q_real = np.zeros((Q,), bool)
    for i, name in enumerate(queue_names):
        q = cluster.queues[name]
        q_weight[i] = q.weight
        q_real[i] = True
        q_reclaim[i] = q.reclaimable()
        if q.queue.capability:
            q_hascap[i] = True
            q_cap[i] = slots.vec(Resource.from_resource_list(q.queue.capability))

    # ---------------------------------------------------------------- nodes
    # Columnar CSR assembly; the heavy scatter/pack loops run in the native
    # serializer (csrc/vcsnap.cc) when available.
    node_names = sorted(cluster.nodes.keys())
    maps.node_names = node_names
    maps.node_index = {n: i for i, n in enumerate(node_names)}
    n_nodes = len(node_names)
    N = pad_dim(n_nodes)
    res_bufs = {k: ([], [], [0]) for k in
                ("alloc", "idle", "used", "rel", "pip")}
    lbl_idx: List[int] = []
    lbl_off = [0]
    tnt_idx: List[int] = []
    tnt_off = [0]
    prt_idx: List[int] = []
    prt_off = [0]
    n_ready = np.zeros((N,), bool)
    n_real = np.zeros((N,), bool)
    n_maxtasks = np.zeros((N,), I)
    n_numtasks = np.zeros((N,), I)
    n_fabric = np.full((N, len(FABRIC_LEVELS)), -1, I)
    fabric_codes: Dict[Tuple[int, str], int] = {}
    label_dict = maps.label_dict
    taint_dict = maps.taint_dict
    port_dict = maps.port_dict
    for i, name in enumerate(node_names):
        node = cluster.nodes[name]
        for key, res in (
            ("alloc", node.allocatable), ("idle", node.idle),
            ("used", node.used), ("rel", node.releasing),
            ("pip", node.pipelined),
        ):
            sb, vb, ob = res_bufs[key]
            slots.csr_append(res, sb, vb)
            ob.append(len(sb))
        n_ready[i] = node.ready()
        n_real[i] = True
        n_maxtasks[i] = node.allocatable.max_task_num
        n_numtasks[i] = len(node.tasks)
        if node.node is not None:
            lbl_idx.extend(
                label_dict[kv] for kv in node.node.labels.items()
                if kv in label_dict
            )
            # Only NoSchedule/NoExecute taints gate placement
            # (PreferNoSchedule is a soft preference).
            tnt_idx.extend(
                taint_dict[(t.key, t.value, t.effect)]
                for t in node.node.taints
                if t.effect in ("NoSchedule", "NoExecute")
            )
            if node.node.unschedulable:
                n_ready[i] = False
            for li, lkey in enumerate(FABRIC_LEVELS):
                v = node.node.labels.get(lkey)
                if v is None:
                    continue
                code = fabric_codes.get((li, v))
                if code is None:
                    code = fabric_codes[(li, v)] = len(fabric_codes)
                n_fabric[i, li] = code
        lbl_off.append(len(lbl_idx))
        tnt_off.append(len(tnt_idx))
        prt_idx.extend(
            port_dict[p]
            for ti in node.tasks.values()
            for p in ti.pod.host_ports
            if p in port_dict
        )
        prt_off.append(len(prt_idx))

    def _res_rows(key: str, rows: int) -> np.ndarray:
        sb, vb, ob = res_bufs[key]
        ob = ob + [ob[-1]] * (rows - (len(ob) - 1))
        return native.scatter_rows_f32(sb, vb, ob, rows, R)

    def _bit_rows(idx: List[int], off: List[int], rows: int,
                  words: int) -> np.ndarray:
        off = off + [off[-1]] * (rows - (len(off) - 1))
        return native.pack_bits_rows(idx, off, rows, words)

    n_alloc = _res_rows("alloc", N)
    n_idle = _res_rows("idle", N)
    n_used = _res_rows("used", N)
    n_rel = _res_rows("rel", N)
    n_pip = _res_rows("pip", N)
    n_labels = _bit_rows(lbl_idx, lbl_off, N, LW)
    n_taints = _bit_rows(tnt_idx, tnt_off, N, TW)
    n_ports = _bit_rows(prt_idx, prt_off, N, PW)

    # ----------------------------------------------------------------- jobs
    maps.job_ids = list(job_order)
    maps.job_index = {j: i for i, j in enumerate(maps.job_ids)}
    J = pad_dim(max(1, len(maps.job_ids)), 4)
    j_min = np.zeros((J,), I)
    j_queue = np.zeros((J,), I)
    j_pri = np.zeros((J,), I)
    j_ready = np.zeros((J,), I)
    j_real = np.zeros((J,), bool)
    for i, jid in enumerate(maps.job_ids):
        job = cluster.jobs[jid]
        j_min[i] = job.min_available
        if job.queue not in maps.queue_index:
            # Jobs with unknown queues must be filtered by the caller
            # (allocate.go:67-71 skips them); never misattribute to row 0.
            raise ValueError(
                f"job {jid} references unknown queue {job.queue!r}; "
                "filter such jobs before encoding"
            )
        j_queue[i] = maps.queue_index[job.queue]
        j_pri[i] = job.priority
        j_ready[i] = job.ready_task_num()
        j_real[i] = True

    # ----------------------------------------------------------------- tasks
    maps.task_uids = [t.uid for t in pending_tasks]
    maps.task_infos = list(pending_tasks)
    P = pad_dim(max(1, len(pending_tasks)), 8)
    t_job = np.zeros((P,), I)
    t_pri = np.zeros((P,), I)
    t_real = np.zeros((P,), bool)
    A = max(1, max((len(t.pod.required_node_affinity) for t in pending_tasks),
                   default=1))
    AP = max(1, max((len(t.pod.preferred_node_affinity)
                     for t in pending_tasks), default=1))
    t_aff = np.zeros((P, A, LW), np.uint32)
    t_affn = np.zeros((P,), I)
    t_pref = np.zeros((P, AP, LW), np.uint32)
    t_prefw = np.zeros((P, AP), F)
    t_hassel = np.zeros((P,), bool)
    req_sb: List[int] = []
    req_vb: List[float] = []
    req_ob = [0]
    init_sb: List[int] = []
    init_vb: List[float] = []
    init_ob = [0]
    sel_idx: List[int] = []
    sel_off = [0]
    tol_idxs: List[int] = []
    tol_off = [0]
    tprt_idx: List[int] = []
    tprt_off = [0]
    # Distinct toleration lists are few; memoize their taint-bit matches.
    tol_cache: Dict[tuple, List[int]] = {}
    taint_items = list(maps.taint_dict.items())
    job_index = maps.job_index
    for i, ti in enumerate(pending_tasks):
        slots.csr_append(ti.resreq, req_sb, req_vb)
        req_ob.append(len(req_sb))
        slots.csr_append(ti.init_resreq, init_sb, init_vb)
        init_ob.append(len(init_sb))
        t_job[i] = job_index[ti.job]
        t_pri[i] = ti.priority
        t_real[i] = True
        sel_pairs = ti.pod.node_selector
        if sel_pairs:
            t_hassel[i] = True
            sel_idx.extend(
                label_dict[kv] for kv in sel_pairs.items()
                if kv in label_dict
            )
        sel_off.append(len(sel_idx))
        # Node-affinity terms are OR-alternatives: one bitset per term.
        t_affn[i] = len(ti.pod.required_node_affinity)
        for a, req_term in enumerate(ti.pod.required_node_affinity[:A]):
            t_aff[i, a] = _pack_bits(
                [maps.label_dict[kv] for kv in req_term.items()
                 if kv in maps.label_dict],
                LW,
            )
        # Preferred node affinity: normalize term weights to sum 10
        # (got/total * MaxPriority in the upstream priority).
        prefs = ti.pod.preferred_node_affinity
        if prefs:
            total_w = float(sum(w for _, w in prefs))
            if total_w > 0:
                for a, (sel, w) in enumerate(prefs[:AP]):
                    t_pref[i, a] = _pack_bits(
                        [maps.label_dict[kv] for kv in sel.items()
                         if kv in maps.label_dict],
                        LW,
                    )
                    t_prefw[i, a] = w / total_w * 10.0
        # Tolerations: a task tolerates a taint bit when any toleration
        # matches key(/value)(/effect) (predicates.go taint check).
        if ti.pod.tolerations:
            ckey = tuple(
                (t.key, t.operator, t.value, t.effect)
                for t in ti.pod.tolerations
            )
            hit = tol_cache.get(ckey)
            if hit is None:
                hit = []
                for key, idx in taint_items:
                    tkey, tval, teff = key
                    for tol in ti.pod.tolerations:
                        key_ok = tol.operator == "Exists" and (
                            tol.key == "" or tol.key == tkey
                        )
                        if tol.operator == "Equal":
                            key_ok = tol.key == tkey and tol.value == tval
                        eff_ok = tol.effect == "" or tol.effect == teff
                        if key_ok and eff_ok:
                            hit.append(idx)
                            break
                tol_cache[ckey] = hit
            tol_idxs.extend(hit)
        tol_off.append(len(tol_idxs))
        if ti.pod.host_ports:
            tprt_idx.extend(
                port_dict[p] for p in ti.pod.host_ports if p in port_dict
            )
        tprt_off.append(len(tprt_idx))

    req_ob += [req_ob[-1]] * (P - (len(req_ob) - 1))
    init_ob += [init_ob[-1]] * (P - (len(init_ob) - 1))
    t_req = native.scatter_rows_f32(req_sb, req_vb, req_ob, P, R)
    t_init = native.scatter_rows_f32(init_sb, init_vb, init_ob, P, R)
    t_sel = _bit_rows(sel_idx, sel_off, P, LW)
    t_tol = _bit_rows(tol_idxs, tol_off, P, TW)
    t_ports = _bit_rows(tprt_idx, tprt_off, P, PW)

    arrays = ClusterArrays(
        nodes=NodeArrays(
            allocatable=n_alloc,
            idle=n_idle,
            used=n_used,
            releasing=n_rel,
            pipelined=n_pip,
            ready=n_ready,
            real=n_real,
            max_tasks=n_maxtasks,
            num_tasks=n_numtasks,
            label_bits=n_labels,
            taint_bits=n_taints,
            port_bits=n_ports,
            fabric=n_fabric,
        ),
        tasks=TaskArrays(
            req=t_req,
            init_req=t_init,
            job=t_job,
            priority=t_pri,
            real=t_real,
            sel_bits=t_sel,
            has_selector=t_hassel,
            aff_bits=t_aff,
            aff_terms=t_affn,
            tol_bits=t_tol,
            port_bits=t_ports,
            pref_bits=t_pref,
            pref_w=t_prefw,
        ),
        jobs=JobArrays(
            min_available=j_min,
            queue=j_queue,
            priority=j_pri,
            ready_base=j_ready,
            real=j_real,
        ),
        queues=QueueArrays(
            weight=q_weight,
            capability=q_cap,
            has_capability=q_hascap,
            reclaimable=q_reclaim,
            deserved=np.zeros((Q, R), F),
            allocated=np.zeros((Q, R), F),
            real=q_real,
        ),
        eps=slots.eps(),
        scalar_slot=slots.is_scalar_slot(),
    )
    return arrays, maps
