"""Vectorized scheduling cycle: the TPU-native fast path.

The object-model session (``framework/session.py``) reproduces the
reference's per-object semantics (``pkg/scheduler/framework/session.go``)
but pays O(cluster) Python work per cycle: a deep-copied snapshot, heap
orderings that dispatch a plugin comparator per comparison, and a per-task
replay of the solver's assignment matrix.  This module is the same cycle —
enqueue, allocate, backfill, session close — expressed over the store's
incremental array mirror (``cache/mirror.py``):

- aggregates (node idle/used, queue allocation, DRF shares, job readiness
  counters) are derived by ``np.add.at``/``bincount`` reductions over the
  pod table instead of object traversals;
- job/queue/namespace orderings precompute one key tuple per job; the
  object path's PriorityQueue pops over total-ordered keys (unique uid
  tie-break) reduce to sorted-list merging (``allocate.go:107-153``), so
  the produced order matches the object path bit-for-bit;
- the assignment matrix from the wave solver is committed in bulk: array
  scatter updates, one batched bind dispatch, and pod records mutated in
  place; the NodeInfo/JobInfo object model is marked stale and lazily
  rebuilt from pods on next access (the fast path itself never reads it);
- pod-group status write-back replicates ``close_session``
  (``framework/framework.go`` jobStatus) and the gang plugin's
  OnSessionClose conditions (``gang.go:140-183``).

Eligibility (``eligible()``): actions within ``FAST_ACTIONS``
({enqueue, allocate, backfill, preempt, reclaim} — preempt/reclaim
dispatch to ``fastpath_evict``), plugins within ``FAST_PLUGINS`` (the
eight built-ins), and the wave solver selected.  Anything else — custom
plugins, unknown actions, solver=sequential — falls back to the object
path, which remains the semantic reference (custom predicate /
node-order / device-mask callbacks still reach the device solver there,
via ``actions/allocate.py``).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .api import (
    PodGroupCondition,
    PodGroupPhase,
    TaskStatus,
    TOPOLOGY_REQUIRE,
)
from .api.resource import (
    MIN_MEMORY,
    MIN_MILLI_CPU,
    MIN_MILLI_SCALAR,
    Resource,
)
from .arrays.affinity import AffinityArgs, empty_affinity
from .framework.arguments import Arguments, get_action_args
from .framework.framework import POD_GROUP_UNSCHEDULABLE
from .framework.session import _session_counter
from .metrics import metrics
from .obs.trace import tracer_of
from .ops.allocate import SolveJobs, SolveNodes, SolveQueues, SolveTasks
from .ops.scoring import ScoreWeights

log = logging.getLogger(__name__)

F = np.float32
I = np.int32

FAST_ACTIONS = {"enqueue", "allocate", "backfill", "preempt", "reclaim",
                "rebalance"}
FAST_PLUGINS = {
    "priority", "gang", "conformance", "drf", "proportion",
    "predicates", "nodeorder", "binpack",
}

ST_PENDING = int(TaskStatus.Pending)
ST_BOUND = int(TaskStatus.Bound)
ST_BINDING = int(TaskStatus.Binding)
ST_RUNNING = int(TaskStatus.Running)
ST_ALLOCATED = int(TaskStatus.Allocated)
ST_RELEASING = int(TaskStatus.Releasing)
ST_SUCCEEDED = int(TaskStatus.Succeeded)
ST_FAILED = int(TaskStatus.Failed)
ST_UNKNOWN = int(TaskStatus.Unknown)

_ALLOCATED_STATUSES = (ST_BOUND, ST_BINDING, ST_RUNNING, ST_ALLOCATED)

# PodGroup phase coding for the cycle's j_phase array (5 = any other
# phase; 0 = no PodGroup).  _close writes back phases only through
# _PHASE_BY_CODE, so code 5 is never produced as a NEW phase.
_PHASE_CODE = {
    PodGroupPhase.Pending.value: 1,
    PodGroupPhase.Inqueue.value: 2,
    PodGroupPhase.Running.value: 3,
    PodGroupPhase.Unknown.value: 4,
}
_PHASE_BY_CODE = {
    1: PodGroupPhase.Pending.value,
    2: PodGroupPhase.Inqueue.value,
    3: PodGroupPhase.Running.value,
    4: PodGroupPhase.Unknown.value,
}
# Vector form for the close write-back (codes 1-4 only; index 0/5 unused).
_PHASE_STR_BY_CODE = np.array(
    ["", _PHASE_BY_CODE[1], _PHASE_BY_CODE[2], _PHASE_BY_CODE[3],
     _PHASE_BY_CODE[4], ""], object,
)


def _pow2(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _pack_bits(n_rows: int, words: int, rows: np.ndarray,
               bits: np.ndarray) -> np.ndarray:
    """Vectorized bitset packing: set ``bits`` in the given rows."""
    out = np.zeros((n_rows, words), np.uint32)
    if len(rows):
        flat = rows.astype(np.int64) * words + (bits >> 5)
        np.bitwise_or.at(
            out.reshape(-1), flat,
            (np.uint32(1) << (bits & 31).astype(np.uint32)),
        )
    return out


def _epoch_cached(m, attr: str, key, build):
    """Node-table cache on the mirror: rebuild via ``build()`` when
    ``key`` (epoch + shape/width components) changed.  Cached arrays are
    write-protected so an in-place mutation of a handed-out reference
    fails loudly instead of corrupting every later cycle."""
    cached = getattr(m, attr, None)
    if cached is not None and cached[0] == key:
        return cached[1:]
    arrays = build()
    for a in arrays:
        a.setflags(write=False)
    setattr(m, attr, (key, *arrays))
    return arrays


def _cmp_key(less):
    """sorted() key from a strict less(a, b) comparator."""
    import functools

    return functools.cmp_to_key(
        lambda a, b: -1 if less(a, b) else (1 if less(b, a) else 0)
    )


def _vec_le(l: np.ndarray, r: np.ndarray, eps: np.ndarray,
            scalar_slot: np.ndarray) -> bool:
    """Epsilon-tolerant Resource.less_equal on dense slot vectors."""
    per = (l < r) | (np.abs(l - r) < eps) | (scalar_slot & (l <= eps))
    return bool(per.all())


def _vec_is_empty(v: np.ndarray, eps: np.ndarray) -> bool:
    return bool((v < eps).all())


class _JobProxy:
    """Just enough of JobInfo for the ordering algorithm."""

    __slots__ = ("row", "uid", "namespace", "queue", "key")

    def __init__(self, row, uid, namespace, queue, key):
        self.row = row
        self.uid = uid
        self.namespace = namespace
        self.queue = queue
        self.key = key


class FastCycle:
    """One vectorized scheduling cycle over the store mirror."""

    # The single entry point (run_cycle_fast) wraps the whole cycle in
    # ``with store._lock``, so every method below runs with the store
    # lock held.
    # vclint: class-holds: _lock

    def __init__(self, store, conf, shard=None):
        self.store = store
        self.conf = conf
        self.m = store.mirror
        # Sharded control plane (shard.py, ISSUE 16): this cycle's
        # shard.ShardContext, or None on the default single-scheduler
        # path (which must stay bitwise identical — every shard branch
        # below is behind `self.shard is not None`).  The session uid
        # carries the shard index so /debug/cycles and the flight
        # recorder attribute cycles per shard for free.
        self.shard = shard
        n = next(_session_counter)
        self.uid = (f"ssn-{n}" if shard is None
                    else f"ssn-{n}@s{shard.index}")
        # Per-shard solver client override: each shard may own its own
        # device lane (bench A/B, service wiring); falls back to the
        # store-wide client.  Resolved once per cycle — both slots are
        # cycle-thread-owned, so no lock is needed beyond ownership.
        self._remote_solver = getattr(store, "remote_solver", None)
        if shard is not None and shard.remote_solver is not None:
            self._remote_solver = shard.remote_solver
        self.action_names = [
            a.strip() for a in conf.actions.split(",") if a.strip()
        ]
        self.plugin_opts: Dict[str, object] = {}
        self._tier_opts_cache: Dict[str, list] = {}
        for tier in conf.tiers:
            for opt in tier.plugins:
                self.plugin_opts.setdefault(opt.name, opt)
        # Pipelined sessions (ISSUE 1): the device solve is dispatched
        # without blocking and committed at the top of the NEXT cycle,
        # hiding the device round trip behind the host lanes.  Opt in
        # per store (bench, service flag) or globally via env.
        flag = getattr(store, "pipeline", None)
        if flag is None:
            flag = os.environ.get("VOLCANO_TPU_PIPELINE", "0") == "1"
        self._pipeline_on = bool(flag)
        # Span tracer (obs/trace.py, ISSUE 3): the cycle's lanes, the
        # pipelined dispatch→fetch→commit chain, and the staleness
        # guard all record spans; a null tracer keeps bare test stores
        # working.
        self.tracer = tracer_of(store)

    # --------------------------------------------------------- eligibility

    def eligible(self) -> bool:
        if not set(self.action_names) <= FAST_ACTIONS:
            return False
        if not set(self.plugin_opts) <= FAST_PLUGINS:
            return False
        args = get_action_args(self.conf.configurations, "allocate")
        if args and args.get_str("solver", "wave") != "wave":
            # The exact sequential solver needs dense per-task affinity
            # inputs; the object path provides them.
            return False
        return True

    def _tier_opts(self, flag: str):
        # Config is immutable for the cycle; the evict comparators consult
        # this hundreds of thousands of times, so cache per flag.
        cache = self._tier_opts_cache
        hit = cache.get(flag)
        if hit is None:
            hit = cache[flag] = [
                opt
                for tier in self.conf.tiers
                for opt in tier.plugins
                if getattr(opt, flag, None)
            ]
        return hit

    def _has(self, name: str) -> bool:
        return name in self.plugin_opts

    # ---------------------------------------------------------- derivation

    def derive(self) -> None:
        """Compute per-cycle aggregates from the pod table.

        The heavy pod-axis reductions no longer rerun from scratch each
        cycle: they live in the mirror's persistent ``CycleAggregates``
        (fastpath_incr.py, ISSUE 8), refreshed by subtract-old/add-new
        delta scatters over the mirror's dirty row set — with a proven
        full-rebuild fallback on node-membership churn, compaction, dirty
        overflow, or ``VOLCANO_TPU_INCREMENTAL=0``.  The cycle works on
        COPIES of the persistent planes; its own mutations (commit,
        unbind, evictions) mark rows dirty and reconcile at the NEXT
        derive."""
        from .fastpath_incr import (
            ALLOC_COLS,
            COL,
            aggregates_of,
            incremental_on,
        )

        m = self.m
        self.Pn = Pn = m.n_pods
        self.Nn = Nn = m.n_nodes
        self.R = R = 2 + len(m.scalar_slots)
        self.jobr = m.p_job[:Pn]

        self.slot_names = ["cpu", "memory"] + list(m.scalar_slots.items)
        self.eps = np.full((R,), MIN_MILLI_SCALAR, F)
        self.eps[0] = MIN_MILLI_CPU
        self.eps[1] = MIN_MEMORY
        self.scalar_slot = np.ones((R,), bool)
        self.scalar_slot[:2] = False

        # Node allocatable (dense); rebuilt only when the node table
        # changed (mirror epoch) — the per-cycle CSR gather costs ~5 ms
        # at 10k nodes.
        def _build_alloc():
            alloc = np.zeros((Nn, R), F)
            if Nn:
                csr_rows = m.node_csr_rows(np.arange(Nn))
                er, si, v = m.c_n_alloc.gather(csr_rows)
                alloc[er, si] = v
            return (alloc,)

        (self.n_alloc,) = _epoch_cached(
            m, "_node_alloc_cache", (m.epoch, Nn, R), _build_alloc
        )
        self.n_alive = m.n_alive[:Nn].copy() if Nn else np.zeros(0, bool)
        self.n_ready = (m.n_ready[:Nn] & self.n_alive) if Nn else np.zeros(0, bool)
        self.n_maxtasks = m.n_maxtasks[:Nn].astype(I)

        # Persistent aggregates: resident mask, node usage planes, the
        # per-(job x status) count table, and the per-job resource
        # sums, delta-refreshed from the dirty set (or rebuilt).
        aggr = aggregates_of(m)
        self.aggr = aggr
        # One env read per cycle: VOLCANO_TPU_INCREMENTAL=0 kills the
        # whole incremental host-lane machinery — the aggregate delta
        # refresh AND the order/encode/commit/close caches below — so
        # the bench A/B (BENCH_HOST=1) measures the full surface.
        self._incr = incremental_on()
        self.derive_mode = aggr.refresh(m, Pn, Nn, R, self.n_alive)
        # Sampled coherence audit of the refreshed planes (ISSUE 13):
        # HERE, right after refresh, the persistent aggregates equal
        # mirror truth by construction — by cycle end they lag the
        # cycle's own commits, so this is the only honest audit point.
        auditor = getattr(self.store, "auditor", None)
        if auditor is not None and auditor.enabled:
            auditor.audit_aggregates_now(m)
        # Device-lane incrementality (ISSUE 9): fold this derive's
        # changed-node capture into the store's DeviceIncremental — the
        # warm-shortlist diff is against the previous SOLVE, which may
        # be several derives back (skip cycles consume empty sets in
        # between).  A full derive poisons the accumulator, so the next
        # solve provably re-ranks fully.
        from .ops.devincr import devincr_on, of_store

        if devincr_on():
            of_store(self.store).accumulate_dirty(
                aggr.last_dirty_nodes if self.derive_mode == "delta"
                else None
            )
        # The cycle's working copies stay float32 (the evict lane's C
        # engine and the solver uploads are 32-bit contracts); the
        # PERSISTENT planes are float64 so the delta arithmetic is
        # exact, and both refresh modes cast the identical f64 values,
        # so the f32 copies are bit-for-bit across modes too.
        self.resident = aggr.resident[:Pn].copy()
        self.n_used = aggr.n_used.astype(F)  # includes releasing
        self.n_releasing = aggr.n_releasing.astype(F)
        self.n_idle = self.n_alloc - self.n_used
        self.n_ntasks = aggr.n_ntasks.astype(I)

        # The eight per-job status counters are column reductions of the
        # persistent count table (exact integers, so the delta path is
        # bit-for-bit with the rebuild).
        self.Jn = Jn = len(m.j_uid)
        sc = aggr.js_counts
        self.j_cnt_alloc = sc[:, ALLOC_COLS].sum(axis=1).astype(I)
        self.j_cnt_succ = sc[:, COL[ST_SUCCEEDED]].astype(I)
        self.j_cnt_fail = sc[:, COL[ST_FAILED]].astype(I)
        self.j_cnt_run = sc[:, COL[ST_RUNNING]].astype(I)
        self.j_cnt_pending = sc[:, COL[ST_PENDING]].astype(I)
        self.j_cnt_empty_pending = aggr.j_empty_pending.astype(I)
        self.j_cnt_total = sc.sum(axis=1).astype(I)
        self.j_cnt_releasing = sc[:, COL[ST_RELEASING]].astype(I)
        self.j_cnt_other = (
            self.j_cnt_total - self.j_cnt_alloc - self.j_cnt_succ
            - self.j_cnt_fail - self.j_cnt_pending - self.j_cnt_releasing
        )
        # ready_task_num (job_info.go:329-348).
        self.j_ready_base = (
            self.j_cnt_alloc + self.j_cnt_succ + self.j_cnt_empty_pending
        )
        # valid_task_num (job_info.go:351-366): allocated|succeeded|pending.
        self.j_valid = self.j_cnt_alloc + self.j_cnt_succ + self.j_cnt_pending

        # Per-job allocated/pending resources (DRF + proportion):
        # float64 persistent planes — resource quantities are integral
        # (milli-CPU / bytes), so the delta scatters are exact — cast
        # to the cycle's f32 working dtype.
        self.j_alloc_res = aggr.j_alloc_res.astype(F)
        self.j_pending_res = aggr.j_pending_res.astype(F)

        # Queues (sorted by name: matches the array encoder's layout).
        self.queue_names = sorted(self.store.queues.keys())
        self.queue_index = {n: i for i, n in enumerate(self.queue_names)}
        self.Qn = len(self.queue_names)
        # Queue-of-job via the mirror's interned queue codes: one small
        # code->index LUT instead of a 12k-job dict-lookup loop.
        lut = np.full(max(len(m.qnames), 1), -1, I)
        for code, nm in enumerate(m.qnames.items):
            qi = self.queue_index.get(nm)
            if qi is not None:
                lut[code] = qi
        self.q_of_job = (
            lut[m.j_queue_code[:Jn]] if Jn else np.full(0, -1, I)
        )

        self.total_res = self.n_alloc[self.n_alive].sum(axis=0) if Nn else np.zeros(R, F)

        # Session job set: jobs with a live PodGroup (snapshot semantics:
        # cache.go snapshot skips jobs with no PodGroup).  flatnonzero,
        # NOT a per-row Python loop — the 12k-iteration interpreter walk
        # sat on the hot cycle thread (ISSUE 8 satellite); every
        # consumer takes it through np.asarray.
        self.session_jobs = np.flatnonzero(m.j_alive[:Jn])
        # Sharded control plane (ISSUE 16): restrict the session to this
        # shard's owned queues.  This is the ONE seam the per-shard
        # mirror view hangs off — _schedulable_rows/_pending_rows/
        # enqueue/backfill/close all derive from session_jobs, while the
        # node planes above stay shared (whole-cluster capacity).
        if self.shard is not None:
            self.session_jobs = self.shard.filter_session_jobs(
                self, self.session_jobs
            )
        # PodGroup refs + status snapshot come straight from the mirror's
        # incrementally-maintained columns (every store add/update
        # funnels through upsert_pod_group) instead of a 45k-object walk
        # per derive.  j_phase codes (_PHASE_CODE): 0 = missing,
        # 1 = Pending, 2 = Inqueue, 3 = Running, 4 = Unknown, 5 = other.
        # The VIEWS alias the mirror arrays on purpose: the cycle's
        # in-place transitions (enqueue's Pending -> Inqueue) and the
        # close write-back update "last written" state that must persist
        # across cycles.
        self.j_pgs = m.j_pg
        self.j_phase = m.j_phase_code[:Jn]
        self.j_st_run = m.j_st_run[:Jn]
        self.j_st_fail = m.j_st_fail[:Jn]
        self.j_st_succ = m.j_st_succ[:Jn]

    # ---------------------------------------------------------- resources

    def _res(self, vec: np.ndarray) -> Resource:
        r = Resource(float(vec[0]), float(vec[1]))
        for i, name in enumerate(self.slot_names[2:], start=2):
            if vec[i]:
                r.set_scalar(name, float(vec[i]))
        return r

    # -------------------------------------------------------------- shares

    def _flush_aggr(self) -> None:
        """Apply deferred per-job/per-queue resource scatter updates.

        _commit defers the j_alloc_res / j_pending_res / q_alloc scatter
        adds (three 200k-entry np.add.at calls at north-star scale) because
        the typical single-round cycle never reads them again; consumers
        that can observe post-commit values flush first.  In-place add.at
        keeps captured references (e.g. _overused_fn's alloc) coherent."""
        pend = getattr(self, "_aggr_pending", None)
        if not pend:
            return
        self._aggr_pending = []
        R = self.R
        for jr_er, si, v, q_er in pend:
            # bincount over flattened (row, slot) indices — several
            # times faster than np.add.at at steady-state entry counts
            # (same exact sums for the integral resource quantities).
            add = np.bincount(
                jr_er.astype(np.int64) * R + si, weights=v,
                minlength=self.Jn * R,
            ).reshape(self.Jn, R).astype(F)
            self.j_alloc_res += add
            self.j_pending_res -= add
            qm = q_er >= 0
            if qm.any():
                qadd = np.bincount(
                    q_er[qm].astype(np.int64) * R + si[qm],
                    weights=v[qm], minlength=self.Qn * R,
                ).reshape(self.Qn, R).astype(F)
                self.q_alloc += qadd

    def _drf_shares(self) -> np.ndarray:
        """Per-job DRF share (drf.go:317-329), vectorized."""
        self._flush_aggr()
        total = self.total_res
        with np.errstate(divide="ignore", invalid="ignore"):
            ratio = np.where(
                total[None, :] > 0,
                self.j_alloc_res / np.where(total[None, :] > 0, total[None, :], 1.0),
                np.where(self.j_alloc_res > 0, 1.0, 0.0),
            )
        return ratio.max(axis=1) if self.R else np.zeros(len(self.j_alloc_res))

    def _proportion(self):
        """Water-fill deserved shares (proportion.go:117-173) over the
        queues that have session jobs.  Mirrors the plugin's Resource-level
        loop exactly (queue counts are small)."""
        self._flush_aggr()
        q_alloc = np.zeros((self.Qn, self.R), F)
        q_req = np.zeros((self.Qn, self.R), F)
        q_seen = np.zeros(self.Qn, bool)
        srows = np.asarray(self.session_jobs, np.int64)
        if len(srows):
            qs = self.q_of_job[srows]
            ok = qs >= 0
            srows_q = srows[ok]
            qs = qs[ok]
            q_seen[qs] = True
            np.add.at(q_alloc, qs, self.j_alloc_res[srows_q])
            np.add.at(q_req, qs,
                      self.j_alloc_res[srows_q] + self.j_pending_res[srows_q])
        self.q_alloc = q_alloc
        self.q_seen = q_seen

        deserved_res: Dict[int, Resource] = {}
        share_by_queue: Dict[str, float] = {}
        if not self._has("proportion"):
            self.q_deserved = np.full((self.Qn, self.R), 3.0e38, F)
            self.q_share = share_by_queue
            self.q_deserved_res = deserved_res
            return

        total = self._res(self.total_res)
        attrs = {}
        for qi in np.flatnonzero(q_seen):
            q = self.store.queues[self.queue_names[qi]]
            attrs[int(qi)] = {
                "weight": q.weight,
                "deserved": Resource.empty(),
                "allocated": self._res(q_alloc[qi]),
                "request": self._res(q_req[qi]),
                "share": 0.0,
            }

        remaining = total.clone()
        meet = set()
        while True:
            total_weight = sum(
                a["weight"] for qi, a in attrs.items() if qi not in meet
            )
            if total_weight == 0:
                break
            increased = Resource.empty()
            decreased = Resource.empty()
            for qi, a in attrs.items():
                if qi in meet:
                    continue
                old = a["deserved"].clone()
                a["deserved"].add(
                    remaining.clone().multi(a["weight"] / float(total_weight))
                )
                if a["request"].less(a["deserved"]):
                    from .api.resource import res_min

                    a["deserved"] = res_min(a["deserved"], a["request"])
                    meet.add(qi)
                # share update
                s = 0.0
                for rn in a["deserved"].resource_names():
                    from .api.resource import share as _share

                    v = _share(a["allocated"].get(rn), a["deserved"].get(rn))
                    if v > s:
                        s = v
                a["share"] = s
                inc, dec = a["deserved"].diff(old)
                increased.add(inc)
                decreased.add(dec)
            remaining.sub(increased).add(decreased)
            if remaining.is_empty():
                break

        self.q_deserved = np.full((self.Qn, self.R), 3.0e38, F)
        for qi, a in attrs.items():
            self.q_deserved[qi] = self._slots_vec(a["deserved"])
            deserved_res[qi] = a["deserved"]
            share_by_queue[self.queue_names[qi]] = a["share"]
        self.q_share = share_by_queue
        self.q_deserved_res = deserved_res

    def _slots_vec(self, r: Resource) -> np.ndarray:
        v = np.zeros((self.R,), F)
        v[0] = r.milli_cpu
        v[1] = r.memory
        if r.scalars:
            for name, quant in r.scalars.items():
                idx = self.m.scalar_slots.index.get(name)
                if idx is not None:
                    v[2 + idx] = quant
        return v

    # ------------------------------------------------------------ ordering

    def _job_keys(self, rows: List[int], drf_share: np.ndarray) -> np.ndarray:
        """[Jn] global rank array encoding the tier-ordered job-order key
        (first-nonzero comparator chain == lexicographic compare).

        Incremental (ISSUE 8 order lane): the key COLUMNS are cheap
        vector expressions, so they are rebuilt every call and diffed
        against the rank cached on the store — only jobs whose key
        columns actually changed re-sort, merged back into the cached
        order by a vectorized lexicographic binary search
        (``fastpath_incr.rank_from_cols``).  The uid tie-break column is
        a unique integer rank, so the order is total and the merged rank
        is bit-identical to a full ``np.lexsort``."""
        from .fastpath_incr import rank_from_cols

        m = self.m
        Jn = self.Jn
        plugin_cols = []
        tier_names = []
        for opt in self._tier_opts("enabled_job_order"):
            if opt.name == "priority":
                plugin_cols.append(-m.j_prio[:Jn])
            elif opt.name == "gang":
                plugin_cols.append(self.j_ready_base >= m.j_minav[:Jn])
            elif opt.name == "drf":
                plugin_cols.append(drf_share[:Jn])
            tier_names.append(opt.name)
        uid_rank = m.job_uid_rank()
        # Primary-first column order (rank_from_cols convention); the
        # mirror-backed create column is COPIED — the cache must hold a
        # frozen snapshot, not a view an upsert can mutate in place.
        cols = list(plugin_cols) + [m.j_create[:Jn].copy(), uid_rank]
        store = self.store
        if not getattr(self, "_incr", True):
            rank, _ = rank_from_cols(cols, None)
            return rank
        cached = getattr(store, "_job_rank_cache", None)
        ckey = (Jn, tuple(tier_names))
        prev = cached[1] if cached is not None and cached[0] == ckey \
            else None
        rank, fresh = rank_from_cols(cols, prev)
        store._job_rank_cache = (ckey, fresh)
        return rank

    def _queue_order_fn(self):
        share = self.q_share
        has_prop = self._has("proportion") and any(
            opt.name == "proportion"
            for opt in self._tier_opts("enabled_queue_order")
        )

        def fn(l, r) -> bool:
            if has_prop:
                ls = share.get(l.name, 0.0)
                rs = share.get(r.name, 0.0)
                if ls != rs:
                    return ls < rs
            if l.queue.creation_timestamp == r.queue.creation_timestamp:
                return l.uid < r.uid
            return l.queue.creation_timestamp < r.queue.creation_timestamp

        return fn

    def _namespace_order_fn(self, ns_share: Dict[str, float]):
        drf_ns = any(
            opt.name == "drf"
            for opt in self._tier_opts("enabled_namespace_order")
        ) and self._has("drf")

        def fn(l: str, r: str) -> bool:
            if drf_ns:
                lw = ns_share.get(l, 0.0)
                rw = ns_share.get(r, 0.0)
                if lw != rw:
                    return lw < rw
            return l < r

        return fn

    def _overused_fn(self):
        """Memoized per-queue overuse verdicts (shares are frozen at sort
        time, so one evaluation per queue per pass suffices)."""
        if not self._has("proportion"):
            return lambda q: False
        self._flush_aggr()
        deserved = self.q_deserved_res
        qidx = self.queue_index
        alloc = self.q_alloc
        cache: Dict[str, bool] = {}

        def fn(q) -> bool:
            hit = cache.get(q.name)
            if hit is not None:
                return hit
            qi = qidx.get(q.name)
            if qi is None or qi not in deserved:
                out = False
            else:
                out = not self._res(alloc[qi]).less_equal(deserved[qi])
            cache[q.name] = out
            return out

        return fn

    def _ns_shares(self, drf_share_unused) -> Dict[str, float]:
        """Weighted namespace DRF shares (drf.go:224-258)."""
        self._flush_aggr()
        if not (self._has("drf") and any(
            opt.name == "drf"
            for opt in self._tier_opts("enabled_namespace_order")
        )):
            return {}
        m = self.m
        srows = np.asarray(self.session_jobs, np.int64)
        if not len(srows):
            return {}
        # One scatter-add over namespace codes replaces the per-job
        # vector accumulation loop.
        nsc = m.j_ns_code[srows]
        agg = np.zeros((int(nsc.max()) + 1, self.R), F)
        np.add.at(agg, nsc, self.j_alloc_res[srows])
        total = self.total_res
        out = {}
        for c in np.unique(nsc).tolist():
            al = agg[c]
            with np.errstate(divide="ignore", invalid="ignore"):
                ratio = np.where(total > 0, al / np.where(total > 0, total, 1.0),
                                 np.where(al > 0, 1.0, 0.0))
            s = float(ratio.max()) if len(ratio) else 0.0
            ns = m.ns_names.items[c]
            w = self.store.namespace_weights.get(ns, 1)
            out[ns] = s / float(max(w, 1))
        return out

    # ------------------------------------------------------------- actions

    def run(self) -> None:
        # PodGroups whose phase was mutated in place mid-cycle (enqueue's
        # Pending -> Inqueue gate): the close write-back must not skip
        # them as "unchanged".  Lives on the STORE and is only cleared
        # after a successful write-back, so a cycle that fails between
        # the mutation and close does not strand the transition
        # unpersisted forever.
        store = self.store
        if not hasattr(store, "_phase_dirty_uids"):
            store._phase_dirty_uids = set()
        self._phase_dirty = store._phase_dirty_uids
        # Per-lane wall-clock breakdown of this cycle (seconds),
        # published as store.last_cycle_lanes for bench.py / operators:
        # derive (mirror -> cycle arrays), order/pending (job ordering +
        # row prep), encode (solver input build), device (solve dispatch
        # + device->host fetch), commit, evict actions, close.  The
        # trace spans (obs/trace.py) both record the span AND
        # accumulate these lanes, so disabling tracing keeps the
        # breakdown.
        self.lanes: Dict[str, float] = {}
        # Cycle accounting for the flight recorder (obs/recorder.py).
        self.stats: Dict[str, object] = {
            "considered": 0, "bound": 0, "dropped": 0,
            "drop_reasons": {}, "fetch_wait_ms": None,
            "dispatched_solve_id": None, "committed_solve_id": None,
            "mut_at_dispatch": None, "mut_at_commit": None,
            "epoch_at_dispatch": None, "epoch_at_commit": None,
            "device_events": [],
        }
        # Clear immediately: a failed cycle (slow-path fallback) must not
        # leave a previous cycle's breakdown masquerading as its own.
        store.last_cycle_lanes = None
        t_wall = time.time()
        t_cycle = time.perf_counter()
        err: Optional[BaseException] = None
        try:
            with self.tracer.span("cycle", cat="cycle",
                                  args={"session": self.uid}):
                self._run_body()
        except BaseException as e:
            err = e
            raise
        finally:
            # Failed cycles record too — a flight recorder that only
            # remembers the good cycles answers no incident question.
            self._record_cycle(t_wall, time.perf_counter() - t_cycle,
                               err)

    def _run_body(self) -> None:
        store = self.store
        tracer = self.tracer
        with tracer.span("derive", lanes=self.lanes):
            self.derive()
            self._proportion()
        self.new_conditions: Dict[int, PodGroupCondition] = {}
        self._evictor = None
        # Async bind batches commit collects; dispatched at cycle end so
        # the dispatcher thread's drain (binder RPCs, Scheduled events)
        # does not contend the GIL with commit/close — in the reference
        # that work runs in the API-server process, not the scheduler's.
        self._bind_batches: List[tuple] = []
        try:
            try:
                # Double-buffered sessions: the previous cycle's
                # dispatched-but-uncommitted solve lands FIRST, so its
                # device round trip ran concurrently with that cycle's
                # close/enqueue and this cycle's derive (pipeline.py).
                self._commit_inflight()
                # A rebalance plan dispatched last cycle commits (or
                # voids) right after the solve, against the freshest
                # state this cycle will see (actions/rebalance.py).
                self._commit_inflight_plan()
                # Workload-injection seam (bench.py steady state, loop
                # tests): new work "arrives" after the commit and before
                # this cycle's actions, so every pipelined cycle both
                # commits session N-1 and dispatches session N.
                feed = getattr(store, "cycle_feed", None)
                if feed is not None:
                    with tracer.span("feed", lanes=self.lanes):
                        feed(self)
                for name in self.action_names:
                    if (self.shard is not None
                            and not self.shard.runs_evictions
                            and name in ("preempt", "reclaim",
                                         "rebalance")):
                        # Evict planners reason over the WHOLE cluster's
                        # victims; only the designated evictor shard
                        # (shard 0) runs them, or two shards would plan
                        # overlapping evictions (shard.py).
                        continue
                    lane = (name if name in ("preempt", "reclaim",
                                             "enqueue", "backfill",
                                             "rebalance")
                            else None)
                    with metrics.action_timer(name), tracer.span(
                            f"action:{name}", cat="action",
                            lanes=(self.lanes if lane else None),
                            lane=lane):
                        if name == "enqueue":
                            self._enqueue()
                        elif name == "allocate":
                            self._allocate()
                        elif name == "backfill":
                            if self._backfill():
                                # Backfill bound BestEffort rows directly
                                # in the mirror; stamp for the staleness
                                # guard (disjoint rows from the solve,
                                # but node task slots moved).
                                self.m.mutation_seq += 1
                        elif name == "preempt":
                            if self._evict_device_on():
                                # Device-native lane (ISSUE 11): plan
                                # victims via the jitted kernel, prove
                                # with a what-if solve, commit (or park)
                                # through the engine — which stamps the
                                # mutation counter itself iff it evicts.
                                from . import whatif

                                whatif.run_evict_action(self, "preempt")
                            else:
                                self._evict_machinery().preempt()
                                # Evictions write p_status directly; the
                                # pipelined staleness guard keys off the
                                # mirror's mutation counter, so stamp the
                                # action (preempt/reclaim run AFTER the
                                # allocate dispatch in the standard
                                # confs).
                                self.m.mutation_seq += 1
                        elif name == "reclaim":
                            if self._evict_device_on():
                                from . import whatif

                                whatif.run_evict_action(self, "reclaim")
                            else:
                                self._evict_machinery().reclaim()
                                self.m.mutation_seq += 1
                        elif name == "rebalance":
                            # Defragmentation planner (ISSUE 5): a
                            # committed plan evicts through the same
                            # machinery as preempt/reclaim and stamps
                            # the mutation counter itself.
                            self._rebalance()
            except BaseException:
                # A failed cycle may leave uncommitted status mutations
                # in the mirror (evictions mid-statement); re-derive
                # dynamic state from the pod records before the caller
                # falls back.  Deferred bind-record walks (node_name on
                # committed pods, normally done post-cycle by the bind
                # dispatcher) must land first or the resync would read
                # committed pods as unbound and double-schedule them —
                # including batches a PRIOR cycle dispatched that the
                # worker has not yet processed.
                store.apply_pending_bind_records()
                self.m.resync_status(self.store.pods)
                raise
            if self._evictor is not None:
                self._evictor.st.flush()
            with tracer.span("close", lanes=self.lanes):
                self._close()
            store.last_cycle_lanes = dict(self.lanes)
        except BaseException:
            # Failures AFTER the action loop (evictor flush, close) must
            # also land the deferred node_name walks before the caller
            # falls back to the object path — the fallback snapshots pod
            # RECORDS, and committed-but-unnamed pods would read as
            # unbound and double-schedule.  Idempotent with the inner
            # handler's application above.
            store.apply_pending_bind_records()
            raise
        finally:
            # Committed binds dispatch even when close fails: binds are
            # idempotent and the commit bookkeeping already happened.
            for keys, hosts, pods, entry in self._bind_batches:
                store.dispatch_binds(keys, hosts, pods, entry=entry)

    # ------------------------------------------------------------- audit

    def _audit_flow(self, old_status: int, new_status: int,
                    reason: str) -> None:
        """Scalar conservation-flow declaration (obs/audit.py): the
        per-row mirror status writers pair each write with one of
        these, so the cycle-end reconcile can balance declared flows
        against the census."""
        a = getattr(self.store, "auditor", None)
        if a is not None and a.enabled and old_status != new_status:
            a.flow(reason, old_status, new_status)

    def _audit_flow_rows(self, rows, new_status: int,
                         reason: str) -> None:
        """Bulk conservation-flow declaration for the vectorized
        status writes — MUST be called before the ``p_status`` write
        (it classifies the rows' old statuses)."""
        a = getattr(self.store, "auditor", None)
        if a is not None and a.enabled and len(rows):
            a.flow_rows(self.m.p_status, rows, int(new_status), reason)

    # ----------------------------------------------------------- journey

    def _journey_shard(self) -> int:
        return -1 if self.shard is None else int(self.shard.index)

    def _journey_masks(self):
        """First-time row masks for the journey's steady-state bulk
        accounting (obs/journey.py): the feed re-pends and re-binds the
        SAME backlog rows every cycle, and per-pod Python capture at
        that scale would dwarf the cycle.  The masks remember which
        rows already recorded their first consideration / first bind,
        so per-pod work is paid once per pod and repeats fold into bulk
        counters — journey cost stays churn-proportional.  Row indices
        are stable for a pod's lifetime; a compaction renumbers them,
        so the masks are keyed on ``compact_gen`` and rebuilt on a
        bump (uid-keyed journey state survives; only the first-seen
        memo resets, costing one re-record per live pod)."""
        m = self.m
        n = len(m.p_uid)
        mk = getattr(self.store, "_journey_masks", None)
        if mk is None or mk[0] != m.compact_gen:
            mk = self.store._journey_masks = (
                m.compact_gen, np.zeros(n, bool), np.zeros(n, bool))
        elif len(mk[1]) < n:
            grow = lambda a: np.concatenate(
                [a, np.zeros(n - len(a), bool)])
            mk = self.store._journey_masks = (
                mk[0], grow(mk[1]), grow(mk[2]))
        return mk

    def _journey_event(self, row: int, kind: str, *,
                       solve_id: int = 0, detail: str = "") -> None:
        """Scalar journey capture for one mirror row."""
        jr = getattr(self.store, "journey", None)
        if jr is None:
            return
        uid = self.m.p_uid[int(row)]
        if uid:
            jr.pod_event(uid, kind, shard=self._journey_shard(),
                         solve_id=solve_id, detail=detail)

    def _journey_rows(self, rows, kind: str, *, solve_id: int = 0,
                      epoch: int = -1, detail: str = "") -> None:
        """Bulk journey capture for the vectorized seams.  For the
        steady-state kinds (``dispatched``/``bound``/``unbound``) only
        FIRST-time rows pay per-pod work (see ``_journey_masks``);
        drops and voids are churn-sized, so every row records."""
        jr = getattr(self.store, "journey", None)
        if jr is None or not len(rows):
            return
        m = self.m
        shard = self._journey_shard()
        if kind in ("dispatched", "bound"):
            gen, considered, bound_seen = self._journey_masks()
            mask = considered if kind == "dispatched" else bound_seen
            fresh = ~mask[rows]
            n_rep = int(len(rows) - np.count_nonzero(fresh))
            if n_rep:
                jr.repeat_rows(n_rep, kind)
            if not fresh.any():
                return
            rows = rows[fresh]
            mask[rows] = True
        elif kind == "unbound":
            # Re-pend loop: the pods' journeys already hold their
            # first-bind latency; count in bulk only.
            jr.repeat_rows(int(len(rows)), kind)
            return
        jr.pod_rows((m.p_uid[i] for i in rows.tolist()), kind,
                    shard=shard, solve_id=solve_id, epoch=epoch,
                    detail=detail)

    def _record_cycle(self, t_wall: float, duration_s: float,
                      err: Optional[BaseException]) -> None:
        """Run the cycle-end audits and seal this cycle into the
        store's flight recorder."""
        from .obs.recorder import CycleRecord

        st = self.stats
        # Runtime auditor (obs/audit.py, ISSUE 13): conservation
        # reconcile + sampled coherence audits + SLO feed.  Runs even
        # when no flight recorder is attached — the anomaly ring and
        # counters are the production surface; the CycleRecord copy is
        # the forensic one.
        anoms = []
        auditor = getattr(self.store, "auditor", None)
        if auditor is not None and auditor.enabled:
            anoms = auditor.end_cycle(self, duration_s, err)
        flight = getattr(self.store, "flight", None)
        if flight is None:
            self.tracer.drain()
            return
        seq = flight.record(CycleRecord(
            session=self.uid, path="fast", t_wall=t_wall,
            shard=None if self.shard is None else int(self.shard.index),
            duration_s=duration_s, lanes=dict(self.lanes),
            pods_considered=int(st["considered"]),
            pods_bound=int(st["bound"]),
            pods_dropped=int(st["dropped"]),
            drop_reasons=dict(st["drop_reasons"]),
            inflight_fetch_wait_ms=st["fetch_wait_ms"],
            dispatched_solve_id=st["dispatched_solve_id"],
            committed_solve_id=st["committed_solve_id"],
            mutation_seq_at_dispatch=st["mut_at_dispatch"],
            mutation_seq_at_commit=st["mut_at_commit"],
            epoch_at_dispatch=st["epoch_at_dispatch"],
            epoch_at_commit=st["epoch_at_commit"],
            device_events=list(st["device_events"]),
            error=type(err).__name__ if err is not None else None,
            spans=self.tracer.drain(),
            rebalance=st.get("rebalance"),
            whatif=st.get("whatif"),
            pool=st.get("pool"),
            anomalies=[a.to_dict() for a in anoms],
        ))
        # Stamp the ring copies with the flight seq, so an operator can
        # walk /debug/anomalies -> /debug/cycles/<seq> for forensics.
        for a in anoms:
            a.cycle_seq = seq

    def _count_drops(self, reasons: Dict[str, int]) -> None:
        """Fold staleness-guard drop counts into the cycle stats and the
        per-reason counter series."""
        st = self.stats
        dr = st["drop_reasons"]
        for reason, n in reasons.items():
            n = int(n)
            if n <= 0:
                continue
            dr[reason] = dr.get(reason, 0) + n
            metrics.pipeline_stale_drops.inc(n, reason=reason)
            st["dropped"] = int(st["dropped"]) + n

    def _count_shortlist_fb(self, exhausted: int, affinity: int) -> None:
        """Fold the two-phase solve's shortlist-fallback rescore counts
        into the per-reason counter series, the cycle stats, and a
        per-store accumulator bench.py resets between A/B passes."""
        if exhausted <= 0 and affinity <= 0:
            return
        acc = getattr(self.store, "_shortlist_fb", None)
        if acc is None:
            acc = self.store._shortlist_fb = {}
        if exhausted > 0:
            metrics.solve_shortlist_fallback.inc(
                exhausted, reason="exhausted")
            acc["exhausted"] = acc.get("exhausted", 0) + exhausted
        if affinity > 0:
            metrics.solve_shortlist_fallback.inc(
                affinity, reason="affinity-required")
            acc["affinity-required"] = (
                acc.get("affinity-required", 0) + affinity)
        self.stats["shortlist_fallbacks"] = (
            int(self.stats.get("shortlist_fallbacks", 0))
            + exhausted + affinity)

    def _record_pool_fetch(self) -> None:
        """Fold the solver pool's last-fetch info (winning replica,
        hedge/failover flags, wait — solver_pool.SolverPool) into the
        cycle's flight record.  Plain RemoteSolver stores carry no
        pool info and record nothing."""
        take = getattr(self._remote_solver, "take_last_fetch_info", None)
        if take is None:
            return
        info = take()
        if info:
            self.stats["pool"] = info

    def _devincr_drop_skip(self) -> None:
        """Void the null-delta skip proof: the previously dispatched
        solve's result was LOST (reply lost / device crash), so even an
        unchanged store must re-dispatch — the lost solve may have
        found placements nobody ever saw."""
        dvc = getattr(self.store, "_devincr_cache", None)
        if dvc is not None:
            dvc.skip_token = None

    def _record_twophase_lanes(self) -> None:
        """Fold the wave solver's coarse/fine dispatch timings into the
        cycle's lane split (device_coarse / device_fine sub-lanes of the
        device lane) and the trace event stream — these are the
        host-side dispatch legs; the residual device wait stays on the
        fetch that consumes the result.  Mesh dispatches annotate both
        events (and the cycle stats) with the node-axis shard count, so
        a trace distinguishes the per-shard sub-lanes from single-device
        ones."""
        from .ops import wave as _wave_mod

        info = _wave_mod.LAST_TWOPHASE
        if not info.get("enabled"):
            return
        lanes = self.lanes
        coarse = float(info.get("coarse_s", 0.0))
        fine = float(info.get("fine_s", 0.0))
        shards = int(info.get("mesh_shards", 1) or 1)
        args = {"mesh_shards": shards} if shards > 1 else None
        if shards > 1:
            self.stats["mesh_shards"] = shards
        dvinfo = info.get("devincr")
        if dvinfo:
            # Device-incremental decision of this dispatch (ISSUE 9):
            # cycle stats + the per-mode counter series.
            self.stats["devincr"] = dict(dvinfo)
            mode = dvinfo.get("mode")
            if mode in ("warm", "full"):
                metrics.device_incremental_solves.inc(mode=mode)
        lanes["device_coarse"] = lanes.get("device_coarse", 0.0) + coarse
        lanes["device_fine"] = lanes.get("device_fine", 0.0) + fine
        now = time.perf_counter_ns()
        if coarse > 0:
            self.tracer.event(
                "device_coarse", "device",
                now - int((coarse + fine) * 1e9), int(coarse * 1e9),
                tid="cycle", args=args,
            )
        if fine > 0:
            self.tracer.event(
                "device_fine", "device", now - int(fine * 1e9),
                int(fine * 1e9), tid="cycle", args=args,
            )

    def _evict_device_on(self) -> bool:
        """True when preempt/reclaim run the device-native
        plan-prove-commit lane (volcano_tpu/whatif.py) instead of the
        host-side victim walk.  ``VOLCANO_TPU_EVICT_DEVICE=0`` (or a
        remote-solver deployment, whose scheduler process cannot run
        the what-if solve) keeps the host walk bind-for-bind."""
        from . import whatif

        return whatif.evict_device_on(self.store)

    def _evict_machinery(self):
        self._flush_aggr()
        if self._evictor is None:
            from .fastpath_evict import FastEvictor

            self._evictor = FastEvictor(self)
        else:
            # Action order is free-form: an allocate/backfill action may
            # have mutated n_idle/n_ntasks since the evictor snapshot.
            self._evictor.resync()
        return self._evictor

    # ------------------------------------------------------------- enqueue

    def _minres_vec(self, pg) -> Optional[np.ndarray]:
        """Dense slot vector of pg.min_resources, cached on the PodGroup.
        None when min_resources names a resource outside the slot layout
        (caller falls back to Resource-object math)."""
        cached = getattr(pg, "_minres_vec", None)
        if cached is not None and cached[0] == self.R:
            return cached[1]
        res = Resource.from_resource_list(pg.min_resources)
        v = np.zeros((self.R,), F)
        v[0] = res.milli_cpu
        v[1] = res.memory
        if res.scalars:
            for name, quant in res.scalars.items():
                idx = self.m.scalar_slots.index.get(name)
                if idx is None:
                    return None
                v[2 + idx] = quant
        try:
            pg._minres_vec = (self.R, v)
        except Exception:
            pass
        return v

    def _enqueue(self) -> None:
        """Gate Pending PodGroups into Inqueue (enqueue.go:52-132).

        The object path's queue/job PriorityQueues have static keys during
        enqueue, so heap pops reduce to: queues in key order, each drained
        of its jobs in key order, with the budget checked between jobs."""
        m = self.m
        store = self.store
        args = get_action_args(self.conf.configurations, "enqueue")
        factor = args.get_float("overcommit-factor", 1.2) if args else 1.2

        # Queue-grouped pending rows, built by array grouping instead of
        # a 12k-row Python loop.  Ordering (queue comparator + job keys)
        # is DEFERRED below the accept-all fast path: when every pending
        # group fits, acceptance is order-independent and the sorts are
        # pure overhead at the north-star shape.
        srows = np.asarray(self.session_jobs, np.int64)
        if not len(srows):
            return
        # Steady-state early-out (ISSUE 8): with no Pending-phase group
        # in the session there is nothing to gate — the queue grouping,
        # unknown-queue scan, and budget prep below are pure overhead
        # (the object path's enqueue likewise does nothing; only its
        # per-job unknown-queue error logs are skipped here, and those
        # re-fire on any cycle that has Pending groups again).
        if not bool((self.j_phase[srows] == 1).any()):
            return
        row_pg = self.j_pgs
        qc = m.j_queue_code[srows]
        uq_codes, uq_first = np.unique(qc, return_index=True)
        uq_codes = uq_codes[np.argsort(uq_first, kind="stable")]
        known = {}
        for c in uq_codes.tolist():
            qname = m.qnames.items[c]
            known[c] = qname if qname in store.queues else None
        bad_codes = [c for c, n in known.items() if n is None]
        if bad_codes:
            # Per-job error log, as the object path emits
            # (enqueue.go:66-69) — unknown queues are rare.
            for row in srows[np.isin(qc, bad_codes)].tolist():
                log.error("Failed to find queue %s for job %s",
                          m.j_queue[row], m.j_uid[row])
        queue_seq = [n for n in (known[c] for c in uq_codes.tolist())
                     if n is not None]
        pend = (self.j_phase[srows] == 1) & np.isin(
            qc, [c for c, n in known.items() if n is not None]
        )
        prows = srows[pend]
        jobs_map: Dict[str, List[int]] = {}
        if len(prows):
            qcp = qc[pend]
            order = np.argsort(qcp, kind="stable")
            qcp_s = qcp[order]
            prows_s = prows[order]
            starts = np.flatnonzero(
                np.concatenate(([True], qcp_s[1:] != qcp_s[:-1]))
            )
            bounds = np.append(starts, len(qcp_s))
            for i, s in enumerate(starts.tolist()):
                jobs_map[known[int(qcp_s[s])]] = (
                    prows_s[s:bounds[i + 1]].tolist()
                )

        eps = self.eps
        scalar_slot = self.scalar_slot
        used_vec = (self.n_used[self.n_alive].sum(axis=0)
                    if self.Nn else np.zeros(self.R, F))
        idle = self.total_res * factor - used_vec

        # Accept-all fast path: when no involved queue has a capability
        # cap and the SUM of every pending group's MinResources fits the
        # overcommitted idle budget, the sequential scan accepts every
        # group (each prefix of charges leaves at least the final idle),
        # so the per-group budget walk collapses to one vector compare.
        if not _vec_is_empty(idle, eps):
            capped = self._has("proportion") and any(
                store.queues[q].queue.capability for q in jobs_map
            )
            if not capped:
                vecs = []
                all_vec = True
                for lst in jobs_map.values():
                    for row in lst:
                        pg = row_pg[row]
                        if pg.min_resources is None:
                            continue
                        v = self._minres_vec(pg)
                        if v is None:
                            all_vec = False
                            break
                        vecs.append(v)
                    if not all_vec:
                        break
                if all_vec:
                    total = (
                        np.sum(np.stack(vecs), axis=0) if vecs
                        else np.zeros(self.R, F)
                    )
                    # Strict fit with slack: the sequential walk below
                    # stops as soon as idle goes empty mid-walk, which
                    # rejects every later group (even MinResources-nil
                    # groups that charge nothing, enqueue.go:98-101).
                    # _vec_le alone tolerates total ≈ idle within eps,
                    # where the walk and the shortcut would diverge —
                    # require a non-empty residual so every prefix of
                    # charges provably leaves a non-empty idle.
                    if (_vec_le(total, idle, eps, scalar_slot)
                            and not _vec_is_empty(idle - total, eps)):
                        inq = PodGroupPhase.Inqueue.value
                        j_uid = m.j_uid
                        dirty = self._phase_dirty
                        j_phase = self.j_phase
                        for lst in jobs_map.values():
                            for row in lst:
                                # j_uid[row] == pg.uid (the PodGroup
                                # dict key) without the property call.
                                row_pg[row].status.phase = inq
                                dirty.add(j_uid[row])
                            j_phase[lst] = 2
                        return

        # Budget walk: order matters from here on (enqueue.go's queue /
        # job PriorityQueue pops), so pay for the sorts now.
        queue_order = self._queue_order_fn()
        drf_share = self._drf_shares()
        jkeys = self._job_keys(self.session_jobs, drf_share).tolist()
        queue_seq.sort(key=_cmp_key(
            lambda l, r: queue_order(store.queues[l], store.queues[r])
        ))
        for lst in jobs_map.values():
            lst.sort(key=jkeys.__getitem__)

        q_cap_vec: Dict[str, Optional[np.ndarray]] = {}
        done = False
        for qname in queue_seq:
            if done:
                break
            for row in jobs_map.get(qname, ()):
                if _vec_is_empty(idle, eps):
                    done = True
                    break
                pg = row_pg[row]
                inqueue = False
                if pg.min_resources is None:
                    inqueue = True
                else:
                    min_vec = self._minres_vec(pg)
                    if min_vec is None:
                        # Unknown resource name: Resource-object fallback.
                        min_req = Resource.from_resource_list(
                            pg.min_resources
                        )
                        if (
                            self._job_enqueueable_obj(qname, pg)
                            and min_req.less_equal(self._res(idle))
                        ):
                            idle = idle - self._slots_vec(min_req)
                            inqueue = True
                    elif (
                        self._job_enqueueable_vec(qname, pg, min_vec,
                                                  q_cap_vec)
                        and _vec_le(min_vec, idle, eps, scalar_slot)
                    ):
                        idle = idle - min_vec
                        inqueue = True
                if inqueue:
                    pg.status.phase = PodGroupPhase.Inqueue.value
                    self.j_phase[row] = 2
                    # The close-phase skip-check compares against this
                    # already-mutated object; record the transition so
                    # the write-back still persists + notifies it.
                    self._phase_dirty.add(pg.uid)

    def _job_enqueueable_vec(self, qname: str, pg, min_vec: np.ndarray,
                             q_cap_vec: Dict) -> bool:
        """proportion's JobEnqueueable veto (proportion.go:231-247)."""
        if not self._has("proportion"):
            return True
        self._flush_aggr()
        queue = self.store.queues.get(qname)
        if queue is None or not queue.queue.capability:
            return True
        if qname not in q_cap_vec:
            q_cap_vec[qname] = self._slots_vec(
                Resource.from_resource_list(queue.queue.capability)
            )
        qi = self.queue_index.get(qname)
        allocated = self.q_alloc[qi] if qi is not None else 0.0
        return _vec_le(min_vec + allocated, q_cap_vec[qname],
                       self.eps, self.scalar_slot)

    def _job_enqueueable_obj(self, qname: str, pg) -> bool:
        if not self._has("proportion"):
            return True
        self._flush_aggr()
        queue = self.store.queues.get(qname)
        if queue is None or not queue.queue.capability:
            return True
        if pg is None or pg.min_resources is None:
            return True
        min_req = Resource.from_resource_list(pg.min_resources)
        qi = self.queue_index.get(qname)
        allocated = (
            self._res(self.q_alloc[qi]) if qi is not None else Resource.empty()
        )
        return min_req.add(allocated).less_equal(
            Resource.from_resource_list(queue.queue.capability)
        )

    # ------------------------------------------------------------ allocate

    # Substrings identifying a crashed/unreachable TPU runtime in the
    # exceptions jax surfaces (vs. a programming error, which must
    # propagate).  The hyperscale-affinity envelope (BASELINE.md) can
    # kill the remote worker mid-solve; those cycles recover by halving
    # the chunk budget and resuming.
    _DEVICE_CRASH_MARKERS = (
        "TPU worker process crashed",
        "worker process crashed",
        "DATA_LOSS",
        "DataLoss",
        "UNAVAILABLE",
        "Socket closed",
        "connection terminated",
        "device or resource busy",
    )
    # Lowest budget scale the crash handler degrades to (1/64 of the
    # configured VOLCANO_TPU_AFF_BUDGET_MB).
    _MIN_BUDGET_SCALE = 1.0 / 64.0
    # Clean affinity cycles before the degraded budget doubles back up.
    _SCALE_RECOVER_AFTER = 8
    # Consecutive remote-solver fetch failures tolerated as "lost
    # reply" before the pipelined commit fails the cycle (a child that
    # keeps replying garbage never fails the send-side probe).
    REMOTE_FETCH_FAIL_CAP = 3

    @classmethod
    def _is_device_crash(cls, e: BaseException) -> bool:
        msg = str(e)
        return isinstance(e, Exception) and any(
            m in msg for m in cls._DEVICE_CRASH_MARKERS
        )

    def _on_device_crash(self, e: Exception) -> None:
        """Degrade the affinity chunk budget and re-probe the device.
        Raises the original error when the runtime did not come back —
        the scheduler's health machinery (UNHEALTHY_AFTER) then takes
        over."""
        store = self.store
        scale = getattr(store, "_aff_budget_scale", 1.0)
        scale = max(scale / 2.0, self._MIN_BUDGET_SCALE)
        store._aff_budget_scale = scale
        store._aff_clean_cycles = 0
        # The device-incremental caches hold buffers allocated on the
        # runtime that just crashed (and a solve that died mid-stream
        # may have half-updated the warm candidates): drop everything —
        # the next solve provably full-recomputes on fresh buffers.
        dvc = getattr(store, "_devincr_cache", None)
        if dvc is not None:
            dvc.invalidate()
        log.error(
            "TPU runtime crash mid-solve (%s); halving affinity chunk "
            "budget to %.3gx and resuming the cycle", e, scale,
        )
        store.record_event(
            "Scheduler/device", "DeviceCrashRecovered",
            f"solve crashed ({type(e).__name__}); chunk budget now "
            f"{scale:.3g}x",
        )
        metrics.device_crash_recoveries.inc()
        stats = getattr(self, "stats", None)
        if stats is not None:
            stats["device_events"].append(
                f"device crash ({type(e).__name__}); "
                f"chunk budget degraded to {scale:.3g}x"
            )
        import jax
        import jax.numpy as jnp

        try:
            jax.device_get(jnp.zeros((8,)) + 1)
        except Exception:
            log.exception("TPU runtime did not recover after crash")
            raise e

    def _allocate(self) -> None:
        from .ops.allocate import solve
        from .ops.wave import solve_wave

        args = get_action_args(self.conf.configurations, "allocate")
        rounds = args.get_int("rounds", 1) if args else 1
        solver = args.get_str("solver", "wave") if args else "wave"
        max_rounds = max(rounds, 1) + (3 if solver == "wave" else 0)
        solve_fn = solve_wave if solver == "wave" else solve

        lanes = self.lanes
        store = self.store
        tracer = self.tracer
        # Null-delta fast cycle (ISSUE 9): when nothing the solve is a
        # function of changed since the previous dispatch — and that
        # dispatch's result was fetched and committed — a re-dispatch
        # would reproduce the identical (empty) outcome, so the cycle
        # skips the solve wholesale.  Any bind-backoff entry disables
        # the skip (backoff windows expire on wall time, not on a
        # mirror version).
        from .ops import devincr as _dvm

        dv_store = None
        if solver == "wave" and _dvm.devincr_on():
            dv_store = _dvm.of_store(store)
            if not store.bind_backoff and dv_store.skip_token is not None:
                tok = self._null_delta_token(solver, rounds)
                if dv_store.skip_token == tok:
                    dv_store.counts["skip"] += 1
                    metrics.device_incremental_solves.inc(mode="skip")
                    self.stats["device_events"].append(
                        "null-delta: solve dispatch skipped")
                    self.stats["solve_skipped"] = True
                    return
        # Solve-input token as of the LAST encode of this lane; the
        # epilogue persists it as the skip token iff nothing mutated
        # after that encode (i.e. the final solve placed nothing).
        self._last_encode_token = None
        retry = False
        rnd = 0
        crashes = 0
        had_aff_chunks = False
        while rnd < max_rounds + crashes:
            if rnd >= max(rounds, 1) + crashes and not retry:
                break
            rnd += 1
            with tracer.span("order", lanes=lanes):
                ordered = self._ordered_jobs()
                prep = self._pending_rows(ordered)
            if prep is None:
                break
            solve_jobs, task_rows = prep
            # Require-contiguous gangs with no whole-gang fabric block
            # sit the solve out (exclusive drop reason
            # topology-infeasible) instead of scattering.
            solve_jobs, task_rows = self._topology_pregate(
                solve_jobs, task_rows)
            if not len(task_rows):
                break
            # Distinct rows entering solves this cycle: retry rounds
            # re-derive a SUBSET of round 1's pending set (commits only
            # shrink it), so the max over rounds is the distinct count —
            # a per-round += would double-count retried rows.
            self.stats["considered"] = max(
                int(self.stats["considered"]), len(task_rows))
            progress_any = False
            never_any = False
            try:
                chunks = list(self._solve_chunks(solve_jobs, task_rows))
                remote = self._remote_solver
                from .parallel.mesh import mesh_from_env

                # store.solve_mesh, or the VOLCANO_TPU_MESH deploy knob
                # (docs/tuning.md); resolves once per store.
                mesh = mesh_from_env(store)
                # Pipelined dispatch (ISSUE 1): a single-chunk wave
                # solve is shipped WITHOUT blocking on the result; the
                # commit lands at the top of the next cycle.  Chunked
                # solves stay synchronous — later chunks must see
                # earlier chunks' placements.  The mesh path pipelines
                # too (ISSUE 7): the InflightSolve payload is simply an
                # AllocResult whose arrays live sharded on the mesh, and
                # fetch()'s jax.device_get assembles them — the
                # staleness guard is host-side numpy either way.
                if (self._pipeline_on and solver == "wave"
                        and len(chunks) == 1):
                    cjobs, crows = chunks[0]
                    had_aff_chunks |= self._chunks_had_terms
                    with tracer.span("encode", lanes=lanes):
                        inputs, pid, profiles, ncls = self._solve_inputs(
                            cjobs, crows, slim=True)
                    # Device-incremental context (ISSUE 9): cache keys
                    # + dirty superset for this dispatch (a token dict
                    # for the remote child, which owns its planes).
                    dv, dv_manifest = self._devincr_prepare(
                        inputs, mesh, remote is not None)
                    kind = "remote" if remote is not None else "local"
                    # The dispatch span opens the solve-id flow; the
                    # matching fetch/commit spans close it in cycle N+1.
                    store._solve_seq += 1
                    solve_id = store._solve_seq
                    with tracer.span(
                            "dispatch", cat="pipeline", flow=solve_id,
                            lanes=lanes, lane="device",
                            args={"kind": kind, "rows": len(crows),
                                  "solve_id": solve_id}):
                        if remote is not None:
                            # The child process rebuilds node classes
                            # from the numpy frame itself; class planes
                            # do not cross the wire — the manifest's
                            # devincr tokens key the child's own
                            # persistent planes.
                            payload = remote.solve_async(
                                inputs, pid, profiles,
                                devincr=dv_manifest)
                            if dv_manifest is not None:
                                # The child solves every frame it
                                # receives: a successful send anchors
                                # the dirty accumulator on its caches.
                                _dvm.of_store(store).anchor_dirty()
                        else:
                            if mesh is not None:
                                payload = self._solve_mesh_dispatch(
                                    mesh, inputs, pid, profiles, ncls,
                                    devincr=dv)
                            else:
                                payload = solve_fn(
                                    *inputs, pid=pid, profiles=profiles,
                                    taint_any=self._taint_any,
                                    node_classes=ncls, devincr=dv)
                                self._record_twophase_lanes()
                            # Start the device->host transfer now; the
                            # fetch at the next cycle's top only waits
                            # for whatever is still in flight.
                            try:
                                payload.assigned.copy_to_host_async()
                            except AttributeError:
                                pass
                        self._last_encode_token = (
                            self._null_delta_token(solver, rounds)
                            if dv_store is not None else None)
                        self._dispatch_async(
                            cjobs, crows, kind, payload, solve_id,
                            devincr_token=self._last_encode_token)
                    self.stats["dispatched_solve_id"] = solve_id
                    break
                for cjobs, crows in chunks:
                    had_aff_chunks |= self._chunks_had_terms
                    with tracer.span("encode", lanes=lanes):
                        inputs, pid, profiles, ncls = self._solve_inputs(
                            cjobs, crows, slim=(solver == "wave"))
                    # Journey: these rows entered a device solve
                    # (first-time rows record; repeats bulk-count).
                    self._journey_rows(crows, "dispatched")
                    # Device-incremental context: single-chunk wave
                    # solves only (chunked solves interleave commits,
                    # so each chunk would need its own proof).
                    dv = dv_manifest = None
                    if solver == "wave" and len(chunks) == 1:
                        dv, dv_manifest = self._devincr_prepare(
                            inputs, mesh, remote is not None)
                        self._last_encode_token = (
                            self._null_delta_token(solver, rounds)
                            if dv_store is not None else None)
                    t0 = time.perf_counter()
                    if solver == "wave" and remote is not None:
                        # Remote-solver split (BASELINE north-star
                        # bridge): inputs cross to the device-owning
                        # process as one C++-packed frame; assignment
                        # vectors come back as numpy.  The child
                        # rebuilds node classes from the frame itself.
                        result = remote.solve(inputs, pid, profiles,
                                              devincr=dv_manifest)
                        if dv_manifest is not None:
                            _dvm.of_store(store).anchor_dirty()
                        mode = getattr(remote, "last_devincr_mode",
                                       None)
                        if mode in ("warm", "full"):
                            metrics.device_incremental_solves.inc(
                                mode=mode)
                    elif solver == "wave" and mesh is not None:
                        result = self._solve_mesh_dispatch(
                            mesh, inputs, pid, profiles, ncls,
                            devincr=dv)
                    elif solver == "wave":
                        result = solve_fn(*inputs, pid=pid,
                                          profiles=profiles,
                                          taint_any=self._taint_any,
                                          node_classes=ncls, devincr=dv)
                        self._record_twophase_lanes()
                    else:
                        result = solve_fn(*inputs)
                    # One batched device->host fetch: through a
                    # remote-TPU tunnel each fetch RPC carries ~100 ms
                    # fixed latency, so three sequential np.asarray()
                    # calls triple the cycle's floor.
                    import jax

                    for arr in (result.assigned, result.never_ready,
                                result.fit_failed):
                        try:
                            arr.copy_to_host_async()
                        except AttributeError:
                            pass
                    # Commit prep that doesn't need the assignments
                    # overlaps the device solve + transfer wait.
                    req_gather = self.m.c_req.gather(crows)
                    self._obj_arrays()
                    if solver == "wave":
                        # The wave solver always carries the two-phase
                        # fallback counters (zeros when disabled); ride
                        # the same batched fetch.
                        (assigned, never_ready, fit_failed, fb_ex,
                         fb_aff) = jax.device_get(
                            (result.assigned, result.never_ready,
                             result.fit_failed, result.fb_exhausted,
                             result.fb_affinity)
                        )
                        self._count_shortlist_fb(int(fb_ex), int(fb_aff))
                    else:
                        assigned, never_ready, fit_failed = (
                            jax.device_get(
                                (result.assigned, result.never_ready,
                                 result.fit_failed)
                            )
                        )
                    assigned = assigned[:len(crows)]
                    # Fabric gate: require-contiguous gangs scattered
                    # across blocks are vetoed before the commit.
                    assigned = self._topology_gate(crows, assigned)
                    dt_dev = time.perf_counter() - t0
                    lanes["device"] = lanes.get("device", 0.0) + dt_dev
                    metrics.device_solve_latency.observe(dt_dev * 1e3)
                    tracer.event("device_solve", "device",
                                 time.perf_counter_ns()
                                 - int(dt_dev * 1e9),
                                 int(dt_dev * 1e9), tid="cycle",
                                 args={"rows": len(crows)})
                    with tracer.span("commit", lanes=lanes):
                        progress = self._commit(
                            cjobs, crows, assigned, never_ready,
                            fit_failed, req_gather,
                        )
                    progress_any |= progress
                    never_any |= bool(never_ready.any())
            except Exception as e:
                # Mid-solve TPU crash: committed chunks already landed;
                # the crashed chunk mutated nothing host-side.  Degrade
                # the chunk budget and re-derive the remaining pending
                # work (committed tasks are no longer pending).
                if crashes >= 3 or not self._is_device_crash(e):
                    raise
                crashes += 1
                self._on_device_crash(e)
                retry = True
                continue
            retry = never_any and progress_any
            if not progress_any:
                break
        if had_aff_chunks and not crashes:
            # Gradual budget recovery: after _SCALE_RECOVER_AFTER clean
            # affinity cycles the degraded budget doubles back toward 1.
            scale = getattr(store, "_aff_budget_scale", 1.0)
            if scale < 1.0:
                clean = getattr(store, "_aff_clean_cycles", 0) + 1
                if clean >= self._SCALE_RECOVER_AFTER:
                    store._aff_budget_scale = min(1.0, scale * 2.0)
                    store._aff_clean_cycles = 0
                else:
                    store._aff_clean_cycles = clean
        if dv_store is not None:
            # Persist the skip proof iff nothing mutated after the last
            # encode — i.e. the final solve of this lane placed nothing
            # (a pipelined dispatch counts: its commit lands next cycle
            # and bumps the mutation counter if it binds, breaking the
            # proof before the next skip check reads it).
            tok_now = (self._null_delta_token(solver, rounds)
                       if self._last_encode_token is not None else None)
            dv_store.skip_token = (
                tok_now if tok_now is not None
                and tok_now == self._last_encode_token else None)

    # --------------------------------------- device-lane incrementality

    def _dirty_nodes_now(self) -> Optional[np.ndarray]:
        """Node rows touched by the mirror's still-unconsumed dirty pod
        rows (old node from the aggregate shadow — the state as of the
        last derive — plus current node), or None when tracking
        overflowed.  Together with the derive-time captures accumulated
        on the DeviceIncremental this is a superset of every node whose
        solve inputs changed since the previous solve (ISSUE 9)."""
        m = self.m
        if m._pod_dirty_overflow:
            return None
        rows = np.flatnonzero(m._pod_dirty_mask[:self.Pn])
        if not len(rows):
            return np.zeros(0, np.int64)
        aggr = self.aggr
        if len(aggr.sh_node) < self.Pn:
            return None
        nds = np.concatenate([
            m.p_node[rows].astype(np.int64),
            aggr.sh_node[rows].astype(np.int64),
        ])
        return np.unique(nds[nds >= 0])

    # Affinity count tables past this size are not content-hashed per
    # solve; warm shortlists simply disable (full re-rank — today's
    # behavior) there.  8 MB ≈ 8 ms of blake2b worst case on the cycle
    # thread, a bounded fraction of the warm win; beyond it the hash
    # itself would eat the saving.  Env-overridable
    # (VOLCANO_TPU_DEVINCR_CNT0_HASH_MAX, bytes): at the 100k-node
    # tier the [E, D] pair outgrows 8 MB while the warm win ALSO grows
    # with N, so deployments whose device lane dwarfs the hash cost
    # raise the cap instead of silently losing warm shortlists at the
    # exact scale they matter most.
    _DEVINCR_CNT0_HASH_MAX = 8_000_000

    @staticmethod
    def _devincr_cnt0_hash_max() -> int:
        raw = os.environ.get("VOLCANO_TPU_DEVINCR_CNT0_HASH_MAX")
        if raw:
            try:
                return max(0, int(raw))
            except ValueError:
                pass
        return FastCycle._DEVINCR_CNT0_HASH_MAX

    def _devincr_prepare(self, inputs, mesh, remote: bool):
        """Assemble the device-incremental cache keys + dirty superset
        for the solve about to dispatch (ISSUE 9).  Returns ``(dv,
        manifest)``: the store's DeviceIncremental primed via
        ``begin_solve`` for local/mesh dispatches, or a JSON-able token
        dict for the remote solver child (which keeps its own
        persistent planes keyed on these frames' tokens)."""
        import hashlib

        from .ops import devincr as _dvm
        from .ops import wave as _wave_mod

        m = self.m
        if not _dvm.devincr_on() or not _wave_mod._two_phase_on():
            return None, None
        gen = getattr(self, "_profile_gen", None)
        if gen is None:
            return None, None
        ws = inputs[4]
        wt = (
            float(ws.binpack_weight),
            tuple(np.asarray(ws.binpack_res, np.float32).tolist()),
            float(ws.least_req_weight), float(ws.most_req_weight),
            float(ws.balanced_weight), float(ws.node_affinity_weight),
        )
        cls_tok = self._cls_sig or f"identity-{m.epoch}"
        static_key = (cls_tok, int(gen), wt, int(self._solve_np),
                      self.R)
        aff = inputs[7]
        cnt0 = np.asarray(aff.cnt0)
        warm_key = None
        if cnt0.nbytes <= self._devincr_cnt0_hash_max():
            if cnt0.any():
                h = hashlib.blake2b(digest_size=16)
                h.update(repr(cnt0.shape).encode())
                h.update(np.ascontiguousarray(cnt0).tobytes())
                cnt0_tok = h.hexdigest()
            else:
                cnt0_tok = f"z{cnt0.shape}"
            warm_key = (static_key, int(m.epoch),
                        int(m.node_liveness_gen), int(m.compact_gen),
                        self.Nn, cnt0_tok)
        dv = self.store._devincr_cache
        if dv is None:
            dv = _dvm.of_store(self.store)
        dirty = dv.take_dirty(self._dirty_nodes_now())
        if remote:
            return None, {
                "static_key": repr(static_key),
                "warm_key": repr(warm_key) if warm_key is not None
                else None,
                "dirty_nodes": (dirty.tolist() if dirty is not None
                                else None),
            }
        dv.set_mesh(mesh)
        dv.begin_solve(static_key, warm_key, dirty)
        return dv, None

    def _null_delta_token(self, solver: str, rounds: int):
        """Content token over every input the allocate lane's solve is
        a function of: equality across cycles proves a re-dispatched
        solve would see bit-equal inputs and reproduce the previous
        (empty) outcome — the null-delta fast cycle's skip proof
        (ISSUE 9).  Conservative by construction: any mirror mutation
        (mutation_seq/dirty_seq), node churn (epoch/liveness), row
        renumbering (compact_gen), PodGroup phase/min-member drift, or
        queue share/deserved change breaks equality."""
        import hashlib

        m = self.m
        Jn = self.Jn
        h = hashlib.blake2b(digest_size=16)
        h.update(m.j_phase_code[:Jn].tobytes())
        h.update(m.j_minav[:Jn].tobytes())
        h.update(np.ascontiguousarray(self.q_deserved).tobytes())
        h.update(np.ascontiguousarray(self.q_alloc).tobytes())
        return (
            int(m.mutation_seq), int(m.epoch), int(m.compact_gen),
            int(m.dirty_seq), int(m.node_liveness_gen),
            self.Pn, self.Nn, Jn, self.Qn, self.R,
            h.hexdigest(), solver, int(rounds),
            tuple(self.action_names), tuple(sorted(self.plugin_opts)),
        )

    # ------------------------------------------------- pipelined sessions

    def _dispatch_async(self, cjobs: List[int], crows: np.ndarray,
                        kind: str, payload, solve_id: int = 0,
                        devincr_token=None) -> None:
        """Park a dispatched-but-unread device solve on the store; the
        device round trip then runs concurrently with this cycle's
        backfill/close/enqueue and the next cycle's derive, and
        ``_commit_inflight`` lands it at the top of cycle N+1 (the
        double-buffered session of ISSUE 1).  ``payload`` is either a
        jax ``AllocResult`` with ``copy_to_host_async`` already issued
        (kind "local") or a ``solver_service.PendingSolve`` (kind
        "remote"); ``solve_id`` is the trace flow id linking this
        dispatch to next cycle's fetch/commit spans."""
        from .pipeline import InflightSolve

        # Commit prep that needs no assignment overlaps the round trip.
        req_gather = self.m.c_req.gather(crows)
        # Journey: these rows entered a device solve (first-time rows
        # record with the flow's solve-id; repeats bulk-count).
        self._journey_rows(crows, "dispatched", solve_id=solve_id)
        shard_idx = None if self.shard is None else self.shard.index
        shard_seq = None
        if self.shard is not None:
            # Cross-shard gate token: sibling commits bump the first
            # component, queue steals the second (shard.py, ISSUE 16).
            shard_seq = (int(self.m.shard_commit_seq),
                         int(self.shard.table.epoch))
        inflight = InflightSolve(
            kind, payload, list(cjobs), crows, req_gather,
            self.m.mutation_seq, self.m.epoch, self.m.compact_gen,
            self.Nn, solve_id=solve_id, dirty_seq=self.m.dirty_seq,
            devincr_token=devincr_token, shard=shard_idx,
            shard_seq=shard_seq,
        )
        if self.shard is None:
            self.store._inflight_solve = inflight
        else:
            self.store._shard_inflight[self.shard.index] = inflight

    def _solve_mesh_dispatch(self, mesh, inputs, pid, profiles, ncls,
                             devincr=None):
        """Dispatch the wave solve over the device mesh: node axis +
        affinity count tensors sharded (parallel/mesh.py
        shard_wave_inputs), the two-phase rankings shard-local with the
        per-profile winner reduction as the only cross-chip step
        (ops/wave.py _topk_nodes).  The sharded devsnap planes pass
        straight through committed; the remaining epoch-stable plane
        (aff.node_dom) rides the store's declared mesh plane cache
        (cleared on close()/compaction, guarded by the store lock this
        cycle already holds)."""
        from .parallel.mesh import sharded_solve_wave_cycle

        result = sharded_solve_wave_cycle(
            mesh, inputs, pid, profiles,
            plane_cache=self.store._mesh_plane_cache,
            epoch=self.m.epoch,
            taint_any=self._taint_any,
            node_classes=ncls,
            devincr=devincr,
        )
        self._record_twophase_lanes()
        return result

    def _commit_inflight(self) -> None:
        """Fetch + commit the previous cycle's dispatched solve (runs
        first, before this cycle's actions).  A staleness guard drops
        rows invalidated by store mutations that landed during the
        overlap — pod deleted/bound/evicted, node gone, capacity taken
        by the fast path — the same per-task semantics the async-bind
        failure queue already has; everything else commits exactly as a
        synchronous cycle would have."""
        from .pipeline import take_inflight

        inflight = take_inflight(
            self.store,
            None if self.shard is None else self.shard.index,
        )
        if inflight is None:
            return
        m = self.m
        lanes = self.lanes
        tracer = self.tracer
        flow = inflight.solve_id or None
        # committed_solve_id is set only once the fetch SUCCEEDS: a
        # record showing a committed id with zero drops for a solve
        # whose reply was lost would read as a clean commit — exactly
        # the investigation the recorder exists for.
        if inflight.compact_gen != m.compact_gen:
            # Pod rows were renumbered while the solve was in flight;
            # the whole result is void (rows are otherwise stable for a
            # pod's lifetime).  The pods are still Pending and re-place
            # this cycle.
            log.info("in-flight solve predates a mirror compaction; "
                     "dropped (%d rows re-place this cycle)",
                     len(inflight.task_rows))
            self._count_drops({"compaction": len(inflight.task_rows)})
            # Row indices are void, but the compaction preserved uids
            # 1:1 — the journey masks rebuilt on the gen bump, so the
            # uid lookup below must NOT use the stale rows.  The void
            # is whole-result: attribute it without row translation.
            jr = getattr(self.store, "journey", None)
            if jr is not None:
                jr.repeat_rows(len(inflight.task_rows), "unbound")
            self.stats["device_events"].append(
                f"solve {inflight.solve_id} voided by mirror compaction"
            )
            inflight.abandon()
            return
        fetch_span = tracer.span(
            "inflight_fetch", cat="pipeline", flow=flow, lanes=lanes,
            lane="device",
            args={"rows": len(inflight.task_rows),
                  "solve_id": inflight.solve_id},
        )
        try:
            with fetch_span:
                assigned = inflight.fetch()
        except Exception as e:
            if inflight.kind == "remote" and isinstance(
                    e, (OSError, ConnectionError, ValueError)):
                # Lost reply (solver child died, connection dropped):
                # the pods are still Pending and re-place below; a
                # persistently DEAD child surfaces synchronously at
                # this cycle's own dispatch (solve_async's send) — but
                # a child that keeps replying garbage (codec drift)
                # never fails the send, so consecutive fetch failures
                # are capped: past the cap the cycle fails loudly and
                # the scheduler's failure/health accounting takes over
                # instead of looping forever placing nothing.
                fails = getattr(
                    self.store, "_remote_fetch_fails", 0) + 1
                self.store._remote_fetch_fails = fails
                if fails >= self.REMOTE_FETCH_FAIL_CAP:
                    log.error(
                        "in-flight remote solve fetch failed %d "
                        "consecutive times; failing the cycle", fails,
                    )
                    raise
                log.warning(
                    "in-flight remote solve reply lost; %d rows "
                    "re-place this cycle",
                    len(inflight.task_rows), exc_info=True,
                )
                self._count_drops(
                    {"lost-reply": len(inflight.task_rows)})
                self._journey_rows(inflight.task_rows, "dropped",
                                   solve_id=inflight.solve_id,
                                   detail="lost-reply")
                self.stats["device_events"].append(
                    f"solve {inflight.solve_id} reply lost "
                    f"({type(e).__name__}); fetch failure "
                    f"{fails}/{self.REMOTE_FETCH_FAIL_CAP}"
                )
                self._devincr_drop_skip()
                self._record_pool_fetch()
                return
            if self._is_device_crash(e):
                # Execution-time crashes surface at the async fetch,
                # not at dispatch: route them through the same budget
                # degradation the synchronous solve gets (halve the
                # affinity chunk budget, re-probe the runtime; raises
                # when the device stayed down so the scheduler's
                # failure/health accounting takes over).
                log.warning(
                    "in-flight solve fetch hit a device crash; %d "
                    "rows re-place this cycle",
                    len(inflight.task_rows),
                )
                # The crash event itself lands via _on_device_crash.
                self._count_drops(
                    {"device-crash": len(inflight.task_rows)})
                self._journey_rows(inflight.task_rows, "dropped",
                                   solve_id=inflight.solve_id,
                                   detail="device-crash")
                self._devincr_drop_skip()
                self._on_device_crash(e)
                return
            # A programming error must propagate, exactly as it would
            # from a synchronous solve.
            raise
        self.store._remote_fetch_fails = 0
        self.stats["committed_solve_id"] = inflight.solve_id or None
        self._count_shortlist_fb(*inflight.fallbacks)
        self._record_pool_fetch()
        if inflight.kind == "remote":
            # The child reported its device-incremental decision in the
            # reply manifest (decoded by the fetch above).
            mode = getattr(self._remote_solver,
                           "last_devincr_mode", None)
            if mode in ("warm", "full"):
                metrics.device_incremental_solves.inc(mode=mode)
        # The residual wait is the pipeline's health signal: it
        # approaches zero exactly when the overlap works.  The
        # dispatch->available round trip is unobservable here (the
        # solve may have finished during the inter-cycle sleep), so
        # device_solve_latency keeps its synchronous-solve meaning and
        # gets nothing from this path.
        fetch_wait_ms = fetch_span.dur_ns / 1e6
        metrics.inflight_fetch_wait.observe(fetch_wait_ms)
        self.stats["fetch_wait_ms"] = round(fetch_wait_ms, 3)
        # Dispatch-vs-commit delta of the solve LANDING this cycle (how
        # much the world moved during its overlap); the solve this cycle
        # dispatches is paired in the NEXT cycle's record.
        self.stats["mut_at_dispatch"] = int(inflight.mutation_seq)
        self.stats["epoch_at_dispatch"] = int(inflight.epoch)
        self.stats["mut_at_commit"] = int(m.mutation_seq)
        self.stats["epoch_at_commit"] = int(m.epoch)
        with tracer.span(
                "inflight_commit", cat="pipeline", flow=flow,
                lanes=lanes, lane="commit",
                args={"solve_id": inflight.solve_id,
                      "dispatch_mutation_seq": inflight.mutation_seq,
                      "dispatch_epoch": inflight.epoch}):
            task_rows = inflight.task_rows
            assigned = np.asarray(assigned[:len(task_rows)]).astype(
                np.int64, copy=False)
            req_gather = inflight.req_gather
            stale = (m.mutation_seq != inflight.mutation_seq
                     or self.Nn != inflight.n_nodes)
            # Cross-shard commit gate (shard.py, ISSUE 16): the token
            # captured at dispatch was (mirror.shard_commit_seq,
            # ownership-table handoff epoch).  An advance of the first
            # component means ANOTHER shard committed binds during the
            # overlap (our own shard never commits after its own
            # pipelined dispatch within one cycle); the second forces
            # re-validation across a queue steal even when nothing else
            # moved.  mutation_seq already makes the commit-race case
            # stale — cross_shard only re-attributes the voids.
            cross_shard = False
            if self.shard is not None and inflight.shard_seq is not None:
                cur_seq = (int(m.shard_commit_seq),
                           int(self.shard.table.epoch))
                cross_shard = cur_seq != inflight.shard_seq
                stale = stale or cross_shard
            if not stale and m.dirty_seq != inflight.dirty_seq:
                # Agreement contract (ISSUE 8): every writer that marks
                # the dirty set also bumps the mutation counter, so a
                # quiet mutation_seq with an advanced dirty_seq means a
                # writer broke the contract — revalidate defensively
                # instead of skipping on the broken proof.
                log.error(
                    "dirty set advanced (%d -> %d) without a "
                    "mutation_seq bump; revalidating in-flight solve "
                    "defensively", inflight.dirty_seq, m.dirty_seq,
                )
                stale = True
            if stale:
                assigned = self._revalidate_inflight(
                    task_rows, assigned,
                    node_churn=(m.epoch != inflight.epoch),
                    cross_shard=cross_shard,
                )
                # Row set changed: let _commit re-gather the committed
                # rows.
                req_gather = None
            # Fabric gate after the staleness guard: rows it vetoes are
            # already -1, so the topology-infeasible reason stays
            # exclusive with the revalidation vocabulary.
            assigned = self._topology_gate(
                task_rows, assigned, solve_id=inflight.solve_id)
            if (assigned >= 0).any():
                self._commit(
                    inflight.solve_jobs, task_rows, assigned,
                    np.zeros(len(inflight.solve_jobs), bool),
                    np.zeros(len(task_rows), bool), req_gather,
                )

    def _revalidate_inflight(self, task_rows: np.ndarray,
                             assigned: np.ndarray,
                             node_churn: bool = False,
                             cross_shard: bool = False) -> np.ndarray:
        """Drop assignment rows invalidated during the overlap; returns
        ``assigned`` with conflicting rows forced to -1.

        Checks, all vectorized: the pod row is still alive + Pending
        (deletes, fast-path binds, evictions, bind-failure resyncs all
        leave some other status), the target node row still exists, is
        alive and ready, and charging the surviving rows neither
        oversubscribes a node's allocatable nor its task slots (rows on
        a conflicted node are dropped wholesale — conservative, the
        next cycle re-places them).

        Constraint-sensitive rows cannot be re-checked cheaply, so they
        drop conservatively and re-place next cycle against fresh
        state: pods with inter-pod terms whenever ANY mutation landed
        (a peer's placement may have moved the affinity landscape), and
        pods with a node selector, node-affinity terms, or tolerations
        when ``node_churn`` says the node table itself changed (labels/
        taints the solve matched against are stale).

        Every dropped row is attributed to exactly ONE reason (first
        matching check, in the order below), counted into the cycle's
        flight record and the ``volcano_pipeline_stale_drop_rows_total``
        series — the per-reason totals sum exactly to the rows dropped:

        - ``deleted``              pod row no longer alive
        - ``competing-bind``       alive but no longer Pending (bound /
                                   evicted / resynced elsewhere)
        - ``constraint-sensitive`` inter-pod terms + any mutation
        - ``node-epoch-churn``     node-sensitive constraints under
                                   epoch churn, or the target node row
                                   gone / not ready
        - ``capacity-taken``       surviving charge would oversubscribe
                                   the node's allocatable or task slots

        One more exclusive reason joins this vocabulary downstream:
        ``topology-infeasible``, applied by the fabric gate
        (``_topology_gate``) that runs right after this guard — a
        require-contiguous gang whose SURVIVING rows span more than one
        fabric block drops wholesale there, so the attribution stays
        one-reason-per-row across both stages.

        Under the sharded control plane (``cross_shard=True``: another
        shard committed binds, or a queue steal landed, during the
        overlap — shard.py, ISSUE 16) the two reasons a sibling's binds
        produce — ``competing-bind`` and ``capacity-taken`` — are
        re-attributed as the single ``cross-shard-conflict`` reason and
        fed to ``volcano_shard_conflicts_total{outcome}`` by losing
        check.  The counts MOVE (never double-counted), so the
        per-reason totals still sum exactly to the rows dropped.
        """
        m = self.m
        nn = self.Nn
        live = assigned >= 0
        alive_m = m.p_alive[task_rows]
        pending_m = alive_m & (m.p_status[task_rows] == ST_PENDING)
        r_deleted = live & ~alive_m
        r_competing = live & alive_m & ~pending_m
        ok = live & pending_m
        has_ip = m.p_has_ip[task_rows]
        r_constraint = ok & has_ip
        ok &= ~has_ip
        r_churn = np.zeros(len(task_rows), bool)
        if node_churn:
            sensitive = (
                m.p_has_tol[task_rows]
                | (m.p_aff_lo[task_rows] < m.p_aff_hi[task_rows])
            )
            er, _li = m.c_sel.gather(task_rows)
            has_sel = np.zeros(len(task_rows), bool)
            has_sel[er] = True
            r_churn |= ok & (sensitive | has_sel)
            ok &= ~(sensitive | has_sel)
        # Target node gone (row beyond today's table) or not ready:
        # the node table moved under the solve — churn.
        node_gone = assigned >= nn
        r_churn |= ok & node_gone
        ok &= ~node_gone
        node = np.clip(assigned, 0, max(nn - 1, 0))
        if nn:
            not_ready = ~self.n_ready[node]
            r_churn |= ok & not_ready
            ok &= ~not_ready
        r_capacity = np.zeros(len(task_rows), bool)
        if ok.any():
            # Capacity re-check against TODAY's derive: the req gather
            # is re-read (a pod update may have changed requests in
            # place).
            rows_ok = task_rows[ok]
            nodes_ok = assigned[ok]
            er, si, v = m.c_req.gather(rows_ok)
            add = np.bincount(
                nodes_ok[er].astype(np.int64) * self.R + si,
                weights=v, minlength=nn * self.R,
            ).reshape(nn, self.R).astype(F)
            ntasks_add = np.bincount(nodes_ok, minlength=nn).astype(I)
            bad = (
                ((self.n_used + add) > self.n_alloc + self.eps[None, :])
                .any(axis=1)
                | ((self.n_ntasks + ntasks_add) > self.n_maxtasks)
            )
            if bad.any():
                r_capacity = ok & bad[node]
                ok &= ~bad[node]
        drops = {
            "deleted": int(np.count_nonzero(r_deleted)),
            "competing-bind": int(np.count_nonzero(r_competing)),
            "constraint-sensitive": int(np.count_nonzero(r_constraint)),
            "node-epoch-churn": int(np.count_nonzero(r_churn)),
            "capacity-taken": int(np.count_nonzero(r_capacity)),
        }
        if cross_shard:
            n_comp = drops.pop("competing-bind")
            n_cap = drops.pop("capacity-taken")
            drops["cross-shard-conflict"] = n_comp + n_cap
            if n_comp:
                metrics.shard_conflicts.inc(
                    n_comp, outcome="competing-bind")
            if n_cap:
                metrics.shard_conflicts.inc(
                    n_cap, outcome="capacity-taken")
            if self.shard is not None:
                self.shard.conflicts += n_comp + n_cap
        self._count_drops(drops)
        # Journey: per-pod exclusive drop attribution (the why-pending
        # evidence chain).  Drop sets are churn-sized; cross-shard
        # conflicts carry the ownership-table handoff epoch so the
        # stitched timeline shows WHICH handoff generation lost.
        if getattr(self.store, "journey", None) is not None:
            epoch = (-1 if self.shard is None
                     else int(self.shard.table.epoch))
            for mask, reason in ((r_deleted, "deleted"),
                                 (r_competing, "competing-bind"),
                                 (r_constraint, "constraint-sensitive"),
                                 (r_churn, "node-epoch-churn"),
                                 (r_capacity, "capacity-taken")):
                if not mask.any():
                    continue
                if cross_shard and reason in ("competing-bind",
                                              "capacity-taken"):
                    reason = "cross-shard-conflict"
                self._journey_rows(task_rows[mask], "dropped",
                                   epoch=epoch, detail=reason)
        out = np.where(ok, assigned, -1)
        n_drop = int(np.count_nonzero(live & (out < 0)))
        if n_drop and not ok.any():
            log.info("in-flight solve fully invalidated by "
                     "concurrent mutations (%d rows)", n_drop)
        elif n_drop:
            log.info(
                "staleness guard dropped %d/%d in-flight rows "
                "(concurrent store mutations); survivors commit",
                n_drop, int(np.count_nonzero(live)),
            )
        return out

    # ------------------------------------------------------ topology gates

    def _topo_active(self) -> bool:
        """Cheap master gate for every fabric-topology hook: the kill
        switch is up, at least one job carries a constraint, and the
        cluster has fabric-labeled nodes.  An unlabeled cluster (or
        ``VOLCANO_TPU_TOPOLOGY=0``) short-circuits every hook, keeping
        the solve inputs — and the remote wire frames — byte-identical
        to the pre-topology build."""
        from .ops import topology as topo

        if not topo.topology_on():
            return False
        m = self.m
        if self.Jn == 0 or not m.j_topo[:self.Jn].any():
            return False
        return topo.has_fabric(m)

    def _topo_block_fit(self, jrow: int):
        """Per-fabric-block whole-gang fit of job ``jrow``'s pending
        tasks (ops/topology.gang_block_fit, fetched host-side), or None
        when the gang has nothing pending.  Returns a dict with the
        padded [Np] block-id plane, the per-block cfit/whole/score
        (trash row sliced off), and the profile counts."""
        import jax

        from .ops import topology as topo

        m = self.m
        _, block, n_blocks = topo.fabric_planes(m)
        if n_blocks == 0:
            return None
        Pn = self.Pn
        pend = np.flatnonzero(
            m.p_alive[:Pn] & (m.p_status[:Pn] == ST_PENDING)
            & ~m.p_be[:Pn] & (self.jobr == jrow)
        )
        if not len(pend):
            return None
        # Distinct profiles of the gang's pending tasks -> dense [U, R]
        # init-request table + per-profile counts (same interning
        # _plan_rebalance's prof_req uses).
        _, first, counts = np.unique(
            m.p_prof[pend], return_index=True, return_counts=True
        )
        order = np.argsort(first)
        urows = pend[first[order]]
        counts = counts[order]
        # Pow2 buckets on every static axis (profile rows, node rows,
        # block rows) so fabric growth and gang-shape churn share a
        # bounded set of compiled kernels (VCL204: planes are padded to
        # the _solve_inputs buckets).
        Up = _pow2(max(len(urows), 1), 4)
        prof_req = np.zeros((Up, self.R), F)
        er, si, v = m.c_init_req.gather(urows)
        prof_req[er, si] = v
        prof_cnt = np.zeros((Up,), I)
        prof_cnt[:len(urows)] = counts
        Np = _pow2(max(self.Nn, 1))

        def padN(a, fill=0):
            out = np.full((Np, *a.shape[1:]), fill, a.dtype)
            out[:len(a)] = a
            return out

        bid = np.full((Np,), -1, I)
        bid[:self.Nn] = block[:self.Nn]
        Bp = _pow2(max(n_blocks, 1), 4)
        bf = topo.gang_block_fit(
            padN(self.n_idle.astype(F)), padN(self.n_ready),
            padN(self.n_ntasks), padN(self.n_maxtasks), bid,
            prof_req, prof_cnt, self.eps, n_blocks=Bp,
        )
        cfit, whole, score = jax.device_get((bf.cfit, bf.whole, bf.score))
        return {
            "block": bid, "n_blocks": n_blocks,
            "cfit": cfit[:n_blocks], "whole": whole[:n_blocks],
            "score": score[:n_blocks], "prof_cnt": prof_cnt,
        }

    def _topology_pregate(self, solve_jobs: List[int],
                          task_rows: np.ndarray):
        """Require-contiguous gate ahead of the solve: a gang no fabric
        block can host WHOLE is excluded from the solve inputs — it
        reports the exclusive drop reason ``topology-infeasible``
        (journey + placement counter, on the gating transition) instead
        of scattering across blocks.  The starvation this creates is
        what the rebalance lane's fabric-defrag targeting relieves."""
        if not self._topo_active():
            return solve_jobs, task_rows
        m = self.m
        jt = m.j_topo
        req_jobs = [j for j in solve_jobs if jt[j] == TOPOLOGY_REQUIRE]
        if not req_jobs:
            return solve_jobs, task_rows
        gated = getattr(self.store, "_topo_gated", None)
        if gated is None:
            gated = self.store._topo_gated = set()
        drop: List[int] = []
        for j in req_jobs:
            tf = self._topo_block_fit(j)
            if tf is None:
                continue
            uid = m.j_uid[j]
            if tf["whole"].any():
                gated.discard(uid)
                continue
            drop.append(j)
            if uid not in gated:
                # Transition accounting only: the gang re-gates every
                # cycle until the fabric changes, and re-counting a
                # standing condition per cycle would swamp both series.
                gated.add(uid)
                metrics.topology_placements.inc(outcome="infeasible")
                self._journey_rows(
                    task_rows[self.jobr[task_rows] == j], "dropped",
                    detail="topology-infeasible",
                )
                log.info(
                    "gang %s requires contiguous placement but no "
                    "fabric block can host it whole; held out of the "
                    "solve (topology-infeasible)", uid,
                )
        if not drop:
            return solve_jobs, task_rows
        dropset = np.zeros(self.Jn, bool)
        dropset[drop] = True
        task_rows = task_rows[~dropset[self.jobr[task_rows]]]
        solve_jobs = [j for j in solve_jobs if not dropset[j]]
        return solve_jobs, task_rows

    def _topo_node_bias(self, solve_jobs, n_pad: int):
        """[n_pad] f32 node-order bias steering the FIRST constrained
        gang of the solve toward its selected fabric block
        (ops/topology.contig_bias), or None when no constraint is live
        — the None case keeps solve_args an 8-tuple, which is the
        wire-byte identity guarantee of the kill switch."""
        from .ops import topology as topo

        if not self._topo_active():
            return None
        jt = self.m.j_topo
        target = next((int(j) for j in solve_jobs if jt[j]), None)
        if target is None:
            return None
        tf = self._topo_block_fit(target)
        if tf is None:
            return None
        sel = topo.select_block(
            tf["whole"], tf["score"],
            require=int(jt[target]) == TOPOLOGY_REQUIRE,
        )
        if sel < 0:
            return None
        bias = topo.contig_bias(tf["block"], sel, n_pad)
        return bias if bias.any() else None

    def _topology_gate(self, task_rows: np.ndarray,
                       assigned: np.ndarray, *,
                       solve_id: int = 0) -> np.ndarray:
        """Post-solve fabric gate: decide each constrained gang's
        placement outcome by the block span of its assigned rows.

        ``require-contiguous`` gangs spanning more than one block (or
        landing off-fabric) are vetoed wholesale — rows drop to -1
        under the exclusive reason ``topology-infeasible`` before any
        commit, so a constrained gang is never bound scattered (the
        constraint's atomicity guarantee; ``gang_block_fit`` is only a
        per-profile upper bound, this is the exact enforcer).  Passing
        gangs count into ``volcano_topology_placements_total`` as
        ``contiguous`` or ``scattered``."""
        from .ops import topology as topo

        if not len(task_rows) or not self._topo_active():
            return assigned
        m = self.m
        jt = m.j_topo
        jobr_rows = self.jobr[task_rows]
        jobs_here = np.unique(jobr_rows)
        topo_jobs = [int(j) for j in jobs_here if j >= 0 and jt[j]]
        if not topo_jobs:
            return assigned
        _, block, _ = topo.fabric_planes(m)
        blk = np.full((max(self.Nn, 1),), -1, I)
        blk[:self.Nn] = block[:self.Nn]
        assigned = np.asarray(assigned).copy()
        veto = np.zeros(len(task_rows), bool)
        for j in topo_jobs:
            rows_mask = ((jobr_rows == j) & (assigned >= 0)
                         & (assigned < self.Nn))
            if not rows_mask.any():
                continue
            bsel = np.unique(blk[assigned[rows_mask]])
            contiguous = bool(len(bsel) == 1 and bsel[0] >= 0)
            if jt[j] == TOPOLOGY_REQUIRE and not contiguous:
                veto |= (jobr_rows == j) & (assigned >= 0)
                metrics.topology_placements.inc(outcome="infeasible")
            else:
                metrics.topology_placements.inc(
                    outcome="contiguous" if contiguous else "scattered"
                )
        if veto.any():
            assigned[veto] = -1
            self._count_drops({"topology-infeasible":
                               int(np.count_nonzero(veto))})
            self._journey_rows(task_rows[veto], "dropped",
                               solve_id=solve_id,
                               detail="topology-infeasible")
        return assigned

    def _solve_chunks(self, solve_jobs: List[int], task_rows: np.ndarray):
        """Split one solve call at job boundaries when the affinity count
        tensors would blow the device-memory budget.

        The solver carries two dense [E, D] int32 count tensors; at
        hyperscale with hostname-domain terms (50k nodes, 12k+ terms)
        that is tens of GB.  Terms active per chunk shrink with the
        chunk's job population, so solving in job-aligned chunks with a
        host commit in between bounds the footprint — and later chunks
        legitimately see earlier chunks' placements (the same state the
        reference's sequential walk would show them)."""
        m = self.m
        raw = os.environ.get("VOLCANO_TPU_AFF_BUDGET_MB", "1024")
        try:
            budget = float(raw) * 1e6
        except ValueError:
            budget = float("nan")
        if not (0 < budget < float("inf")):  # catches NaN, 0, negatives
            if raw != "1024":
                log.warning(
                    "VOLCANO_TPU_AFF_BUDGET_MB=%r is not a positive "
                    "number; using 1024", raw,
                )
            budget = 1024e6
        # Crash-recovery degradation (see _on_device_crash): smaller
        # chunks bound the device footprint after a TPU-worker crash.
        budget *= getattr(self.store, "_aff_budget_scale", 1.0)
        # Footprint scales with the terms the PENDING rows actually touch
        # (the solver compacts [E, D] to active terms), not the mirror's
        # full interned term table.
        er_a, ei_a = m.c_ip_aff.gather(task_rows)
        er_n, ei_n = m.c_ip_anti.gather(task_rows)
        er_s, ei_s, _ = m.c_ip_soft.gather(task_rows)
        refs_row = np.concatenate([er_a, er_n, er_s])
        refs_term = np.concatenate([ei_a, ei_n, ei_s])
        from .ops.wave import bucket_pow2

        E = len(np.unique(refs_term)) if len(refs_term) else 0
        # Crash-recovery bookkeeping: only solves that actually carried
        # affinity terms count as "clean affinity cycles" for walking
        # the degraded chunk budget back up.
        self._chunks_had_terms = E > 0
        # Force domain interning BEFORE sizing (only when terms exist —
        # plain workloads skip the O(N x K) interning walk): the domain
        # table fills lazily in node_dom() (hostname domains intern per
        # node row), so a fresh store's first budget decision otherwise
        # sees D=1, estimates the count tensors at ~0.1 MB, and never
        # chunks — shipping an [E, D~N] int32 pair (6.5 GB at
        # 50k x 500k) that intermittently OOM-crashed the TPU worker
        # (the BASELINE.md hyperscale known limit, root-caused round 4).
        if E:
            m.node_dom()
        D = max(1, len(m.domains))
        # Two int32 [Ep, D] tensors; budget against the solver's actual
        # padded bucket (headroom + pow2 round-up reaches 2.5x raw).
        cost = float(bucket_pow2(E, floor=1)) * D * 8.0 if E else 0.0
        if cost <= budget or len(solve_jobs) <= 1:
            if cost > budget:
                log.warning(
                    "affinity count tensors ~%.0f MB exceed the %.0f MB "
                    "budget but a single job cannot be split",
                    cost / 1e6, budget / 1e6,
                )
            yield solve_jobs, task_rows
            return
        order = np.argsort(refs_row, kind="stable")
        refs_row = refs_row[order]
        refs_term = refs_term[order]
        # 2x factor: each chunk's term count re-pads to the next pow2
        # bucket (worst case ~2x its raw share), so splitting at the
        # raw cost alone leaves per-chunk tensors over budget.
        n_chunks = min(int(np.ceil(cost * 2.0 / budget)), len(solve_jobs))
        target = max(1, int(np.ceil(len(task_rows) / n_chunks)))
        jr = self.jobr[task_rows]
        # Job segment boundaries in the job-contiguous task_rows.
        seg_starts = np.flatnonzero(
            np.concatenate(([True], jr[1:] != jr[:-1]))
        )
        seg_ends = np.concatenate((seg_starts[1:], [len(task_rows)]))

        def emit(cjobs, lo, hi):
            i0, i1 = np.searchsorted(refs_row, [lo, hi])
            e_chunk = len(np.unique(refs_term[i0:i1]))
            padded = (
                bucket_pow2(e_chunk, floor=1) * D * 8.0 if e_chunk else 0.0
            )
            if padded > budget:
                log.warning(
                    "solve chunk of %d jobs still carries ~%.0f MB of "
                    "affinity count tensors (budget %.0f MB)",
                    len(cjobs), padded / 1e6, budget / 1e6,
                )
            return cjobs, task_rows[lo:hi]

        chunk_jobs: List[int] = []
        lo = 0
        hi = 0
        ji = 0
        for s, e in zip(seg_starts, seg_ends):
            hi = int(e)
            chunk_jobs.append(solve_jobs[ji])
            ji += 1
            if hi - lo >= target and ji < len(solve_jobs):
                yield emit(chunk_jobs, lo, hi)
                chunk_jobs = []
                lo = hi
        if hi > lo or chunk_jobs:
            yield emit(chunk_jobs, lo, hi)

    def _schedulable_rows(self) -> List[int]:
        m = self.m
        srows = np.asarray(self.session_jobs, np.int64)
        if not len(srows):
            return []
        keep = self.j_phase[srows] != 1  # Inqueue gate: skip Pending groups
        # gang JobValid (gang.go:51-72): registered whenever the gang
        # plugin is configured (JobValid has no enable flag).
        if self._has("gang"):
            keep &= self.j_valid[srows] >= m.j_minav[srows]
        # Queue existence: q_of_job is -1 for unknown queues (derive).
        keep &= self.q_of_job[srows] >= 0
        return srows[keep].tolist()

    def _ordered_jobs(self) -> List[int]:
        """Namespace round-robin x queue order x job order, as sorted-list
        merging (allocate.go:107-153).  Returns job rows in processing
        order.

        Heap pops over total-ordered keys (the uid tie-break makes every
        comparator total) produce exactly sorted order, so the object
        path's PriorityQueues reduce to lexsorts over interned
        namespace/queue code columns; the final round-robin ("one job per
        namespace per round") is a second lexsort on (position-within-
        namespace, namespace-rank)."""
        m = self.m
        rows = self._schedulable_rows()
        if not rows:
            return []
        drf_share = self._drf_shares()
        jkeys = self._job_keys(rows, drf_share)
        ns_share = self._ns_shares(drf_share)
        overused = self._overused_fn()
        queue_order = self._queue_order_fn()
        ns_order = self._namespace_order_fn(ns_share)

        rows_arr = np.asarray(rows, np.int64)
        nsc = m.j_ns_code[rows_arr]
        qc = m.j_queue_code[rows_arr]
        qinfo = self.store.queues

        # Rank the few distinct namespaces/queues with the comparator
        # closures (the per-JOB work stays in numpy).
        # First-appearance order feeds the stable sorts so comparator ties
        # (if any plugin comparator were non-total) resolve exactly as the
        # object path's insertion-ordered scans did.
        ns_codes, ns_first = np.unique(nsc, return_index=True)
        ns_codes = ns_codes[np.argsort(ns_first, kind="stable")]
        ns_names = [m.ns_names.items[c] for c in ns_codes.tolist()]
        ns_sorted = sorted(ns_names, key=_cmp_key(ns_order))
        ns_rank_of = {n: i for i, n in enumerate(ns_sorted)}
        ns_rank_by_code = np.full(int(ns_codes.max()) + 1, -1, np.int64)
        for c, n in zip(ns_codes.tolist(), ns_names):
            ns_rank_by_code[c] = ns_rank_of[n]

        q_codes, q_first = np.unique(qc, return_index=True)
        q_codes = q_codes[np.argsort(q_first, kind="stable")]
        q_names = [m.qnames.items[c] for c in q_codes.tolist()]
        q_sorted = sorted(q_names,
                          key=_cmp_key(lambda a, b: queue_order(qinfo[a],
                                                                qinfo[b])))
        q_rank_of = {n: i for i, n in enumerate(q_sorted)}
        q_rank_by_code = np.full(int(q_codes.max()) + 1, -1, np.int64)
        for c, n in zip(q_codes.tolist(), q_names):
            # Overused queues drop out of this pass entirely
            # (allocate.go:126-143).
            q_rank_by_code[c] = -1 if overused(qinfo[n]) else q_rank_of[n]

        ns_r = ns_rank_by_code[nsc]
        q_r = q_rank_by_code[qc]
        keep = q_r >= 0
        rows_arr = rows_arr[keep]
        if not len(rows_arr):
            return []
        ns_r = ns_r[keep]
        q_r = q_r[keep]
        # Within a namespace: queues in queue order, jobs by job key.
        order1 = np.lexsort((jkeys[rows_arr], q_r, ns_r))
        seq = rows_arr[order1]
        ns_s = ns_r[order1]
        # Position within the namespace group (groups are contiguous now).
        starts = np.concatenate(([True], ns_s[1:] != ns_s[:-1]))
        group_start = np.maximum.accumulate(
            np.where(starts, np.arange(len(seq)), 0)
        )
        k = np.arange(len(seq)) - group_start
        # Round-robin: k-th jobs of every namespace, namespaces in order.
        final = np.lexsort((ns_s, k))
        return seq[final].tolist()

    def _pending_rows(self, ordered: List[int]):
        """Pending task rows in processing order (job-contiguous)."""
        m = self.m
        Pn = self.Pn
        status = m.p_status[:Pn]
        alive = m.p_alive[:Pn]
        pending = alive & (status == ST_PENDING) & ~m.p_be[:Pn]
        if not pending.any():
            return None
        rows_all = np.flatnonzero(pending)
        if self.store.bind_backoff:
            # Tasks inside their bind-failure backoff window sit out the
            # cycle (the rate-limited errTasks queue, cache.go:627-649).
            # O(backed-off) host work, not O(pending): each entry carries
            # its pod uid, mapped to a current row via the mirror.
            now = time.time()
            blocked = [
                m.p_row.get(uid, -1)
                for _, nb, uid in self.store.bind_backoff.values()
                if now < nb
            ]
            if blocked:
                rows_all = rows_all[
                    ~np.isin(rows_all, np.asarray(blocked, np.int64))
                ]
            if not len(rows_all):
                return None
        jr = self.jobr[rows_all]
        # Rank of each job in the processing order.
        jrank = np.full(self.Jn + 1, -1, np.int64)
        solve_jobs: List[int] = list(ordered)
        jrank[solve_jobs] = np.arange(len(solve_jobs))
        ranks = jrank[jr]
        keep = ranks >= 0
        rows_all = rows_all[keep]
        if not len(rows_all):
            return None
        ranks = ranks[keep]
        # Incremental reuse (ISSUE 8 order lane): the produced task
        # order is a pure function of (rows_all, ranks, the static
        # per-row prio/create/uid columns, the priority flag).  The
        # steady-state cycle re-pends the same rows in the same job
        # order, so the 100k-row lexsort + tie-break walk is skipped on
        # a content match; compaction renumbers rows, so the key pins
        # compact_gen.
        m_ = self.m
        prio_enabled = any(
            opt.name == "priority"
            for opt in self._tier_opts("enabled_task_order")
        )
        cache = (getattr(self.store, "_pending_order_cache", None)
                 if getattr(self, "_incr", True) else None)
        if (cache is not None
                and cache[0] == (m_.compact_gen, prio_enabled)
                and np.array_equal(cache[1], rows_all)
                and np.array_equal(cache[2], ranks)):
            kept_jobs, task_rows = cache[3]
            return list(kept_jobs), task_rows
        # Task order within a job: priority desc, creation asc, uid asc
        # (priority plugin task_order + session default tie-break).
        prio = m.p_prio[rows_all]
        prio_key = -prio if prio_enabled else np.zeros_like(prio)
        create = m.p_create[rows_all]
        # Numeric lexsort first; the uid tie-break (session default) only
        # matters within groups whose (rank, prio, create) triple repeats —
        # creation timestamps are unique monotonic counters, so such groups
        # are rare, and the 100k-element string-array build the full
        # string lexsort needed is skipped entirely.
        order = np.lexsort((create, prio_key, ranks))
        rs, ps, cs = ranks[order], prio_key[order], create[order]
        dup = np.flatnonzero(
            (rs[1:] == rs[:-1]) & (ps[1:] == ps[:-1]) & (cs[1:] == cs[:-1])
        )
        if len(dup):
            p_uid = m.p_uid
            starts = np.flatnonzero(np.concatenate(
                ([True], (rs[1:] != rs[:-1]) | (ps[1:] != ps[:-1])
                 | (cs[1:] != cs[:-1]))
            ))
            ends = np.concatenate((starts[1:], [len(order)]))
            for s, e in zip(starts.tolist(), ends.tolist()):
                if e - s > 1:
                    order[s:e] = sorted(
                        order[s:e], key=lambda i: p_uid[rows_all[i]]
                    )
        task_rows = rows_all[order]
        # Keep only jobs that actually have pending tasks, preserving order.
        present = np.unique(self.jobr[task_rows])
        present_set = set(int(j) for j in present)
        kept_jobs = [j for j in solve_jobs if j in present_set]
        if not kept_jobs:
            return None
        # Freeze + remember for the next cycle's content match (the
        # result rides read-only through encode/commit).
        task_rows.setflags(write=False)
        if getattr(self, "_incr", True):
            self.store._pending_order_cache = (
                (m_.compact_gen, prio_enabled), rows_all, ranks,
                (kept_jobs, task_rows),
            )
        return kept_jobs, task_rows

    # ------------------------------------------------------- solver inputs

    def _score_weights(self) -> ScoreWeights:
        import jax.numpy as jnp

        width = self.R
        merged = {
            "binpack_weight": 0.0,
            "binpack_res": [1.0] * width,
            "least_req_weight": 0.0,
            "most_req_weight": 0.0,
            "balanced_weight": 0.0,
            "node_affinity_weight": 0.0,
        }
        for opt in self._tier_opts("enabled_node_order"):
            if opt.name == "binpack":
                args = Arguments(opt.arguments)
                weight = max(args.get_int("binpack.weight", 1), 1)
                cpu_w = max(args.get_int("binpack.cpu", 1), 0)
                mem_w = max(args.get_int("binpack.memory", 1), 0)
                dense = [0.0] * width
                dense[0] = float(cpu_w)
                dense[1] = float(mem_w)
                for name in (args.get("binpack.resources") or "").split(","):
                    name = name.strip()
                    if not name:
                        continue
                    idx = self.m.scalar_slots.index.get(name)
                    if idx is not None:
                        dense[2 + idx] = float(max(
                            args.get_int(f"binpack.resources.{name}", 1), 0
                        ))
                merged["binpack_weight"] += float(weight)
                merged["binpack_res"] = dense
            elif opt.name == "nodeorder":
                args = Arguments(opt.arguments)
                merged["least_req_weight"] += float(
                    args.get_int("leastrequested.weight", 1))
                merged["most_req_weight"] += float(
                    args.get_int("mostrequested.weight", 0))
                merged["balanced_weight"] += float(
                    args.get_int("balancedresource.weight", 1))
                merged["node_affinity_weight"] += float(
                    args.get_int("nodeaffinity.weight", 1))
        return ScoreWeights(
            binpack_weight=float(merged["binpack_weight"]),
            binpack_res=jnp.asarray(merged["binpack_res"], jnp.float32),
            least_req_weight=float(merged["least_req_weight"]),
            most_req_weight=float(merged["most_req_weight"]),
            balanced_weight=float(merged["balanced_weight"]),
            node_affinity_weight=float(merged["node_affinity_weight"]),
        )

    def _tol_bits_for(self, rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(elem_rows, taint_idx) pairs of tolerated taints per task row.

        Cached per pod feature blob, keyed by the taint-dictionary size
        (append-only: a grown dictionary only adds new taints to test)."""
        m = self.m
        taints = m.taints.items
        nt = len(taints)
        er: List[int] = []
        ti: List[int] = []
        # Tolerations are rare; the p_has_tol column turns the 100k-row
        # feature walk into a scan over just the tolerating rows.
        if not m.p_has_tol[rows].any():
            return np.array(er, np.int64), np.array(ti, np.int64)
        for local in np.flatnonzero(m.p_has_tol[rows]).tolist():
            r = rows[local]
            feat = m.p_feat[r]
            if feat is None or not feat.tol:
                continue
            cache = getattr(feat, "_tol_cache", None)
            if cache is None or cache[0] != nt:
                idxs = []
                for k, (tkey, tval, teff) in enumerate(taints):
                    for tol in feat.tol:
                        if tol.operator == "Exists":
                            key_ok = tol.key == "" or tol.key == tkey
                        else:
                            key_ok = tol.key == tkey and tol.value == tval
                        eff_ok = tol.effect == "" or tol.effect == teff
                        if key_ok and eff_ok:
                            idxs.append(k)
                            break
                cache = (nt, idxs)
                try:
                    feat._tol_cache = cache
                except Exception:
                    pass
            for k in cache[1]:
                er.append(local)
                ti.append(k)
        return np.array(er, np.int64), np.array(ti, np.int64)

    def _task_field_arrays(self, rows: np.ndarray):
        """Per-task solver feature arrays for the given mirror rows
        (leading dim = len(rows)): requests, selector/toleration/port
        bit planes, required/preferred node-affinity alternatives.

        Called with all pending rows on the non-slim (sequential parity)
        path, and with only the profile first-occurrence rows on the
        wave path — tasks sharing a store-interned profile id have
        identical spec-level features, so one row represents them all.
        """
        m = self.m
        P = len(rows)
        R = self.R
        LW = _pow2(max(1, (len(m.labels) + 31) // 32), 1)
        TW = _pow2(max(1, (len(m.taints) + 31) // 32), 1)
        PW = _pow2(max(1, (len(m.ports) + 31) // 32), 1)

        req = np.zeros((P, R), F)
        init_req = np.zeros((P, R), F)
        er, si, v = m.c_req.gather(rows)
        req[er, si] = v
        er, si, v = m.c_init_req.gather(rows)
        init_req[er, si] = v
        sel_bits = np.zeros((P, LW), np.uint32)
        er, li = m.c_sel.gather(rows)
        sel_bits[:P] = _pack_bits(P, LW, er, li)
        tol_bits = np.zeros((P, TW), np.uint32)
        er, ti = self._tol_bits_for(rows)
        if len(er):
            tol_bits[:P] = _pack_bits(P, TW, er, ti)
        port_bits = np.zeros((P, PW), np.uint32)
        er, pi = m.c_ports.gather(rows)
        if len(er):
            port_bits[:P] = _pack_bits(P, PW, er, pi)

        # Required node-affinity alternatives.
        aff_lo = m.p_aff_lo[rows]
        aff_hi = m.p_aff_hi[rows]
        n_alts = (aff_hi - aff_lo).astype(np.int64)
        A = _pow2(max(1, int(n_alts.max()) if P else 1), 1)
        aff_bits = np.zeros((P, A, LW), np.uint32)
        aff_terms = np.zeros((P,), I)
        aff_terms[:P] = n_alts
        if n_alts.any():
            alt_rows = np.concatenate([
                np.arange(lo, hi) for lo, hi in zip(aff_lo, aff_hi) if hi > lo
            ]).astype(np.int64)
            task_of_alt = np.repeat(np.arange(P), n_alts)
            slot_of_alt = np.concatenate([
                np.arange(h - l) for l, h in zip(aff_lo, aff_hi) if h > l
            ])
            er, li = m.c_aff_alt.gather(alt_rows)
            flat = _pack_bits(len(alt_rows), LW, er, li)
            aff_bits[task_of_alt, slot_of_alt] = flat

        # Preferred node affinity (normalized to [0,10] per task).
        pref_lo = m.p_pref_lo[rows]
        pref_hi = m.p_pref_hi[rows]
        n_pref = (pref_hi - pref_lo).astype(np.int64)
        AP = _pow2(max(1, int(n_pref.max()) if P else 1), 1)
        pref_bits = np.zeros((P, AP, LW), np.uint32)
        pref_w = np.zeros((P, AP), F)
        if n_pref.any():
            pr_rows = np.concatenate([
                np.arange(lo, hi) for lo, hi in zip(pref_lo, pref_hi) if hi > lo
            ]).astype(np.int64)
            task_of_pr = np.repeat(np.arange(P), n_pref)
            slot_of_pr = np.concatenate([
                np.arange(h - l) for l, h in zip(pref_lo, pref_hi) if h > l
            ])
            er, li = m.c_pref.gather(pr_rows)
            flat = _pack_bits(len(pr_rows), LW, er, li)
            pref_bits[task_of_pr, slot_of_pr] = flat
            w = np.array([m.pref_w[r] for r in pr_rows], F)
            totals = np.zeros(P, F)
            np.add.at(totals, task_of_pr, w)
            wn = np.where(totals[task_of_pr] > 0,
                          w / totals[task_of_pr] * 10.0, 0.0)
            pref_w[task_of_pr, slot_of_pr] = wn
        return (req, init_req, port_bits, sel_bits, aff_bits, aff_terms,
                tol_bits, pref_bits, pref_w)

    def _device_snapshot(self):
        """The store's persistent device-resident snapshot, or None on
        paths that ship numpy (remote solver frames — the child process
        owns its device state) or when disabled (VOLCANO_TPU_DEVSNAP=0).
        A mesh store gets the mesh-sharded snapshot: node planes commit
        with the node-axis NamedSharding and delta scatters stay
        shard-local (ops/devsnap.py), so the mesh path no longer
        re-ships numpy planes every cycle."""
        if (self._remote_solver is not None
                or os.environ.get("VOLCANO_TPU_DEVSNAP", "1") == "0"):
            return None
        from .ops.devsnap import for_store

        return for_store(self.store,
                         mesh=getattr(self.store, "solve_mesh", None))

    def _solve_inputs(self, solve_jobs: List[int], task_rows: np.ndarray,
                      slim: bool = False):
        self._flush_aggr()
        m = self.m
        P = len(task_rows)
        # Task axis stays exact: solve_wave pads to wave multiples (the
        # jit-shape bucket), so a power-of-two pad here would only add waves.
        Pp = P
        N = self.Nn
        Np = _pow2(max(N, 1))
        R = self.R
        J = len(solve_jobs)
        Jp = _pow2(max(J, 1), 4)
        Qp = _pow2(max(self.Qn, 1), 4)

        LW = _pow2(max(1, (len(m.labels) + 31) // 32), 1)
        TW = _pow2(max(1, (len(m.taints) + 31) // 32), 1)
        PW = _pow2(max(1, (len(m.ports) + 31) // 32), 1)

        # ---- nodes
        # Label/taint bit planes change only on node-table edits or
        # interner growth: cache them on the mirror keyed by
        # (node epoch, word widths) instead of re-gathering the node
        # CSR every cycle (~10 ms at 10k nodes).
        n_label_bits = np.zeros((Np, LW), np.uint32)
        n_taint_bits = np.zeros((Np, TW), np.uint32)
        if N:
            def _build_bits():
                csr_rows = m.node_csr_rows(np.arange(N))
                er, li = m.c_n_labels.gather(csr_rows)
                lb = _pack_bits(N, LW, er, li)
                er, ti = m.c_n_taints.gather(csr_rows)
                return lb, _pack_bits(N, TW, er, ti)

            lbits, tbits = _epoch_cached(
                m, "_node_bits_cache", (m.epoch, N, LW, TW), _build_bits
            )
            n_label_bits[:N] = lbits
            n_taint_bits[:N] = tbits
        n_ports = np.zeros((Np, PW), np.uint32)
        rows_res = np.flatnonzero(self.resident)
        if len(rows_res):
            er, pi = m.c_ports.gather(rows_res)
            if len(er):
                nrows = m.p_node[:self.Pn][rows_res][er]
                n_ports[:N] = _pack_bits(N, PW, nrows, pi)

        def padN(a, fill=0.0):
            out = np.full((Np, *a.shape[1:]), fill, a.dtype)
            out[:len(a)] = a
            return out

        # Wave path: pipelined is identically zero at solve start and
        # releasing is usually all-zero outside eviction cycles; both
        # broadcast as [1, R] dummies in the kernel (FutureIdle adds /
        # subtracts them), skipping their [Np, R] upload.
        releasing_np = self.n_releasing.astype(F)
        if slim and not releasing_np.any():
            releasing_in = np.zeros((1, R), F)
        else:
            releasing_in = padN(releasing_np)
        # Device-resident snapshot (ops/devsnap.py): the node planes that
        # move only with the NODE table — allocatable, max-task counts,
        # readiness, label/taint bit planes — live on the device across
        # cycles, updated by per-row delta scatters from the mirror's
        # dirty set instead of full re-uploads.  Per-cycle planes (idle,
        # ntasks, ports) still ship fresh.  The host copies above stay
        # the taint-feature source (solve_wave must not fetch a device
        # array back through the tunnel just to compute a static flag).
        self._taint_any = bool(n_taint_bits.any()) if slim else None
        snap = self._device_snapshot() if slim else None
        # Node-class compaction (two-phase solve, ops/nodeclass.py):
        # the class grouping is a pure function of the node table, so
        # it rides the same epoch-keyed mirror cache as the bit planes;
        # the wave solver gets the planes pre-built (it must never
        # fetch device-resident node planes back just to group them).
        node_classes = None
        cls_id_host = None
        cls_sig = ""
        from .ops import wave as _wave_mod

        use_classes = (
            slim and N and _wave_mod._two_phase_on()
            and _wave_mod._nodeclass_on()
        )
        if use_classes:
            def _build_classes():
                from .ops.nodeclass import build_node_classes

                cl, n_real, sig = build_node_classes(
                    n_label_bits, n_taint_bits, padN(self.n_ready),
                    padN(self.n_alloc.astype(F)), padN(self.n_maxtasks),
                )
                return (cl.class_id, cl.label_bits, cl.taint_bits,
                        cl.ready, np.array(sig), np.array(n_real))

            (cls_id_host, cls_lb, cls_tb, cls_rd, sig_arr,
             _n_real) = _epoch_cached(
                m, "_node_class_cache", (m.epoch, Np, R, LW, TW),
                _build_classes,
            )
            cls_sig = str(sig_arr)
        if snap is not None and N:
            build = {
                # rows=None -> full padded plane; rows array -> just
                # those rows (devsnap's delta scatter, so a one-node
                # change never materializes full [Np, *] host copies).
                "allocatable": lambda rows: (
                    padN(self.n_alloc.astype(F)) if rows is None
                    else self.n_alloc[rows].astype(F)),
                "max_tasks": lambda rows: (
                    padN(self.n_maxtasks) if rows is None
                    else self.n_maxtasks[rows]),
                "ready": lambda rows: (
                    padN(self.n_ready) if rows is None
                    else self.n_ready[rows]),
                "label_bits": lambda rows: (
                    n_label_bits if rows is None
                    else n_label_bits[rows]),
                "taint_bits": lambda rows: (
                    n_taint_bits if rows is None
                    else n_taint_bits[rows]),
            }
            if use_classes:
                # class_id is [Np] row-indexed, so it shares the node
                # planes' dirty-row delta machinery — valid exactly
                # while the class SET (tables_sig) held still, because
                # classes order by sorted signature (ops/nodeclass.py).
                # A changed set returns None for the delta rows, which
                # devsnap answers with a full upload of THIS plane only
                # ([Np] int32 — tiny); label/taint/capacity planes keep
                # their row scatters.
                prev_sig = getattr(snap, "_last_cls_sig", None)
                build["class_id"] = lambda rows: (
                    cls_id_host if rows is None
                    else (cls_id_host[rows] if prev_sig == cls_sig
                          else None))
            planes = snap.node_planes(m, (m.epoch, Np, R, LW, TW), build)
            if use_classes:
                snap._last_cls_sig = cls_sig
            alloc_in = planes["allocatable"]
            maxt_in = planes["max_tasks"]
            ready_in = planes["ready"]
            lbits_in = planes["label_bits"]
            tbits_in = planes["taint_bits"]
            if use_classes:
                from .ops.nodeclass import NodeClasses

                tables = snap.class_tables(
                    (cls_sig, cls_lb.shape, cls_tb.shape), {
                        "label_bits": lambda: cls_lb,
                        "taint_bits": lambda: cls_tb,
                        "ready": lambda: cls_rd,
                    })
                node_classes = NodeClasses(
                    class_id=planes["class_id"],
                    label_bits=tables["label_bits"],
                    taint_bits=tables["taint_bits"],
                    ready=tables["ready"],
                )
        else:
            alloc_in = padN(self.n_alloc.astype(F))
            maxt_in = padN(self.n_maxtasks)
            ready_in = padN(self.n_ready)
            lbits_in = n_label_bits
            tbits_in = n_taint_bits
            if use_classes:
                from .ops.nodeclass import NodeClasses

                node_classes = NodeClasses(
                    class_id=cls_id_host, label_bits=cls_lb,
                    taint_bits=cls_tb, ready=cls_rd,
                )
        nodes = SolveNodes(
            idle=padN(self.n_idle.astype(F)),
            allocatable=alloc_in,
            releasing=releasing_in,
            pipelined=(np.zeros((1, R), F) if slim
                       else np.zeros((Np, R), F)),
            ntasks=padN(self.n_ntasks),
            max_tasks=maxt_in,
            ports=n_ports,
            ready=ready_in,
            label_bits=lbits_in,
            taint_bits=tbits_in,
        )

        # ---- tasks
        sj = np.asarray(solve_jobs, np.int64)
        jrank = np.zeros(self.Jn + 1, I)
        jrank[sj] = np.arange(J, dtype=I)
        tjob = jrank[self.jobr[task_rows]]
        t_job = np.full((Pp,), -1, I)
        t_job[:P] = tjob
        t_real = np.zeros((Pp,), bool)
        t_real[:P] = True

        if slim:
            # Wave-solver path: the kernel reads only job/real per-task
            # (req/init_req and every predicate input come from the
            # profile rows, ops/wave.py _solve_wave), so the dense
            # [P, ...] feature arrays are neither built (encode time)
            # nor shipped (upload time).  Profile rows are gathered
            # straight from the mirror at the first-occurrence task rows
            # (_profiles_from_rows).
            tasks = SolveTasks(
                req=np.zeros((1, R), F),
                init_req=np.zeros((1, R), F),
                job=t_job,
                real=t_real,
                ports=np.zeros((1, 1), np.uint32),
                sel_bits=np.zeros((1, 1), np.uint32),
                aff_bits=np.zeros((1, 1, 1), np.uint32),
                aff_terms=np.zeros((1,), I),
                tol_bits=np.zeros((1, 1), np.uint32),
                pref_bits=np.zeros((1, 1, 1), np.uint32),
                pref_w=np.zeros((1, 1), F),
            )
        else:
            (req, init_req, port_bits, sel_bits, aff_bits, aff_terms,
             tol_bits, pref_bits, pref_w) = self._task_field_arrays(
                task_rows)
            tasks = SolveTasks(
                req=req,
                init_req=init_req,
                job=t_job,
                real=t_real,
                ports=port_bits,
                sel_bits=sel_bits,
                aff_bits=aff_bits,
                aff_terms=aff_terms,
                tol_bits=tol_bits,
                pref_bits=pref_bits,
                pref_w=pref_w,
            )

        # ---- jobs
        j_min = np.full((Jp,), 1 << 30, I)
        j_queue = np.zeros((Jp,), I)
        j_ready_base = np.zeros((Jp,), I)
        j_min[:J] = m.j_minav[sj]
        j_queue[:J] = np.maximum(self.q_of_job[sj], 0)
        j_ready_base[:J] = self.j_ready_base[sj]
        jobs = SolveJobs(
            queue=j_queue, min_available=j_min, ready_base=j_ready_base
        )

        # ---- queues
        deserved = np.full((Qp, R), 3.0e38, F)
        q_alloc = np.zeros((Qp, R), F)
        deserved[:self.Qn] = self.q_deserved
        q_alloc[:self.Qn] = self.q_alloc
        queues = SolveQueues(deserved=deserved, allocated=q_alloc)

        aff, pid, profiles = self._affinity_and_profiles(
            task_rows, None if slim else tasks, Np
        )
        weights = self._score_weights()
        # Device-incremental key inputs (ISSUE 9): the class-table
        # content signature (or the identity marker — epoch-keyed) and
        # the padded node axis, read by _devincr_prepare.
        self._cls_sig = cls_sig if use_classes else ""
        self._solve_np = Np
        solve_args = (nodes, tasks, jobs, queues, weights, self.eps,
                      self.scalar_slot, aff)
        if slim:
            # Topology node-order bias (9th solve_args element, sharded
            # under mesh and framed over the remote wire like any node
            # plane).  Appended ONLY when a fabric constraint is live:
            # the 8-tuple form keeps frames and traces byte-identical
            # to the pre-topology build (the kill-switch guarantee).
            bias = self._topo_node_bias(solve_jobs, Np)
            if bias is not None:
                solve_args = solve_args + (bias,)
        return (
            solve_args,
            pid,
            profiles,
            node_classes,
        )

    def _encode_cache_key(self, P: int) -> tuple:
        """Validity key of the per-cycle encode cache: everything the
        cached profile/affinity structures are a function of EXCEPT the
        task-row content itself (compared by array equality).  Row ids
        pin ``compact_gen``; interner/membership sizes pin the static
        dictionaries (append-only, so a size match proves the cached
        rows' encodings are still current); ``epoch`` + domain/topo
        widths pin the node-domain table the counts index into."""
        m = self.m
        return (
            P, self.Pn, self.R, m.compact_gen, m.epoch,
            len(m.terms), m.term_members_total,
            len(m.labels), len(m.taints),
            len(m.ports), len(m.topo_keys), len(m.domains),
        )

    def _term_cnt0(self, active_members: List[np.ndarray],
                   term_key: np.ndarray, Ep: int) -> np.ndarray:
        """[Ep, D] resident-member counts per domain for the active
        terms — the only piece of the affinity encoding that moves with
        pod placement, so it is recomputed each cycle even on an encode
        cache hit (the membership structures it walks are cached)."""
        m = self.m
        D = max(1, len(m.domains))
        cnt0 = np.zeros((Ep, D), I)
        node = m.p_node[:self.Pn]
        node_dom_raw = m.node_dom()
        for le, members in enumerate(active_members):
            if not len(members):
                continue
            residents = members[self.resident[members]]
            if len(residents):
                dom = node_dom_raw[node[residents], term_key[le]]
                dom = dom[dom >= 0]
                if len(dom):
                    np.add.at(cnt0[le], dom, 1)
        return cnt0

    def _affinity_and_profiles(self, task_rows: np.ndarray, tasks,
                               Np: int):
        """Affinity inputs + refined profile ids + SolveProfiles, all at
        profile granularity — nothing dense in [P, E] is ever built.

        - Active-term compaction: only terms some pending task is involved
          with enter the solve; inactive terms cannot influence it (their
          counts are neither gated on nor scored).
        - Profile refinement: store-interned profile ids split wherever
          per-cycle term membership differs within a profile (a sibling's
          topology-spread term matches every pod of the job).  Membership
          hashes are accumulated sparsely from the term member lists; the
          collision probability of the two independent 20-bit-coefficient
          hashes is ~2^-40 per pair.
        - Incremental (ISSUE 8 encode lane): on the wave path the whole
          profile/affinity encoding is a pure function of the task-row
          content and the append-only static dictionaries, so it is
          cached on the store and reused when both match — only the
          per-domain resident counts (``_term_cnt0``) and the padded
          node-domain plane rebuild each cycle.
        """
        from .ops.wave import SolveProfiles

        m = self.m
        P = len(task_rows)

        # Profile content generation (ISSUE 9): a monotone token that
        # moves whenever the profile/affinity encoding is (re)built —
        # an encode-cache hit keeps it, so the device-incremental lane
        # can key its persistent [U, C] static planes and warm
        # shortlists on "the same profile rows as last solve".  Any
        # rebuild (even one producing identical content) bumps it:
        # conservative, the caches just recompute once.
        self._profile_gen = None

        if tasks is None and getattr(self, "_incr", True):
            cached = getattr(self.store, "_encode_cache", None)
            ckey = self._encode_cache_key(P)
            if (cached is not None and cached["key"] == ckey
                    and np.array_equal(cached["task_rows"], task_rows)):
                self._profile_gen = cached.get("gen")
                self._pid_out = cached["pid"]
                E = cached["E"]
                K = max(1, len(m.topo_keys))
                if E == 0:
                    return (empty_affinity(Np, 1), cached["pid"],
                            cached["profiles"])
                term_key = cached["term_key"]
                Ep = cached["Ep"]
                cnt0 = self._term_cnt0(cached["members"], term_key, Ep)
                node_dom_raw = m.node_dom()
                node_dom = np.full((Np, K), -1, I)
                node_dom[:len(node_dom_raw)] = node_dom_raw
                aff = AffinityArgs(
                    node_dom=node_dom,
                    term_key=term_key,
                    cnt0=cnt0,
                    t_req_aff=np.zeros((1, Ep), bool),
                    t_req_anti=np.zeros((1, Ep), bool),
                    t_matches=np.zeros((1, Ep), bool),
                    t_soft=np.zeros((1, Ep), F),
                )
                return aff, cached["pid"], cached["profiles"]

        pid_raw = m.p_prof[task_rows].astype(np.int64)

        # ---- active terms: union of pending tasks' involvement ----------
        er_a, ei_a = m.c_ip_aff.gather(task_rows)
        er_n, ei_n = m.c_ip_anti.gather(task_rows)
        er_s, ei_s, ev_s = m.c_ip_soft.gather(task_rows)
        active = np.unique(np.concatenate([ei_a, ei_n, ei_s]))
        E = len(active)
        gen = getattr(self.store, "_encode_gen", 0) + 1
        self.store._encode_gen = gen
        self._profile_gen = gen
        if E == 0:
            aff = empty_affinity(Np, 1)
            profiles = self._profiles_from_rows(
                tasks, task_rows, pid_raw, None, aff, P
            )
            if tasks is None and getattr(self, "_incr", True):
                self.store._encode_cache = {
                    "key": self._encode_cache_key(P),
                    "task_rows": task_rows.copy(),
                    "pid": self._pid_out, "E": 0,
                    "profiles": profiles, "gen": gen,
                }
            return aff, self._pid_out, profiles

        # Renumber active terms by first reference in task order so each
        # wave's terms form a narrow window (the solver slices every
        # [*, E] tensor to that window — wave.py _term_windows).
        local = np.full(self.Pn, -1, np.int64)
        local[task_rows] = np.arange(P)
        first_ref = np.full(len(m.terms), P, np.int64)
        if len(ei_a):
            np.minimum.at(first_ref, ei_a, er_a)
        if len(ei_n):
            np.minimum.at(first_ref, ei_n, er_n)
        if len(ei_s):
            np.minimum.at(first_ref, ei_s, er_s)
        for e in active:
            members = np.asarray(m.term_members[int(e)], np.int64)
            if len(members):
                loc = local[members[members < self.Pn]]
                loc = loc[loc >= 0]
                if len(loc):
                    first_ref[e] = min(first_ref[e], int(loc.min()))
        active = active[np.argsort(first_ref[active], kind="stable")]

        term_local = np.full(len(m.terms), -1, np.int64)
        term_local[active] = np.arange(E)
        from .ops.wave import bucket_pow2

        Ep = bucket_pow2(E, floor=1)

        # ---- sparse membership hash + per-term local membership ---------
        rng = np.random.RandomState(0x7A5E)
        coef = rng.randint(1, 1 << 20, size=(E, 2)).astype(np.int64)
        h1 = np.zeros(P, np.int64)
        h2 = np.zeros(P, np.int64)
        member_locs: List[np.ndarray] = []
        active_members: List[np.ndarray] = []
        node_dom_raw = m.node_dom()
        K = max(1, len(m.topo_keys))
        term_key = np.zeros((Ep,), I)
        for le in range(E):
            e = int(active[le])
            _sel, key, _ns = m.term_info[e]
            term_key[le] = m.topo_keys.index.get(key, 0)
            members = np.asarray(m.term_members[e], np.int64)
            members = members[members < self.Pn] if len(members) else members
            active_members.append(members)
            if len(members):
                loc = local[members]
                loc = loc[loc >= 0]
                if len(loc):
                    h1[loc] += coef[le, 0]
                    h2[loc] += coef[le, 1]
                member_locs.append(loc)
            else:
                member_locs.append(np.zeros(0, np.int64))
        cnt0 = self._term_cnt0(active_members, term_key, Ep)

        combo = (
            pid_raw * np.int64(1_000_003)
            + h1 * np.int64(8191)
            + h2
        )
        profiles = self._profiles_from_rows(
            tasks, task_rows, combo, (member_locs, term_local, Ep,
                                      er_a, ei_a, er_n, ei_n,
                                      er_s, ei_s, ev_s, pid_raw), None, P
        )
        node_dom = np.full((Np, K), -1, I)
        node_dom[:len(node_dom_raw)] = node_dom_raw
        aff = AffinityArgs(
            node_dom=node_dom,
            term_key=term_key,
            cnt0=cnt0,
            t_req_aff=np.zeros((1, Ep), bool),
            t_req_anti=np.zeros((1, Ep), bool),
            t_matches=np.zeros((1, Ep), bool),
            t_soft=np.zeros((1, Ep), F),
        )
        if tasks is None and getattr(self, "_incr", True):
            self.store._encode_cache = {
                "key": self._encode_cache_key(P),
                "task_rows": task_rows.copy(),
                "pid": self._pid_out, "E": E, "Ep": Ep,
                "term_key": term_key, "members": active_members,
                "profiles": profiles, "gen": gen,
            }
        return aff, self._pid_out, profiles

    def _verify_membership_grouping(self, pid, u, combo, term_parts, P):
        """Hash-collision guard: every task's term-membership set must
        equal its profile representative's (the coefficients are fixed per
        process, so an unchecked collision would repeat every cycle).
        Sparse O(memberships) check; exact regrouping on mismatch."""
        (member_locs, _tl, _Ep, _ea, _eia, _en, _ein, _es, _eis, _evs,
         pid_raw) = term_parts
        if not any(len(loc) for loc in member_locs):
            return pid, u
        t_all = np.concatenate([loc for loc in member_locs if len(loc)])
        e_all = np.concatenate([
            np.full(len(loc), le, np.int64)
            for le, loc in enumerate(member_locs) if len(loc)
        ])
        order = np.lexsort((e_all, t_all))
        pt, pe = t_all[order], e_all[order]
        counts = np.bincount(pt, minlength=P)
        offs = np.concatenate(([0], np.cumsum(counts)))
        rep = u[pid]
        ok = bool((counts == counts[rep]).all())
        if ok:
            sel = np.flatnonzero(counts > 0)
            if len(sel):
                lens = counts[sel]
                cum = np.concatenate(([0], np.cumsum(lens)[:-1]))
                base = np.arange(int(lens.sum())) - np.repeat(cum, lens)
                pos_t = base + np.repeat(offs[sel], lens)
                pos_r = base + np.repeat(offs[rep[sel]], lens)
                ok = bool((pe[pos_t] == pe[pos_r]).all())
        if ok:
            return pid, u
        log.warning("profile membership hash collision; exact regrouping")
        keys = {}
        pid2 = np.zeros(P, np.int64)
        u2 = []
        for t in range(P):
            key = (int(pid_raw[t]),
                   tuple(pe[offs[t]:offs[t + 1]].tolist()))
            got = keys.get(key)
            if got is None:
                got = len(u2)
                keys[key] = got
                u2.append(t)
            pid2[t] = got
        return pid2, np.asarray(u2, np.int64)

    def _profiles_from_rows(self, tasks, task_rows: np.ndarray,
                            combo: np.ndarray, term_parts, aff_empty,
                            P: int):
        """Renumber combo ids by first occurrence and gather one profile
        row per distinct id (plus sparse [U, E] term columns)."""
        from .ops.wave import SolveProfiles

        _, first, inv = np.unique(combo, return_index=True,
                                  return_inverse=True)
        order = np.argsort(first, kind="stable")
        rank = np.empty(len(order), np.int64)
        rank[order] = np.arange(len(order))
        pid = rank[inv]
        u = first[order]  # local first-occurrence row per profile
        if term_parts is not None:
            pid, u = self._verify_membership_grouping(
                pid, u, combo, term_parts, P
            )
        self._pid_out = pid
        U = len(u)

        if tasks is None:
            # Slim (wave) path: build the U profile feature rows straight
            # from the mirror at the first-occurrence task rows — the
            # dense [P, ...] arrays were never built.
            (p_req, p_init_req, p_ports, p_sel, p_affb, p_afft, p_tol,
             p_prefb, p_prefw) = self._task_field_arrays(task_rows[u])

            def g(a):
                return a

            rows_by_field = (p_req, p_init_req, p_ports, p_sel, p_affb,
                             p_afft, p_tol, p_prefb, p_prefw)
        else:
            def g(a):
                return np.asarray(a)[u]

            rows_by_field = (tasks.req, tasks.init_req, tasks.ports,
                             tasks.sel_bits, tasks.aff_bits,
                             tasks.aff_terms, tasks.tol_bits,
                             tasks.pref_bits, tasks.pref_w)

        if term_parts is None:
            Ep = 1
            u_req_aff = np.zeros((U, 1), bool)
            u_req_anti = np.zeros((U, 1), bool)
            u_matches = np.zeros((U, 1), bool)
            u_soft = np.zeros((U, 1), F)
        else:
            (member_locs, term_local, Ep, er_a, ei_a, er_n, ei_n,
             er_s, ei_s, ev_s, _pid_raw) = term_parts
            u_index = np.full(P, -1, np.int64)
            u_index[u] = np.arange(U)
            u_req_aff = np.zeros((U, Ep), bool)
            u_req_anti = np.zeros((U, Ep), bool)
            u_matches = np.zeros((U, Ep), bool)
            u_soft = np.zeros((U, Ep), F)
            for le, loc in enumerate(member_locs):
                if len(loc):
                    sel = u_index[loc]
                    sel = sel[sel >= 0]
                    if len(sel):
                        u_matches[sel, le] = True

            def scatter(er, ei, out, val=None):
                ur = u_index[er]
                keep = ur >= 0
                lei = term_local[ei[keep]]
                urk = ur[keep]
                ok = lei >= 0
                if val is None:
                    out[urk[ok], lei[ok]] = True
                else:
                    np.add.at(out, (urk[ok], lei[ok]), val[keep][ok])

            scatter(er_a, ei_a, u_req_aff)
            scatter(er_n, ei_n, u_req_anti)
            scatter(er_s, ei_s, u_soft, val=ev_s)

        (f_req, f_init_req, f_ports, f_sel, f_affb, f_afft, f_tol,
         f_prefb, f_prefw) = rows_by_field
        return SolveProfiles(
            req=g(f_req),
            init_req=g(f_init_req),
            ports=g(f_ports),
            sel_bits=g(f_sel),
            aff_bits=g(f_affb),
            aff_terms=g(f_afft),
            tol_bits=g(f_tol),
            pref_bits=g(f_prefb),
            pref_w=g(f_prefw),
            t_req_aff=u_req_aff,
            t_req_anti=u_req_anti,
            t_matches=u_matches,
            t_soft=u_soft,
        )

    # -------------------------------------------------------------- commit

    def _obj_arrays(self):
        """Object ndarrays over the mirror's pod / bind-key / node-name
        lists: fancy indexing + one ``tolist`` replaces 100k-iteration
        Python list comprehensions in the commit path.

        Persistent across cycles (ISSUE 8 commit lane): the arrays live
        on the STORE keyed by (compact_gen, pod_obj_gen) — rows never
        renumber between compactions and record slots only move on
        copy-on-write upserts/removals, so the steady state extends the
        tail for appended rows instead of re-walking 100k records."""
        arrs = getattr(self, "_obj_arr_cache", None)
        if arrs is not None:
            return arrs
        m = self.m
        store = self.store
        Pn, Nn = self.Pn, self.Nn
        # No epoch component: the object arrays read only the pod/key/
        # name LISTS, which are append-only (tail extension below) with
        # record slots versioned by pod_obj_gen — node upserts must not
        # invalidate the 100k-element walk this cache exists to avoid.
        key = (m.compact_gen, m.pod_obj_gen)
        cached = (getattr(store, "_objarr_cache", None)
                  if getattr(self, "_incr", True) else None)
        if cached is not None and cached[0] == key:
            _, built_pn, built_nn, pod_a, key_a, name_a = cached
            if built_pn == Pn and built_nn == Nn:
                arrs = self._obj_arr_cache = (pod_a, key_a, name_a)
                return arrs
            if built_pn <= Pn and built_nn <= Nn:
                # Appended rows/nodes only: extend the tails.
                if built_pn < Pn:
                    pod_a = np.concatenate((pod_a, np.fromiter(
                        m.p_pod[built_pn:Pn], dtype=object,
                        count=Pn - built_pn)))
                    key_a = np.concatenate((key_a, np.fromiter(
                        m.p_key[built_pn:Pn], dtype=object,
                        count=Pn - built_pn)))
                if built_nn < Nn:
                    name_a = np.concatenate((name_a, np.fromiter(
                        m.n_name[built_nn:Nn], dtype=object,
                        count=Nn - built_nn)))
                store._objarr_cache = (key, Pn, Nn, pod_a, key_a,
                                       name_a)
                arrs = self._obj_arr_cache = (pod_a, key_a, name_a)
                return arrs
        # np.fromiter, NOT ndarray slice-assign: the latter probes
        # every element for sequence-ness (60x slower on dataclass
        # records).
        pod_a = np.fromiter(m.p_pod[:Pn], dtype=object, count=Pn)
        key_a = np.fromiter(m.p_key[:Pn], dtype=object, count=Pn)
        name_a = np.fromiter(m.n_name[:Nn], dtype=object, count=Nn)
        if getattr(self, "_incr", True):
            # The kill switch disables persistence here too: a store in
            # VOLCANO_TPU_INCREMENTAL=0 mode must not pin 100k pod
            # records across cycles through a cache nothing will read.
            store._objarr_cache = (key, Pn, Nn, pod_a, key_a, name_a)
        arrs = self._obj_arr_cache = (pod_a, key_a, name_a)
        return arrs

    def _commit(self, solve_jobs: List[int], task_rows: np.ndarray,
                assigned: np.ndarray, never_ready: np.ndarray,
                fit_failed: np.ndarray, req_gather=None) -> bool:
        """Apply the assignment matrix in bulk (the vectorized _replay)."""
        m = self.m
        store = self.store
        jrank_never = never_ready[:len(solve_jobs)]
        committed = assigned >= 0
        if not committed.any():
            return False

        rows = task_rows[committed]
        nodes_c = assigned[committed]
        stats = getattr(self, "stats", None)
        if stats is not None:
            stats["bound"] = int(stats["bound"]) + len(rows)

        # Divergence guard (vectorized analog of the replay's re-check):
        # charged capacity must not exceed allocatable.
        if req_gather is not None:
            # Subset the caller's full-task gather (prepared while the
            # device solve ran) down to the committed rows — identity
            # when everything committed (the steady north-star case).
            er_all, si_all, v_all = req_gather
            if committed.all():
                er, si, v = er_all, si_all, v_all
            else:
                em = committed[er_all]
                new_idx = np.cumsum(committed) - 1
                er = new_idx[er_all[em]]
                si = si_all[em]
                v = v_all[em]
        else:
            er, si, v = m.c_req.gather(rows)
        # bincount over flattened (node, slot) indices is several times
        # faster than np.add.at for 200k+ scatter entries.
        add = np.bincount(
            nodes_c[er].astype(np.int64) * self.R + si,
            weights=v, minlength=self.Nn * self.R,
        ).reshape(self.Nn, self.R).astype(F)
        new_used = self.n_used + add
        over = new_used > self.n_alloc + self.eps[None, :]
        if over.any() and bool((add[over.any(axis=1)] > 0).any()):
            bad = np.flatnonzero(over.any(axis=1))
            log.error(
                "Device/host divergence: %d nodes oversubscribed; "
                "falling back to object path this cycle", len(bad),
            )
            raise RuntimeError("fastpath divergence")

        # Array state updates.  The rows change dynamic state, so they
        # enter the mirror's dirty set (the next derive's delta refresh
        # reconciles the persistent aggregates) and the mutation counter
        # moves with them — the dirty set and the staleness guard must
        # agree on what "changed" means (commit runs before this cycle's
        # dispatch captures its sequence, so the guard semantics are
        # unchanged).
        self._audit_flow_rows(rows, ST_BOUND, "commit-bind")
        # Journey: the placement landed (first-time rows record the
        # bind — and their time-to-bind — with the committing solve's
        # flow id; steady-state re-binds bulk-count).
        self._journey_rows(
            rows, "bound",
            solve_id=int(self.stats.get("committed_solve_id") or 0))
        m.p_status[rows] = ST_BOUND
        m.p_node[rows] = nodes_c
        m.mark_pods_dirty(rows)
        m.mutation_seq += 1
        if self.shard is not None:
            # Cross-shard commit gate (shard.py, ISSUE 16): siblings
            # whose overlapped solve raced these binds attribute their
            # voids as cross-shard-conflict.
            m.shard_commit_seq += 1
        self.n_used = new_used
        self.n_idle = self.n_idle - add
        self.n_ntasks += np.bincount(
            nodes_c, minlength=self.Nn
        ).astype(I)
        self.resident[rows] = True

        # Job counters (affects readiness for later rounds + close).
        jr = self.jobr[rows]
        bc = np.bincount(jr, minlength=self.Jn).astype(I)
        self.j_cnt_alloc += bc
        self.j_cnt_pending -= bc
        self.j_ready_base = (
            self.j_cnt_alloc + self.j_cnt_succ + self.j_cnt_empty_pending
        )
        # (er, si, v) reused from the divergence guard's gather above.
        # The j_alloc_res/j_pending_res/q_alloc scatter updates are
        # deferred (see _flush_aggr): later rounds and the evict
        # machinery flush before reading.
        if not hasattr(self, "_aggr_pending"):
            self._aggr_pending = []
        self._aggr_pending.append((jr[er], si, v, self.q_of_job[jr][er]))

        # Pod records + bind dispatch (async in the reference,
        # cache.go:536-552; here one batched dispatch).
        binder = store.binder
        bind_keys = getattr(binder, "bind_keys", None)
        notify = store._watchers
        pod_a, key_a, name_a = self._obj_arrays()
        # Bound hostnames land in the mirror as ONE batched column write
        # (the vectorized replacement for the 100k pod-record setattr
        # walk, which now only runs for record consumers — deferred to
        # the bind dispatcher or the sync-bind path below).
        m.p_node_name[rows] = name_a[nodes_c]
        defer_records = (
            getattr(store, "async_bind", False)
            and not notify
            and not store.n_volume_pods
            and not m.p_pod_nones
        )
        if defer_records:
            # The reference sets pod.NodeName via the API server on the
            # async bind, observed later by informers — not inside the
            # scheduling cycle (cache.go:536-552).  Register the object
            # ARRAYS with the store and ship the entry to the bind
            # dispatcher; its worker thread does the 100k-element tolist
            # + node_name walk post-cycle (~45 ms off the commit lane at
            # north-star scale).  Cycle-visible state (mirror arrays) is
            # already updated above; any failure path about to read pod
            # records forces the walk first (apply_pending_bind_records
            # — registration at commit time covers prior cycles' not-
            # yet-processed batches too).
            entry = store.defer_bind_records(
                key_a[rows], name_a[nodes_c], pod_a[rows]
            )
            self._bind_batches.append((None, None, None, entry))
            store.mark_objects_stale()
            return True
        pod_l = pod_a[rows].tolist()
        host_l = name_a[nodes_c].tolist()
        # Tombstoned rows can't be committed in the common case; the
        # mirror counts them so the 100k-element defensive None scan
        # (identity, NOT `in`: `in` calls the dataclass __eq__) only
        # runs when one exists.
        if not m.p_pod_nones or not any(p is None for p in pod_l):
            # Common case: every committed row has a live pod record.
            # Object-array gathers + one zip setattr walk instead of
            # four per-pod appends (this path covers 100k rows at
            # north-star scale).
            for pod, hostname in zip(pod_l, host_l):
                pod.node_name = hostname
            keys = key_a[rows].tolist()
            hosts = host_l
            bound_pods = pod_l
            bound_rows = rows.tolist()
        else:
            keys = []
            hosts = []
            bound_pods = []
            bound_rows = []
            key_l = key_a[rows].tolist()
            for row, pod, hostname, key in zip(
                    rows.tolist(), pod_l, host_l, key_l):
                if pod is None:
                    continue
                pod.node_name = hostname
                keys.append(key)
                hosts.append(hostname)
                bound_pods.append(pod)
                bound_rows.append(row)
        from .cache.interface import BindFailure, VolumeBindFailure

        # Volume gate (statement.go allocate->AllocateVolumes, commit->
        # BindVolumes): pods carrying claims go through the volume binder
        # BEFORE their bind dispatches; a claim failure reverts exactly
        # that pod to Pending.  Volume-free clusters skip on the store's
        # exact O(1) counter (the 100k-pod truthiness scan is not free,
        # and gating on store.pvcs would bypass custom volume binders).
        if store.n_volume_pods and any(
                pod.volumes for pod in bound_pods):
            vb = store.volume_binder
            vol_failed = []
            for pod, hostname, key in zip(bound_pods, hosts, keys):
                if not pod.volumes:
                    continue
                try:
                    vb.allocate_volumes(pod, hostname)
                    vb.bind_volumes(pod)
                except VolumeBindFailure as e:
                    store.record_event(f"Pod/{key}", "FailedScheduling",
                                       str(e))
                    vol_failed.append(key)
            if vol_failed:
                self._revert_failed_binds(vol_failed, keys, bound_rows,
                                          bound_pods)
                fset = set(vol_failed)
                kept = [
                    (k, h, p, r) for k, h, p, r
                    in zip(keys, hosts, bound_pods, bound_rows)
                    if k not in fset
                ]
                keys = [k for k, _, _, _ in kept]
                hosts = [h for _, h, _, _ in kept]
                bound_pods = [p for _, _, p, _ in kept]
                bound_rows = [r for _, _, _, r in kept]

        if getattr(store, "async_bind", False):
            # Async dispatch (cache.go:536-552): the cycle only pays a
            # list append (batches go to the dispatcher at cycle end —
            # see run()); failures surface via drain_bind_failures at
            # the next cycle's start and re-enter Pending with backoff.
            self._bind_batches.append((keys, hosts, bound_pods, None))
        else:
            try:
                if bind_keys is not None:
                    bind_keys(keys, hosts)
                else:
                    failed = []
                    for pod, hostname, key in zip(bound_pods, hosts, keys):
                        try:
                            binder.bind(pod, hostname)
                        except BindFailure:
                            failed.append(key)
                    if failed:
                        raise BindFailure(failed)
            except BindFailure as bf:
                self._revert_failed_binds(bf.failed, keys, bound_rows,
                                          bound_pods)
                failed = set(bf.failed)
                bound_pods = [
                    pod for pod, key in zip(bound_pods, keys)
                    if key not in failed
                ]
        if notify:
            for pod in bound_pods:
                store._notify("Pod", "bind", pod)

        store.mark_objects_stale()
        return True

    def _revert_failed_binds(self, failed_keys, keys: List[str],
                             bound_rows: List[int],
                             bound_pods: List[object]) -> None:
        """Undo the commit bookkeeping for binds the binder reports
        failed (cache.go errTasks resync): the tasks return to Pending
        and the next cycle retries them.

        The revert is per-task, as in the reference: a gang whose member
        bind fails stays partially bound below min_available until the
        retry succeeds — the reference likewise leaves the other members
        bound while errTasks resyncs the failed one, with the gang
        plugin's session-close conditions and the job's lifecycle
        policies handling a persistently failing member."""
        failed = set(failed_keys)
        idx = [i for i, k in enumerate(keys) if k in failed]
        if not idx:
            return
        log.warning("%d binds failed; tasks resync to Pending", len(idx))
        self._unbind_rows(np.array([bound_rows[i] for i in idx], np.int64))
        for i in idx:
            bound_pods[i].node_name = None
        for i in idx:
            # Claims the failed pod pinned/bound roll back with it
            # (release only after every failed pod's node_name is
            # cleared, so shared claims held by co-failed pods free up).
            if bound_pods[i].volumes:
                self.store.release_claims_for(bound_pods[i])

    def _unbind_rows(self, rows_f: np.ndarray) -> None:
        """Return bound mirror rows to Pending, reversing the commit's
        bookkeeping (node capacity/task slots, job and queue counters) —
        the vectorized core shared by the bind-failure resync above and
        the steady-state workload feed (``store.cycle_feed``), which
        re-pends just-committed rows to emulate continuous pod arrival
        at constant backlog.  Pod RECORDS are not touched; callers that
        need ``pod.node_name`` cleared do it themselves."""
        m = self.m
        self._flush_aggr()
        R = self.R
        nodes_f = m.p_node[rows_f].astype(np.int64)
        # The steady-state feed re-pends the SAME rows every cycle; the
        # static-spec gather over 100k rows is content-cached (rows are
        # stable between compactions, specs immutable per row).
        cache = (getattr(self.store, "_unbind_gather_cache", None)
                 if getattr(self, "_incr", True) else None)
        if (cache is not None and cache[0] == m.compact_gen
                and np.array_equal(cache[1], rows_f)):
            er, si, v = cache[2]
        else:
            er, si, v = m.c_req.gather(rows_f)
            if getattr(self, "_incr", True):
                self.store._unbind_gather_cache = (
                    m.compact_gen, rows_f.copy(), (er, si, v))
        # Every scatter below is a bincount over flattened indices —
        # np.add.at at the feed's 100k-row scale was the single largest
        # host cost of the pipelined steady state (~50 ms/cycle).
        sub = np.bincount(
            nodes_f[er] * R + si, weights=v, minlength=self.Nn * R,
        ).reshape(self.Nn, R).astype(F)
        self.n_used = self.n_used - sub
        self.n_idle = self.n_idle + sub
        self.n_ntasks -= np.bincount(
            nodes_f, minlength=self.Nn
        )[:self.Nn].astype(I)
        self._audit_flow_rows(rows_f, ST_PENDING, "unbind")
        # Journey: bulk-count only — the feed's re-pend loop and the
        # bind-failure resync both leave the pods' first-bind latency
        # (already recorded) standing.
        self._journey_rows(rows_f, "unbound")
        m.p_status[rows_f] = ST_PENDING
        m.p_node[rows_f] = -1
        m.p_node_name[rows_f] = None
        m.mark_pods_dirty(rows_f)
        self.resident[rows_f] = False
        jr = self.jobr[rows_f]
        # Ungrouped bound pods (no job row) carry no job/queue
        # accounting — mask them out of the job-side scatters (the old
        # np.add.at silently folded index -1 into the LAST job row).
        jok = jr >= 0
        jbc = np.bincount(
            jr[jok], minlength=self.Jn
        )[:self.Jn].astype(I)
        self.j_cnt_alloc -= jbc
        self.j_cnt_pending += jbc
        self.j_ready_base = (
            self.j_cnt_alloc + self.j_cnt_succ + self.j_cnt_empty_pending
        )
        er_j = jok[er]
        jadd = np.bincount(
            jr[er][er_j].astype(np.int64) * R + si[er_j],
            weights=v[er_j], minlength=self.Jn * R,
        ).reshape(self.Jn, R).astype(F)
        self.j_alloc_res -= jadd
        self.j_pending_res += jadd
        q_of = np.where(jok, self.q_of_job[np.maximum(jr, 0)], -1)
        qmask = q_of >= 0
        if qmask.any():
            er_q = qmask[er]
            self.q_alloc -= np.bincount(
                q_of[er][er_q].astype(np.int64) * R + si[er_q],
                weights=v[er_q], minlength=self.Qn * R,
            ).reshape(self.Qn, R).astype(F)
        # Mirror state moved: an overlapping dispatch must re-validate.
        m.mutation_seq += 1

    # ------------------------------------------------------------ backfill

    def _backfill(self) -> bool:
        """Place zero-request pending tasks (backfill.go:39-88).
        Returns True when any row was bound (mirror state moved)."""
        m = self.m
        Pn = self.Pn
        status = m.p_status[:Pn]
        be_rows = np.flatnonzero(
            m.p_alive[:Pn] & (status == ST_PENDING) & m.p_be[:Pn]
        )
        if not len(be_rows):
            return False
        schedulable = set(self._schedulable_rows())
        # Node order: store insertion order (dict iteration in the object
        # path) == mirror row order.
        live_nodes = [i for i in range(self.Nn) if self.n_alive[i]]
        has_pred = self._has("predicates")
        bound_rows = []
        for row in be_rows:
            jrow = self.jobr[row]
            if jrow < 0 or jrow not in schedulable:
                continue
            feat = m.p_feat[row]
            placed = None
            for ni in live_nodes:
                if has_pred and not self._host_predicate(row, feat, ni):
                    continue
                placed = ni
                break
            if placed is not None:
                self._audit_flow(int(m.p_status[row]), ST_BOUND,
                                 "backfill-bind")
                self._journey_event(row, "bound", detail="backfill")
                m.p_status[row] = ST_BOUND
                m.p_node[row] = placed
                m.p_node_name[row] = m.n_name[placed]
                self.n_ntasks[placed] += 1
                self.resident[row] = True
                self.j_cnt_alloc[jrow] += 1
                self.j_cnt_pending[jrow] -= 1
                self.j_cnt_empty_pending[jrow] -= 1
                bound_rows.append(row)
        if bound_rows:
            # Direct mirror writes above: the dirty set must see them
            # (the caller stamps mutation_seq when this returns True).
            m.mark_pods_dirty(np.asarray(bound_rows, np.int64))
            # ready_base: empty-pending shrank, alloc grew -> net unchanged;
            # recompute for exactness.
            self.j_ready_base = (
                self.j_cnt_alloc + self.j_cnt_succ + self.j_cnt_empty_pending
            )
            store = self.store
            binder = store.binder
            bind_batch = getattr(binder, "bind_batch", None)
            pairs = []
            pair_rows = []
            for row in bound_rows:
                pod = store.pods.get(m.p_uid[row])
                if pod is None:
                    continue
                hostname = m.n_name[m.p_node[row]]
                pod.node_name = hostname
                pairs.append((pod, hostname))
                pair_rows.append(row)
            from .cache.interface import BindFailure

            failed_keys = set()
            try:
                if bind_batch is not None:
                    bind_batch(pairs)
                else:
                    for pod, hostname in pairs:
                        binder.bind(pod, hostname)
            except BindFailure as bf:
                failed_keys = set(bf.failed)
            if failed_keys:
                # BestEffort revert: no resource accounting to undo, only
                # status/placement/counters (errTasks resync semantics).
                log.warning(
                    "%d backfill binds failed; tasks resync to Pending",
                    len(failed_keys),
                )
                kept = []
                reverted = []
                for row, (pod, hostname) in zip(pair_rows, pairs):
                    key = f"{pod.namespace}/{pod.name}"
                    if key not in failed_keys:
                        kept.append((pod, hostname))
                        continue
                    jrow = self.jobr[row]
                    self._audit_flow(int(m.p_status[row]), ST_PENDING,
                                     "backfill-revert")
                    self._journey_event(row, "dropped",
                                        detail="bind-failed")
                    m.p_status[row] = ST_PENDING
                    self.n_ntasks[m.p_node[row]] -= 1
                    m.p_node[row] = -1
                    m.p_node_name[row] = None
                    self.resident[row] = False
                    reverted.append(row)
                    pod.node_name = None
                    if jrow >= 0:
                        self.j_cnt_alloc[jrow] -= 1
                        self.j_cnt_pending[jrow] += 1
                        self.j_cnt_empty_pending[jrow] += 1
                if reverted:
                    m.mark_pods_dirty(np.asarray(reverted, np.int64))
                pairs = kept
                self.j_ready_base = (
                    self.j_cnt_alloc + self.j_cnt_succ
                    + self.j_cnt_empty_pending
                )
            for pod, _ in pairs:
                if store._watchers:
                    store._notify("Pod", "bind", pod)
            store.mark_objects_stale()
            stats = getattr(self, "stats", None)
            if stats is not None:
                stats["bound"] = int(stats["bound"]) + len(pairs)
        return bool(bound_rows)

    def _host_predicate(self, row: int, feat, ni: int) -> bool:
        """Host predicates for best-effort tasks (predicates.go:144-293,
        minus resource fit)."""
        m = self.m
        if not self.n_ready[ni]:
            return False
        if self.n_maxtasks[ni] > 0 and self.n_ntasks[ni] >= self.n_maxtasks[ni]:
            return False
        node = m.node_objs[ni]
        labels = node.labels if node is not None else {}
        pod = self.store.pods.get(m.p_uid[row])
        if pod is None:
            return False
        if pod.node_selector and not all(
            labels.get(k) == v for k, v in pod.node_selector.items()
        ):
            return False
        terms = pod.required_node_affinity
        if terms and not any(
            all(labels.get(k) == v for k, v in t.items()) for t in terms
        ):
            return False
        for taint in (node.taints if node is not None else []):
            if taint.effect not in ("NoSchedule", "NoExecute"):
                continue
            ok = False
            for tol in pod.tolerations:
                if tol.operator == "Exists":
                    key_ok = tol.key == "" or tol.key == taint.key
                else:
                    key_ok = tol.key == taint.key and tol.value == taint.value
                if key_ok and (tol.effect == "" or tol.effect == taint.effect):
                    ok = True
                    break
            if not ok:
                return False
        if pod.host_ports:
            used = set()
            res_on_node = np.flatnonzero(
                self.resident & (m.p_node[:self.Pn] == ni)
            )
            for rr in res_on_node:
                f = m.p_feat[rr]
                if f is not None:
                    used.update(f.ports)
            my = {m.ports.index.get(p) for p in pod.host_ports}
            if used & my:
                return False
        return True

    # ----------------------------------------------------------- rebalance

    # Pipelined cycles see starvation one commit behind, so a gang must
    # stay starved this many consecutive rebalance passes before a plan
    # forms (gives the in-flight allocate dispatch its chance to bind).
    REBALANCE_STREAK_PIPELINED = 2
    # Cooldown (in rebalance passes) after a gang's plan is rejected:
    # a persistently starved gang whose what-if keeps failing must not
    # re-pay the frag kernel + what-if solve every cycle.  The world
    # changing enough to help (pods finishing, nodes joining) takes
    # many cycles anyway; a commit or leaving the starved set clears it.
    REBALANCE_REJECT_BACKOFF = 8

    def _rebalance(self) -> None:
        """Gang-aware defragmentation lane (ISSUE 5, docs/rebalance.md).

        Picks the most-starved schedulable gang, scores per-node
        fragmentation against its profile table (ops/rebalance.py — one
        kernel over the same planes the wave solver reads), selects a
        bounded drain set under per-PodGroup disruption budgets, and
        proves the migration with a what-if ``solve_wave`` over the
        hypothetically drained cluster (victims re-entered as pending
        alongside the gang, riding the exact allocate jit).  The plan
        commits — victims evicted through the ``fastpath_evict``
        machinery, restores registered with the migration ledger — only
        when the what-if shows strict improvement: the gang reaches
        ready AND every victim re-places.  Pipelined stores park the
        what-if as ``pipeline.InflightPlan`` and commit next cycle
        behind the staleness guard."""
        from .actions.rebalance import rebalance_enabled

        from . import whatif

        store = self.store
        if not rebalance_enabled():
            return
        remote = self._remote_solver
        if remote is not None:
            from . import whatif

            if not whatif.whatif_offload_on(remote):
                # Single-connection remote deployments keep the lane
                # off (the plan solve would contend for the one strict
                # request/reply connection); a solver POOL with an
                # idle non-primary replica offloads the plan solve
                # there instead (ISSUE 15).  A mesh is fine since
                # ISSUE 11: the engine's hypothetical patches touch
                # only per-cycle host planes, so the sharded devsnap
                # dispatch carries them unchanged.
                return
        ledger = store.migrations
        if ledger is not None and ledger.active(store, "rebalance"):
            # One REBALANCE wave at a time: budgets stay trivially
            # honest and a half-done wave never compounds.  (Preempt/
            # reclaim entries share the ledger but gate per gang —
            # their victims may legitimately stay Pending for a long
            # time and must not wedge this lane.)
            return
        if store._inflight_plan is not None:
            return
        jrow = self._find_starved_gang()
        if jrow is None:
            return
        plan = self._plan_rebalance(jrow)
        if plan is None:
            return
        whatif.dispatch_plan(self, plan)

    def _find_starved_gang(self) -> Optional[int]:
        """Most-starved schedulable gang (largest min_available
        shortfall, lowest row tie-break) whose starvation has persisted
        long enough (see REBALANCE_STREAK_PIPELINED)."""
        m = self.m
        store = self.store
        srows = np.asarray(self.session_jobs, np.int64)
        streaks = getattr(store, "_rebalance_streaks", None)
        if streaks is None:
            streaks = store._rebalance_streaks = {}
        if not len(srows):
            streaks.clear()
            return None
        mask = (
            (self.j_phase[srows] != 1)  # Inqueue gate, as _schedulable_rows
            & (self.j_cnt_pending[srows] > 0)
            & (self.j_ready_base[srows] < m.j_minav[srows])
            & (self.j_valid[srows] >= m.j_minav[srows])
            & (self.q_of_job[srows] >= 0)
        )
        cand = srows[mask]
        uids = {m.j_uid[int(r)] for r in cand}
        for uid in list(streaks):
            if uid not in uids:
                del streaks[uid]
        for uid in uids:
            streaks[uid] = streaks.get(uid, 0) + 1
        # Rejection cooldown: gangs whose last plan was rejected sit
        # out REBALANCE_REJECT_BACKOFF passes; leaving the starved set
        # clears the slate.
        backoff = getattr(store, "_rebalance_backoff", None)
        if backoff is None:
            backoff = store._rebalance_backoff = {}
        for uid in list(backoff):
            if uid not in uids:
                del backoff[uid]
            elif backoff[uid] > 0:
                backoff[uid] -= 1
        if not len(cand):
            return None
        need_streak = (self.REBALANCE_STREAK_PIPELINED
                       if self._pipeline_on else 1)
        need = (m.j_minav[cand] - self.j_ready_base[cand]).astype(np.int64)
        for r in cand[np.lexsort((cand, -need))]:
            uid = m.j_uid[int(r)]
            if streaks.get(uid, 0) >= need_streak \
                    and backoff.get(uid, 0) <= 0:
                return int(r)
        return None

    def _rebalance_backoff_set(self, gang_uid: str) -> None:
        backoff = getattr(self.store, "_rebalance_backoff", None)
        if backoff is None:
            backoff = self.store._rebalance_backoff = {}
        backoff[gang_uid] = self.REBALANCE_REJECT_BACKOFF

    def _plan_rebalance(self, jrow: int):
        """Score fragmentation and select a drain set for one starved
        gang; returns a ``whatif.WhatIfPlan`` (action "rebalance",
        victims re-solved) or None."""
        import jax

        from . import whatif
        from .actions.rebalance import drain_cap, max_unavailable_of
        from .ops.rebalance import frag_scores, select_drain_set

        m = self.m
        store = self.store
        Pn = self.Pn
        with self.tracer.span("rebalance_plan", cat="rebalance",
                              args={"gang": m.j_uid[jrow]}):
            need = int(m.j_minav[jrow] - self.j_ready_base[jrow])
            if need <= 0:
                return None
            pend = np.flatnonzero(
                m.p_alive[:Pn] & (m.p_status[:Pn] == ST_PENDING)
                & ~m.p_be[:Pn] & (self.jobr == jrow)
            )
            if not len(pend):
                return None
            gang_rows = pend[np.argsort(m.p_create[pend], kind="stable")]
            # Distinct profiles of the gang's pending tasks -> dense
            # [U, R] init-request table (the planner's notion of "a
            # gang task"; same profile interning _profile_tasks keys
            # on).
            _, first = np.unique(m.p_prof[gang_rows], return_index=True)
            urows = gang_rows[np.sort(first)]
            # Pad the profile axis to a pow2 bucket (all-zero rows are
            # inert: no requested slot -> fit 0) so gangs with varying
            # distinct-profile counts share one compiled kernel.
            Up = _pow2(max(len(urows), 1), 4)
            prof_req = np.zeros((Up, self.R), F)
            er, si, v = m.c_init_req.gather(urows)
            prof_req[er, si] = v
            # Migratable victims: Running residents with requests, not
            # critical (conformance-exempt), without inter-pod terms
            # (their what-if re-placement would need live term-count
            # surgery), and never the starved gang itself.
            vict = np.flatnonzero(
                self.resident[:Pn]
                & (m.p_status[:Pn] == ST_RUNNING)
                & ~m.p_critical[:Pn]
                & ~m.p_has_ip[:Pn]
                & (self.jobr >= 0)
                & (self.jobr != jrow)
            )
            if len(vict):
                vict = vict[m.c_req.lens(vict) > 0]
            # Node axis padded to the same pow2 bucket _solve_inputs
            # uses, so node churn (9999 -> 10000 nodes) does not
            # recompile the kernel on the cycle thread.  Padded rows
            # are not-ready (frag 0) and zero-capacity (fit 0).
            Np = _pow2(max(self.Nn, 1))
            evictable = np.zeros((Np, self.R), F)
            vnode = np.zeros(0, np.int64)
            if len(vict):
                vnode = m.p_node[:Pn][vict].astype(np.int64)
                er, si, v = m.c_req.gather(vict)
                np.add.at(evictable, (vnode[er], si), v)

            def padN(a, fill=0):
                out = np.full((Np, *a.shape[1:]), fill, a.dtype)
                out[:len(a)] = a
                return out

            fs = frag_scores(
                padN(self.n_idle.astype(F)),
                padN(self.n_alloc.astype(F)),
                padN(self.n_ready), evictable, prof_req, self.eps,
            )
            frag, fit_now, fit_freed = jax.device_get(
                (fs.frag, fs.fit_now, fs.fit_freed)
            )
            frag = frag[:self.Nn]
            fit_now = fit_now[:self.Nn]
            fit_freed = fit_freed[:self.Nn]
            alive = self.n_alive
            frag_mean = (float(frag[alive].mean())
                         if alive.any() else 0.0)
            metrics.rebalance_frag_score.set(frag_mean)
            # Fabric-defrag targeting (ops/topology): when the starved
            # gang carries a topology constraint, the drain set
            # concentrates on ONE target fabric block — the block whose
            # drains free the most gang capacity — so the migration
            # wave assembles a whole slice instead of shaving capacity
            # evenly across the fabric.  Outside the target block the
            # gain and frag signals are zeroed; select_drain_set (and
            # its disruption-budget charging) is unchanged.
            if m.j_topo[jrow] and self._topo_active():
                from .ops import topology as topo

                tf = self._topo_block_fit(jrow)
                if tf is not None:
                    frag_b = np.asarray(jax.device_get(topo.fabric_frag(
                        tf["cfit"], tf["whole"], tf["prof_cnt"]
                    )))
                    metrics.topology_frag_score.set(
                        float(frag_b.mean()) if len(frag_b) else 0.0)
                    blk = tf["block"][:self.Nn]
                    nb = tf["n_blocks"]
                    total_need = int(np.sum(tf["prof_cnt"]))
                    freed_sum = np.zeros(nb + 1, np.float64)
                    np.add.at(freed_sum,
                              np.where(blk >= 0, blk, nb), fit_freed)
                    freed_sum = freed_sum[:nb]
                    if (nb and total_need > 0
                            and freed_sum.max() >= total_need):
                        target = int(np.argmax(freed_sum))
                        on_blk = blk == target
                        # The drain wave only has to close the target
                        # block's SHORTFALL — its standing free
                        # capacity (cfit) already counts toward the
                        # gang; the classic need (minav - ready) would
                        # demand the whole gang out of drains alone
                        # and starve forever on a mostly-free block.
                        short = int(np.maximum(
                            np.asarray(tf["prof_cnt"], np.int64)
                            - np.asarray(tf["cfit"][target], np.int64),
                            0).sum())
                        if short <= 0:
                            # Block already whole: the pregate lifts
                            # next cycle; nothing to drain.
                            return None
                        need = short
                        frag = np.where(on_blk, frag, 0.0)
                        fit_freed = np.where(on_blk, fit_freed, fit_now)
                    elif m.j_topo[jrow] == TOPOLOGY_REQUIRE:
                        # No block gains capacity from any drain: no
                        # migration wave can make this gang contiguous.
                        whatif.count_plan(
                            self, "rebalance", "rejected-topology",
                            gang=m.j_uid[jrow], need=need,
                        )
                        self._rebalance_backoff_set(m.j_uid[jrow])
                        return None
            # Per-node victim lists only for DRAIN CANDIDATES (frag-
            # positive nodes whose drain gains capacity): the Python
            # walk is then bounded by the fragmentation hotspots, not
            # the cluster's whole Running population.
            victims_by_node: List[List[int]] = [
                [] for _ in range(self.Nn)
            ]
            victim_group: Dict[int, str] = {}
            if len(vict):
                cand_mask = (fit_freed > fit_now) & (frag > 0.0)
                on_cand = cand_mask[vnode]
                for row, n in zip(vict[on_cand].tolist(),
                                  vnode[on_cand].tolist()):
                    victims_by_node[n].append(row)
                    victim_group[row] = m.j_uid[int(self.jobr[row])]
            # Remaining per-group disruption budget after waves already
            # in flight (PDB max_unavailable equivalent).
            ledger = store.migrations
            budget_left: Dict[str, int] = {}
            for uid in set(victim_group.values()):
                row = m.j_row.get(uid, -1)
                pg = m.j_pg[row] if row >= 0 else None
                used = (ledger.disrupted(store, uid)
                        if ledger is not None else 0)
                budget_left[uid] = max_unavailable_of(pg) - used
            nodes, budget_blocked = select_drain_set(
                frag, fit_now, fit_freed, need, victims_by_node,
                victim_group, budget_left, drain_cap(),
            )
            if not nodes:
                if budget_blocked:
                    whatif.count_plan(
                        self, "rebalance", "rejected-budget",
                        gang=m.j_uid[jrow],
                        need=need, frag=round(frag_mean, 4),
                    )
                # Cooldown either way: no drain set can form until the
                # cluster moves, so re-scoring every cycle is waste.
                self._rebalance_backoff_set(m.j_uid[jrow])
                return None
            victim_rows = np.asarray(
                [r for n in nodes for r in victims_by_node[n]],
                np.int64,
            )
            budgets: Dict[str, int] = {}
            for r in victim_rows.tolist():
                g = victim_group[r]
                budgets[g] = budgets.get(g, 0) + 1
            return whatif.WhatIfPlan(
                action="rebalance",
                gang_job=int(jrow), gang_uid=m.j_uid[jrow],
                gang_rows=gang_rows, victim_rows=victim_rows,
                victim_jobs=self.jobr[victim_rows].astype(np.int64),
                drain_nodes=np.asarray(nodes, np.int64), need=need,
                frag_before=frag_mean, budgets=budgets,
                resolve_victims=True,
            )

    def _commit_inflight_plan(self) -> None:
        """Land (or void) the previous cycle's pipelined what-if plan —
        rebalance, preempt or reclaim — through the shared engine
        (``whatif.commit_inflight_plan``): any mutation/epoch/compaction
        /node-count drift voids the plan wholesale."""
        if self.shard is not None and not self.shard.runs_evictions:
            # The parked plan belongs to the evictor shard (shard 0);
            # a sibling popping it would commit evictions planned
            # against another shard's view.
            return
        from . import whatif

        whatif.commit_inflight_plan(self)

    # --------------------------------------------------------------- close

    def _close(self) -> None:
        """Gang OnSessionClose conditions + PodGroup status write-back
        (gang.go:140-183 + framework.go jobStatus).

        Change detection runs vectorized against the derive-time status
        snapshot (j_phase/j_st_*); Python touches only the rows that
        actually write back."""
        m = self.m
        store = self.store
        srows = np.asarray(self.session_jobs, np.int64)
        if not len(srows):
            if self._has("gang"):
                # An emptied session must not freeze the gauge at the
                # previous cycle's count.
                metrics.unschedule_job_count.set(0)
            self._phase_dirty.clear()
            return

        unsched_mask = np.zeros(self.Jn, bool)
        cond_changed = np.zeros(self.Jn, bool)
        if self._has("gang"):
            unready = srows[
                self.j_ready_base[srows] < m.j_minav[srows]
            ]
            unsched_mask[unready] = True
            gang_events = []
            gauge_pairs = []
            retry_keys = []
            set_gauges = True
            unready_counts = (
                m.j_minav[unready] - self.j_ready_base[unready]
            )
            if len(unready):
                counts = self._ensure_status_counts()
                csub = counts[unready]
                # Steady-state reuse (ISSUE 8 close lane): a
                # persistently-unready set whose live status breakdown
                # did not move produces the SAME signatures, messages,
                # gauge values, and retry keys as last cycle — reuse
                # the cached lists and skip the hash/group/list build
                # (retry counters still increment, gauges keep their
                # already-set values).  Any signature the mirror has
                # not persisted (external condition writers) falls
                # through to the full build.
                cache = (getattr(store, "_close_gang_cache", None)
                         if getattr(self, "_incr", True) else None)
                if (cache is not None and cache["jn"] == self.Jn
                        and np.array_equal(cache["unready"], unready)
                        and np.array_equal(cache["ucounts"],
                                           unready_counts)
                        and np.array_equal(cache["csub"], csub)
                        and bool((cache["sigs"]
                                  == m.j_cond_sig[unready]).all())):
                    retry_keys = cache["retry_keys"]
                    gauge_pairs = cache["gauge_pairs"]
                    set_gauges = False
                    unready_built = False
                else:
                    unready_built = True
            else:
                unready_built = False
            if unready_built:
                # Group-wise messages: jobs sharing (status counts,
                # minAvailable, unready, total) share the message text,
                # so one np.unique + one build per GROUP replaces 25k
                # per-row memo probes at config-4 scale.
                comp = np.concatenate([
                    csub,
                    m.j_minav[unready][:, None].astype(np.int64),
                    unready_counts[:, None].astype(np.int64),
                    self.j_cnt_total[unready][:, None].astype(np.int64),
                ], axis=1)
                # 1-D composite hash (np.unique axis=0 pays a 66 ms void
                # argsort at 25k rows): two independent wrapping dot
                # products; a colliding pair would merely share message
                # text, at ~2^-100 odds over the row space.
                rng = np.random.RandomState(0x5EED)
                with np.errstate(over="ignore"):
                    hv = (
                        comp * rng.randint(
                            1, 1 << 62, size=comp.shape[1]
                        ).astype(np.int64)[None, :]
                    ).sum(axis=1)
                    hv2 = (
                        comp * rng.randint(
                            1, 1 << 62, size=comp.shape[1]
                        ).astype(np.int64)[None, :]
                    ).sum(axis=1)
                    hv = hv * np.int64(1_000_003) + hv2
                _, reps, inv = np.unique(
                    hv, return_index=True, return_inverse=True
                )
                grp_msgs = [
                    self._gang_message(int(unready[ri])) for ri in reps
                ]
                # Same key shape as mirror.upsert_pod_group's refresh:
                # hash((reason, message)) — the two must match or the
                # throttle re-fires after every external status write.
                grp_sigs = np.array(
                    [hash(("NotEnoughResources", s)) & 0x7FFFFFFFFFFFFFFF
                     for s in grp_msgs],
                    np.int64,
                )
                sigs = grp_sigs[inv]
                # Condition refresh throttling (job_updater.go
                # isPodGroupConditionsUpdated): the mirror keeps the
                # hash of the Unschedulable condition last written, so
                # persistently-unschedulable jobs skip the per-object
                # scan/rewrite entirely.
                need = np.flatnonzero(sigs != m.j_cond_sig[unready])
                j_pgs = self.j_pgs
                uid_l = self.uid
                cond_sig = m.j_cond_sig
                for li in need.tolist():
                    row = int(unready[li])
                    pg = j_pgs[row]
                    if pg is None:
                        continue
                    msg = grp_msgs[inv[li]]
                    conditions = [
                        c for c in pg.status.conditions
                        if c.type != POD_GROUP_UNSCHEDULABLE
                    ]
                    conditions.append(PodGroupCondition(
                        type=POD_GROUP_UNSCHEDULABLE,
                        status="True",
                        transition_id=uid_l,
                        reason="NotEnoughResources",
                        message=msg,
                    ))
                    pg.status.conditions = conditions
                    cond_changed[row] = True
                    cond_sig[row] = sigs[li]
                    gang_events.append((
                        m.j_event_key[row]
                        or f"PodGroup/{pg.namespace}/{pg.name}",
                        "Unschedulable", msg,
                    ))
                jk = m.j_gauge_key
                uids = m.j_uid
                retry_keys = [
                    jk[row] or (("job_name", uids[row].split("/")[-1]),)
                    for row in unready.tolist()
                ]
                gauge_pairs = list(zip(retry_keys,
                                       unready_counts.tolist()))
                if getattr(self, "_incr", True):
                    store._close_gang_cache = {
                        "jn": self.Jn, "unready": unready,
                        "ucounts": unready_counts, "csub": csub,
                        "sigs": sigs, "retry_keys": retry_keys,
                        "gauge_pairs": gauge_pairs,
                    }
            if gang_events:
                store.record_events_deferred(gang_events)
            if set_gauges:
                metrics.unschedule_task_count.set_many(gauge_pairs)
            metrics.job_retry_counts.inc_many(retry_keys)
            metrics.unschedule_job_count.set(len(unready))

        # jobStatus write-back, skipping unchanged PodGroups
        # (framework.go jobStatus + job_updater.go
        # isPodGroupStatusUpdated: only changed statuses are written).
        cur_code = self.j_phase[srows]
        running_a = self.j_cnt_run[srows]
        failed_a = self.j_cnt_fail[srows]
        succ_a = self.j_cnt_succ[srows]
        alloc_a = self.j_cnt_alloc[srows] + succ_a
        new_code = np.where(
            (running_a != 0) & unsched_mask[srows],
            np.int8(4),  # Unknown
            np.where(
                alloc_a >= m.j_minav[srows],
                np.int8(3),  # Running
                np.where(cur_code != 2, np.int8(1), cur_code),
            ),
        )
        changed = (
            (new_code != cur_code)
            | (running_a != self.j_st_run[srows])
            | (failed_a != self.j_st_fail[srows])
            | (succ_a != self.j_st_succ[srows])
            | cond_changed[srows]
        ) & (cur_code != 0)  # code 0 = no PodGroup
        if self._phase_dirty:
            # In-place transitions (enqueue's Pending -> Inqueue) made
            # the snapshot match the mutated object; force those rows.
            j_row = m.j_row
            dirty = np.zeros(self.Jn, bool)
            Jn = self.Jn
            for uid in self._phase_dirty:
                row = j_row.get(uid, -1)
                if 0 <= row < Jn:
                    dirty[row] = True
            changed |= dirty[srows] & (cur_code != 0)
        idx = np.flatnonzero(changed)
        failed_status_uids = None
        if len(idx):
            rows_arr = srows[idx]
            codes = new_code[idx]
            rows_l = rows_arr.tolist()
            run_l = running_a[idx].tolist()
            fail_l = failed_a[idx].tolist()
            succ_l = succ_a[idx].tolist()
            # new_code only produces codes 1-4 (all named phases), so the
            # string lookup vectorizes; the snapshot arrays update in
            # four vector writes instead of per-row stores.
            phase_l = _PHASE_STR_BY_CODE[codes].tolist()
            self.j_phase[rows_arr] = codes
            self.j_st_run[rows_arr] = running_a[idx]
            self.j_st_fail[rows_arr] = failed_a[idx]
            self.j_st_succ[rows_arr] = succ_a[idx]
            j_pgs = self.j_pgs
            updater = store.status_updater
            batch_update = getattr(updater, "update_pod_groups", None)
            update = updater.update_pod_group
            written: List[object] = []
            watchers = store._watchers
            for row, ph, running, failed, succeeded in zip(
                    rows_l, phase_l, run_l, fail_l, succ_l):
                pg = j_pgs[row]
                if pg is None:
                    continue
                status = pg.status
                status.phase = ph
                status.running = running
                status.failed = failed
                status.succeeded = succeeded
                if batch_update is not None:
                    written.append(pg)
                else:
                    update(pg)
                if watchers:
                    store._notify("PodGroup", "status", pg)
            if written:
                # One write-back call per close (job_updater.go batches
                # its API writes the same way; a remote updater would
                # otherwise pay 12k round trips).
                try:
                    batch_update(written)
                except Exception:
                    # The local status already advanced, so the change
                    # detection would skip these rows forever; re-mark
                    # them dirty (after the clear below) so the next
                    # cycle rewrites the batch.
                    log.exception(
                        "status batch write failed; %d PodGroups "
                        "re-marked dirty for the next cycle",
                        len(written),
                    )
                    failed_status_uids = [pg.uid for pg in written]
        # Every pending in-place transition has now been persisted (or
        # superseded); a failure above leaves the set intact for the
        # next cycle.
        self._phase_dirty.clear()
        if failed_status_uids:
            self._phase_dirty.update(failed_status_uids)

    def _ensure_status_counts(self) -> np.ndarray:
        """[Jn, S+1] per-(job x status-class) counts over LIVE state —
        the persistent derive-time table adjusted by the rows the cycle
        itself dirtied (commit binds, evictions), instead of a full
        pod-axis scan per close (fastpath_incr.live_status_counts).
        Columns follow ``fastpath_incr.STATUS_VALUES`` order."""
        counts = getattr(self, "_status_counts", None)
        if counts is None:
            from .fastpath_incr import aggregates_of

            counts = self._status_counts = aggregates_of(
                self.m).live_status_counts(self.m, self.Pn)
        return counts

    def _gang_message(self, row: int) -> str:
        """Replicates gang.go's unschedulable message via job.fit_error()."""
        from .fastpath_incr import N_STATUS, STATUS_VALUES

        m = self.m
        counts = self._ensure_status_counts()
        unready = int(m.j_minav[row] - self.j_ready_base[row])
        total = int(self.j_cnt_total[row])
        key = (counts[row].tobytes(), int(m.j_minav[row]), unready, total)
        memo = getattr(self, "_gang_msg_memo", None)
        if memo is None:
            memo = self._gang_msg_memo = {}
        msg = memo.get(key)
        if msg is None:
            reasons = {
                TaskStatus(STATUS_VALUES[ci]).name: int(n)
                for ci, n in enumerate(counts[row][:N_STATUS])
                if n
            }
            reasons["minAvailable"] = int(m.j_minav[row])
            parts = sorted(f"{v} {k}" for k, v in reasons.items())
            fit = f"pod group is not ready, {', '.join(parts)}."
            msg = memo[key] = (
                f"{unready}/{total} tasks in gang unschedulable: {fit}"
            )
        return msg


def run_cycle_fast(store, conf, shard=None) -> bool:
    """Run one scheduling cycle on the fast path; False = not eligible
    (caller should fall back to the object-session path).  ``shard`` is
    the calling loop's shard.ShardContext under the sharded control
    plane (ISSUE 16) — cycles stay atomic under the store lock, so
    shards interleave at cycle granularity and only the PIPELINED
    overlap races across shards (the optimistic commit gate's domain)."""
    cycle = FastCycle(store, conf, shard=shard)
    if not cycle.eligible():
        return False
    with store._lock:
        cycle.run()
    if shard is not None:
        shard.cycles += 1
    return True
