"""vtpuctl: the framework CLI (pkg/cli + cmd/cli in the reference)."""

from .main import main

__all__ = ["main"]
