"""vtpuctl: job and queue management CLI.

Command surface mirrors vcctl (cmd/cli/job.go:11-67, cmd/cli/queue.go):

  vtpuctl job run|list|view|suspend|resume|delete
  vtpuctl queue create|list|delete|operate

Talks JSON/HTTP to a running framework Service (volcano_tpu.service), the
way vcctl talks to the API server.  ``vtpuctl job run -f job.yaml`` accepts
a YAML job spec; flags cover the quick path (vsub-style).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request

import yaml

DEFAULT_SERVER = "http://127.0.0.1:11250"


def _request(server: str, method: str, path: str, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        server + path, data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as err:
        payload = err.read().decode()
        try:
            message = json.loads(payload).get("error", payload)
        except Exception:
            message = payload
        print(f"Error: {message}", file=sys.stderr)
        sys.exit(1)
    except urllib.error.URLError as err:
        print(f"Error: cannot reach server {server}: {err.reason}",
              file=sys.stderr)
        sys.exit(1)


# ------------------------------------------------------------------ job cmds


def job_run(args):
    if args.filename:
        with open(args.filename) as f:
            spec = yaml.safe_load(f)
    else:
        if not args.name:
            print("Error: --name or -f required", file=sys.stderr)
            sys.exit(1)
        spec = {
            "name": args.name,
            "namespace": args.namespace,
            "minAvailable": args.min_available or args.replicas,
            "queue": args.queue,
            "tasks": [
                {
                    "name": "default",
                    "replicas": args.replicas,
                    "containers": [
                        {"cpu": args.cpu, "memory": args.memory}
                    ],
                }
            ],
        }
    out = _request(args.server, "POST", "/apis/jobs", spec)
    print(f"run job {out['namespace']}/{out['name']} successfully")


def job_list(args):
    jobs = _request(
        args.server, "GET",
        f"/apis/jobs?namespace={args.namespace}" if args.namespace
        else "/apis/jobs",
    )
    fmt = "{:<12}{:<24}{:<12}{:>8}{:>9}{:>11}{:>8}{:>7}"
    print(fmt.format("Namespace", "Name", "Phase", "Pending", "Running",
                     "Succeeded", "Failed", "Retry"))
    for j in jobs:
        s = j["status"]
        print(fmt.format(j["namespace"], j["name"], s["phase"], s["pending"],
                         s["running"], s["succeeded"], s["failed"],
                         s["retryCount"]))


def job_view(args):
    job = _request(args.server, "GET",
                   f"/apis/jobs/{args.namespace}/{args.name}")
    print(yaml.safe_dump(job, sort_keys=False))


def _job_command(args, action: str, verb: str):
    _request(
        args.server, "POST", "/apis/commands",
        {"action": action, "targetKind": "Job", "targetName": args.name,
         "targetNamespace": args.namespace},
    )
    print(f"{verb} job {args.namespace}/{args.name} successfully")


def job_suspend(args):
    _job_command(args, "AbortJob", "suspend")


def job_resume(args):
    _job_command(args, "ResumeJob", "resume")


def job_delete(args):
    _request(args.server, "DELETE",
             f"/apis/jobs/{args.namespace}/{args.name}")
    print(f"delete job {args.namespace}/{args.name} successfully")


# ---------------------------------------------------------------- queue cmds


def queue_create(args):
    _request(
        args.server, "POST", "/apis/queues",
        {"name": args.name, "weight": args.weight,
         "reclaimable": not args.no_reclaim},
    )
    print(f"create queue {args.name} successfully")


def queue_list(args):
    queues = _request(args.server, "GET", "/apis/queues")
    fmt = "{:<24}{:>8}  {:<10}{:<12}"
    print(fmt.format("Name", "Weight", "State", "Reclaimable"))
    for q in queues:
        print(fmt.format(q["name"], q["weight"], q["state"],
                         str(q["reclaimable"])))


def queue_delete(args):
    _request(args.server, "DELETE", f"/apis/queues/{args.name}")
    print(f"delete queue {args.name} successfully")


def queue_operate(args):
    action = "OpenQueue" if args.action == "open" else "CloseQueue"
    _request(
        args.server, "POST", "/apis/commands",
        {"action": action, "targetKind": "Queue", "targetName": args.name},
    )
    print(f"{args.action} queue {args.name} successfully")


# --------------------------------------------------------------------- parse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="vtpuctl",
                                description="volcano-tpu batch CLI")
    p.add_argument("--server", default=DEFAULT_SERVER,
                   help="framework API endpoint")
    sub = p.add_subparsers(dest="group", required=True)

    job = sub.add_parser("job", help="job operations")
    jsub = job.add_subparsers(dest="cmd", required=True)

    run = jsub.add_parser("run", help="submit a job")
    run.add_argument("-f", "--filename", help="YAML job spec")
    run.add_argument("--name")
    run.add_argument("-n", "--namespace", default="default")
    run.add_argument("--queue", default="default")
    run.add_argument("-r", "--replicas", type=int, default=1)
    run.add_argument("--min-available", type=int, default=0)
    run.add_argument("--cpu", default="1")
    run.add_argument("--memory", default="1Gi")
    run.set_defaults(func=job_run)

    lst = jsub.add_parser("list", help="list jobs")
    lst.add_argument("-n", "--namespace", default="")
    lst.set_defaults(func=job_list)

    for name, fn in (("view", job_view), ("suspend", job_suspend),
                     ("resume", job_resume), ("delete", job_delete)):
        c = jsub.add_parser(name)
        c.add_argument("--name", required=True)
        c.add_argument("-n", "--namespace", default="default")
        c.set_defaults(func=fn)

    queue = sub.add_parser("queue", help="queue operations")
    qsub = queue.add_subparsers(dest="cmd", required=True)

    qc = qsub.add_parser("create")
    qc.add_argument("--name", required=True)
    qc.add_argument("--weight", type=int, default=1)
    qc.add_argument("--no-reclaim", action="store_true")
    qc.set_defaults(func=queue_create)

    ql = qsub.add_parser("list")
    ql.set_defaults(func=queue_list)

    qd = qsub.add_parser("delete")
    qd.add_argument("--name", required=True)
    qd.set_defaults(func=queue_delete)

    qo = qsub.add_parser("operate")
    qo.add_argument("--name", required=True)
    qo.add_argument("-a", "--action", choices=["open", "close"],
                    required=True)
    qo.set_defaults(func=queue_operate)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    args.func(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())


# ---------------------------------------------------------------- v-binaries
# Standalone entry points mirroring the reference's vsub/vjobs/vqueues/
# vcancel/vsuspend/vresume binaries (cmd/cli/ subdirs): each is the
# corresponding subcommand with the same flags.

def _shim(prefix):
    def entry(argv=None):
        args = list(sys.argv[1:] if argv is None else argv)
        # --server is a root-parser flag: lift it in front of the
        # injected subcommand; everything else stays behind it.
        pre, rest = [], []
        i = 0
        while i < len(args):
            a = args[i]
            if a == "--server" and i + 1 < len(args):
                pre.extend(args[i:i + 2])
                i += 2
                continue
            if a.startswith("--server="):
                pre.append(a)
            else:
                rest.append(a)
            i += 1
        return main(pre + prefix + rest)

    return entry


vsub = _shim(["job", "run"])
vjobs = _shim(["job", "list"])
vcancel = _shim(["job", "delete"])
vsuspend = _shim(["job", "suspend"])
vresume = _shim(["job", "resume"])
vqueues = _shim(["queue", "list"])
