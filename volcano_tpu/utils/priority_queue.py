"""Heap-backed priority queue over a less-function
(pkg/scheduler/util/priority_queue.go:26-94)."""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Callable, List, Tuple


class _Item:
    __slots__ = ("value", "less", "seq")

    def __init__(self, value, less, seq):
        self.value = value
        self.less = less
        self.seq = seq

    def __lt__(self, other: "_Item") -> bool:
        if self.less(self.value, other.value):
            return True
        if self.less(other.value, self.value):
            return False
        return self.seq < other.seq  # stable


class PriorityQueue:
    """Pops the least element per ``less_fn`` (ties broken by insert order)."""

    def __init__(self, less_fn: Callable[[Any, Any], bool]):
        self._less = less_fn
        self._heap: List[_Item] = []
        self._seq = itertools.count()

    def push(self, value) -> None:
        heapq.heappush(self._heap, _Item(value, self._less, next(self._seq)))

    def pop(self):
        if not self._heap:
            return None
        return heapq.heappop(self._heap).value

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)
