"""Predicate/prioritize helpers (pkg/scheduler/util/scheduler_helper.go).

The reference fans these out over 16 goroutines with adaptive node sampling
(scheduler_helper.go:43-183); the TPU rebuild's allocate path replaces them
with one kernel, so these host versions serve the preempt/reclaim/backfill
paths where victim selection is per-node anyway.  Selection is deterministic
(first max) instead of random-among-max (scheduler_helper.go:201-212).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..api import FitErrors, NodeInfo, TaskInfo


def predicate_nodes(task: TaskInfo, nodes: List[NodeInfo],
                    predicate_fn) -> Tuple[List[NodeInfo], FitErrors]:
    """All nodes passing the predicate + aggregated fit errors."""
    feasible: List[NodeInfo] = []
    errors = FitErrors()
    for node in nodes:
        try:
            predicate_fn(task, node)
        except Exception as err:
            errors.set_node_error(node.name, err)
            continue
        feasible.append(node)
    return feasible, errors


def prioritize_nodes(task: TaskInfo, nodes: List[NodeInfo],
                     batch_fn, map_fn) -> Dict[float, List[NodeInfo]]:
    """score -> nodes map (PrioritizeNodes: map scores + batch scores)."""
    scores: Dict[str, float] = {n.name: 0.0 for n in nodes}
    for node in nodes:
        scores[node.name] += map_fn(task, node)
    for name, s in (batch_fn(task, nodes) or {}).items():
        if name in scores:
            scores[name] += s
    by_score: Dict[float, List[NodeInfo]] = {}
    for node in nodes:
        by_score.setdefault(scores[node.name], []).append(node)
    return by_score


def sort_nodes(node_scores: Dict[float, List[NodeInfo]]) -> List[NodeInfo]:
    out: List[NodeInfo] = []
    for score in sorted(node_scores.keys(), reverse=True):
        out.extend(node_scores[score])
    return out


def validate_victims(preemptor: TaskInfo, node: NodeInfo,
                     victims: List[TaskInfo]) -> None:
    """Raise unless the victims' resources satisfy the preemptor
    (scheduler_helper.go:224-239)."""
    if not victims:
        raise ValueError("no victims")
    future_idle = node.future_idle()
    for victim in victims:
        future_idle.add(victim.resreq)
    if not preemptor.init_resreq.less_equal(future_idle):
        raise ValueError(
            f"not enough resources: requested <{preemptor.init_resreq}>, "
            f"but future idle <{future_idle}>"
        )
