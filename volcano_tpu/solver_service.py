"""Remote-solver split: the device-owning solver as its own process.

The north-star bridge (BASELINE.json; the reference's two planes likewise
communicate only through serialized API-server state,
``pkg/scheduler/cache/cache.go:492-554``): the scheduler process — store,
controllers, session encode, commit — runs WITHOUT touching an
accelerator, shipping each cycle's solver inputs over a socket as one
C++-packed frame (``cache/snapwire.py`` / ``csrc/vcsnap.cc``), and the
solver process — which owns the TPU — runs ``ops.wave.solve_wave`` and
returns the assignment vectors the commit consumes.

Wire protocol (one TCP connection, request/response):

    [u64 little-endian frame length][frame bytes]

Request manifest: ``{"op": "solve", "tree": <spec>, "wave": int|None}``
(``tree`` is the ``snapwire.flatten_tree`` spec of
``(solve_args, pid, profiles)``), or ``{"op": "ping"}``.
Response manifest: ``{"op": "result", "tree": ...}`` with
``(assigned, pipelined, never_ready, fit_failed, iters, fb_exhausted,
fb_affinity)`` — the trailing two are the two-phase shortlist-fallback
counters (decoders accept the pre-two-phase 5-tuple as zeros) — or
``{"op": "error", "message": ...}``.

Run the solver:  ``vtpu-solver --port 18477``  (or
``python -m volcano_tpu.solver_service``).
Point a scheduler at it:  ``vtpu-service --remote-solver 127.0.0.1:18477``.

Failure semantics: a transport or solver error fails the cycle; the
scheduler's next period retries (the store is untouched — solve is pure).
The client reconnects per error, so a restarted solver process heals
without scheduler intervention (its jit cache re-warms via the
persistent compilation cache).
"""

from __future__ import annotations

import argparse
import logging
import socket
import struct
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")
# A full hyperscale chunk is ~1 GB of count tensors; anything beyond this
# is a corrupt length prefix, not a snapshot.
MAX_FRAME = 8 << 30


def _registry():
    from .arrays.affinity import AffinityArgs
    from .ops.allocate import (
        SolveJobs,
        SolveNodes,
        SolveQueues,
        SolveTasks,
    )
    from .ops.scoring import ScoreWeights
    from .ops.wave import SolveProfiles

    return {
        cls.__name__: cls
        for cls in (SolveNodes, SolveTasks, SolveJobs, SolveQueues,
                    ScoreWeights, AffinityArgs, SolveProfiles)
    }


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    # Two sendalls, no prefix+payload concatenation: at hyperscale a
    # frame carries ~GB of count tensors and the concat would copy it.
    sock.sendall(_LEN.pack(len(payload)))
    sock.sendall(payload)


def recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, 8))
    if n > MAX_FRAME:
        raise ValueError(f"frame length {n} exceeds limit")
    return _recv_exact(sock, n)


# ------------------------------------------------------------------ server


class SolverServer:
    """Owns the local JAX device; serves solve requests over TCP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 18477):
        self._sock = socket.create_server((host, port))
        self.port = self._sock.getsockname()[1]
        self.host = host
        self._stop = threading.Event()
        self.solves = 0

    def serve_forever(self) -> None:
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            log.info("solver client connected: %s", addr)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ handling

    def _serve_conn(self, conn: socket.socket) -> None:
        from .cache import snapwire as sw
        from .ops.devincr import DeviceIncremental

        registry = _registry()
        # Per-connection device-incremental caches (ISSUE 9): the
        # scheduler sends cache-generation tokens in each solve frame's
        # manifest, so the child keeps its own persistent static planes
        # and warm-shortlist candidates across solves — one context per
        # connection (one scheduler per connection by protocol).
        devincr = DeviceIncremental()
        try:
            while True:
                try:
                    req = recv_frame(conn)
                except (ConnectionError, ValueError, OSError):
                    return
                try:
                    reply = self._handle(req, registry, sw, devincr)
                except Exception as e:  # solver-side error -> client raises
                    log.exception("solve failed")
                    # The scheduler anchored its dirty accumulator at
                    # SEND time (it cannot see this failure distinctly
                    # from a slow solve), so the failed frame's dirty
                    # rows will be absent from later frames: drop every
                    # cached plane — the next solve provably
                    # full-recomputes (and sheds any buffer a
                    # mid-execution crash poisoned).
                    devincr.invalidate()
                    reply = sw.encode_frame(
                        [], {"op": "error", "message": f"{type(e).__name__}: {e}"}
                    )
                try:
                    send_frame(conn, reply)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, req: bytes, registry, sw, devincr=None) -> bytes:
        manifest, arrays = sw.decode_frame(req)
        op = manifest.get("op")
        if op == "ping":
            try:
                import jax

                backend = jax.default_backend()
            except Exception as e:  # pragma: no cover
                backend = f"unavailable: {e}"
            return sw.encode_frame(
                [], {"op": "pong", "solves": self.solves,
                     "backend": backend}
            )
        if op != "solve":
            return sw.encode_frame(
                [], {"op": "error", "message": f"unknown op {op!r}"}
            )
        # Received views are read-only; the solver only reads them.
        solve_args, pid, profiles = sw.unflatten_tree(
            manifest["tree"], arrays, registry
        )
        from .ops.wave import solve_wave
        from .scheduler import enable_compilation_cache

        enable_compilation_cache()

        import jax

        kw = {}
        if manifest.get("wave") is not None:
            kw["wave"] = int(manifest["wave"])
        import time as _time

        # Device-incremental tokens (ISSUE 9): the scheduler's frame
        # names the cache generations its static planes / warm
        # shortlists are valid under; this child's per-connection
        # context applies the same key/dirty-superset discipline the
        # local path does (ops/devincr.py).  Frames without the section
        # (older schedulers, kill switch) solve exactly as before.
        dv = None
        dv_tokens = manifest.get("devincr")
        if devincr is not None and dv_tokens:
            dirty = dv_tokens.get("dirty_nodes")
            devincr.begin_solve(
                dv_tokens.get("static_key"),
                dv_tokens.get("warm_key"),
                None if dirty is None else np.asarray(dirty, np.int64),
            )
            dv = devincr
        t0 = _time.perf_counter()
        res = solve_wave(*solve_args, pid=pid, profiles=profiles,
                         devincr=dv, **kw)
        out = jax.device_get(
            (res.assigned, res.pipelined, res.never_ready, res.fit_failed,
             res.iters if res.iters is not None else np.int32(0),
             res.fb_exhausted if res.fb_exhausted is not None
             else np.int32(0),
             res.fb_affinity if res.fb_affinity is not None
             else np.int32(0))
        )
        solve_ms = (_time.perf_counter() - t0) * 1e3
        self.solves += 1
        arrays_out = []
        tree = sw.flatten_tree(tuple(np.asarray(x) for x in out), arrays_out)
        reply = {"op": "result", "tree": tree,
                 "solve_ms": round(solve_ms, 1)}
        if dv is not None:
            reply["devincr_mode"] = dv.last_mode
        return sw.encode_frame(arrays_out, reply)


# ------------------------------------------------------------------ client


class RemoteSolver:
    """Client-side drop-in for ``solve_wave`` over the snapshot bridge.

    One persistent connection; reconnects after any transport error so a
    restarted solver process heals transparently.  Thread-compatible with
    the scheduler's single cycle thread (no internal locking needed
    beyond reconnect)."""

    def __init__(self, address: str, timeout: float = 300.0):
        if "//" in address:
            address = address.split("//", 1)[1]
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None  # guarded-by: _lock
        # Outstanding pipelined request (solve_async): the wire protocol
        # is strict request/reply, so at most one may be unread.
        self._pending: Optional["PendingSolve"] = None  # guarded-by: _lock
        # Round-trip + payload telemetry for the BASELINE overhead table.
        self.requests = 0
        self.bytes_out = 0
        self.bytes_in = 0
        self.last_solve_ms: Optional[float] = None
        # Device-incremental decision the child reported for the last
        # decoded reply ("warm" | "full" | None) — the scheduler folds
        # it into volcano_device_incremental_solves_total.
        self.last_devincr_mode: Optional[str] = None
        # Span sink (obs/trace.py Tracer; service.py wires the store's
        # in, the default is the shared no-op): the pipelined send and
        # fetch legs then land in the cycle trace as "rpc" track spans.
        from .obs.trace import null_tracer

        self.tracer = null_tracer()

    # holds: _lock
    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(
                (self.host, self.port), timeout=self.timeout
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._pending = None
            self._close_locked()

    def _roundtrip(self, payload: bytes) -> bytes:
        with self._lock:
            if self._pending is not None:
                raise RuntimeError(
                    "a pipelined solve is in flight; fetch or abandon "
                    "it before a synchronous round trip"
                )
            try:
                sock = self._connect()
                send_frame(sock, payload)
                return recv_frame(sock)
            except (OSError, ConnectionError, ValueError):
                # One reconnect attempt (solver restart); then give up
                # and let the cycle fail/retry next period.
                self._close_locked()
                try:
                    sock = self._connect()
                    send_frame(sock, payload)
                    return recv_frame(sock)
                except (OSError, ConnectionError, ValueError):
                    self._close_locked()
                    raise

    def ping(self) -> dict:
        from .cache import snapwire as sw

        manifest, _ = sw.decode_frame(
            self._roundtrip(sw.encode_frame([], {"op": "ping"}))
        )
        return manifest

    def _encode_request(self, solve_args: Sequence, pid, profiles,
                        wave: Optional[int],
                        devincr: Optional[dict] = None) -> bytes:
        from .cache import snapwire as sw

        arrays: list = []
        tree = sw.flatten_tree(
            (tuple(solve_args), np.asarray(pid), profiles), arrays
        )
        manifest = {"op": "solve", "tree": tree, "wave": wave}
        if devincr is not None:
            # Cache-generation tokens keying the child's persistent
            # device-incremental planes (ISSUE 9; see _serve_conn).
            manifest["devincr"] = devincr
        return sw.encode_frame(arrays, manifest)

    def _decode_result(self, reply: bytes):
        from .cache import snapwire as sw
        from .ops.allocate import AllocResult

        self.bytes_in += len(reply) + 8
        manifest, rarrays = sw.decode_frame(reply)
        if manifest.get("op") == "error":
            raise RuntimeError(
                f"remote solver failed: {manifest.get('message')}"
            )
        self.last_solve_ms = manifest.get("solve_ms")
        self.last_devincr_mode = manifest.get("devincr_mode")
        vals = sw.unflatten_tree(manifest["tree"], rarrays, _registry())
        assigned, pipelined, never_ready, fit_failed, iters = vals[:5]
        # Replies predating the two-phase solve carry 5 entries; the
        # shortlist-fallback counters then read as zero.
        if len(vals) >= 7:
            fb_exhausted, fb_affinity = vals[5], vals[6]
        else:
            fb_exhausted = fb_affinity = np.int32(0)
        return AllocResult(
            assigned=assigned, pipelined=pipelined,
            never_ready=never_ready, fit_failed=fit_failed,
            idle=None, q_alloc=None, iters=iters,
            fb_exhausted=fb_exhausted, fb_affinity=fb_affinity,
        )

    def solve(self, solve_args: Sequence, pid, profiles,
              wave: Optional[int] = None,
              devincr: Optional[dict] = None):
        """Ship (solve_args, pid, profiles); return an AllocResult-shaped
        namedtuple of numpy arrays (assigned/pipelined/never_ready/
        fit_failed/iters; idle/q_alloc stay device-side concerns and are
        not transported — the host commit recomputes both)."""
        payload = self._encode_request(solve_args, pid, profiles, wave,
                                       devincr)
        self.requests += 1
        self.bytes_out += len(payload) + 8
        with self.tracer.timed_event(
                "rpc:solve", args={"bytes_out": len(payload) + 8}):
            return self._decode_result(self._roundtrip(payload))

    def solve_async(self, solve_args: Sequence, pid, profiles,
                    wave: Optional[int] = None,
                    devincr: Optional[dict] = None) -> "PendingSolve":
        """Pipelined dispatch: send frame N and return WITHOUT reading
        the reply, so the child's upload+solve+fetch runs concurrently
        with the scheduler's host lanes; ``PendingSolve.fetch`` receives
        it (normally at the top of cycle N+1 — the double-buffered
        session of ISSUE 1).  One request may be outstanding at a time
        (the wire protocol is strict request/reply on one connection).

        Send errors reconnect-and-resend once, like ``solve`` — no reply
        is outstanding yet, so the resend is safe.  A fetch error does
        NOT resend: the frame may be mid-solve in the child, and the
        caller's staleness machinery already treats a lost reply as "this
        cycle placed nothing" (the pods stay Pending and re-place)."""
        payload = self._encode_request(solve_args, pid, profiles, wave,
                                       devincr)
        with self.tracer.timed_event(
                "rpc:solve_send", args={"bytes_out": len(payload) + 8}):
            with self._lock:
                if self._pending is not None:
                    raise RuntimeError(
                        "a remote solve is already in flight; fetch or "
                        "abandon it before dispatching another"
                    )
                try:
                    sock = self._connect()
                    send_frame(sock, payload)
                except (OSError, ConnectionError, ValueError):
                    self._close_locked()
                    sock = self._connect()
                    send_frame(sock, payload)
                handle = PendingSolve(self)
                self._pending = handle
        self.requests += 1
        self.bytes_out += len(payload) + 8
        return handle

    def _finish_async(self, handle: "PendingSolve") -> bytes:
        with self._lock:
            if self._pending is not handle:
                raise RuntimeError("stale PendingSolve handle")
            self._pending = None
            try:
                return recv_frame(self._sock)
            except (OSError, ConnectionError, ValueError):
                # The connection's request/reply framing is now
                # indeterminate; drop it so the next dispatch starts
                # clean on a fresh socket.
                self._close_locked()
                raise

    def _abandon_async(self, handle: "PendingSolve") -> None:
        with self._lock:
            if self._pending is not handle:
                return
            self._pending = None
            # The unread reply would desynchronize the next request;
            # closing the socket resets the framing (the server logs the
            # dead peer and drops the reply).
            self._close_locked()


class PendingSolve:
    """An unread remote-solve reply (see ``RemoteSolver.solve_async``)."""

    def __init__(self, client: RemoteSolver):
        self._client = client

    def fetch(self):
        """Receive + decode the reply; returns the AllocResult-shaped
        numpy namedtuple ``RemoteSolver.solve`` returns."""
        with self._client.tracer.timed_event("rpc:solve_fetch"):
            return self._client._decode_result(
                self._client._finish_async(self)
            )

    def abandon(self) -> None:
        self._client._abandon_async(self)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="volcano-tpu remote solver (device-owning process)"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=18477)
    parser.add_argument("--announce", action="store_true",
                        help="print 'SOLVER <port>' once listening "
                             "(spawners parse this)")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = SolverServer(host=args.host, port=args.port)
    if args.announce:
        print(f"SOLVER {server.port}", flush=True)
    log.info("solver listening on %s:%d", server.host, server.port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()


if __name__ == "__main__":
    main()
