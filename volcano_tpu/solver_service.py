"""Remote-solver split: the device-owning solver as its own process.

The north-star bridge (BASELINE.json; the reference's two planes likewise
communicate only through serialized API-server state,
``pkg/scheduler/cache/cache.go:492-554``): the scheduler process — store,
controllers, session encode, commit — runs WITHOUT touching an
accelerator, shipping each cycle's solver inputs over a socket as one
C++-packed frame (``cache/snapwire.py`` / ``csrc/vcsnap.cc``), and the
solver process — which owns the TPU — runs ``ops.wave.solve_wave`` and
returns the assignment vectors the commit consumes.

Wire protocol v2 (one TCP connection, request/response):

    [u64 little-endian frame length][frame bytes]

Request manifest: ``{"op": "solve", "tree": <spec>, "wave": int|None}``
(``tree`` is the ``snapwire.flatten_tree`` spec of
``(solve_args, pid, profiles)``), or ``{"op": "ping"}``.
Response manifest: ``{"op": "result", "tree": ...}`` with
``(assigned, pipelined, never_ready, fit_failed, iters, fb_exhausted,
fb_affinity)`` — the trailing two are the two-phase shortlist-fallback
counters (decoders accept the pre-two-phase 5-tuple as zeros) — or
``{"op": "error", "message": ...}``.

Protocol v2 additions (ISSUE 10; a v1 manifest without them behaves
exactly as before):

- **Delta solve frames** (``VOLCANO_TPU_WIRE``, default on): the child
  keeps a per-connection mirror of the last materialized solve-args
  arrays, keyed by a client-assigned generation.  A solve manifest may
  carry ``"wire": {"gen": g}`` (full frame: the frame's arrays replace
  the mirror wholesale) or ``"wire": {"gen": g, "base": b, "recs":
  [...]}`` (delta frame: per mirror slot, ``[REC_SAME]`` reuses the
  mirrored array, ``[REC_FULL, p]`` replaces it with frame array p,
  ``[REC_DELTA, d, p]`` patches the changed row ranges of descriptor
  array d with the row payload array p — ``cache/snapwire.py``
  ``delta_apply``).  Every reply echoes ``"ack_gen": g``; a delta
  whose ``base`` is not the mirror's generation gets a ``{"op":
  "resync", "have_gen": ...}`` reply WITHOUT solving, so a reconnect,
  child restart, or token mismatch always falls back to a full frame —
  never a stale solve.  The client tracks connection identity itself
  (any reconnect voids its wire cache), so resync is a defense in
  depth, not a steady-state round trip.
- **Scatter-gather transport**: frames are sent as header bytes plus
  ``memoryview``s of the array data via ``socket.sendmsg`` (writev)
  and received with ``recv_into`` a preallocated buffer — a full
  frame costs ~0 extra host copies, a delta frame costs bytes
  proportional to churn.
- **Same-host shared memory** (``VOLCANO_TPU_SHM=1``): array payloads
  ride a ``multiprocessing.shared_memory`` segment (``"shm": {"name",
  "slots"}`` in the manifest, arrays list empty on the socket) so
  co-located scheduler/solver pairs skip the TCP stack for bulk bytes.
  A child that cannot attach the segment replies an
  ``ShmUnavailable`` error; the client then disables the lane and
  re-sends over TCP — the fallback costs one cycle, never a stale
  solve.  See docs/tuning.md "Remote wire".

Run the solver:  ``vtpu-solver --port 18477``  (or
``python -m volcano_tpu.solver_service``).
Point a scheduler at it:  ``vtpu-service --remote-solver 127.0.0.1:18477``.

Failure semantics: a transport or solver error fails the cycle; the
scheduler's next period retries (the store is untouched — solve is pure).
The client reconnects per error, so a restarted solver process heals
without scheduler intervention (its jit cache re-warms via the
persistent compilation cache).
"""

from __future__ import annotations

import argparse
import itertools
import logging
import os
import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger(__name__)

_LEN = struct.Struct("<Q")
# A full hyperscale chunk is ~1 GB of count tensors; anything beyond this
# is a corrupt length prefix, not a snapshot.
MAX_FRAME = 8 << 30


def _registry():
    from .arrays.affinity import AffinityArgs
    from .ops.allocate import (
        SolveJobs,
        SolveNodes,
        SolveQueues,
        SolveTasks,
    )
    from .ops.scoring import ScoreWeights
    from .ops.wave import SolveProfiles

    return {
        cls.__name__: cls
        for cls in (SolveNodes, SolveTasks, SolveJobs, SolveQueues,
                    ScoreWeights, AffinityArgs, SolveProfiles)
    }


def wire_mode() -> str:
    """The delta-frame lane switch (docs/tuning.md "Remote wire"), read
    per frame so bench.py can A/B inside one process: ``"on"`` (delta
    frames when the wire cache holds, the default), ``"off"`` (classic
    v1 full frames, no wire section at all — the kill switch), or
    ``"fallback"`` (the v2 machinery runs but every frame deliberately
    voids the cache first, exercising the full-frame fallback path —
    the bench A/B's forced-fallback lever)."""
    v = os.environ.get("VOLCANO_TPU_WIRE", "1").strip().lower()
    if v in ("0", "off", "no"):
        return "off"
    if v == "fallback":
        return "fallback"
    return "on"


def shm_on() -> bool:
    """Same-host shared-memory payload lane (docs/tuning.md)."""
    return os.environ.get("VOLCANO_TPU_SHM", "0") == "1"


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Receive exactly n bytes into ONE preallocated buffer.  The old
    chunk-list + ``b"".join`` made a second full copy of every frame;
    ``recv_into`` fills the final buffer directly (and the returned
    ``bytearray`` is writable, so the child's mirror can patch delta
    rows into it in place)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return buf


# sendmsg iovec budget per call (IOV_MAX is 1024 on Linux; stay under).
_SENDMSG_MAX_PARTS = 512


def send_frame_views(sock: socket.socket, total: int, parts) -> None:
    """Scatter-gather frame send: the length prefix plus the codec's
    header/data buffers go out via ``socket.sendmsg`` (writev) with no
    concatenation — zero extra host copies for the array payload.
    Handles partial sends by advancing through the buffer list."""
    bufs = [_LEN.pack(total)]
    bufs.extend(parts)
    if not hasattr(sock, "sendmsg"):  # pragma: no cover - exotic hosts
        sock.sendall(b"".join(bytes(b) for b in bufs))
        return
    i = 0
    while i < len(bufs):
        sent = sock.sendmsg(bufs[i:i + _SENDMSG_MAX_PARTS])
        while i < len(bufs) and sent >= len(bufs[i]):
            sent -= len(bufs[i])
            i += 1
        if sent:
            bufs[i] = memoryview(bufs[i])[sent:]


def send_frame(sock: socket.socket, payload: bytes) -> None:
    # Two sendalls, no prefix+payload concatenation: at hyperscale a
    # frame carries ~GB of count tensors and the concat would copy it.
    sock.sendall(_LEN.pack(len(payload)))
    sock.sendall(payload)


def recv_frame(sock: socket.socket) -> bytearray:
    (n,) = _LEN.unpack(_recv_exact(sock, 8))
    if n > MAX_FRAME:
        raise ValueError(f"frame length {n} exceeds limit")
    return _recv_exact(sock, n)


# ----------------------------------------------------------- shm payloads


class ShmUnavailable(RuntimeError):
    """The child could not attach the client's shared-memory segment
    (different host, unlinked segment, resized race).  The error reply
    carries this type name; the client disables the shm lane and
    re-sends payloads over TCP — one lost cycle, never a stale solve."""


# Segment names embed the pid plus a PROCESS-GLOBAL sequence: two live
# clients in one process (two stores, a bench A/B) must never both
# create "vtpu_wire_<pid>_1".
_SHM_SEQ = itertools.count(1)


class _ShmLane:
    """Client side of the same-host payload lane: one resizable
    ``multiprocessing.shared_memory`` segment the scheduler writes each
    frame's array payloads into (8-aligned slots); the socket carries
    only the manifest.  The strict request/reply protocol (at most one
    solve outstanding) guarantees the child finished reading a frame's
    slots before the next frame overwrites them."""

    def __init__(self):
        self._seg = None

    def write(self, arrays: List[np.ndarray]) -> dict:
        from multiprocessing import shared_memory

        from .cache import snapwire as sw

        # Same wire-format restrictions as the socket codec, checked
        # up front so an unsupported array fails like the TCP path
        # (not a bare KeyError from the slot builder below).
        for a in arrays:
            if a.dtype not in sw._DTYPE_CODE:
                raise TypeError(f"unsupported wire dtype {a.dtype}")
            if a.ndim > sw.WIRE_MAX_DIMS:
                raise ValueError(f"unsupported wire ndim {a.ndim}")
        # Slot alignment is the frame codec's: the 8-byte rule that
        # lays out socket frames also lays out segment slots.
        need = sum(sw._align8(a.nbytes) for a in arrays)
        if self._seg is None or need > self._seg.size:
            old = self._seg
            size = max(need, 1 << 20)
            if old is not None:
                size = max(size, 2 * old.size)
            self._seg = shared_memory.SharedMemory(
                name=f"vtpu_wire_{os.getpid()}_{next(_SHM_SEQ)}",
                create=True, size=size,
            )
            if old is not None:
                old.close()
                old.unlink()
        slots = []
        off = 0
        for a in arrays:
            if a.nbytes:
                np.frombuffer(self._seg.buf, np.uint8, count=a.nbytes,
                              offset=off)[:] = a.reshape(-1).view(np.uint8)
            slots.append([int(sw._DTYPE_CODE[a.dtype]), list(a.shape),
                          off])
            off += sw._align8(a.nbytes)
        return {"name": self._seg.name, "slots": slots}

    def close(self) -> None:
        if self._seg is not None:
            try:
                self._seg.close()
                self._seg.unlink()
            except (OSError, BufferError):
                # Best-effort teardown: a still-live numpy view keeps
                # the mmap exported (BufferError); the segment unlinks
                # when the last holder drops it.
                pass
            self._seg = None


class _ShmReader:
    """Child side: attaches the client's segment (cached by name) and
    views the frame's payload arrays out of it."""

    def __init__(self):
        self._seg = None
        self._name = None
        # Segments replaced by growth whose payload views may still be
        # alive: keep them referenced (log-bounded — growth doubles)
        # instead of a close() that hits BufferError and then re-raises
        # unraisably from SharedMemory.__del__ at GC time.
        self._retired: List = []

    def arrays(self, section: dict) -> List[np.ndarray]:
        from .cache import snapwire as sw

        name = section.get("name")
        if name != self._name:
            if self._seg is not None:
                self._retired.append(self._seg)
                self._seg = None
                self._name = None
            try:
                from multiprocessing import shared_memory

                seg = shared_memory.SharedMemory(name=name, create=False)
            except (OSError, ValueError, TypeError) as e:
                raise ShmUnavailable(f"cannot attach segment "
                                     f"{name!r}: {e}") from e
            # py3.10 registers ATTACHED segments with the resource
            # tracker too, which would unlink the client's live segment
            # when this process exits; the creator owns the unlink.
            # Skip when creator and reader share a process (in-process
            # bench server): attach and create then share ONE tracker
            # entry, and unregistering here would delete the creator's.
            try:
                creator_pid = int(str(name).split("_")[2])
            except (IndexError, ValueError):
                creator_pid = -1
            if creator_pid != os.getpid():
                try:  # pragma: no cover - stdlib-version dependent
                    from multiprocessing import resource_tracker

                    resource_tracker.unregister(seg._name,
                                                "shared_memory")
                except Exception:
                    pass
            self._seg, self._name = seg, name
        out = []
        size = self._seg.size
        for code, shape, off in section.get("slots", ()):
            code, off = int(code), int(off)
            if not 0 <= code < len(sw._DTYPES):
                raise ShmUnavailable(f"bad dtype code {code}")
            dt = sw._DTYPES[code]
            shape = tuple(int(d) for d in shape)
            # Unbounded python-int arithmetic: np.prod over hostile
            # dims (e.g. [2**32, 2**32]) wraps int64 to 0 and would
            # sail through the bounds check below.
            count = 1
            for d in shape:
                count *= d
            nbytes = count * dt.itemsize
            if min(shape, default=0) < 0 or off < 0 \
                    or nbytes > size - off:
                raise ShmUnavailable("slot outside segment bounds")
            out.append(np.frombuffer(self._seg.buf, dt, count=count,
                                     offset=off).reshape(shape))
        return out

    def close(self) -> None:
        if self._seg is not None:
            self._retired.append(self._seg)
            self._seg = None
            self._name = None
        retired, self._retired = self._retired, []
        for seg in retired:
            try:
                seg.close()
            except (OSError, BufferError):
                # A frame's payload views may still be alive (teardown
                # mid-request); dropping the reference suffices.
                pass


def _readonly_view(a: np.ndarray) -> np.ndarray:
    """A zero-copy non-writable view (the base array stays writable —
    the mirror's in-place delta patches are unaffected)."""
    v = a.view()
    v.flags.writeable = False
    return v


# ----------------------------------------------------------- wire mirror


class _WireMirror:
    """The child's per-connection mirror of the last materialized
    solve-args array list (protocol v2 delta frames).  ``gen`` is the
    client-assigned generation of the mirrored state; -1 = empty or
    poisoned (the next frame must be full or gets a resync reply)."""

    def __init__(self):
        self.gen = -1
        self.arrays: List[np.ndarray] = []

    def poison(self) -> None:
        """Drop the mirrored state: the next delta frame gets a resync
        reply and the client falls back to a full frame.  The single
        owner of the poison invariant — gen and arrays reset together."""
        self.gen = -1
        self.arrays = []

    def apply(self, sw, wire: dict, payload: List[np.ndarray],
              payload_shared: bool) -> List[np.ndarray]:
        """Materialize the solve arrays for this frame and advance the
        mirror.  Raises ``ValueError`` on a malformed frame (the mirror
        is poisoned first, so the NEXT delta resyncs rather than
        patching inconsistent state)."""
        gen = int(wire["gen"])
        recs = wire.get("recs")
        if recs is None:
            # Full frame: payload IS the slot list.  Shared-memory
            # payloads are views into the client's segment, which the
            # next frame overwrites — mirror slots must own their
            # bytes.  Socket payloads are views into this frame's
            # private recv buffer and are kept as-is (zero copies).
            self.arrays = [np.array(a) if payload_shared else a
                           for a in payload]
            self.gen = gen
            return self.arrays
        base = int(wire.get("base", -2))
        if base != self.gen or len(recs) != len(self.arrays):
            raise _ResyncNeeded(self.gen)
        try:
            out = []
            for i, rec in enumerate(recs):
                tag = int(rec[0])
                if tag == sw.REC_SAME:
                    out.append(self.arrays[i])
                elif tag == sw.REC_FULL:
                    a = payload[int(rec[1])]
                    out.append(np.array(a) if payload_shared else a)
                elif tag == sw.REC_DELTA:
                    a = self.arrays[i]
                    if not (a.flags.writeable and a.flags.c_contiguous):
                        a = np.array(a)  # one-time private writable copy
                    sw.delta_apply(a, np.ascontiguousarray(
                        payload[int(rec[1])], np.int64),
                        payload[int(rec[2])], base, base)
                    out.append(a)
                else:
                    raise ValueError(f"unknown wire record tag {tag}")
        except Exception:
            # A half-applied delta leaves the mirror inconsistent;
            # poison it so the next delta frame resyncs to full.
            self.poison()
            raise
        self.arrays = out
        self.gen = gen
        return out


class _ResyncNeeded(Exception):
    """The mirror does not hold the delta's base generation (reconnect
    race, poisoned mirror): reply ``{"op": "resync"}`` without solving."""

    def __init__(self, have_gen: int):
        super().__init__(f"mirror at gen {have_gen}")
        self.have_gen = have_gen


# ------------------------------------------------------------------ server


class SolverServer:
    """Owns the local JAX device; serves solve requests over TCP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 18477):
        self._sock = socket.create_server((host, port))
        self.port = self._sock.getsockname()[1]
        self.host = host
        self._stop = threading.Event()
        self.solves = 0
        # Fault-injection hook (bench.py BENCH_POOL straggler schedule,
        # tests/test_solver_pool.py): called with the running solve
        # count; a positive return sleeps that many seconds before the
        # reply ships — a reply-side straggler, exactly the tail the
        # pool's hedged dispatch exists to cut.  None in production.
        self.solve_delay_fn = None

    def serve_forever(self) -> None:
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            log.info("solver client connected: %s", addr)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ handling

    def _serve_conn(self, conn: socket.socket) -> None:
        from .cache import snapwire as sw
        from .ops.devincr import DeviceIncremental

        registry = _registry()
        # Per-connection device-incremental caches (ISSUE 9): the
        # scheduler sends cache-generation tokens in each solve frame's
        # manifest, so the child keeps its own persistent static planes
        # and warm-shortlist candidates across solves — one context per
        # connection (one scheduler per connection by protocol).
        devincr = DeviceIncremental()
        # Per-connection wire mirror + shm attachment (protocol v2):
        # the delta-frame base state lives with the connection — a
        # reconnect starts empty, so the first frame is always full.
        mirror = _WireMirror()
        shm = _ShmReader()
        try:
            while True:
                try:
                    req = recv_frame(conn)
                except (ConnectionError, ValueError, OSError):
                    return
                try:
                    reply = self._handle(req, registry, sw, devincr,
                                         mirror, shm)
                except _ResyncNeeded as rs:
                    # The mirror does not hold the delta's base: no
                    # solve ran, but the scheduler anchored its dirty
                    # accumulator at send time — drop the cached device
                    # planes so the post-fallback solve provably
                    # full-recomputes over the rows this frame carried.
                    devincr.invalidate()
                    reply = sw.encode_frame(
                        [], {"op": "resync", "have_gen": rs.have_gen}
                    )
                except Exception as e:  # solver-side error -> client raises
                    log.exception("solve failed")
                    # The scheduler anchored its dirty accumulator at
                    # SEND time (it cannot see this failure distinctly
                    # from a slow solve), so the failed frame's dirty
                    # rows will be absent from later frames: drop every
                    # cached plane — the next solve provably
                    # full-recomputes (and sheds any buffer a
                    # mid-execution crash poisoned).  The wire mirror is
                    # likewise untrustworthy (the frame may have half-
                    # applied); poison it so the next delta resyncs.
                    devincr.invalidate()
                    mirror.poison()
                    reply = sw.encode_frame(
                        [], {"op": "error", "message": f"{type(e).__name__}: {e}"}
                    )
                try:
                    send_frame(conn, reply)
                except OSError:
                    return
        finally:
            shm.close()
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, req: bytes, registry, sw, devincr=None,
                mirror=None, shm=None) -> bytes:
        manifest, arrays = sw.decode_frame(req)
        op = manifest.get("op")
        if op == "ping":
            try:
                import jax

                backend = jax.default_backend()
            except Exception as e:  # pragma: no cover
                backend = f"unavailable: {e}"
            return sw.encode_frame(
                [], {"op": "pong", "solves": self.solves,
                     "backend": backend, "wire": 2}
            )
        if op != "solve":
            return sw.encode_frame(
                [], {"op": "error", "message": f"unknown op {op!r}"}
            )
        # Same-host shm lane: the socket frame carried only the
        # manifest; the payload arrays live in the client's segment.
        shm_section = manifest.get("shm")
        if shm_section is not None:
            if shm is None:
                raise ShmUnavailable("no shm reader on this connection")
            arrays = shm.arrays(shm_section)
        # Delta solve frames (protocol v2): materialize this frame's
        # slot arrays through the per-connection mirror.  A frame
        # without the section solves exactly as v1 (and poisons the
        # mirror — mixed v1/v2 clients on one connection cannot
        # interleave safely).
        wire = manifest.get("wire")
        ack_gen = None
        if wire is not None and mirror is not None:
            arrays = mirror.apply(sw, wire, arrays,
                                  payload_shared=shm_section is not None)
            ack_gen = int(wire["gen"])
        elif mirror is not None:
            mirror.poison()
        # Solve inputs are read-only BY CONTRACT.  v1's bytes-backed
        # views enforced that for free; the v2 recv buffer, shm segment
        # and mirror slots are all writable (the mirror patches delta
        # rows in place).  Hand the solver non-writable VIEWS so any
        # in-place mutation downstream raises loudly instead of
        # silently diverging the child's mirror from the client's wire
        # cache while the generations still match.
        arrays = [_readonly_view(a) for a in arrays]
        solve_args, pid, profiles = sw.unflatten_tree(
            manifest["tree"], arrays, registry
        )
        from .ops.wave import solve_wave
        from .scheduler import enable_compilation_cache

        enable_compilation_cache()

        import jax

        kw = {}
        if manifest.get("wave") is not None:
            kw["wave"] = int(manifest["wave"])
        import time as _time

        # Device-incremental tokens (ISSUE 9): the scheduler's frame
        # names the cache generations its static planes / warm
        # shortlists are valid under; this child's per-connection
        # context applies the same key/dirty-superset discipline the
        # local path does (ops/devincr.py).  Frames without the section
        # (older schedulers, kill switch) solve exactly as before.
        dv = None
        dv_tokens = manifest.get("devincr")
        if devincr is not None and dv_tokens:
            dirty = dv_tokens.get("dirty_nodes")
            devincr.begin_solve(
                dv_tokens.get("static_key"),
                dv_tokens.get("warm_key"),
                None if dirty is None else np.asarray(dirty, np.int64),
            )
            dv = devincr
        t0 = _time.perf_counter()
        res = solve_wave(*solve_args, pid=pid, profiles=profiles,
                         devincr=dv, **kw)
        out = jax.device_get(
            (res.assigned, res.pipelined, res.never_ready, res.fit_failed,
             res.iters if res.iters is not None else np.int32(0),
             res.fb_exhausted if res.fb_exhausted is not None
             else np.int32(0),
             res.fb_affinity if res.fb_affinity is not None
             else np.int32(0))
        )
        solve_ms = (_time.perf_counter() - t0) * 1e3
        self.solves += 1
        if self.solve_delay_fn is not None:
            delay = float(self.solve_delay_fn(self.solves))
            if delay > 0:
                _time.sleep(delay)
        arrays_out = []
        tree = sw.flatten_tree(tuple(np.asarray(x) for x in out), arrays_out)
        reply = {"op": "result", "tree": tree,
                 "solve_ms": round(solve_ms, 1)}
        if ack_gen is not None:
            # Explicit per-reply acknowledgement of the frame generation
            # this result was solved from; the client cross-checks it
            # against the generation it dispatched (a mismatch voids
            # the wire cache and the reply — never a stale solve).
            reply["ack_gen"] = ack_gen
        if dv is not None:
            reply["devincr_mode"] = dv.last_mode
        return sw.encode_frame(arrays_out, reply)


# ------------------------------------------------------------------ client


class _WireCache:
    """Client side of the delta-frame lane: private copies of the last
    solve-args arrays the child provably mirrors (what frame ``gen``
    materialized to), plus the reason the next frame must ship full.
    Copies, not references — encode inputs may be views of persistent
    planes the scheduler mutates in place, and the diff must run
    against the bytes the child actually holds."""

    def __init__(self):
        self.spec = None     # tree spec of the mirrored frame
        self.arrays = None   # list of private np copies, slot order
        self.pending_reason: Optional[str] = None

    def invalidate(self, reason: Optional[str] = None) -> None:
        if reason is not None and self.arrays is not None \
                and self.pending_reason is None:
            self.pending_reason = reason
        self.spec = None
        self.arrays = None


# Below this many bytes (or above this changed-row fraction) a slot
# ships whole: the descriptor + range bookkeeping would cost more than
# the rows it saves.
_DELTA_MIN_BYTES = 1024
_DELTA_MAX_FRACTION = 0.5


class RemoteSolver:
    """Client-side drop-in for ``solve_wave`` over the snapshot bridge.

    One persistent connection; reconnects after any transport error so a
    restarted solver process heals transparently.  Thread-compatible with
    the scheduler's single cycle thread (no internal locking needed
    beyond reconnect)."""

    def __init__(self, address: str, timeout: float = 300.0):
        if "//" in address:
            address = address.split("//", 1)[1]
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None  # guarded-by: _lock
        # Outstanding pipelined request (solve_async): the wire protocol
        # is strict request/reply, so at most one may be unread.
        self._pending: Optional["PendingSolve"] = None  # guarded-by: _lock
        # Round-trip + payload telemetry for the BASELINE overhead table.
        self.requests = 0
        self.bytes_out = 0
        self.bytes_in = 0
        self.last_solve_ms: Optional[float] = None
        # Device-incremental decision the child reported for the last
        # decoded reply ("warm" | "full" | None) — the scheduler folds
        # it into volcano_device_incremental_solves_total.
        self.last_devincr_mode: Optional[str] = None
        # Delta-frame wire state (protocol v2).  All wire-cache access
        # happens on the scheduler's single cycle thread (encode under
        # _lock, decode after the reply), like the telemetry counters.
        self._wire = _WireCache()
        self._gen = 0
        # Set when the child proves it speaks protocol v1 (a reply with
        # no ack_gen): the delta lane self-disables for this client's
        # life — rolling upgrades degrade to v1 full frames instead of
        # dropping every reply (like the shm lane's self-disable).
        self._wire_v1_child = False
        self._shm = _ShmLane() if shm_on() else None
        # Frame telemetry for the metrics counters + bench wire tails.
        self.frame_counts = {"full": 0, "delta": 0}
        self.frame_bytes = {"full": 0, "delta": 0}
        self.wire_fallbacks: Dict[str, int] = {}
        self.last_frame_kind: Optional[str] = None
        self.last_wire_gen: Optional[int] = None
        # Span sink (obs/trace.py Tracer; service.py wires the store's
        # in, the default is the shared no-op): the pipelined send and
        # fetch legs then land in the cycle trace as "rpc" track spans.
        from .obs.trace import null_tracer

        self.tracer = null_tracer()

    # holds: _lock
    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(
                (self.host, self.port), timeout=self.timeout
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            if self._shm is not None and not self._wire_v1_child:
                self._handshake_locked()
        return self._sock

    # holds: _lock
    def _handshake_locked(self) -> None:
        """One ping round trip on a fresh connection while the shm lane
        is armed.  A protocol-v1 child cannot report ShmUnavailable —
        it never reads the manifest's shm section, it just errors on
        the empty array list — so every shm solve would fail as a
        generic child error forever.  Probe the advertised wire
        version up front instead and degrade to v1 TCP frames before
        the first payload ships (the delta-lane skew heals itself via
        the missing ack_gen; this handshake exists for shm)."""
        from .cache import snapwire as sw

        send_frame(self._sock, sw.encode_frame([], {"op": "ping"}))
        manifest, _ = sw.decode_frame(recv_frame(self._sock))
        try:
            wire_version = int(manifest.get("wire") or 0)
        except (TypeError, ValueError):
            wire_version = 0
        if wire_version < 2:
            self._wire_v1_child = True
            self._disable_shm(
                "protocol-v1 solver (no wire>=2 in pong)")

    def _close_locked(self, reason: Optional[str] = None) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        # The child's mirror lives with the connection: any close voids
        # the wire cache, so the next frame after a reconnect is full
        # by construction (``reason`` labels the fallback counter).
        self._wire.invalidate(reason)
        if self._shm is not None:
            # An abandoned/lost solve may still be mid-read in the old
            # child thread: retire the segment (its mapping stays valid
            # until the child drops it) so the next frame writes fresh
            # memory instead of tearing the in-flight read — the strict
            # request/reply overwrite guarantee does not span a close.
            self._shm.close()

    def close(self) -> None:
        with self._lock:
            self._pending = None
            self._close_locked()
        if self._shm is not None:
            self._shm.close()
            self._shm = None

    # holds: _lock
    def _retry_locked(self, attempt):
        """Run ``attempt`` (a thunk that connects/sends/receives on the
        socket); on a transport error, reconnect once (solver restart)
        and re-run it — frames are REBUILT by the thunk, not resent,
        because the close voided the wire cache — then give up closing
        again, letting the cycle fail/retry next period."""
        try:
            return attempt()
        except (OSError, ConnectionError, ValueError):
            self._close_locked("reconnect")
            try:
                return attempt()
            except (OSError, ConnectionError, ValueError):
                self._close_locked("reconnect")
                raise

    def _roundtrip(self, payload: bytes) -> bytes:
        with self._lock:
            if self._pending is not None:
                raise RuntimeError(
                    "a pipelined solve is in flight; fetch or abandon "
                    "it before a synchronous round trip"
                )

            def attempt():
                sock = self._connect()
                send_frame(sock, payload)
                return recv_frame(sock)

            return self._retry_locked(attempt)

    def ping(self) -> dict:
        from .cache import snapwire as sw

        manifest, _ = sw.decode_frame(
            self._roundtrip(sw.encode_frame([], {"op": "ping"}))
        )
        return manifest

    def _count_fallback(self, reason: str) -> None:
        from .metrics import metrics

        self.wire_fallbacks[reason] = \
            self.wire_fallbacks.get(reason, 0) + 1
        metrics.remote_frame_fallback.inc(reason=reason)

    def _disable_shm(self, why: str) -> None:
        """The child cannot attach the segment (different host, stale
        name): drop the lane for the rest of this client's life and
        void the wire cache — the child errored before mirroring the
        frame, so the next frame must ship full, over TCP."""
        log.warning("remote solver shm lane disabled: %s", why)
        self._count_fallback("shm")
        self._wire.invalidate()
        if self._shm is not None:
            self._shm.close()
            self._shm = None

    def _build_frame(self, solve_args: Sequence, pid, profiles,
                     wave: Optional[int], devincr: Optional[dict]):
        """Encode one solve frame against the wire cache: ``(total_len,
        buffers, kind, gen)``.  ``kind`` is "full" or "delta"; ``gen``
        is the frame generation (None with the kill switch off).  The
        wire cache is updated to the frame's content HERE — a failed
        send closes the socket, which voids the cache, so the cache
        only ever describes bytes the child received in order."""
        from .cache import snapwire as sw

        arrays: list = []
        tree = sw.flatten_tree(
            (tuple(solve_args), np.asarray(pid), profiles), arrays
        )
        manifest = {"op": "solve", "tree": tree, "wave": wave}
        if devincr is not None:
            # Cache-generation tokens keying the child's persistent
            # device-incremental planes (ISSUE 9; see _serve_conn).
            manifest["devincr"] = devincr
        mode = wire_mode()
        if self._wire_v1_child:
            # The child already proved it cannot speak the delta lane.
            mode = "off"
        w = self._wire
        kind = "full"
        gen: Optional[int] = None
        if mode == "off":
            # Kill switch: classic v1 frames, no wire section.  A later
            # flip back on must not diff against a cache the child was
            # never told about (v1 frames poison the child mirror too).
            w.invalidate()
            payload = arrays
        else:
            if mode == "fallback":
                # Forced-fallback A/B lever: exercise the full-frame
                # fallback machinery (and its counter) every frame.
                w.invalidate("forced")
            arrs = [np.ascontiguousarray(a).reshape(np.shape(a))
                    for a in arrays]
            gen = self._gen + 1
            if w.arrays is None or w.spec != tree \
                    or len(arrs) != len(w.arrays):
                if w.arrays is not None and w.pending_reason is None:
                    # The pytree shape itself drifted (profile table
                    # growth, affinity terms appearing): slots no
                    # longer align, ship whole.
                    w.pending_reason = "spec-change"
                if w.pending_reason is not None:
                    self._count_fallback(w.pending_reason)
                    w.pending_reason = None
                manifest["wire"] = {"gen": gen}
                payload = arrs
                w.arrays = [np.array(a) for a in arrs]
                w.spec = tree
            else:
                kind = "delta"
                recs = []
                payload = []
                for i, a in enumerate(arrs):
                    base = w.arrays[i]
                    r = sw.diff_rows(a, base)
                    if r is not None and not len(r):
                        recs.append([sw.REC_SAME])
                        continue
                    rows = a.shape[0] if a.ndim else 0
                    changed = int((r[:, 1] - r[:, 0]).sum()) \
                        if r is not None else rows
                    if r is None or a.nbytes < _DELTA_MIN_BYTES \
                            or changed > rows * _DELTA_MAX_FRACTION:
                        recs.append([sw.REC_FULL, len(payload)])
                        payload.append(a)
                        w.arrays[i] = np.array(a)
                        continue
                    desc = sw.ranges_to_desc(r)
                    rowpay = sw.gather_rows(a, r)
                    recs.append(
                        [sw.REC_DELTA, len(payload), len(payload) + 1])
                    payload.append(desc)
                    payload.append(rowpay)
                    # Patch the private mirror copy to the new bytes —
                    # the same scatter the child runs.
                    sw.delta_apply(w.arrays[i], desc, rowpay, 0, 0)
                manifest["wire"] = {"gen": gen, "base": self._gen,
                                    "recs": recs}
            self._gen = gen
        if self._shm is not None:
            # Same-host lane: payloads ride the shared segment; the
            # socket frame carries only the manifest.
            manifest["shm"] = self._shm.write(
                [np.ascontiguousarray(a).reshape(np.shape(a))
                 for a in payload])
            payload = []
        total, parts = sw.encode_frame_views(payload, manifest)
        return total, parts, kind, gen

    # holds: _lock
    def _send_solve_locked(self, solve_args, pid, profiles, wave,
                           devincr):
        from .metrics import metrics

        sock = self._connect()
        try:
            total, parts, kind, gen = self._build_frame(
                solve_args, pid, profiles, wave, devincr)
        except (TypeError, ValueError) as e:
            # Deterministic local encode failure (unsupported wire
            # dtype/ndim): NOT a transport error — surface it without
            # letting the reconnect retry recycle a healthy socket,
            # re-encode the identical frame, and count a spurious
            # reason=reconnect fallback.
            raise TypeError(f"solve frame encode failed: {e}") from e
        send_frame_views(sock, total, parts)
        self.frame_counts[kind] += 1
        self.frame_bytes[kind] += total + 8
        metrics.remote_frame_bytes.inc(total + 8, kind=kind)
        self.last_frame_kind = kind
        self.last_wire_gen = gen
        return total, kind, gen

    def _decode_result(self, reply: bytes,
                       expect_gen: Optional[int] = None):
        from .cache import snapwire as sw
        from .ops.allocate import AllocResult

        self.bytes_in += len(reply) + 8
        manifest, rarrays = sw.decode_frame(reply)
        if manifest.get("op") == "resync":
            # The child's mirror does not hold the delta's base (it
            # never solved this frame).  Void the cache so the next
            # frame ships full; ValueError makes the pipelined fetch
            # treat this as a lost reply — the pods stay Pending and
            # re-place, never a stale solve.
            self._wire.invalidate("gen-mismatch")
            self._count_fallback("gen-mismatch")
            self._wire.pending_reason = None
            raise ValueError(
                f"remote solver mirror resync (child at gen "
                f"{manifest.get('have_gen')})"
            )
        if manifest.get("op") == "error":
            msg = str(manifest.get("message"))
            if msg.startswith("ShmUnavailable"):
                self._disable_shm(msg)
                raise ValueError(f"remote solver dropped frame: {msg}")
            # The child poisons its mirror on any solve exception (the
            # frame may have half-applied); void the wire cache so the
            # NEXT frame ships full instead of a doomed delta that
            # would cost a second lost cycle to the resync round trip.
            if self._wire.arrays is not None:
                self._count_fallback("child-error")
            self._wire.invalidate()
            self._wire.pending_reason = None
            raise RuntimeError(f"remote solver failed: {msg}")
        if expect_gen is not None \
                and manifest.get("ack_gen") != expect_gen:
            if manifest.get("ack_gen") is None:
                # The child solved but never saw the wire section: a
                # protocol-v1 solver (rolling upgrade, scheduler
                # first).  Degrade to v1 full frames for this client's
                # life instead of dropping every reply — a permanent
                # solve outage under version skew.  The reply itself is
                # trustworthy ONLY for a full frame (a v1 child reads a
                # delta frame's descriptor arrays as solve args); the
                # strict request/reply protocol means the first wire
                # frame on a connection — always full — is the one that
                # exposes the skew, so the delta case is pure defense.
                self._wire_v1_child = True
                self._wire.invalidate()
                self._wire.pending_reason = None
                self._count_fallback("v1-child")
                if self.last_frame_kind != "full":
                    with self._lock:
                        self._close_locked()
                    raise ValueError(
                        "protocol-v1 remote solver solved a delta "
                        "frame; reply dropped"
                    )
            else:
                # The reply acknowledges a different frame than the one
                # dispatched: the connection's framing (or the child's
                # mirror) cannot be trusted — void everything, DROP THE
                # SOCKET (a desynced reply stream would shift every
                # later reply by one forever), and drop the reply
                # rather than commit a solve of unknown inputs.
                self._wire.invalidate("ack-mismatch")
                self._count_fallback("ack-mismatch")
                self._wire.pending_reason = None
                with self._lock:
                    self._close_locked()
                raise ValueError(
                    f"remote solver acked gen "
                    f"{manifest.get('ack_gen')}, expected {expect_gen}"
                )
        self.last_solve_ms = manifest.get("solve_ms")
        self.last_devincr_mode = manifest.get("devincr_mode")
        vals = sw.unflatten_tree(manifest["tree"], rarrays, _registry())
        assigned, pipelined, never_ready, fit_failed, iters = vals[:5]
        # Replies predating the two-phase solve carry 5 entries; the
        # shortlist-fallback counters then read as zero.
        if len(vals) >= 7:
            fb_exhausted, fb_affinity = vals[5], vals[6]
        else:
            fb_exhausted = fb_affinity = np.int32(0)
        return AllocResult(
            assigned=assigned, pipelined=pipelined,
            never_ready=never_ready, fit_failed=fit_failed,
            idle=None, q_alloc=None, iters=iters,
            fb_exhausted=fb_exhausted, fb_affinity=fb_affinity,
        )

    def solve(self, solve_args: Sequence, pid, profiles,
              wave: Optional[int] = None,
              devincr: Optional[dict] = None):
        """Ship (solve_args, pid, profiles); return an AllocResult-shaped
        namedtuple of numpy arrays (assigned/pipelined/never_ready/
        fit_failed/iters; idle/q_alloc stay device-side concerns and are
        not transported — the host commit recomputes both)."""
        with self.tracer.timed_event("rpc:solve"):
            with self._lock:
                if self._pending is not None:
                    raise RuntimeError(
                        "a pipelined solve is in flight; fetch or "
                        "abandon it before a synchronous round trip"
                    )
                def attempt():
                    total, _kind, gen = self._send_solve_locked(
                        solve_args, pid, profiles, wave, devincr)
                    return total, gen, recv_frame(self._sock)

                total, gen, reply = self._retry_locked(attempt)
            self.requests += 1
            self.bytes_out += total + 8
            return self._decode_result(reply, gen)

    def solve_async(self, solve_args: Sequence, pid, profiles,
                    wave: Optional[int] = None,
                    devincr: Optional[dict] = None) -> "PendingSolve":
        """Pipelined dispatch: send frame N and return WITHOUT reading
        the reply, so the child's upload+solve+fetch runs concurrently
        with the scheduler's host lanes; ``PendingSolve.fetch`` receives
        it (normally at the top of cycle N+1 — the double-buffered
        session of ISSUE 1).  One request may be outstanding at a time
        (the wire protocol is strict request/reply on one connection).

        Send errors reconnect-and-REBUILD once, like ``solve`` — no
        reply is outstanding yet, and the reconnect voided the wire
        cache, so the retry ships a full frame.  A fetch error does
        NOT resend: the frame may be mid-solve in the child, and the
        caller's staleness machinery already treats a lost reply as
        "this cycle placed nothing" (the pods stay Pending and
        re-place)."""
        with self.tracer.timed_event("rpc:solve_send"):
            with self._lock:
                if self._pending is not None:
                    raise RuntimeError(
                        "a remote solve is already in flight; fetch or "
                        "abandon it before dispatching another"
                    )
                total, _kind, gen = self._retry_locked(
                    lambda: self._send_solve_locked(
                        solve_args, pid, profiles, wave, devincr))
                handle = PendingSolve(self, gen)
                self._pending = handle
        self.requests += 1
        self.bytes_out += total + 8
        return handle

    def wire_socket(self) -> Optional[socket.socket]:
        """The live connection's socket (None when disconnected) — the
        solver pool selects over these to race a hedged reply against
        the primary's (solver_pool.SolverPool._wait_first)."""
        with self._lock:
            return self._sock

    def reply_ready(self, timeout: float = 0.0) -> bool:
        """True when reply bytes are waiting on the connection (or the
        connection is gone — the fetch then fails promptly, which is
        as 'ready' as a dead socket gets).  Waits up to ``timeout``
        seconds.  Read-side probe only; never consumes bytes."""
        import select as _select

        with self._lock:
            sock = self._sock
        if sock is None:
            return True
        ready, _, _ = _select.select([sock], [], [], max(timeout, 0.0))
        return bool(ready)

    def _finish_async(self, handle: "PendingSolve") -> bytes:
        with self._lock:
            if self._pending is not handle:
                raise RuntimeError("stale PendingSolve handle")
            self._pending = None
            if self._sock is None:
                # The connection died while this solve was parked
                # (solver-child kill/restart between dispatch and
                # fetch): the reply is unrecoverable.  Surface the
                # standard lost-reply error the pipelined staleness
                # machinery already handles — not an AttributeError
                # on the dead socket slot.
                raise ConnectionError(
                    "solver connection closed while a solve was "
                    "in flight")
            try:
                return recv_frame(self._sock)
            except (OSError, ConnectionError, ValueError):
                # The connection's request/reply framing is now
                # indeterminate; drop it so the next dispatch starts
                # clean on a fresh socket.
                self._close_locked("reconnect")
                raise

    def _abandon_async(self, handle: "PendingSolve") -> None:
        with self._lock:
            if self._pending is not handle:
                return
            self._pending = None
            # The unread reply would desynchronize the next request;
            # closing the socket resets the framing (the server logs the
            # dead peer and drops the reply).
            self._close_locked("abandon")


class PendingSolve:
    """An unread remote-solve reply (see ``RemoteSolver.solve_async``).
    Carries the dispatched frame's wire generation so the fetch can
    verify the reply's explicit ``ack_gen`` against it."""

    def __init__(self, client: RemoteSolver, gen: Optional[int] = None):
        self._client = client
        self.gen = gen

    def fetch(self):
        """Receive + decode the reply; returns the AllocResult-shaped
        numpy namedtuple ``RemoteSolver.solve`` returns."""
        with self._client.tracer.timed_event("rpc:solve_fetch"):
            return self._client._decode_result(
                self._client._finish_async(self), self.gen
            )

    def abandon(self) -> None:
        self._client._abandon_async(self)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="volcano-tpu remote solver (device-owning process)"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=18477)
    parser.add_argument("--announce", action="store_true",
                        help="print 'SOLVER <port>' once listening "
                             "(spawners parse this)")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = SolverServer(host=args.host, port=args.port)
    if args.announce:
        print(f"SOLVER {server.port}", flush=True)
    log.info("solver listening on %s:%d", server.host, server.port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()


if __name__ == "__main__":
    main()
