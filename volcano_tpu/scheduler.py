"""Scheduler driver: the per-period session loop
(pkg/scheduler/scheduler.go).

Every ``schedule_period`` (default 1 s): re-read the YAML config (hot
reload, scheduler.go:77,89-106), open a session, execute the configured
action list, close the session.  Config parsing failures keep the last good
config.
"""

from __future__ import annotations

import logging
import threading
import time
from pathlib import Path
from typing import List, Optional, Sequence

from . import actions as _actions  # noqa: F401  (registers actions)
from . import plugins as _plugins  # noqa: F401  (registers plugins)
from .cache import ClusterStore
from .framework import (
    DEFAULT_SCHEDULER_CONF,
    close_session,
    get_action,
    open_session,
    parse_scheduler_conf,
)
from .metrics import metrics

log = logging.getLogger(__name__)

_compile_cache_enabled = False


def enable_compilation_cache() -> None:
    """Persist XLA executables across processes (wave-solver compiles run
    multiple seconds; a restarted scheduler would otherwise pay them
    again).  Opt out with VOLCANO_TPU_COMPILE_CACHE=0 or point the cache
    elsewhere with VOLCANO_TPU_COMPILE_CACHE=<dir>."""
    global _compile_cache_enabled
    if _compile_cache_enabled:
        return
    _compile_cache_enabled = True
    import os

    loc = os.environ.get("VOLCANO_TPU_COMPILE_CACHE", "")
    if loc == "0":
        return
    if not loc:
        loc = os.path.join(
            os.path.expanduser("~"), ".cache", "volcano_tpu_xla"
        )
    try:
        import jax

        os.makedirs(loc, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", loc)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as err:  # pragma: no cover - cache is best-effort
        log.warning("compilation cache unavailable: %s", err)


import contextlib


@contextlib.contextmanager
def _device_trace():
    """JAX profiler hook (SURVEY.md 5.1: histograms + device trace for
    kernel/transfer time).  Set VOLCANO_TPU_TRACE_DIR=<dir> to capture a
    per-cycle device trace viewable in TensorBoard/Perfetto; unset, this
    is a no-op context.  Best-effort: profiler failures (unwritable dir,
    trace already active) must not abort the scheduling cycle, so entry
    and exit errors are swallowed here — jax.profiler.trace raises at
    __enter__, which a plain try around its construction cannot catch."""
    import os

    trace_dir = os.environ.get("VOLCANO_TPU_TRACE_DIR")
    if not trace_dir:
        yield
        return
    started = False
    try:
        import jax

        jax.profiler.start_trace(trace_dir)
        started = True
    except Exception as err:  # pragma: no cover - profiler is best-effort
        log.warning("device trace unavailable: %s", err)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as err:  # pragma: no cover
                log.warning("device trace stop failed: %s", err)


class Scheduler:
    def __init__(
        self,
        store: ClusterStore,
        conf_path: Optional[str] = None,
        conf_str: Optional[str] = None,
        schedule_period: float = 1.0,
        gate=None,
        shard=None,
    ):
        self.store = store
        self.conf_path = conf_path
        self.conf_str = conf_str
        self.schedule_period = schedule_period
        # Optional leadership gate: the periodic loop skips cycles while it
        # returns False (active/passive HA, see volcano_tpu.ha).
        self.gate = gate
        # Sharded control plane (shard.py, ISSUE 16): this loop's
        # shard.ShardContext, or None for the default single-scheduler
        # path.  A sharded loop runs the fast path only (the object
        # session is not shard-aware and would double-schedule foreign
        # queues) and drains only its OWN in-flight slot on stop.
        self.shard = shard
        self._stop = threading.Event()
        # run()/stop() may race from different operator threads (service
        # shutdown vs a late start); the lifecycle lock makes the leak
        # window (two run() calls both spawning loop threads) impossible.
        self._lifecycle_lock = threading.Lock()
        # guarded-by: _lifecycle_lock
        self._thread: Optional[threading.Thread] = None
        self._last_conf = None
        self._consecutive_failures = 0

    # --------------------------------------------------------------- config

    def _load_conf(self):
        conf_str = self.conf_str
        if self.conf_path:
            try:
                conf_str = Path(self.conf_path).read_text()
            except OSError as err:
                log.error("Failed to read scheduler conf %s: %s",
                          self.conf_path, err)
                conf_str = None
        if conf_str is None:
            conf_str = DEFAULT_SCHEDULER_CONF
        try:
            conf = parse_scheduler_conf(conf_str)
        except Exception:
            log.exception("Failed to parse scheduler conf; keeping last")
            if self._last_conf is not None:
                return self._last_conf
            conf = parse_scheduler_conf(DEFAULT_SCHEDULER_CONF)
        self._last_conf = conf
        return conf

    # ---------------------------------------------------------------- cycle

    def run_once(self) -> None:
        """One scheduling cycle (scheduler.go:71-87).

        Eligible configurations (built-in plugins, enqueue/allocate/backfill
        actions) run on the vectorized fast path over the store's array
        mirror; anything else uses the object-session path.

        The cyclic GC is suspended for the duration of the cycle: at
        100k-pod scale a generation-2 collection walks the store's
        millions of live objects (plus jax's gc callback) and was
        measured adding 2.3 s to a 0.9 s preempt+reclaim cycle.  A
        young-generation sweep runs after the cycle, off the latency
        path; the service loop performs periodic full collections
        between periods (service.py) so cyclic garbage still gets
        reclaimed."""
        import gc

        gc_was_enabled = gc.isenabled()
        if gc_was_enabled:
            gc.disable()
        try:
            self._run_once_inner()
        finally:
            if gc_was_enabled:
                gc.enable()
                gc.collect(0)

    def _run_once_inner(self) -> None:
        conf = self._load_conf()
        action_names = [
            a.strip() for a in conf.actions.split(",") if a.strip()
        ]
        # Queued async-bind failures re-enter Pending (with backoff) before
        # the cycle snapshots — on this thread, for BOTH the fast path and
        # the object-session fallback (cache.go errTasks resync).
        drain = getattr(self.store, "drain_bind_failures", None)
        if drain is not None:
            drain()
        # Work stealing (shard.py, ISSUE 16): an idle shard claims the
        # most-starved foreign queue BEFORE its cycle snapshots, so the
        # stolen backlog is schedulable this very cycle.
        if self.shard is not None:
            self.shard.maybe_steal(self.store)
        with metrics.e2e_timer(), _device_trace():
            if self._fastpath_enabled() or self.shard is not None:
                enable_compilation_cache()
                from .fastpath import run_cycle_fast

                try:
                    if run_cycle_fast(self.store, conf, shard=self.shard):
                        return
                except Exception:
                    if self.shard is not None:
                        # The object session is not shard-aware: falling
                        # back would re-schedule every shard's queues
                        # from one thread and double-bind against the
                        # siblings' in-flight solves.  Fail the cycle
                        # loudly instead; the loop's failure accounting
                        # and healthy() surface it.
                        raise
                    if not self._fallback_sensible():
                        # At hyperscale the object session takes hours
                        # per cycle; silently "falling back" would stall
                        # scheduling while masking the device failure.
                        log.exception(
                            "Fast path failed and the cluster is too "
                            "large for the object-session fallback "
                            "(override with VOLCANO_TPU_FALLBACK=always)"
                        )
                        raise
                    log.exception(
                        "Fast path failed; falling back to object session"
                    )
            if self.shard is not None:
                # Ineligible config (custom plugins / solver) under
                # sharding: there is no shard-aware fallback.  Loud
                # failure > silently double-scheduling foreign queues.
                raise RuntimeError(
                    "sharded scheduler requires a fast-path-eligible "
                    "configuration (VOLCANO_TPU_SHARDS=1 restores the "
                    "object-session fallback)"
                )
            # An in-flight pipelined solve must not survive into the
            # object session: its pods still read as Pending there and
            # would double-schedule when the fast path later committed
            # the stale assignment.  Abandoning is safe — the pods
            # re-place on whichever path runs this cycle.
            from .pipeline import abandon_inflight, abandon_inflight_plan

            abandon_inflight(self.store)
            # A parked rebalance plan is also fast-path-only state; it
            # mutates nothing until committed, so dropping it is free.
            abandon_inflight_plan(self.store)
            # The object session snapshots pod RECORDS as scheduling
            # truth: force any deferred bind-record walks (node_name on
            # committed pods, normally applied post-cycle by the bind
            # dispatcher) before building it, or committed pods read as
            # unbound and double-schedule.
            apply_records = getattr(
                self.store, "apply_pending_bind_records", None
            )
            if apply_records is not None:
                apply_records()
            self._run_object_session(conf, action_names)

    def _run_object_session(self, conf, action_names) -> None:
        """One object-session cycle, traced + flight-recorded (the fast
        path records its own cycles inside FastCycle.run)."""
        import time as _time

        from .obs.recorder import CycleRecord
        from .obs.trace import tracer_of

        tracer = tracer_of(self.store)
        lanes = {}
        t_wall = _time.time()
        t0 = _time.perf_counter()
        ssn = None
        err = None
        try:
            with tracer.span("cycle", cat="object"):
                with tracer.span("open", lanes=lanes):
                    ssn = open_session(
                        self.store, conf.tiers, conf.configurations
                    )
                try:
                    for name in action_names:
                        action = get_action(name)
                        if action is None:
                            log.warning("Unknown action %s", name)
                            continue
                        with metrics.action_timer(name), tracer.span(
                                f"action:{name}", cat="action",
                                lanes=lanes, lane=name):
                            action.execute(ssn)
                finally:
                    with tracer.span("close", lanes=lanes):
                        close_session(ssn)
        except BaseException as e:
            err = e
            raise
        finally:
            flight = getattr(self.store, "flight", None)
            if flight is not None:
                flight.record(CycleRecord(
                    session=getattr(ssn, "uid", ""), path="object",
                    t_wall=t_wall,
                    duration_s=_time.perf_counter() - t0,
                    lanes=lanes,
                    error=type(err).__name__ if err is not None else None,
                    spans=tracer.drain(),
                ))
            else:
                tracer.drain()

    @staticmethod
    def _fastpath_enabled() -> bool:
        import os

        return os.environ.get("VOLCANO_TPU_FASTPATH", "1") != "0"

    # Above this tasks x nodes product the object-session fallback is
    # slower than retrying the fast path next period (the object walk is
    # O(tasks x nodes) Python).
    FALLBACK_MAX_WORK = 50_000_000

    def _fallback_sensible(self) -> bool:
        import os

        import numpy as np

        from .api import TaskStatus

        mode = os.environ.get("VOLCANO_TPU_FALLBACK", "auto")
        if mode == "always":
            return True
        if mode == "never":
            return False
        m = self.store.mirror
        # The object walk is O(pending tasks x nodes): a mostly-scheduled
        # large cluster with a handful of pending pods falls back fine.
        pending = int(np.count_nonzero(
            (m.p_status[:m.n_pods] == int(TaskStatus.Pending))
            & m.p_alive[:m.n_pods]
        ))
        return (pending * max(m.n_nodes, 1)) <= self.FALLBACK_MAX_WORK

    # ----------------------------------------------------------------- loop

    def run(self) -> None:
        """Start the periodic loop in a background thread (no-op when
        it is already running; restartable after ``stop()``)."""
        with self._lifecycle_lock:
            if self._thread is not None and self._thread.is_alive():
                return
            # A prior stop() left the event set; clear it under the
            # lifecycle lock (stop() sets it under the same lock) so the
            # fresh thread actually loops.
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True
            )
            self._thread.start()

    # Consecutive failed cycles before healthy() reports False (a crashed
    # TPU runtime is unrecoverable in-process; the health signal lets a
    # supervisor or the HA standby take over — SURVEY.md 5.3).
    UNHEALTHY_AFTER = 3

    def healthy(self) -> bool:
        return self._consecutive_failures < self.UNHEALTHY_AFTER

    # Full (gen-2) garbage collections run between periods every N
    # cycles: run_once suspends the cyclic GC while the cycle runs, so
    # cyclic garbage must be swept here, in the period slack, where the
    # multi-second walk of a 100k-pod store's object graph cannot touch
    # cycle latency.
    GC_FULL_EVERY = 120

    def _loop(self):
        import gc

        cycles = 0
        while not self._stop.is_set():
            t0 = time.time()
            try:
                if self.gate is None or self.gate():
                    self.run_once()
                    self._consecutive_failures = 0
                    cycles += 1
                    if cycles % self.GC_FULL_EVERY == 0:
                        gc.collect()
                else:
                    # A standby runs no cycles; stale leader-era failures
                    # must not keep its health check red.
                    self._consecutive_failures = 0
            except Exception:
                self._consecutive_failures += 1
                log.exception(
                    "Scheduling cycle failed (%d consecutive)",
                    self._consecutive_failures,
                )
            elapsed = time.time() - t0
            self._stop.wait(max(self.schedule_period - elapsed, 0.0))

    # stop(): how long to wait for the loop thread.  Cycles never block
    # on the device any more (the pipelined dispatch is asynchronous and
    # the fetch happens at cycle top), so a healthy thread exits within
    # one cycle; the bound covers a wedged device runtime.
    STOP_TIMEOUT = 30.0

    def stop(self, timeout: Optional[float] = None) -> None:
        """Stop the periodic loop and drain the pipelined dispatch.

        Joins the loop thread (it must die — a silently-leaked thread
        kept scheduling behind restarts), then abandons any in-flight
        device solve left parked between cycles: the solved pods are
        still Pending store-side, so nothing is lost — a restarted
        scheduler simply re-places them on its first cycle."""
        with self._lifecycle_lock:
            # Set inside the lifecycle lock: a concurrent run() could
            # otherwise clear the event between our set and the join,
            # leaving this stop() waiting 30 s on a thread that will
            # never exit.
            self._stop.set()
            t = self._thread
            if t is not None:
                t.join(self.STOP_TIMEOUT if timeout is None else timeout)
                if t.is_alive():
                    log.error(
                        "scheduler loop thread did not exit within "
                        "%.0fs; in-flight state NOT drained",
                        self.STOP_TIMEOUT if timeout is None else timeout,
                    )
                    return
                self._thread = None
        # Only after the thread is dead: the cycle thread owns the
        # in-flight handle while it runs.  A sharded loop drains only
        # its OWN slot — its siblings' parked solves are still live.
        from .pipeline import abandon_inflight, abandon_inflight_plan

        if self.shard is not None:
            abandon_inflight(self.store, shard=self.shard.index)
            if self.shard.runs_evictions:
                abandon_inflight_plan(self.store)
        else:
            abandon_inflight(self.store)
            abandon_inflight_plan(self.store)
