"""In-process metrics registry with Prometheus text exposition.

Implements the reference's metric set under the same ``volcano`` namespace
(``pkg/scheduler/metrics/metrics.go:38-110``, ``queue.go:25-124``,
``job.go:25-36``, ``namespace.go:25-44``) plus TPU-native series for device
solve latency and snapshot transfer volume.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from contextlib import contextmanager
from typing import Dict, List, Tuple

# Buckets follow prometheus.DefBuckets spirit; values recorded in the unit
# named by the metric (ms / us).
_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
    250, 500, 1000, 2500, 5000, 10000,
)
_N_BUCKETS = len(_DEFAULT_BUCKETS)

LabelKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted(labels.items()))


# Writers and the scrape synchronize on one registry lock (the series
# of a Metrics instance all share it): unguarded dict inserts from a
# cycle thread raced expose_text's iteration ("dictionary changed size
# during iteration" on a scrape mid-cycle).  Series constructed outside
# a registry (tests) get their own lock.


class _Histogram:
    """Bounded histogram: per label set, fixed bucket counts + sum +
    count — NOT the raw observation list (a long-running scheduler
    observes forever; the list grew without bound)."""

    def __init__(self, name: str, help_: str,
                 lock: "threading.Lock" = None):
        self.name = name
        self.help = help_
        self._lock = lock or threading.Lock()
        # LabelKey -> [per-bucket counts (+1 overflow slot), sum, count]
        self.data: Dict[LabelKey, list] = {}

    def observe(self, value: float, **labels):
        key = _labels_key(labels)
        with self._lock:
            state = self.data.get(key)
            if state is None:
                state = self.data[key] = [[0] * (_N_BUCKETS + 1), 0.0, 0]
            state[0][bisect_left(_DEFAULT_BUCKETS, value)] += 1
            state[1] += value
            state[2] += 1


class _Gauge:
    def __init__(self, name: str, help_: str,
                 lock: "threading.Lock" = None):
        self.name = name
        self.help = help_
        self._lock = lock or threading.Lock()
        self.data: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels):
        key = _labels_key(labels)
        with self._lock:
            self.data[key] = value

    def set_many(self, pairs):
        """Bulk update from prebuilt (label-key-tuple, value) pairs — the
        per-job gauges (25k+ unschedulable jobs at scale) skip the
        per-call kwargs/sort overhead, and take the lock once."""
        with self._lock:
            self.data.update(pairs)


class _Counter:
    def __init__(self, name: str, help_: str,
                 lock: "threading.Lock" = None):
        self.name = name
        self.help = help_
        self._lock = lock or threading.Lock()
        self.data: Dict[LabelKey, float] = {}

    def inc(self, value: float = 1.0, **labels):
        key = _labels_key(labels)
        with self._lock:
            self.data[key] = self.data.get(key, 0.0) + value

    def inc_many(self, keys, value: float = 1.0):
        """Bulk increment from prebuilt label-key tuples (one lock
        acquisition for the batch)."""
        with self._lock:
            data = self.data
            get = data.get
            for key in keys:
                data[key] = get(key, 0.0) + value


class Metrics:
    """The volcano metric family (thread-safe)."""

    def __init__(self):
        # Shared by every series of this registry AND by expose_text:
        # one lock means a scrape sees a consistent point-in-time view
        # and writers can never resize a dict mid-iteration.
        self._lock = threading.Lock()
        ns = "volcano"
        self.e2e_scheduling_latency = _Histogram(
            f"{ns}_e2e_scheduling_latency_milliseconds",
            "E2e scheduling latency in milliseconds",
        )
        self.plugin_scheduling_latency = _Histogram(
            f"{ns}_plugin_scheduling_latency_microseconds",
            "Plugin scheduling latency in microseconds",
        )
        self.action_scheduling_latency = _Histogram(
            f"{ns}_action_scheduling_latency_microseconds",
            "Action scheduling latency in microseconds",
        )
        self.task_scheduling_latency = _Histogram(
            f"{ns}_task_scheduling_latency_microseconds",
            "Task scheduling latency in microseconds",
        )
        self.schedule_attempts = _Counter(
            f"{ns}_schedule_attempts_total",
            "Number of attempts to schedule pods, by the result",
        )
        self.pod_preemption_victims = _Gauge(
            f"{ns}_pod_preemption_victims", "Number of selected preemption victims"
        )
        self.total_preemption_attempts = _Counter(
            f"{ns}_total_preemption_attempts",
            "Total preemption attempts in the cluster till now",
        )
        self.unschedule_task_count = _Gauge(
            f"{ns}_unschedule_task_count", "Number of tasks could not be scheduled"
        )
        self.unschedule_job_count = _Gauge(
            f"{ns}_unschedule_job_count", "Number of jobs could not be scheduled"
        )
        self.job_retry_counts = _Counter(
            f"{ns}_job_retry_counts", "Number of retry counts for one job"
        )
        self.job_share = _Gauge(f"{ns}_job_share", "Share for one job")
        self.queue_allocated_milli_cpu = _Gauge(
            f"{ns}_queue_allocated_milli_cpu",
            "Allocated CPU count for one queue",
        )
        self.queue_allocated_memory_bytes = _Gauge(
            f"{ns}_queue_allocated_memory_bytes",
            "Allocated memory for one queue",
        )
        self.queue_request_milli_cpu = _Gauge(
            f"{ns}_queue_request_milli_cpu", "Request CPU count for one queue"
        )
        self.queue_request_memory_bytes = _Gauge(
            f"{ns}_queue_request_memory_bytes", "Request memory for one queue"
        )
        self.queue_deserved_milli_cpu = _Gauge(
            f"{ns}_queue_deserved_milli_cpu", "Deserved CPU count for one queue"
        )
        self.queue_deserved_memory_bytes = _Gauge(
            f"{ns}_queue_deserved_memory_bytes", "Deserved memory for one queue"
        )
        self.queue_share = _Gauge(f"{ns}_queue_share", "Share for one queue")
        self.queue_weight = _Gauge(f"{ns}_queue_weight", "Weight for one queue")
        self.queue_overused = _Gauge(
            f"{ns}_queue_overused", "If one queue is overused"
        )
        self.queue_pod_group_inqueue_count = _Gauge(
            f"{ns}_queue_pod_group_inqueue_count",
            "Number of Inqueue PodGroup in this queue",
        )
        self.queue_pod_group_pending_count = _Gauge(
            f"{ns}_queue_pod_group_pending_count",
            "Number of pending PodGroup in this queue",
        )
        self.queue_pod_group_running_count = _Gauge(
            f"{ns}_queue_pod_group_running_count",
            "Number of running PodGroup in this queue",
        )
        self.queue_pod_group_unknown_count = _Gauge(
            f"{ns}_queue_pod_group_unknown_count",
            "Number of unknown PodGroup in this queue",
        )
        self.namespace_share = _Gauge(
            f"{ns}_namespace_share", "Share for one namespace"
        )
        self.namespace_weight = _Gauge(
            f"{ns}_namespace_weight", "Weight for one namespace"
        )
        self.namespace_weighted_share = _Gauge(
            f"{ns}_namespace_weighted_share", "Weighted share for one namespace"
        )
        # TPU-native additions.
        self.device_solve_latency = _Histogram(
            f"{ns}_device_solve_latency_milliseconds",
            "Device allocate-solver latency in milliseconds",
        )
        self.inflight_fetch_wait = _Histogram(
            f"{ns}_inflight_fetch_wait_milliseconds",
            "Residual wait fetching the pipelined in-flight solve at "
            "cycle top; approaches zero when the overlap hides the "
            "device round trip",
        )
        self.device_crash_recoveries = _Counter(
            f"{ns}_device_crash_recoveries_total",
            "Mid-solve TPU runtime crashes recovered by degrading the "
            "affinity chunk budget",
        )
        self.snapshot_transfer_bytes = _Gauge(
            f"{ns}_snapshot_transfer_bytes",
            "Bytes transferred host->device for the session snapshot",
        )
        self.solve_shortlist_fallback = _Counter(
            f"{ns}_solve_shortlist_fallback_total",
            "Two-phase solve full-N rescores after a profile's "
            "candidate shortlist ran dry, by reason: exhausted (every "
            "candidate claimed by earlier waves) or affinity-required "
            "(required inter-pod terms drifted from the solve-start "
            "counts the shortlist was built on)",
        )
        self.device_incremental_solves = _Counter(
            f"{ns}_device_incremental_solves_total",
            "Device-lane incremental solve decisions by mode: warm "
            "(shortlists warm-started from the previous solve's "
            "per-block candidates over the dirty node set), full (the "
            "proven full re-rank: cache key drift — class-set, "
            "profile-set, node churn, compaction, affinity-count "
            "content — dirty overflow, or first solve), or skip (a "
            "null-delta cycle proved the dispatch would reproduce the "
            "previous empty outcome and skipped it wholesale; "
            "VOLCANO_TPU_DEVINCR=0 disables the lane and counts "
            "nothing)",
        )
        self.host_incremental_derives = _Counter(
            f"{ns}_host_incremental_derives_total",
            "Derive-lane aggregate refreshes by mode: delta "
            "(subtract-old/add-new scatters over the mirror's dirty "
            "row set) or full (the proven rebuild fallback: first "
            "derive, node-membership churn, compaction, dirty-set overflow "
            "past VOLCANO_TPU_DIRTY_CAP, or VOLCANO_TPU_INCREMENTAL=0)",
        )
        self.remote_frame_bytes = _Counter(
            f"{ns}_remote_frame_bytes_total",
            "Remote-solver wire bytes shipped scheduler->solver "
            "(length prefix included), by frame kind: full (the whole "
            "materialized solve-args frame — first frame of a "
            "connection, kill switch off, or any fallback) or delta "
            "(only changed row ranges and changed planes against the "
            "child's per-connection mirror, protocol v2)",
        )
        self.remote_frame_fallback = _Counter(
            f"{ns}_remote_frame_fallback_total",
            "Delta-lane frames forced back to a full frame, by "
            "reason: reconnect (socket re-established, child mirror "
            "gone), abandon (pipelined reply dropped, framing reset), "
            "spec-change (the solve-args pytree shape drifted, slots "
            "no longer align), gen-mismatch (child replied resync: "
            "its mirror does not hold the delta's base), ack-mismatch "
            "(reply acknowledged a different generation than "
            "dispatched), child-error (the solve errored in the child "
            "and poisoned its mirror), v1-child (the solver speaks "
            "protocol v1 — no ack_gen in replies; the delta lane "
            "self-disabled), shm (shared-memory segment unattachable; "
            "lane disabled), forced (VOLCANO_TPU_WIRE=fallback A/B "
            "lever)",
        )
        self.pipeline_stale_drops = _Counter(
            f"{ns}_pipeline_stale_drop_rows_total",
            "In-flight solve rows that did not commit, by reason: the "
            "staleness guard's per-row drops (deleted, competing-bind, "
            "capacity-taken, constraint-sensitive, node-epoch-churn, "
            "cross-shard-conflict, topology-infeasible) plus "
            "whole-result voids (compaction, lost-reply, "
            "device-crash)",
        )
        self.shard_conflicts = _Counter(
            f"{ns}_shard_conflicts_total",
            "Optimistic cross-shard commit conflicts (shard.py, ISSUE "
            "16): in-flight rows voided because another shard's binds "
            "landed during the overlap, by losing check — "
            "competing-bind (the row itself was taken: steal race) or "
            "capacity-taken (the target node's capacity was).  These "
            "rows also count as the cross-shard-conflict reason of "
            "volcano_pipeline_stale_drop_rows_total; they re-place "
            "next cycle, never lost",
        )
        self.shard_steals = _Counter(
            f"{ns}_shard_steals_total",
            "Work-stealing queue ownership handoffs: an idle shard "
            "claimed the most-starved foreign queue via the ownership "
            "table's epoch-bumped handoff token (shard.py)",
        )
        self.rebalance_plans = _Counter(
            f"{ns}_rebalance_plans_total",
            "Rebalance migration plans by outcome: committed (what-if "
            "solve proved the starved gang places AND every victim "
            "re-places; evictions dispatched), rejected-no-gain (plan "
            "solve failed the strict-improvement bar), rejected-budget "
            "(per-PodGroup disruption budgets blocked an otherwise "
            "sufficient drain set), stale-voided (store mutated "
            "between the pipelined plan dispatch and its commit)",
        )
        self.whatif_plans = _Counter(
            f"{ns}_whatif_plans_total",
            "What-if engine plans by action (preempt | reclaim | "
            "rebalance) and outcome: committed (the hypothetical solve "
            "proved the wave's goal; evictions dispatched), "
            "rejected-no-gain (the solve failed the action's bar), "
            "rejected-budget (per-PodGroup disruption budgets blocked "
            "an otherwise sufficient wave), stale-voided (store "
            "mutated between the pipelined plan dispatch and its "
            "commit), lost-reply (an offloaded plan solve's reply "
            "died with its pool replica; the plan mutated nothing "
            "and re-forms).  Rebalance outcomes also count in the "
            "historical volcano_rebalance_plans_total series",
        )
        self.preempt_evictions = _Counter(
            f"{ns}_preempt_evictions_total",
            "Pods evicted by committed device-native preempt/reclaim "
            "plans, by action; counted at the cycle-end evictor "
            "dispatch.  Each victim is restored as Pending by the "
            "migration ledger when its termination completes — zero "
            "lost pods unconditionally",
        )
        self.rebalance_evictions = _Counter(
            f"{ns}_rebalance_evictions_total",
            "Pods evicted by committed rebalance plans (each is "
            "restored as Pending when its termination completes and "
            "re-places through the allocate lane)",
        )
        self.rebalance_frag_score = _Gauge(
            f"{ns}_rebalance_frag_score",
            "Mean per-node fragmentation score at the last rebalance "
            "planning pass: fraction of idle stranded on nodes unable "
            "to host any task of the starved gang's profiles (0 = no "
            "stranded idle, 1 = fully idle yet useless)",
        )
        self.topology_placements = _Counter(
            f"{ns}_topology_placements_total",
            "Gang placements through the topology gate (ops/topology, "
            "ISSUE 20) by outcome: contiguous (every bound task landed "
            "in one fabric block), scattered (a prefer-contiguous gang "
            "bound across blocks; bias lost to capacity), infeasible "
            "(a require-contiguous gang was held back — no block can "
            "host the whole gang right now, or a post-solve check "
            "caught a scattered assignment and vetoed it; the gang "
            "re-places after defragmentation)",
        )
        self.topology_frag_score = _Gauge(
            f"{ns}_topology_frag_score",
            "Mean per-block fabric fragmentation at the last rebalance "
            "planning pass for a topology-constrained gang: fraction "
            "of the gang placeable on partial blocks that cannot host "
            "it whole (0 = some block fits the entire gang, higher = "
            "capacity stranded across partial slices)",
        )
        self.solver_pool_dispatch = _Counter(
            f"{ns}_solver_pool_dispatch_total",
            "Solver-pool frame dispatches by replica and kind: "
            "primary (the health-scored allocate-lane target), hedge "
            "(the identical frame re-dispatched to a second replica "
            "after the primary's reply exceeded its rolling-p99 "
            "deadline), or whatif (a plan-proving solve offloaded to "
            "an idle non-primary replica)",
        )
        self.solver_pool_failover = _Counter(
            f"{ns}_solver_pool_failover_total",
            "Solver-pool primary changes away from a failed replica: "
            "the previous primary's dispatch or fetch failed and the "
            "next dispatch routed to a healthy replica (whose first "
            "frame ships full by construction — deltas re-engage "
            "after it)",
        )
        self.solver_pool_hedge_wins = _Counter(
            f"{ns}_solver_pool_hedge_wins_total",
            "Hedged solver-pool dispatches whose hedge reply landed "
            "(and committed) before the straggling primary's; the "
            "loser's reply is drained later, keeping its mirror "
            "coherent via ack_gen",
        )
        self.solver_pool_replica_health = _Gauge(
            f"{ns}_solver_pool_replica_health",
            "Per-replica solver-pool health score: 1 / (1 + "
            "consecutive failures) — 1.0 is healthy, decaying toward "
            "0 as dispatch/fetch failures accumulate; failed replicas "
            "are re-probed on a doubling cooldown and snap back to "
            "1.0 when the probe succeeds",
        )
        self.audit_anomalies = _Counter(
            f"{ns}_audit_anomalies_total",
            "Runtime-auditor anomalies by catalogued reason "
            "(obs/audit.py; docs/observability.md anomaly catalog).  "
            "Nonzero means an invariant the scheduler relies on was "
            "observed violated at runtime — a page, not a trend",
        )
        self.audit_cycles = _Counter(
            f"{ns}_audit_cycles_total",
            "Auditor cycle-end passes by mode: reconciled (census "
            "compared against the declared flows), skipped (no flows, "
            "unmoved mutation counter), or sampled (coherence audits "
            "of the registered cache slots also ran)",
        )
        self.slo_burn_rate = _Gauge(
            f"{ns}_slo_budget_burn_rate",
            "Error-budget burn rate per SLO lane (obs/slo.py): "
            "(fraction of window cycles over the declared target) / "
            "allowed fraction.  >= 1.0 means the lane is consuming "
            "its error budget faster than the SLO allows",
        )
        self.pod_time_to_first_consider = _Histogram(
            f"{ns}_pod_time_to_first_consider_milliseconds",
            "Pod-journey latency (obs/journey.py) from mirror enqueue "
            "to the pod's FIRST entry into a device solve, per queue "
            "— the queue-backlog component of scheduling latency",
        )
        self.pod_time_to_bind = _Histogram(
            f"{ns}_pod_time_to_bind_milliseconds",
            "Pod-journey latency from mirror enqueue to the pod's "
            "FIRST committed bind, per queue — the end-to-end wait "
            "signal the ttb SLO lane budgets "
            "(VOLCANO_TPU_SLO_TTB_P99_MS)",
        )
        self.gang_time_to_full_bind = _Histogram(
            f"{ns}_gang_time_to_full_bind_milliseconds",
            "Gang-journey latency from the gang's first member "
            "enqueue to its LAST member's first bind — the gang-level "
            "time-to-full-bind the per-pod series can't show",
        )
        self.journey_events = _Counter(
            f"{ns}_journey_events_total",
            "Pod-journey events captured by kind (enqueued / "
            "dispatched / dropped / bound / evicted / ...); bulk "
            "steady-state repeats are counted by the journey's "
            "internal counters, not here",
        )
        # Registry-wide lock sharing: rebind every series to THIS
        # registry's lock (done before any concurrent use) so writers
        # serialize with expose_text's iteration.
        for attr in vars(self).values():
            if isinstance(attr, (_Histogram, _Gauge, _Counter)):
                attr._lock = self._lock

    # ------------------------------------------------------------- helpers

    @contextmanager
    def plugin_timer(self, plugin: str, on_session: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.plugin_scheduling_latency.observe(
                (time.perf_counter() - t0) * 1e6,
                plugin=plugin, OnSession=on_session,
            )

    @contextmanager
    def action_timer(self, action: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.action_scheduling_latency.observe(
                (time.perf_counter() - t0) * 1e6, action=action
            )

    @contextmanager
    def e2e_timer(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.e2e_scheduling_latency.observe(
                (time.perf_counter() - t0) * 1e3
            )

    def register_preemption_attempt(self):
        self.total_preemption_attempts.inc()

    def update_preemption_victim_count(self, count: int):
        self.pod_preemption_victims.set(count)

    # ----------------------------------------------------------- exposition

    def expose_text(self) -> str:
        """Prometheus text format 0.0.4.

        Snapshot-then-format: only the cheap data copies happen under
        the registry lock (the lock the hot-path writers share); the
        string formatting of a large scrape — 25k+ per-job series at
        config-4 scale — runs outside it, so a scrape never stalls the
        scheduling cycle for the formatting's duration."""
        snap: List[tuple] = []
        with self._lock:
            for attr in vars(self).values():
                if isinstance(attr, _Gauge):
                    snap.append(("gauge", attr.name, attr.help,
                                 dict(attr.data)))
                elif isinstance(attr, _Counter):
                    snap.append(("counter", attr.name, attr.help,
                                 dict(attr.data)))
                elif isinstance(attr, _Histogram):
                    # Bucket-count lists mutate in place under observe;
                    # copy them so the formatting below reads a
                    # consistent point-in-time state.
                    snap.append(("histogram", attr.name, attr.help, {
                        key: (list(counts), total, n)
                        for key, (counts, total, n) in attr.data.items()
                    }))
        out: List[str] = []
        for kind, name, help_, data in snap:
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {kind}")
            if kind in ("gauge", "counter"):
                for key, v in data.items():
                    lbl = ",".join(f'{k}="{val}"' for k, val in key)
                    out.append(f"{name}{{{lbl}}} {v}")
                continue
            for key, (counts, total, n) in data.items():
                lbl_items = [f'{k}="{val}"' for k, val in key]
                cnt = 0
                for i, b in enumerate(_DEFAULT_BUCKETS):
                    cnt += counts[i]
                    items = lbl_items + [f'le="{b}"']
                    out.append(
                        f"{name}_bucket{{{','.join(items)}}} {cnt}"
                    )
                items = lbl_items + ['le="+Inf"']
                out.append(f"{name}_bucket{{{','.join(items)}}} {n}")
                lbl = ",".join(lbl_items)
                out.append(f"{name}_sum{{{lbl}}} {total}")
                out.append(f"{name}_count{{{lbl}}} {n}")
        return "\n".join(out) + "\n"


metrics = Metrics()
