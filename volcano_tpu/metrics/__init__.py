"""Metrics registry (pkg/scheduler/metrics).

Same metric names as the reference so dashboards carry over
(metrics.go:38-110, queue.go, job.go, namespace.go), implemented as an
in-process registry with a Prometheus text-format exposition endpoint
(``volcano_tpu.metrics.http``) instead of the Go prometheus client.
TPU-native additions: device solve time and host<->device transfer bytes.
"""

from .metrics import Metrics, metrics

__all__ = ["Metrics", "metrics"]
