"""Low-overhead trace spans for the scheduling cycle.

Design constraints (ISSUE 3): the hot path records ~30 spans per cycle
at a 100-300 ms cycle budget, so a span costs two
``time.perf_counter_ns()`` reads and ONE object append — no string
formatting, no dict merging, no allocation beyond the record itself.
The same span that traces a lane also accumulates the cycle's
``lanes[...]`` seconds (bench.py compatibility), so disabling tracing
(``VOLCANO_TPU_TRACE=0``) keeps the lane breakdown intact while
skipping the record append.

Threading model: ``span()`` (and the parent stack under it) belongs to
the single scheduling-cycle thread — exactly the thread that owns the
store lock for the cycle.  Other threads (the bind dispatcher, remote
RPC clients) contribute through ``event()``, which appends a
parentless record under the tracer's lock and never touches the stack.
``drain()`` hands the accumulated spans to the flight recorder at cycle
end.

Span timestamps are monotonic (``perf_counter_ns``) shifted to the
epoch by a per-tracer anchor captured at construction, so exported
traces from one process share one timeline.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Dict, List, Optional


class SpanRecord:
    """One completed span.  ``ts_ns`` is epoch nanoseconds; ``flow`` is
    the cross-cycle link id (the pipelined solve-id) or None; ``tid``
    names the logical track ("cycle" for the scheduling thread, "rpc" /
    "bind" for helper threads)."""

    __slots__ = ("name", "cat", "ts_ns", "dur_ns", "span_id",
                 "parent_id", "flow", "tid", "args")

    def __init__(self, name, cat, ts_ns, dur_ns, span_id, parent_id,
                 flow, tid, args):
        self.name = name
        self.cat = cat
        self.ts_ns = ts_ns
        self.dur_ns = dur_ns
        self.span_id = span_id
        self.parent_id = parent_id
        self.flow = flow
        self.tid = tid
        self.args = args

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "cat": self.cat,
            "ts_ns": self.ts_ns,
            "dur_ns": self.dur_ns,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "tid": self.tid,
        }
        if self.flow is not None:
            d["flow"] = self.flow
        if self.args:
            d["args"] = self.args
        return d


class _Span:
    """Context-manager handle; always times (the lane accumulation must
    survive tracing being disabled), appends a record only when the
    tracer is enabled."""

    __slots__ = ("tr", "name", "cat", "flow", "lanes", "lane", "args",
                 "t0", "span_id", "parent_id", "dur_ns")

    def __init__(self, tr, name, cat, flow, lanes, lane, args):
        self.tr = tr
        self.name = name
        self.cat = cat
        self.flow = flow
        self.lanes = lanes
        self.lane = lane
        self.args = args

    def __enter__(self):
        tr = self.tr
        if tr.enabled:
            # The parent stack exists only when recording: the shared
            # disabled tracer serves MANY stores (possibly from many
            # threads), so a disabled span must not touch shared state.
            stack = tr._stack
            self.parent_id = stack[-1] if stack else 0
            self.span_id = next(tr._ids)
            stack.append(self.span_id)
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        tr = self.tr
        dur = self.dur_ns = t1 - self.t0
        lanes = self.lanes
        if lanes is not None:
            lane = self.lane
            lanes[lane] = lanes.get(lane, 0.0) + dur * 1e-9
        if tr.enabled:
            tr._stack.pop()
            args = self.args
            if exc_type is not None:
                args = dict(args) if args else {}
                args["error"] = exc_type.__name__
            tr._spans.append(SpanRecord(
                self.name, self.cat, tr._anchor_ns + self.t0, dur,
                self.span_id, self.parent_id, self.flow, "cycle", args,
            ))
        return False


class Tracer:
    """Per-store span sink.  One instance per ``ClusterStore``; the
    cycle thread records spans, ``drain()`` moves them into the flight
    recorder's per-cycle record."""

    def __init__(self, enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get("VOLCANO_TPU_TRACE", "1") != "0"
        self.enabled = bool(enabled)
        # epoch_ns = anchor + perf_counter_ns (captured together).
        self._anchor_ns = time.time_ns() - time.perf_counter_ns()
        self._spans: List[SpanRecord] = []
        self._stack: List[int] = []  # cycle-thread-only parent stack
        self._ids = itertools.count(1)
        # Guards _spans against cross-thread event() appends racing a
        # cycle-end drain(); span() itself stays lock-free (same thread
        # as drain()).
        self._lock = threading.Lock()

    # ------------------------------------------------------------- spans

    def span(self, name: str, cat: str = "cycle",
             flow: Optional[int] = None,
             lanes: Optional[Dict[str, float]] = None,
             lane: Optional[str] = None,
             args: Optional[dict] = None) -> _Span:
        """Cycle-thread span.  ``lanes``/``lane`` additionally
        accumulate the elapsed seconds into the cycle's lane dict (the
        bench-compatible ``last_cycle_lanes`` breakdown)."""
        return _Span(self, name, cat, flow, lanes,
                     lane if lane is not None else name, args)

    def event(self, name: str, cat: str, t0_ns: int, dur_ns: int,
              tid: str = "rpc", flow: Optional[int] = None,
              args: Optional[dict] = None) -> None:
        """Append a completed span from ANY thread (RPC clients, the
        bind dispatcher).  ``t0_ns`` is a ``perf_counter_ns`` reading."""
        if not self.enabled:
            return
        rec = SpanRecord(name, cat, self._anchor_ns + t0_ns, dur_ns,
                         next(self._ids), 0, flow, tid, args)
        with self._lock:
            self._spans.append(rec)

    def timed_event(self, name: str, cat: str = "rpc",
                    tid: str = "rpc", flow: Optional[int] = None,
                    args: Optional[dict] = None) -> "_TimedEvent":
        """Thread-safe time-this-block context manager over ``event()``
        — the one shared shape for RPC call sites (remote side-effect
        clients, the remote solver's send/fetch legs)."""
        return _TimedEvent(self, name, cat, tid, flow, args)

    def drain(self) -> List[SpanRecord]:
        """Hand the accumulated spans over (cycle end) and reset."""
        with self._lock:
            spans, self._spans = self._spans, []
        del self._stack[:]
        return spans


class _TimedEvent:
    """Times a block and appends it via ``Tracer.event`` (no parent
    stack, so safe from any thread and on the shared disabled
    tracer)."""

    __slots__ = ("tr", "name", "cat", "tid", "flow", "args", "t0")

    def __init__(self, tr, name, cat, tid, flow, args):
        self.tr = tr
        self.name = name
        self.cat = cat
        self.tid = tid
        self.flow = flow
        self.args = args

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        tr = self.tr
        if tr.enabled:
            tr.event(self.name, self.cat, self.t0,
                     time.perf_counter_ns() - self.t0, tid=self.tid,
                     flow=self.flow, args=self.args)
        return False


_NULL = Tracer(enabled=False)


def null_tracer() -> Tracer:
    """Shared disabled tracer for call sites whose cache object carries
    no tracer (bare test doubles standing in for a ClusterStore)."""
    return _NULL


def tracer_of(obj) -> Tracer:
    """The object's tracer, or the shared disabled one."""
    tr = getattr(obj, "tracer", None)
    return tr if tr is not None else _NULL
