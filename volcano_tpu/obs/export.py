"""Chrome/Perfetto ``trace_event`` export of flight-recorder cycles.

Produces the JSON object format (``{"traceEvents": [...]}``) that both
``chrome://tracing`` and https://ui.perfetto.dev load directly:

- every span becomes one complete event (``"ph": "X"``, microsecond
  ``ts``/``dur``); parent/child structure is conveyed by nesting on the
  same track, which the viewers reconstruct from the timestamps;
- spans sharing a ``flow`` id (the pipelined solve-id) are additionally
  linked with flow arrows: ``"ph": "s"`` at the first span of the flow
  (the dispatch in cycle N), ``"ph": "t"`` steps in between, and
  ``"ph": "f", "bp": "e"`` at the last (the commit in cycle N+1) — the
  visible dispatch→commit arrow across the cycle boundary;
- one instant event (``"ph": "i"``) per device event (crash /
  budget-degradation) and per drop-reason tally, so "17 rows dropped:
  capacity-taken" is readable at the cycle where it happened;
- metadata events name the process and the logical threads ("cycle",
  "rpc", "bind");
- pod journeys (obs/journey.py, ISSUE 18) export as ASYNC tracks: one
  ``"ph": "b"``/``"e"`` pair per pod uid bracketing its timeline, with
  one ``"ph": "n"`` instant per journey event (kind / shard /
  drop-reason args).  A journey event carrying a solve-id joins that
  solve's flow, so the arrow runs dispatch span → pod bind — the
  pod-centric view laid over the cycle-centric spans.

Spec: the Trace Event Format document (Google, monorail-hosted); only
the stable subset above is emitted.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

PID = 1
_TID_ORDER = ("cycle", "rpc", "bind")


def _tid_of(name: str, table: Dict[str, int]) -> int:
    tid = table.get(name)
    if tid is None:
        tid = table[name] = len(table) + 1
    return tid


def trace_events(records: Iterable,
                 journey: Optional[Iterable[dict]] = None) -> List[dict]:
    """Flatten CycleRecords into a trace_event list (ts in us).
    ``journey`` is an optional iterable of journey rows
    (``JourneyLog.trace_rows()``) exported as async per-pod tracks."""
    events: List[dict] = []
    tid_table: Dict[str, int] = {}
    for known in _TID_ORDER:
        _tid_of(known, tid_table)
    # flow id -> list of (ts_us, index into events) for arrow phases.
    flows: Dict[int, List[int]] = {}

    for rec in records:
        for span in rec.spans:
            ts_us = span.ts_ns / 1e3
            args = dict(span.args) if span.args else {}
            args.setdefault("cycle_seq", rec.seq)
            ev = {
                "name": span.name,
                "cat": span.cat,
                "ph": "X",
                "ts": ts_us,
                "dur": span.dur_ns / 1e3,
                "pid": PID,
                "tid": _tid_of(span.tid, tid_table),
                "args": args,
            }
            events.append(ev)
            if span.flow is not None:
                flows.setdefault(int(span.flow), []).append(
                    len(events) - 1
                )
        base_ts = rec.t_wall * 1e6
        for msg in rec.device_events:
            events.append({
                "name": msg, "cat": "device", "ph": "i", "s": "p",
                "ts": base_ts, "pid": PID,
                "tid": _tid_of("cycle", tid_table),
                "args": {"cycle_seq": rec.seq},
            })
        for reason, count in sorted(rec.drop_reasons.items()):
            events.append({
                "name": f"drop:{reason}", "cat": "staleness",
                "ph": "i", "s": "t", "ts": base_ts, "pid": PID,
                "tid": _tid_of("cycle", tid_table),
                "args": {"cycle_seq": rec.seq, "rows": count},
            })
        # Audit anomalies (ISSUE 13): one process-scoped instant per
        # finding, so a correctness failure is visible on the latency
        # timeline at the cycle where it was detected.
        for anom in getattr(rec, "anomalies", ()) or ():
            events.append({
                "name": f"anomaly:{anom.get('reason', '?')}",
                "cat": "audit", "ph": "i", "s": "p", "ts": base_ts,
                "pid": PID, "tid": _tid_of("cycle", tid_table),
                "args": {"cycle_seq": rec.seq,
                         "detail": anom.get("detail", {})},
            })

    # Pod-journey async tracks: rows are chronological per uid (the
    # ring preserves capture order); emitted BEFORE the flow arrows so
    # a solve-id-carrying journey instant joins its solve's flow.
    if journey:
        jtid = _tid_of("journey", tid_table)
        by_uid: Dict[str, List[dict]] = {}
        for row in journey:
            by_uid.setdefault(row["uid"], []).append(row)
        for uid, rows in by_uid.items():
            name = f"pod {uid}"
            events.append({
                "name": name, "cat": "journey", "ph": "b", "id": uid,
                "ts": rows[0]["ts_us"], "pid": PID, "tid": jtid,
            })
            for row in rows:
                args = {k: v for k, v in row.items()
                        if k not in ("uid", "ts_us")}
                events.append({
                    "name": row["kind"], "cat": "journey", "ph": "n",
                    "id": uid, "ts": row["ts_us"], "pid": PID,
                    "tid": jtid, "args": args,
                })
                sid = row.get("solve_id")
                if sid:
                    flows.setdefault(int(sid), []).append(
                        len(events) - 1)
            events.append({
                "name": name, "cat": "journey", "ph": "e", "id": uid,
                "ts": rows[-1]["ts_us"], "pid": PID, "tid": jtid,
            })

    # Flow arrows: start at the chronologically first span of each flow,
    # finish at the last, step through the middle.
    for flow_id, idxs in flows.items():
        idxs.sort(key=lambda i: events[i]["ts"])
        for pos, i in enumerate(idxs):
            src = events[i]
            ph = "s" if pos == 0 else (
                "f" if pos == len(idxs) - 1 else "t"
            )
            fev = {
                "name": "solve", "cat": "flow", "ph": ph,
                "id": flow_id, "ts": src["ts"], "pid": PID,
                "tid": src["tid"],
            }
            if ph == "f":
                fev["bp"] = "e"
            events.append(fev)

    # Metadata: process + track names.
    meta = [{
        "name": "process_name", "ph": "M", "pid": PID,
        "args": {"name": "volcano-tpu scheduler"},
    }]
    for name, tid in tid_table.items():
        meta.append({
            "name": "thread_name", "ph": "M", "pid": PID, "tid": tid,
            "args": {"name": name},
        })
    return meta + events


def perfetto_trace(records: Iterable,
                   journey: Optional[Iterable[dict]] = None) -> dict:
    """The JSON-object container both viewers accept."""
    return {
        "traceEvents": trace_events(records, journey=journey),
        "displayTimeUnit": "ms",
    }


def write_trace(path: str, records: Iterable,
                journey: Optional[Iterable[dict]] = None) -> str:
    """Dump records to ``path`` as Perfetto-loadable JSON; returns the
    path."""
    with open(path, "w") as f:
        json.dump(perfetto_trace(records, journey=journey), f)
    return path
