"""Cycle flight recorder: a fixed-size ring of per-cycle records.

The scheduler's interesting behavior spans TWO cycles since the
pipelined sessions landed (dispatch in N, commit in N+1), and the only
prior visibility was ``store.last_cycle_lanes`` — last cycle only, lane
seconds only.  The flight recorder keeps the last N cycles (default
256, ``VOLCANO_TPU_FLIGHT_CYCLES``) of everything a post-hoc "why did
cycle 48231 drop 17 rows" investigation needs:

- the lane breakdown (derive/feed/encode/device/order/commit/close),
- pods considered / bound / dropped, drop counts BY REASON (the
  staleness guard's deleted / competing-bind / capacity-taken /
  constraint-sensitive / node-epoch-churn, the topology gate's
  topology-infeasible, plus the whole-result voids compaction /
  lost-reply / device-crash),
- the in-flight fetch wait (the pipeline's health signal),
- device crash / budget-degradation events,
- mirror ``mutation_seq`` / node-table ``epoch`` at dispatch vs commit
  (how much the world moved during the overlap),
- the dispatched and committed solve-ids (the cross-cycle link),
- the cycle's trace spans (``obs.trace``), and
- the runtime auditor's anomalies for the cycle (``obs.audit``).

Concurrency: the cycle thread records (holding the store lock — the
ring lock nests strictly inside it and is never taken around store
state); the HTTP ``/debug`` handlers and bench read from their own
threads.  Everything shared is guarded by ``_lock`` (vclint-checked).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 256


class CycleRecord:
    """One scheduling cycle's accounting.  Plain data; built by the
    cycle thread, sealed by ``FlightRecorder.record`` (which assigns
    ``seq``), then read-only."""

    __slots__ = (
        "seq", "session", "path", "t_wall", "duration_s", "shard",
        "lanes",
        "pods_considered", "pods_bound", "pods_dropped", "drop_reasons",
        "inflight_fetch_wait_ms", "dispatched_solve_id",
        "committed_solve_id", "mutation_seq_at_dispatch",
        "mutation_seq_at_commit", "epoch_at_dispatch", "epoch_at_commit",
        "device_events", "error", "spans", "rebalance", "whatif",
        "pool", "anomalies",
    )

    def __init__(self, session: str = "", path: str = "fast",
                 t_wall: float = 0.0, duration_s: float = 0.0,
                 shard: Optional[int] = None,
                 lanes: Optional[Dict[str, float]] = None,
                 pods_considered: int = 0, pods_bound: int = 0,
                 pods_dropped: int = 0,
                 drop_reasons: Optional[Dict[str, int]] = None,
                 inflight_fetch_wait_ms: Optional[float] = None,
                 dispatched_solve_id: Optional[int] = None,
                 committed_solve_id: Optional[int] = None,
                 mutation_seq_at_dispatch: Optional[int] = None,
                 mutation_seq_at_commit: Optional[int] = None,
                 epoch_at_dispatch: Optional[int] = None,
                 epoch_at_commit: Optional[int] = None,
                 device_events: Optional[List[str]] = None,
                 error: Optional[str] = None,
                 spans: Optional[list] = None,
                 rebalance: Optional[dict] = None,
                 whatif: Optional[dict] = None,
                 pool: Optional[dict] = None,
                 anomalies: Optional[List[dict]] = None):
        self.seq = -1  # assigned by FlightRecorder.record
        self.session = session
        self.path = path
        self.t_wall = t_wall
        self.duration_s = duration_s
        # The recording shard's index under VOLCANO_TPU_SHARDS>1, None
        # on the single-scheduler path.  The store's ONE recorder is
        # shared by every shard's cycle thread (the ring lock
        # serializes them), so /debug/cycles and /debug/trace already
        # aggregate all shards — the tag says who recorded what.
        self.shard = shard
        self.lanes = lanes or {}
        self.pods_considered = pods_considered
        self.pods_bound = pods_bound
        self.pods_dropped = pods_dropped
        self.drop_reasons = drop_reasons or {}
        self.inflight_fetch_wait_ms = inflight_fetch_wait_ms
        self.dispatched_solve_id = dispatched_solve_id
        self.committed_solve_id = committed_solve_id
        self.mutation_seq_at_dispatch = mutation_seq_at_dispatch
        self.mutation_seq_at_commit = mutation_seq_at_commit
        self.epoch_at_dispatch = epoch_at_dispatch
        self.epoch_at_commit = epoch_at_commit
        self.device_events = device_events or []
        self.error = error
        self.spans = spans or []
        # Rebalance lane accounting for the cycle, when the lane ran:
        # outcome, gang uid, need, drain/victim counts, frag score
        # (fastpath.FastCycle._rebalance).  None when the lane was idle.
        self.rebalance = rebalance
        # Device-native preempt/reclaim plan accounting (ISSUE 11,
        # volcano_tpu/whatif.py): action, outcome, gang uid, victim
        # counts.  None when neither lane planned anything.
        self.whatif = whatif
        # Solver-pool fetch accounting for the cycle (ISSUE 15,
        # volcano_tpu/solver_pool.py): winning replica, hedge /
        # failover flags, residual wait.  None for single-connection
        # (or local-solver) stores.
        self.pool = pool
        # Runtime-auditor findings for THIS cycle (ISSUE 13,
        # obs/audit.py Anomaly.to_dict): empty on a healthy cycle.
        self.anomalies = anomalies or []

    def to_dict(self, include_spans: bool = False) -> dict:
        d = {
            "seq": self.seq,
            "session": self.session,
            "path": self.path,
            "t_wall": self.t_wall,
            "shard": self.shard,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "lanes_ms": {
                k: round(v * 1e3, 3) for k, v in self.lanes.items()
            },
            "pods_considered": self.pods_considered,
            "pods_bound": self.pods_bound,
            "pods_dropped": self.pods_dropped,
            "drop_reasons": dict(self.drop_reasons),
            "inflight_fetch_wait_ms": self.inflight_fetch_wait_ms,
            "dispatched_solve_id": self.dispatched_solve_id,
            "committed_solve_id": self.committed_solve_id,
            "mutation_seq_at_dispatch": self.mutation_seq_at_dispatch,
            "mutation_seq_at_commit": self.mutation_seq_at_commit,
            "epoch_at_dispatch": self.epoch_at_dispatch,
            "epoch_at_commit": self.epoch_at_commit,
            "device_events": list(self.device_events),
            "error": self.error,
            "rebalance": (dict(self.rebalance)
                          if self.rebalance is not None else None),
            "whatif": (dict(self.whatif)
                       if self.whatif is not None else None),
            "pool": (dict(self.pool)
                     if self.pool is not None else None),
            "anomalies": [dict(a) for a in self.anomalies],
        }
        if include_spans:
            d["spans"] = [s.to_dict() for s in self.spans]
        return d


class FlightRecorder:
    """Fixed-size ring of the most recent ``capacity`` CycleRecords."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    "VOLCANO_TPU_FLIGHT_CYCLES", DEFAULT_CAPACITY))
            except ValueError:
                capacity = DEFAULT_CAPACITY
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._ring: List[CycleRecord] = []  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock

    def record(self, rec: CycleRecord) -> int:
        """Seal + append a cycle record; returns its assigned seq."""
        with self._lock:
            self._seq += 1
            rec.seq = self._seq
            self._ring.append(rec)
            if len(self._ring) > self.capacity:
                del self._ring[0]
            return rec.seq

    def recent(self, n: Optional[int] = None) -> List[CycleRecord]:
        """The most recent ``n`` records (all retained when None,
        none when ``n <= 0``), oldest first."""
        with self._lock:
            ring = list(self._ring)
        if n is None:
            return ring
        n = int(n)
        return ring[-n:] if n > 0 else []

    def get(self, seq: int) -> Optional[CycleRecord]:
        with self._lock:
            for rec in reversed(self._ring):
                if rec.seq == seq:
                    return rec
        return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def last(self) -> Optional[CycleRecord]:
        with self._lock:
            return self._ring[-1] if self._ring else None
