"""Observability layer: trace spans, cycle flight recorder, Perfetto
export (ISSUE 3), runtime conservation auditor + SLO layer (ISSUE 13).

Six stdlib-only modules, importable without jax/numpy so the store and
the HTTP service can wire them unconditionally:

- ``trace``    — the low-overhead span API (``perf_counter_ns``; one
  small record appended per span, nothing else on the fast path) the
  cycle lanes, the pipelined dispatch→fetch→commit chain, the object
  session's action/plugin boundaries, and the remote RPC clients all
  record into.
- ``recorder`` — the fixed-size ring buffer (default 256 cycles) of
  per-cycle ``CycleRecord``s: lane breakdown, pods considered / bound /
  dropped, staleness-guard drop counts by reason, in-flight fetch wait,
  device crash events, mirror ``mutation_seq``/``epoch`` at dispatch vs
  commit, and the cycle's spans.
- ``export``   — Chrome/Perfetto ``trace_event`` JSON (loadable in
  ``chrome://tracing`` / https://ui.perfetto.dev), with flow arrows
  linking a pipelined solve's dispatch span in cycle N to its
  fetch/commit spans in cycle N+1 via the solve-id, plus one instant
  event per audit anomaly so correctness failures are visible on the
  latency timeline.
- ``audit``    — the always-on runtime conservation auditor (ISSUE
  13): a double-entry ledger of pod-count flows reconciled against
  mirror truth every cycle, sampled coherence audits of the registered
  cache slots, the migration-ledger zero-lost-pods check, and the
  anomaly ring behind ``/debug/anomalies``.
- ``slo``      — per-lane latency windows with declared budgets and
  error-budget burn tracking; breaches surface as auditor anomalies
  and in ``/debug/health``.
- ``journey``  — pod-centric plane (ISSUE 18): a bounded columnar
  per-pod event timeline (enqueued → dispatched → dropped/evicted →
  bound) captured at every sanctioned writer, feeding per-queue
  time-to-bind / gang full-bind latency, the ``/debug/pods/<uid>``
  why-pending explainer, Perfetto async tracks, and the endurance
  conservation check (``journey-orphan`` / ``journey-incomplete``).

Consumers: ``service.py`` exposes ``/debug/cycles``,
``/debug/cycles/<seq>``, ``/debug/trace?cycles=K``, ``/debug/health``
and ``/debug/anomalies``; ``bench.py`` writes one trace file per
config and folds drop-reason totals, per-lane p50/p95, and the audit
overhead block into its machine-readable JSON tail.  docs/tracing.md
and docs/observability.md document all of it.
"""

from .audit import Anomaly, Auditor
from .journey import JourneyLog, journey_on
from .recorder import CycleRecord, FlightRecorder
from .slo import SLOTracker
from .trace import SpanRecord, Tracer, null_tracer

__all__ = [
    "Anomaly",
    "Auditor",
    "CycleRecord",
    "FlightRecorder",
    "JourneyLog",
    "journey_on",
    "SLOTracker",
    "SpanRecord",
    "Tracer",
    "null_tracer",
]
