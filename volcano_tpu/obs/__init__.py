"""Observability layer: trace spans, cycle flight recorder, Perfetto
export (ISSUE 3).

Three stdlib-only modules, importable without jax/numpy so the store and
the HTTP service can wire them unconditionally:

- ``trace``    — the low-overhead span API (``perf_counter_ns``; one
  small record appended per span, nothing else on the fast path) the
  cycle lanes, the pipelined dispatch→fetch→commit chain, the object
  session's action/plugin boundaries, and the remote RPC clients all
  record into.
- ``recorder`` — the fixed-size ring buffer (default 256 cycles) of
  per-cycle ``CycleRecord``s: lane breakdown, pods considered / bound /
  dropped, staleness-guard drop counts by reason, in-flight fetch wait,
  device crash events, mirror ``mutation_seq``/``epoch`` at dispatch vs
  commit, and the cycle's spans.
- ``export``   — Chrome/Perfetto ``trace_event`` JSON (loadable in
  ``chrome://tracing`` / https://ui.perfetto.dev), with flow arrows
  linking a pipelined solve's dispatch span in cycle N to its
  fetch/commit spans in cycle N+1 via the solve-id.

Consumers: ``service.py`` exposes ``/debug/cycles``,
``/debug/cycles/<seq>`` and ``/debug/trace?cycles=K``; ``bench.py``
writes one trace file per config and folds drop-reason totals plus
per-lane p50/p95 into its machine-readable JSON tail.  docs/tracing.md
documents all of it.
"""

from .recorder import CycleRecord, FlightRecorder
from .trace import SpanRecord, Tracer, null_tracer

__all__ = [
    "CycleRecord",
    "FlightRecorder",
    "SpanRecord",
    "Tracer",
    "null_tracer",
]
