"""Pod-journey tracing: per-pod scheduling timelines (ISSUE 18).

Every observability layer so far is cycle-centric — lane spans, flight
records, conservation flows, SLO windows — but none answers the
question a batch-system user actually asks: *where did my pod's time
go, and why is it still pending?*  With the sharded control plane a
single pod's life spans shards (considered on shard A, voided by a
cross-shard conflict, re-placed by shard B), so the signal cannot be
reconstructed from any one recorder.  ``JourneyLog`` is the pod-centric
plane: a bounded columnar event ring plus a per-pod summary, captured
at every sanctioned mirror/fast-path writer (the writer-discipline lint
VCL706 guarantees no writer bypasses it).

Event vocabulary (docs/observability.md):

- ``enqueued``           pod row created in the mirror (store edge)
- ``status-sync``        external status overwrite (update / resync)
- ``dispatched``         first entered a device solve (solve_id, shard)
- ``dropped``            staleness-guard drop, one exclusive reason
                         (``cross-shard-conflict`` carries the losing
                         shard and the ownership handoff epoch)
- ``bound``              commit/backfill landed the placement
- ``unbound``            bind-failure resync or steady-state re-pend
- ``evicted`` / ``evict-reverted``  fastpath_evict state transitions
- ``migration-planned``  what-if plan committed this pod as a victim
- ``restored``           migration ledger re-added it under a new uid
- ``removed``            pod row tombstoned (store edge)

Cost discipline: the fast path feeds per-pod Python work only for
*state changes* — first consideration, first bind, drops, evictions,
churn edges.  The steady-state feed (re-pend + re-bind of the same
100k rows every cycle) is folded into bulk counters by the caller
(``fastpath.FastCycle._journey_rows``'s row masks), so per-cycle
journey cost is proportional to churn, not backlog.  The endurance
gate measures the envelope (<2% of cycle time vs the journey-off leg).

Latency feeds: first-dispatch observes time-to-first-consider, first
bind observes time-to-bind (per queue) and the gang's
time-to-full-bind once every member seen is bound; time-to-bind also
feeds the ``ttb`` SLO lane (``VOLCANO_TPU_SLO_TTB_P99_MS``) whose
burn-rate breaches surface as ``slo-budget-exceeded`` anomalies.

Conservation: ``conservation_check(bound_uids)`` proves every pod
bound at the end of a fault schedule has a complete, orphan-free
journey — a state rooted at ``enqueued`` (``journey-orphan``
otherwise) with a recorded bind and monotone event order across shard
handoffs (``journey-incomplete`` otherwise).  A/B harnesses that ran
with the journey detached re-adopt via ``pod_resync`` (synthetic
roots, explicitly tolerated).

Stdlib-only (``array`` ring, one small lock), like the rest of
``obs/``; kill switch ``VOLCANO_TPU_JOURNEY=0`` leaves the store with
``journey = None`` so hot paths pay one attribute load.
"""

from __future__ import annotations

import os
import threading
import time
from array import array
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from .audit import Anomaly

DEFAULT_EVENTS = 65536

# TaskStatus bit-flags that mean "this pod holds (or held) a placement"
# (api/types.py): Allocated | Binding | Bound | Running | Succeeded.
_BOUND_MASK = (1 << 1) | (1 << 3) | (1 << 4) | (1 << 5) | (1 << 7)

KINDS = (
    "enqueued", "status-sync", "dispatched", "dropped", "bound",
    "unbound", "evicted", "evict-reverted", "migration-planned",
    "restored", "removed",
)
_KIND_CODE = {k: i for i, k in enumerate(KINDS)}

# Per-pod drop-chain depth (why-pending evidence window).
_DROP_CHAIN = 8
# Bench-percentile sample windows.
_TTB_WINDOW = 4096
_GANG_WINDOW = 1024
_QUEUE_WINDOW = 256
# Per-kind metric counts fold into the registry counter in batches of
# this many events (read paths flush too, so totals stay fresh).
_FLUSH_EVERY = 256


def journey_on() -> bool:
    return os.environ.get("VOLCANO_TPU_JOURNEY", "1") != "0"


def ring_capacity() -> int:
    try:
        return max(int(os.environ.get("VOLCANO_TPU_JOURNEY_EVENTS",
                                      DEFAULT_EVENTS)), 1024)
    except ValueError:
        return DEFAULT_EVENTS


def _pct(vals: List[float], q: float) -> Optional[float]:
    if not vals:
        return None
    vals = sorted(vals)
    i = min(int(q * (len(vals) - 1) + 0.5), len(vals) - 1)
    return round(vals[i], 3)


class _PodState:
    """Per-pod journey summary (the stitched cross-shard view)."""

    __slots__ = ("queue", "gang", "enq_ns", "first_ns", "bound_ns",
                 "last_ns", "last_kind", "status", "drops", "solve_id",
                 "shard", "monotone", "synthetic", "restored_from")

    def __init__(self, queue: str, gang: str, now_ns: int,
                 synthetic: bool = False):
        self.queue = queue
        self.gang = gang
        self.enq_ns = now_ns
        self.first_ns: Optional[int] = None
        self.bound_ns: Optional[int] = None
        self.last_ns = now_ns
        self.last_kind = "enqueued"
        self.status = 1  # TaskStatus.Pending
        # Recent (reason, shard) drop attributions, newest last.
        self.drops: deque = deque(maxlen=_DROP_CHAIN)
        self.solve_id = 0
        self.shard = -1
        self.monotone = True
        # True when adopted by pod_resync (journey was detached when
        # the pod entered): conservation treats the root as complete.
        self.synthetic = synthetic
        self.restored_from: Optional[str] = None


class _GangState:
    __slots__ = ("first_enq_ns", "members", "bound", "alive", "done")

    def __init__(self, now_ns: int):
        self.first_enq_ns = now_ns
        self.members = 0
        self.bound = 0
        self.alive = 0
        self.done = False


class JourneyLog:
    """Bounded columnar per-pod event timeline + per-pod summaries.

    Writers call under the store lock (mirror writers / fast path) or
    from bench teardown; readers are the /debug HTTP threads.  All
    shared state is guarded by the journey's own ``_lock`` — never
    taken around store state, so a /debug/pods scrape cannot block the
    cycle thread on store work.
    """

    def __init__(self, capacity: Optional[int] = None, slo=None,
                 auditor=None):
        cap = ring_capacity() if capacity is None else max(int(capacity), 8)
        self._cap = cap
        self._lock = threading.Lock()
        # Wall anchor (obs/trace.py idiom): perf_counter deltas stay
        # monotone; adding the anchor aligns exported timestamps with
        # the tracer's span clock.
        self._anchor_ns = time.time_ns() - time.perf_counter_ns()
        # Columnar ring, overwrite-oldest.  guarded-by: _lock
        self._ev_uid: List[Optional[str]] = [None] * cap
        self._ev_detail: List[Optional[str]] = [None] * cap
        self._ev_kind = array("b", bytes(cap))
        self._ev_shard = array("i", bytes(4 * cap))
        self._ev_solve = array("q", bytes(8 * cap))
        self._ev_epoch = array("q", bytes(8 * cap))
        self._ev_ts = array("q", bytes(8 * cap))
        self._head = 0  # next write slot; guarded-by: _lock
        self._count = 0  # events ever written; guarded-by: _lock
        # Summaries.  guarded-by: _lock
        self._pods: Dict[str, _PodState] = {}
        self._gangs: Dict[str, _GangState] = {}
        # Counters.  guarded-by: _lock
        self.events_total = 0
        self.rebinds = 0  # steady-state re-pend loop, counted in bulk
        self.reconsiders = 0
        self.unbinds_bulk = 0
        self.bound_total = 0
        # Per-kind event counts batched toward the registry counter:
        # per-event inc() took the GLOBAL metrics lock (shared with the
        # scrape and every other series) plus a sorted-tuple build per
        # event — folding every _FLUSH_EVERY events amortizes that
        # ~256x.  guarded-by: _lock
        self._kind_counts: Dict[str, int] = {}
        self._unflushed = 0
        self._metrics = None  # lazy ..metrics handle (import cycle)
        # Self-timed capture cost (the in-process truth, audit_stats
        # idiom): nanoseconds spent inside the capture entry points,
        # two perf_counter reads per CALL (not per event).
        self.capture_ns = 0
        # Latency sample windows for the bench tail / queue rollup.
        self._ttb_ms: deque = deque(maxlen=_TTB_WINDOW)
        self._ttfc_ms: deque = deque(maxlen=_TTB_WINDOW)
        self._gang_ttfb_ms: deque = deque(maxlen=_GANG_WINDOW)
        self._queue_ttb: Dict[str, deque] = {}
        self._queue_counts: Dict[str, Dict[str, int]] = {}
        # SLO feed (ttb lane) + breach intake (auditor.report).
        self.slo = slo
        self.auditor = auditor

    # ------------------------------------------------------------ capture

    def pod_event(self, uid: Optional[str], kind: str, *,
                  status: int = -1, queue: str = "", gang: str = "",
                  shard: int = -1, solve_id: int = 0, epoch: int = -1,
                  detail: str = "") -> None:
        """Record one event for one pod (writers hold the store lock)."""
        if not uid:
            return
        t0 = time.perf_counter_ns()
        now = time.time_ns() - self._anchor_ns
        with self._lock:
            self._apply(uid, kind, now, status, queue, gang, shard,
                        solve_id, epoch, detail)
            self.capture_ns += time.perf_counter_ns() - t0

    def pod_rows(self, uids: Iterable[Optional[str]], kind: str, *,
                 shard: int = -1, solve_id: int = 0, epoch: int = -1,
                 detail: str = "") -> None:
        """Bulk capture sharing one timestamp/lock acquisition (the
        fast path's vectorized writers)."""
        t0 = time.perf_counter_ns()
        now = time.time_ns() - self._anchor_ns
        with self._lock:
            for uid in uids:
                if uid:
                    self._apply(uid, kind, now, -1, "", "", shard,
                                solve_id, epoch, detail)
            self.capture_ns += time.perf_counter_ns() - t0

    def repeat_rows(self, n: int, kind: str) -> None:
        """Steady-state bulk accounting: the feed re-pends and re-binds
        the SAME rows every cycle; their journeys are already complete,
        so only counters move (per-cycle journey cost stays
        churn-proportional — see the module docstring)."""
        if n <= 0:
            return
        t0 = time.perf_counter_ns()
        with self._lock:
            if kind == "bound":
                self.rebinds += n
            elif kind == "dispatched":
                self.reconsiders += n
            else:
                self.unbinds_bulk += n
            self.capture_ns += time.perf_counter_ns() - t0

    def pod_resync(self, pairs: Iterable[Tuple[Optional[str], int]]
                   ) -> None:
        """Bulk status adoption (mirror.resync_status, or a harness
        re-attaching a detached journey): missing pods get synthetic
        roots; pods whose status says placed get a state-sync bind so
        the conservation invariant holds across the blind window."""
        t0 = time.perf_counter_ns()
        now = time.time_ns() - self._anchor_ns
        with self._lock:
            for uid, status in pairs:
                if not uid:
                    continue
                st = self._pods.get(uid)
                if st is None:
                    st = self._pods[uid] = _PodState(
                        "", "", now, synthetic=True)
                st.status = int(status)
                if (status & _BOUND_MASK) and st.bound_ns is None:
                    self._mark_bound(uid, st, now, via="state-sync")
            self.capture_ns += time.perf_counter_ns() - t0

    def pod_restored(self, old_uid: str, new_uid: str) -> None:
        """Migration-ledger stitch: the restored pod's fresh journey
        links back to the evicted victim's uid."""
        now = time.time_ns() - self._anchor_ns
        with self._lock:
            st = self._pods.get(new_uid)
            if st is not None:
                st.restored_from = old_uid
            self._apply(new_uid, "restored", now, -1, "", "", -1, 0,
                        -1, old_uid)

    # ------------------------------------------------------- apply (locked)

    def _apply(self, uid: str, kind: str, now: int, status: int,
               queue: str, gang: str, shard: int, solve_id: int,
               epoch: int, detail: str) -> None:
        st = self._pods.get(uid)
        if kind == "enqueued":
            if st is None:
                st = self._pods[uid] = _PodState(queue, gang, now)
                if gang:
                    g = self._gangs.get(gang)
                    if g is None:
                        g = self._gangs[gang] = _GangState(now)
                    g.members += 1
                    g.alive += 1
                qc = self._queue_counts.setdefault(
                    queue, {"enqueued": 0, "bound": 0})
                qc["enqueued"] += 1
            if status >= 0:
                st.status = status
                if (status & _BOUND_MASK) and st.bound_ns is None:
                    self._mark_bound(uid, st, now, via="state-sync")
        elif st is None:
            # Event for a pod the journey never saw enqueue (adopted
            # mid-life, e.g. re-attach after an A/B window): synthesize
            # the root so the timeline stays rooted.
            st = self._pods[uid] = _PodState(queue, gang, now,
                                             synthetic=True)
        if now < st.last_ns:
            st.monotone = False
        st.last_ns = now
        st.last_kind = kind
        if kind == "dispatched":
            st.solve_id = solve_id
            st.shard = shard
            if st.first_ns is None:
                st.first_ns = now
                ms = (now - st.enq_ns) / 1e6
                self._ttfc_ms.append(ms)
                if self._metrics is None:
                    from ..metrics import metrics

                    self._metrics = metrics
                self._metrics.pod_time_to_first_consider.observe(
                    ms, queue=st.queue or "none")
        elif kind == "dropped":
            st.drops.append((detail, shard))
        elif kind == "bound":
            st.status = 1 << 4  # TaskStatus.Bound
            if st.bound_ns is None:
                self._mark_bound(uid, st, now)
        elif kind == "status-sync":
            if status >= 0:
                st.status = status
                if (status & _BOUND_MASK) and st.bound_ns is None:
                    self._mark_bound(uid, st, now, via="state-sync")
        elif kind == "removed":
            self._pods.pop(uid, None)
            if st.gang:
                g = self._gangs.get(st.gang)
                if g is not None:
                    g.alive -= 1
                    if g.alive <= 0:
                        del self._gangs[st.gang]
        # Ring append (columnar, overwrite-oldest).
        i = self._head
        self._ev_uid[i] = uid
        self._ev_detail[i] = detail or None
        self._ev_kind[i] = _KIND_CODE.get(kind, 0)
        self._ev_shard[i] = shard
        self._ev_solve[i] = solve_id
        self._ev_epoch[i] = epoch
        self._ev_ts[i] = now
        self._head = (i + 1) % self._cap
        self._count += 1
        self.events_total += 1
        kc = self._kind_counts
        kc[kind] = kc.get(kind, 0) + 1
        self._unflushed += 1
        if self._unflushed >= _FLUSH_EVERY:
            self._flush_kind_counts()

    def _flush_kind_counts(self) -> None:
        """Fold the batched per-kind counts into the registry counter
        (caller holds ``_lock``); also runs on every read path so a
        scrape after a quiet spell sees fresh totals."""
        if not self._kind_counts:
            return
        if self._metrics is None:
            from ..metrics import metrics

            self._metrics = metrics
        inc = self._metrics.journey_events.inc
        for kind, n in self._kind_counts.items():
            inc(n, kind=kind)
        self._kind_counts.clear()
        self._unflushed = 0

    def _mark_bound(self, uid: str, st: _PodState, now: int,
                    via: str = "commit") -> None:
        st.bound_ns = now
        self.bound_total += 1
        ms = (now - st.enq_ns) / 1e6
        self._ttb_ms.append(ms)
        q = st.queue or "none"
        self._queue_ttb.setdefault(q, deque(maxlen=_QUEUE_WINDOW)) \
            .append(ms)
        qc = self._queue_counts.setdefault(
            q, {"enqueued": 0, "bound": 0})
        qc["bound"] += 1
        if self._metrics is None:
            from ..metrics import metrics

            self._metrics = metrics
        self._metrics.pod_time_to_bind.observe(ms, queue=q)
        if self.slo is not None and not st.synthetic:
            for breach in self.slo.observe_sample("ttb", ms):
                if self.auditor is not None:
                    self.auditor.report(
                        Anomaly("slo-budget-exceeded", breach))
        if st.gang:
            g = self._gangs.get(st.gang)
            if g is not None:
                g.bound += 1
                if not g.done and g.members > 0 \
                        and g.bound >= g.members:
                    g.done = True
                    gms = (now - g.first_enq_ns) / 1e6
                    self._gang_ttfb_ms.append(gms)
                    self._metrics.gang_time_to_full_bind.observe(gms)

    # -------------------------------------------------------------- reads

    def _ring_indices(self) -> List[int]:
        if self._count < self._cap:
            return list(range(self._head))
        return list(range(self._head, self._cap)) + \
            list(range(self._head))

    def _row(self, i: int) -> dict:
        row = {
            "uid": self._ev_uid[i],
            "kind": KINDS[self._ev_kind[i]],
            "ts_us": round((self._anchor_ns + self._ev_ts[i]) / 1e3, 1),
        }
        if self._ev_shard[i] >= 0:
            row["shard"] = self._ev_shard[i]
        if self._ev_solve[i]:
            row["solve_id"] = self._ev_solve[i]
        if self._ev_epoch[i] >= 0:
            row["handoff_epoch"] = self._ev_epoch[i]
        if self._ev_detail[i]:
            row["detail"] = self._ev_detail[i]
        return row

    def trace_rows(self) -> List[dict]:
        """Chronological ring dump for the Perfetto exporter."""
        with self._lock:
            return [self._row(i) for i in self._ring_indices()]

    def timeline(self, uid: str) -> Optional[dict]:
        """The /debug/pods/<uid> body: stitched cross-shard event list
        (oldest first) + summary + why-pending verdict.  Returns None
        for a pod the journey never saw."""
        with self._lock:
            st = self._pods.get(uid)
            events = [self._row(i) for i in self._ring_indices()
                      if self._ev_uid[i] == uid]
            if st is None and not events:
                return None
            body = {"uid": uid, "events": events}
            if st is not None:
                body.update({
                    "queue": st.queue,
                    "gang": st.gang,
                    "status": st.status,
                    "enqueued_us": round(
                        (self._anchor_ns + st.enq_ns) / 1e3, 1),
                    "time_to_first_consider_ms": (
                        round((st.first_ns - st.enq_ns) / 1e6, 3)
                        if st.first_ns is not None else None),
                    "time_to_bind_ms": (
                        round((st.bound_ns - st.enq_ns) / 1e6, 3)
                        if st.bound_ns is not None else None),
                    "last_kind": st.last_kind,
                    "monotone": st.monotone,
                    "restored_from": st.restored_from,
                    "why_pending": self._verdict(st),
                })
            else:
                body["why_pending"] = "removed (events only)"
            return body

    def why_pending(self, uid: str) -> str:
        with self._lock:
            st = self._pods.get(uid)
            if st is None:
                return "unknown (no journey state)"
            return self._verdict(st)

    def _verdict(self, st: _PodState) -> str:
        """Compress the recent drop-reason chain into one operator
        sentence, e.g. ``capacity-taken x4 on shard 1,
        cross-shard-conflict on shard 0``."""
        if st.status & _BOUND_MASK:
            return "bound"
        if st.last_kind in ("evicted", "migration-planned"):
            return f"{st.last_kind} (awaiting restore)"
        # Drop evidence wins over the never-dispatched check: a pregate
        # hold (e.g. topology-infeasible) drops the pod without it ever
        # entering a solve, and THAT is the verdict, not "backlog".
        if not st.drops:
            if st.first_ns is None:
                return "never considered (queue backlog)"
            return "considered, no drops recorded (awaiting commit)"
        parts: List[str] = []
        run: Optional[Tuple[str, int]] = None
        n = 0
        for reason, shard in st.drops:
            key = (reason, shard)
            if key == run:
                n += 1
                continue
            if run is not None:
                parts.append(self._drop_phrase(run, n))
            run, n = key, 1
        if run is not None:
            parts.append(self._drop_phrase(run, n))
        return ", ".join(parts)

    @staticmethod
    def _drop_phrase(key: Tuple[str, int], n: int) -> str:
        reason, shard = key
        out = reason or "dropped"
        if n > 1:
            out += f" x{n}"
        if shard >= 0:
            out += f" on shard {shard}"
        return out

    def queue_rollup(self) -> dict:
        """Per-queue scheduling-latency rollup for /debug/health."""
        with self._lock:
            self._flush_kind_counts()
            out: Dict[str, dict] = {}
            for q, counts in sorted(self._queue_counts.items()):
                win = list(self._queue_ttb.get(q, ()))
                out[q] = {
                    "enqueued_total": counts["enqueued"],
                    "bound_total": counts["bound"],
                    "ttb_p50_ms": _pct(win, 0.50),
                    "ttb_p99_ms": _pct(win, 0.99),
                }
            return {
                "queues": out,
                "pods_tracked": len(self._pods),
                "gangs_tracked": len(self._gangs),
                "events_total": self.events_total,
            }

    def stats(self) -> dict:
        """The bench JSON-tail journey block."""
        with self._lock:
            self._flush_kind_counts()
            ttb = list(self._ttb_ms)
            ttfc = list(self._ttfc_ms)
            gang = list(self._gang_ttfb_ms)
            return {
                "events": self.events_total,
                "capture_ms": round(self.capture_ns / 1e6, 3),
                "events_dropped": max(self._count - self._cap, 0),
                "pods": len(self._pods),
                "bound": self.bound_total,
                "rebinds": self.rebinds,
                "reconsiders": self.reconsiders,
                "ttfc_p50_ms": _pct(ttfc, 0.50),
                "ttb_p50_ms": _pct(ttb, 0.50),
                "ttb_p95_ms": _pct(ttb, 0.95),
                "ttb_p99_ms": _pct(ttb, 0.99),
                "gang_ttfb_p50_ms": _pct(gang, 0.50),
                "gang_ttfb_p99_ms": _pct(gang, 0.99),
            }

    # ------------------------------------------------------- conservation

    def conservation_check(self, bound_uids: Iterable[str]
                           ) -> List[Anomaly]:
        """The endurance-gate invariant: every pod bound at the end of
        the fault schedule has a complete, orphan-free journey.

        - ``journey-orphan``: a bound pod with NO journey state — some
          writer bypassed the capture seams entirely.
        - ``journey-incomplete``: state exists but the bind was never
          recorded, or the event order went non-monotone across a
          shard handoff.

        Synthetic roots (``pod_resync`` adoption after a deliberate
        detach window) count as complete — the adoption is itself the
        recorded provenance.
        """
        orphans: List[str] = []
        incomplete: List[str] = []
        with self._lock:
            for uid in bound_uids:
                st = self._pods.get(uid)
                if st is None:
                    orphans.append(uid)
                elif st.bound_ns is None or not st.monotone:
                    incomplete.append(uid)
        out: List[Anomaly] = []
        if orphans:
            out.append(Anomaly("journey-orphan", {
                "count": len(orphans), "uids": orphans[:5],
            }))
        if incomplete:
            out.append(Anomaly("journey-incomplete", {
                "count": len(incomplete), "uids": incomplete[:5],
            }))
        return out
