"""Annotation-derived runtime lock enforcement (``VOLCANO_TPU_LOCKDEP=1``).

The ``# guarded-by:`` comments that vclint's lockcheck family enforces
statically (VCL101/102) describe a runtime contract: *this attribute is
only touched while that lock is held*.  This module turns the same
annotations — parsed by the same code, ``tools/vclint/annotations.py``
— into live enforcement:

- ``enable_lockdep(store)`` installs class-level data descriptors over
  every ``# guarded-by:`` attribute of the ``LOCK_FILES`` classes.  A
  get/set on an **armed** instance asserts the declared lock is held by
  the current thread; a miss is reported to the store's auditor ring as
  a ``lockdep-violation`` anomaly (attribute, declared lock, thread
  name, trimmed stack) — reported, never raised, so a probe cannot
  crash the scheduler it is observing.
- Every ``threading.Lock``/``RLock``/``Condition`` reachable from the
  store's object graph is wrapped in a ``_LockProxy`` that maintains a
  per-thread held-lock multiset plus a process-wide acquisition-order
  graph.  A new edge that closes a cycle (thread 1 takes A then B,
  thread 2 takes B then A) is reported once as a ``lock-order-cycle``
  anomaly with the offending path.

Lock identity is BY NAME (the attribute name the lock lives under),
exactly matching lockcheck's leaf-name semantics — the static and
runtime checkers agree byte-for-byte because they share one annotation
parser and one naming rule.  Same-name edges (``store._lock`` nesting
``auditor._lock``: both leaves are ``_lock``) are skipped in the order
graph for the same reason lockcheck cannot distinguish them.

Static suppressions are honored at runtime: an access whose source line
(or contiguous comment block above) carries ``# vclint:
disable=VCL101/VCL102 -- reason`` is not reported, so the one reviewed
unguarded read in the tree stays quiet under enforcement too.

Kill switch: everything here is gated on ``VOLCANO_TPU_LOCKDEP`` (off
by default).  When off, ``enable_lockdep`` returns False without
touching any class and the constructor-site ``attach`` hooks are a
single global-flag test — zero steady-state overhead.

Stdlib only.  When ``tools/vclint/annotations.py`` is not importable
(installed package without the repo checkout), lockdep disables itself
rather than guessing.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import traceback
from typing import Dict, List, Optional, Set

# ------------------------------------------------------------------ switch

def lockdep_on() -> bool:
    return os.environ.get("VOLCANO_TPU_LOCKDEP", "0") not in ("0", "")


# Armed process-wide once enable_lockdep succeeds; reset() clears it.
# Checked FIRST on every hook so the off path costs one global load.
_active = False

MAX_REPORTS = 64  # process-wide anomaly cap: a hot broken site must
#                   not flood the ring that is trying to describe it

# ------------------------------------------------- per-thread held tracking


class _Held(threading.local):
    def __init__(self):
        self.counts: Dict[str, int] = {}  # lock name -> recursion depth
        self.order: List[str] = []        # distinct names, acquire order


_held = _Held()


def held_locks() -> Dict[str, int]:
    """Snapshot of the calling thread's held-lock multiset (tests)."""
    return dict(_held.counts)


def _holding(name: str) -> bool:
    return _held.counts.get(name, 0) > 0


def _note_acquire(name: str) -> None:
    depth = _held.counts.get(name, 0)
    _held.counts[name] = depth + 1
    if depth == 0:
        for prev in _held.order:
            if prev != name:  # same-name nesting is invisible to the
                _order_edge(prev, name)  # static checker too
        _held.order.append(name)


def _note_release(name: str) -> None:
    depth = _held.counts.get(name, 0)
    if depth <= 1:
        _held.counts.pop(name, None)
        try:
            _held.order.remove(name)
        except ValueError:
            pass
    else:
        _held.counts[name] = depth - 1


# ------------------------------------------------------- lock-order graph

_graph_lock = threading.Lock()
_edges: Dict[str, Set[str]] = {}      # guarded-by: _graph_lock
_reported_cycles: Set[tuple] = set()  # guarded-by: _graph_lock


def _reaches(src: str, dst: str) -> Optional[List[str]]:
    """Path src -> ... -> dst over ``_edges`` (caller holds
    ``_graph_lock``), or None."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _order_edge(held: str, acquiring: str) -> None:
    with _graph_lock:
        succ = _edges.setdefault(held, set())
        if acquiring in succ:
            return
        succ.add(acquiring)
        back = _reaches(acquiring, held)
        if back is None:
            return
        key = (held, acquiring)
        if key in _reported_cycles:
            return
        _reported_cycles.add(key)
        cycle = back + [acquiring]
    _report_cycle(held, acquiring, cycle)


# ------------------------------------------------------------- lock proxy


class _LockProxy:
    """Wraps a Lock/RLock/Condition, tracking acquisition by the
    attribute NAME it was found under.  Unknown methods (``wait``,
    ``notify`` …) delegate — a Condition's internal release inside
    ``wait`` is deliberately not tracked: attributes guarded by the
    condition are owned for the whole ``with`` block, which is exactly
    the static annotation's semantics."""

    __slots__ = ("_vcld_lock", "_vcld_name")

    def __init__(self, lock, name: str):
        self._vcld_lock = lock
        self._vcld_name = name

    def acquire(self, *args, **kwargs):
        got = self._vcld_lock.acquire(*args, **kwargs)
        if got:
            _note_acquire(self._vcld_name)
        return got

    def release(self, *args, **kwargs):
        self._vcld_lock.release(*args, **kwargs)
        _note_release(self._vcld_name)

    def __enter__(self):
        got = self._vcld_lock.__enter__()
        _note_acquire(self._vcld_name)
        return got

    def __exit__(self, *exc):
        _note_release(self._vcld_name)
        return self._vcld_lock.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "_vcld_lock"), item)

    def __repr__(self):
        return f"<lockdep proxy '{self._vcld_name}' {self._vcld_lock!r}>"


_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()),
               threading.Condition)


# -------------------------------------------------------------- reporting

_reporters_lock = threading.Lock()
_reporters: List[object] = []        # auditors; guarded-by: _reporters_lock
_report_count = 0                    # guarded-by: _reporters_lock
_seen_violations: Set[tuple] = set()  # guarded-by: _reporters_lock


def _deliver(anomaly) -> None:
    global _report_count
    with _reporters_lock:
        if _report_count >= MAX_REPORTS:
            return
        _report_count += 1
        targets = list(_reporters)
    for auditor in targets:
        try:
            auditor.report(anomaly)
        except Exception:
            pass  # the probe must never take down the probed


def _stack_summary(frame, limit: int = 6) -> List[str]:
    out = []
    for entry in traceback.extract_stack(frame, limit=limit):
        out.append(f"{entry.filename}:{entry.lineno}:{entry.name}")
    return out


def _report_cycle(held: str, acquiring: str, cycle: List[str]) -> None:
    from .audit import Anomaly

    _deliver(Anomaly("lock-order-cycle", {
        "held": held,
        "acquiring": acquiring,
        "cycle": cycle,
        "thread": threading.current_thread().name,
        "stack": _stack_summary(sys._getframe(2)),
    }))


# Split so the suppression scanner does not read this pattern itself
# as a (malformed) suppression comment.
_DISABLE_RE = re.compile(
    r"#\s*vclint:\s*"
    r"disable=([A-Za-z0-9,\s]+?)(?:--|$)")
_suppress_cache: Dict[tuple, bool] = {}


def _static_suppressed(filename: str, lineno: int, code: str) -> bool:
    """True when the access site carries the SAME suppression the
    static checker honors — same line, or a contiguous comment block
    directly above (findings.Suppressions semantics)."""
    key = (filename, lineno, code)
    cached = _suppress_cache.get(key)
    if cached is not None:
        return cached
    import linecache

    def _match(text: str) -> bool:
        m = _DISABLE_RE.search(text)
        if not m:
            return False
        codes = {c.strip() for c in m.group(1).split(",")}
        return code in codes or "all" in codes

    lines = linecache.getlines(filename)
    hit = False
    if 0 < lineno <= len(lines):
        if _match(lines[lineno - 1]):
            hit = True
        else:
            i = lineno - 1
            while i >= 1 and lines[i - 1].lstrip().startswith("#"):
                if _match(lines[i - 1]):
                    hit = True
                    break
                i -= 1
    _suppress_cache[key] = hit
    return hit


# Methods the static checker exempts from guard analysis — the runtime
# must not be stricter than the contract it enforces.
_EXEMPT_FRAMES = {"__init__", "__new__", "__del__", "__repr__"}


def _report_violation(cls_name: str, attr: str, lock: str,
                      access: str, frame) -> None:
    code = "VCL102" if access == "write" else "VCL101"
    if frame is not None:
        if frame.f_code.co_name in _EXEMPT_FRAMES:
            return
        if _static_suppressed(frame.f_code.co_filename, frame.f_lineno,
                              code):
            return
    key = (cls_name, attr, access)
    with _reporters_lock:
        if key in _seen_violations:
            return
        _seen_violations.add(key)
    from .audit import Anomaly

    _deliver(Anomaly("lockdep-violation", {
        "class": cls_name,
        "attribute": attr,
        "lock": lock,
        "access": access,
        "thread": threading.current_thread().name,
        "held": sorted(_held.counts),
        "stack": _stack_summary(frame),
    }))


# ------------------------------------------------------------ descriptors

_MISSING = object()


class _GuardedDescriptor:
    """Class-level data descriptor over one ``# guarded-by:``
    attribute.  Values live in the instance ``__dict__`` under the same
    name (a data descriptor wins the lookup, so storage stays where
    debuggers and ``vars()`` expect it).  Enforcement fires only for
    instances armed by ``attach`` while lockdep is active — everything
    else pays two dict probes."""

    __slots__ = ("attr", "lock", "cls_name", "default")

    def __init__(self, attr: str, lock: str, cls_name: str,
                 default=_MISSING):
        self.attr = attr
        self.lock = lock
        self.cls_name = cls_name
        self.default = default

    def __get__(self, obj, objtype=None):
        if obj is None:
            if self.default is _MISSING:
                return self
            return self.default
        d = obj.__dict__
        if _active and d.get("_vclockdep_armed") \
                and not _holding(self.lock):
            _report_violation(self.cls_name, self.attr, self.lock,
                              "read", sys._getframe(1))
        val = d.get(self.attr, _MISSING)
        if val is _MISSING:
            if self.default is _MISSING:
                raise AttributeError(
                    f"{self.cls_name} has no attribute {self.attr!r}")
            return self.default
        return val

    def __set__(self, obj, value):
        d = obj.__dict__
        if _active and d.get("_vclockdep_armed") \
                and not _holding(self.lock):
            _report_violation(self.cls_name, self.attr, self.lock,
                              "write", sys._getframe(1))
        d[self.attr] = value

    def __delete__(self, obj):
        obj.__dict__.pop(self.attr, None)


# ------------------------------------------------------------ installation

def _load_annotations():
    """The shared annotation parser — as a package import when
    ``tools`` is on the path, by file location otherwise (it is
    deliberately dependency-free so this is safe), or None."""
    try:
        from tools.vclint import annotations  # type: ignore
        return annotations
    except Exception:
        pass
    try:
        import importlib.util
        from pathlib import Path

        path = (Path(__file__).resolve().parents[2]
                / "tools" / "vclint" / "annotations.py")
        if not path.is_file():
            return None
        spec = importlib.util.spec_from_file_location(
            "_vclockdep_annotations", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


_install_lock = threading.Lock()
_installed = False
_wrapped_classes: Set[type] = set()  # guarded-by: _install_lock


def _class_allows_descriptors(cls: type) -> bool:
    # __slots__ classes have no instance __dict__ for value storage;
    # the static checker covers them, the runtime skips them.
    return not any("__slots__" in k.__dict__
                   for k in cls.__mro__ if k is not object)


def _install_descriptors(ann) -> None:
    global _installed
    with _install_lock:
        if _installed:
            return
        import importlib

        for rel in ann.LOCK_FILES:
            mod_name = rel[:-3].replace("/", ".")
            try:
                mod = importlib.import_module(mod_name)
                source = open(mod.__file__, "r").read()
                model = ann.build_model(rel, source)
            except Exception:
                continue  # a missing optional module never blocks the rest
            for info in model.classes:
                cls = getattr(mod, info.name, None)
                if (not isinstance(cls, type) or not info.guarded
                        or not _class_allows_descriptors(cls)):
                    continue
                for attr, g in info.guarded.items():
                    existing = cls.__dict__.get(attr, _MISSING)
                    if existing is not _MISSING and (
                            hasattr(existing, "__get__")
                            or hasattr(existing, "__set__")):
                        continue  # property/slot: already mediated
                    setattr(cls, attr, _GuardedDescriptor(
                        attr, g.lock, f"{mod_name}.{info.name}",
                        default=existing))
                _wrapped_classes.add(cls)
        _installed = True


# ------------------------------------------------------------- attachment

def attach(obj) -> None:
    """Walk ``obj``'s object graph: wrap every reachable lock in a
    ``_LockProxy`` and arm every instance of a descriptor-wrapped
    class.  Constructor call sites (store, shard table, solver pool)
    invoke this unconditionally — the flag test below is the entire
    cost when lockdep is off."""
    if not _active:
        return
    seen = set()
    stack = [obj]
    while stack:
        o = stack.pop()
        if id(o) in seen:
            continue
        seen.add(id(o))
        if isinstance(o, (list, tuple, set, frozenset)):
            stack.extend(o)
            continue
        if isinstance(o, dict):
            stack.extend(o.values())
            continue
        cls = type(o)
        if not getattr(cls, "__module__", "").startswith("volcano_tpu"):
            continue
        d = getattr(o, "__dict__", None)
        if d is None:
            continue
        if cls in _wrapped_classes:
            d["_vclockdep_armed"] = True
        for name, val in list(d.items()):
            if isinstance(val, _LOCK_TYPES):
                d[name] = _LockProxy(val, name)
            elif isinstance(val, (_LockProxy, str, bytes, int, float,
                                  bool, type(None))):
                continue
            else:
                stack.append(val)


def register_reporter(auditor) -> None:
    with _reporters_lock:
        if auditor not in _reporters:
            _reporters.append(auditor)


def enable_lockdep(store) -> bool:
    """Arm lockdep over ``store``'s object graph.  Called at the tail
    of ``ClusterStore.__init__``; returns False (having changed
    nothing) when the kill switch is off or the annotation parser is
    unavailable."""
    global _active
    if not lockdep_on():
        return False
    ann = _load_annotations()
    if ann is None:
        return False
    _install_descriptors(ann)
    _active = True
    register_reporter(store.auditor)
    attach(store)
    return True


def reset() -> None:
    """Disarm enforcement and drop accumulated state (tests).  Already
    installed descriptors and proxies stay in place — with ``_active``
    cleared they are inert pass-throughs."""
    global _active, _report_count
    _active = False
    with _reporters_lock:
        _reporters.clear()
        _seen_violations.clear()
        _report_count = 0
    with _graph_lock:
        _edges.clear()
        _reported_cycles.clear()


def stats() -> dict:
    """Debug snapshot (tests, /debug handlers)."""
    with _reporters_lock:
        reports = _report_count
        violations = len(_seen_violations)
    with _graph_lock:
        edges = sum(len(v) for v in _edges.values())
        cycles = len(_reported_cycles)
    return {"active": _active, "reports": reports,
            "violations": violations, "order_edges": edges,
            "order_cycles": cycles}
