"""SLO layer: per-lane latency windows with declared budgets and
error-budget burn tracking (ISSUE 13).

A *budget* declares "lane X's p99 stays under T ms, with at most
``allowed_frac`` of cycles over T" — the three shipped lanes are the
north-star trio: whole-cycle latency (``cycle``), the device lane
(``device``), and the idle-skip floor (``idle`` — cycles that
dispatched no solve must stay near the null-delta cost, or the "idle
is cheap" contract of the incremental lanes has silently rotted).

Tracking is a fixed sliding window (deque of the last ``window``
observations per lane) — bounded memory, exact percentiles over the
window, no decay math.  The *burn rate* is the classic error-budget
ratio: (violations / the CONFIGURED window size) / allowed_frac; a
burn rate >= 1.0 means the lane is consuming its error budget faster
than the SLO allows.  The denominator is deliberately the configured
window, not the filled portion: while the window is still filling,
each violation must be worth 1/window of budget, not 1/len — judging
a 10%-allowed budget over 16 early samples makes TWO expected fault
spikes an anomaly, which is exactly the startup flake the ISSUE 15
endurance pool leg exposed (clustered one-time jit compiles early in
the window fired edges a full window would absorb).  ``observe``
reports breach EDGES (enter-breach transitions, re-armed when the
window drops back under), so a sustained breach costs one anomaly,
not one per cycle; the auditor (obs/audit.py) turns those into
``slo-budget-exceeded`` anomalies.

Budgets come from env (``VOLCANO_TPU_SLO_CYCLE_P99_MS`` /
``VOLCANO_TPU_SLO_DEVICE_P99_MS`` / ``VOLCANO_TPU_SLO_IDLE_P99_MS`` /
``VOLCANO_TPU_SLO_TTB_P99_MS``,
unset = tracked but unbudgeted) or programmatically via ``declare`` —
the endurance harness declares explicit budgets and fails on burn.
The ``ttb`` lane is pod-centric, not cycle-centric: the journey log
(obs/journey.py, ISSUE 18) feeds one observation per first bind via
``observe_sample``.

Stdlib-only; internally synchronized (one small lock) so /debug reads
never contend the cycle thread for more than a dict copy.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Dict, List, Optional

DEFAULT_WINDOW = 256
# Minimum observations before a burn-rate breach can fire: percentile
# math over a handful of warmup cycles is noise, not signal.
MIN_SAMPLES = 16
DEFAULT_ALLOWED_FRAC = 0.01

_ENV_BUDGETS = (
    ("cycle", "VOLCANO_TPU_SLO_CYCLE_P99_MS"),
    ("device", "VOLCANO_TPU_SLO_DEVICE_P99_MS"),
    ("idle", "VOLCANO_TPU_SLO_IDLE_P99_MS"),
    # Pod time-to-bind (obs/journey.py, ISSUE 18): one observation per
    # FIRST bind, fed via observe_sample — the pod-centric SLO lane.
    ("ttb", "VOLCANO_TPU_SLO_TTB_P99_MS"),
)


class Budget:
    __slots__ = ("lane", "target_ms", "allowed_frac")

    def __init__(self, lane: str, target_ms: float,
                 allowed_frac: float = DEFAULT_ALLOWED_FRAC):
        self.lane = lane
        self.target_ms = float(target_ms)
        self.allowed_frac = max(float(allowed_frac), 1e-6)


def _pct(vals: List[float], q: float) -> float:
    vals = sorted(vals)
    i = min(int(q * (len(vals) - 1) + 0.5), len(vals) - 1)
    return vals[i]


class SLOTracker:
    """Per-lane sliding-window latency tracker with budget burn."""

    def __init__(self, window: int = DEFAULT_WINDOW):
        self.window = max(int(window), MIN_SAMPLES)
        self._lock = threading.Lock()
        self._lanes: Dict[str, deque] = {}  # guarded-by: _lock
        self.budgets: Dict[str, Budget] = {}  # guarded-by: _lock
        self._breached: Dict[str, bool] = {}  # guarded-by: _lock
        # Monotone per-lane violation counters (the burn *counters*; the
        # instantaneous burn *rate* is in snapshot()).
        self.violations: Dict[str, int] = {}  # guarded-by: _lock
        self.observations: Dict[str, int] = {}  # guarded-by: _lock
        for lane, env in _ENV_BUDGETS:
            raw = os.environ.get(env)
            if raw:
                try:
                    self.budgets[lane] = Budget(lane, float(raw))
                except ValueError:
                    pass

    def declare(self, lane: str, target_ms: float,
                allowed_frac: float = DEFAULT_ALLOWED_FRAC) -> None:
        with self._lock:
            self.budgets[lane] = Budget(lane, target_ms, allowed_frac)
            self._breached.pop(lane, None)

    # ------------------------------------------------------------ observe

    def observe(self, duration_s: float, lanes: Dict[str, float],
                idle: bool = False) -> List[dict]:
        """Feed one cycle; returns breach-edge dicts (possibly empty).
        ``lanes`` is the cycle's lane-seconds dict; ``idle`` marks a
        cycle that dispatched no solve (the idle-skip floor lane)."""
        obs = {"cycle": duration_s * 1e3}
        dev = lanes.get("device")
        if dev is not None:
            obs["device"] = dev * 1e3
        if idle:
            obs["idle"] = duration_s * 1e3
        breaches: List[dict] = []
        with self._lock:
            for lane, ms in obs.items():
                self._feed_locked(lane, ms, breaches)
        return breaches

    def observe_sample(self, lane: str, ms: float) -> List[dict]:
        """Feed one out-of-cycle observation (e.g. the journey's
        per-pod time-to-bind) into ``lane`` with the same budget /
        burn-rate / breach-edge semantics as ``observe``."""
        breaches: List[dict] = []
        with self._lock:
            self._feed_locked(lane, float(ms), breaches)
        return breaches

    # holds: _lock
    def _feed_locked(self, lane: str, ms: float,
                     breaches: List[dict]) -> None:
        from ..metrics import metrics

        win = self._lanes.get(lane)
        if win is None:
            win = self._lanes[lane] = deque(maxlen=self.window)
        win.append(ms)
        self.observations[lane] = (
            self.observations.get(lane, 0) + 1)
        b = self.budgets.get(lane)
        if b is None:
            return
        if ms > b.target_ms:
            self.violations[lane] = (
                self.violations.get(lane, 0) + 1)
        if len(win) < MIN_SAMPLES:
            return
        over = sum(1 for v in win if v > b.target_ms)
        # Burn over the CONFIGURED window (unfilled slots count
        # healthy) — see the module docstring.
        burn = (over / self.window) / b.allowed_frac
        was = self._breached.get(lane, False)
        now = burn >= 1.0
        self._breached[lane] = now
        metrics.slo_burn_rate.set(round(burn, 4), lane=lane)
        if now and not was:
            breaches.append({
                "lane": lane,
                "target_ms": b.target_ms,
                "observed_ms": round(ms, 3),
                "window_p99_ms": round(_pct(list(win), 0.99), 3),
                "burn_rate": round(burn, 2),
                "over_in_window": over,
                "window": len(win),
            })

    # ------------------------------------------------------------- reads

    def snapshot(self) -> dict:
        """The /debug/health "slo" section: per-lane p50/p99 over the
        window, declared budgets, burn rates, breach state."""
        with self._lock:
            lanes = {k: list(v) for k, v in self._lanes.items()}
            budgets = dict(self.budgets)
            breached = dict(self._breached)
            violations = dict(self.violations)
            observations = dict(self.observations)
        out = {}
        for lane, vals in sorted(lanes.items()):
            b = budgets.get(lane)
            entry = {
                "window": len(vals),
                "p50_ms": round(_pct(vals, 0.50), 3) if vals else None,
                "p99_ms": round(_pct(vals, 0.99), 3) if vals else None,
                "observations": observations.get(lane, 0),
            }
            if b is not None:
                over = sum(1 for v in vals if v > b.target_ms)
                burn = ((over / self.window) / b.allowed_frac
                        if vals else 0.0)
                entry.update({
                    "target_p99_ms": b.target_ms,
                    "allowed_frac": b.allowed_frac,
                    "violations_total": violations.get(lane, 0),
                    "burn_rate": round(burn, 4),
                    "breached": breached.get(lane, False),
                    "budget_remaining": round(max(1.0 - burn, 0.0), 4),
                })
            out[lane] = entry
        return out
