"""Runtime conservation auditor: always-on correctness observation.

PR 3's flight recorder and the metrics registry observe *latency*; this
module observes *correctness* while the scheduler runs (ISSUE 13).  The
rebuild now carries exactly the state a long-running deployment can
silently corrupt — 8+ registered cache slots, devincr skip tokens,
per-connection wire mirrors, a cross-action migration ledger — and a
corruption that only a from-scratch test rebuild would notice is a
corruption production never notices.  Three mechanisms, all cheap
enough to stay on in production:

1. **Conservation ledger** (``ConservationLedger``) — an append-only
   double-entry record of pod-count flows.  Every writer of the
   mirror's dynamic pod state declares its transition (pending→bound at
   commit, bound→pending on unbind/revert, running→releasing on evict,
   added / deleted at the store edge, restore re-adds from the
   migration ledger); each entry debits one status class and credits
   another.  At cycle end the auditor reconciles the declared net flow
   against an independent census of the mirror truth (one bincount over
   ``p_status``/``p_alive``), so any lost or duplicated pod surfaces as
   a structured ``conservation-mismatch`` anomaly within ONE cycle
   instead of at test time.  A cycle with no flows and an unmoved
   ``mutation_seq`` skips the census (the null-delta idle case) —
   except on sampled cycles, which force it, bounding detection latency
   for writers that forgot both the flow AND the mutation counter.

2. **Coherence sampling audits** — amortized spot-checks of the
   registered cache slots against from-scratch truth, riding the
   existing ``VOLCANO_TPU_INCR_VERIFY`` machinery but always-on at a
   configurable sample rate (``VOLCANO_TPU_AUDIT_SAMPLE``, default one
   audited cycle in 64) instead of all-or-nothing: the persistent
   ``CycleAggregates`` planes re-verify against ``_build_aggregates``
   (``aggregate-divergence``); the encode cache and the devincr static
   planes are guarded by content sentinels — a strided content
   signature that must hold still while the slot's cache key holds
   still (``cache-content-mutated``); the remote solver's wire mirror
   must keep a monotone generation and frozen mirror bytes per
   generation (``wire-mirror-divergence``); and every migration-ledger
   entry whose victim is gone must carry its restore
   (``ledger-restore-lost`` — the zero-lost-pods contract).

3. **SLO feed** — the auditor drives ``obs.slo.SLOTracker`` with each
   cycle's lane latencies and turns budget burn-rate breaches into
   ``slo-budget-exceeded`` anomalies (rate-limited to the breach edge).

Anomalies land in a bounded ring (``/debug/anomalies``), in the cycle's
flight-recorder record (``CycleRecord.anomalies`` → Perfetto instant
events), and in ``volcano_audit_anomalies_total``.  The full reason
catalog lives in docs/observability.md; vclint's VCL6xx family keeps
the two 1:1.

Threading: flow recording and ``end_cycle`` run on writers that hold
the store lock; ``/debug/health`` and ``/debug/anomalies`` read from
HTTP threads.  Everything shared is guarded by the auditor's own
``_lock`` (never taken around store state, so the debug endpoints can
never block the cycle thread on store work).

Stdlib-only at module scope (numpy is imported lazily inside the few
functions that touch mirror arrays), like the rest of ``obs/``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Virtual status classes for the double-entry ledger's store edge: a
# pod appearing debits ADDED, a pod leaving credits GONE.  Real classes
# are the raw TaskStatus ints (opaque to this module).
ADDED = -1
GONE = -2

# Census width: raw status values are clipped into [0, CENSUS_W).
# TaskStatus values are BIT FLAGS up to 1 << 9 = 512 (api/types.py), so
# the width must clear 512; 1024 leaves headroom plus an aliasing
# bucket that would itself show up as a mismatch.  (64 — the original
# "single digits" assumption — silently aliased Releasing (1 << 6)
# into the clip bucket while the declared flow kept the raw class, so
# any cycle ending with an evicted-but-not-yet-terminated pod reported
# a phantom conservation-mismatch.  Unreachable before ISSUE 15: the
# device-native evict lanes were off for remote stores, and the local
# suites never asserted anomaly counts across a grace window.)
CENSUS_W = 1024

DEFAULT_SAMPLE = 64
DEFAULT_RING = 256
DEFAULT_LEDGER_ENTRIES = 4096


def audit_on() -> bool:
    return os.environ.get("VOLCANO_TPU_AUDIT", "1") != "0"


def sample_rate() -> int:
    try:
        return max(int(os.environ.get("VOLCANO_TPU_AUDIT_SAMPLE",
                                      DEFAULT_SAMPLE)), 1)
    except ValueError:
        return DEFAULT_SAMPLE


class Anomaly:
    """One detected invariant violation.  ``reason`` is a catalogued
    string (docs/observability.md; vclint VCL6xx keeps the catalog
    honest); ``detail`` is a small JSON-safe dict."""

    __slots__ = ("reason", "detail", "t_wall", "cycle_seq")

    def __init__(self, reason: str, detail: Optional[dict] = None,
                 cycle_seq: Optional[int] = None):
        self.reason = reason
        self.detail = detail or {}
        self.t_wall = time.time()
        self.cycle_seq = cycle_seq

    def to_dict(self) -> dict:
        return {
            "reason": self.reason,
            "detail": dict(self.detail),
            "t_wall": self.t_wall,
            "cycle_seq": self.cycle_seq,
        }


class ConservationLedger:
    """Append-only double-entry record of declared pod-count flows.

    Writers call ``flow`` under the store lock; the auditor serializes
    access with its own lock (see Auditor).  ``net`` accumulates the
    per-class delta since the last reconcile; ``entries`` keeps the
    most recent transitions for post-hoc inspection; ``totals`` counts
    rows per flow reason forever (monotonic, like a counter series)."""

    __slots__ = ("net", "entries", "totals")

    def __init__(self, max_entries: int = DEFAULT_LEDGER_ENTRIES):
        self.net: Dict[int, int] = {}
        self.entries: deque = deque(maxlen=max_entries)
        self.totals: Dict[str, int] = {}

    def record(self, reason: str, src: int, dst: int, n: int) -> None:
        if n <= 0 or src == dst:
            return
        self.net[src] = self.net.get(src, 0) - n
        self.net[dst] = self.net.get(dst, 0) + n
        self.entries.append((reason, src, dst, n))
        self.totals[reason] = self.totals.get(reason, 0) + n

    def reset_net(self) -> None:
        self.net = {}


class _Sentinel:
    """Content sentinel over one registered cache slot: while the
    slot's cache key holds still, a strided signature of its array
    content must hold still too (an in-place mutation of cached planes
    is exactly the corruption the cache keys cannot see)."""

    __slots__ = ("key", "sig")

    def __init__(self):
        self.key = None
        self.sig = None


def _content_sig(arrays) -> int:
    """Strided content signature over a list of numpy arrays — samples
    at most ~4096 elements per array so a 100k-row plane costs
    microseconds, not a full pass."""
    import numpy as np
    import zlib

    sig = 0
    for a in arrays:
        if a is None:
            sig = zlib.crc32(b"\x00", sig)
            continue
        if not isinstance(a, np.ndarray):
            # Device buffers / scalars: identity of the repr only (a
            # host sync to hash device bytes would be its own hot-path
            # bug).
            sig = zlib.crc32(str((type(a).__name__, getattr(
                a, "shape", None))).encode(), sig)
            continue
        flat = a.reshape(-1)
        stride = max(1, len(flat) // 4096)
        sample = np.ascontiguousarray(flat[::stride])
        sig = zlib.crc32(sample.tobytes(), sig)
        sig = zlib.crc32(str((a.shape, a.dtype.str)).encode(), sig)
    return sig


class Auditor:
    """Per-store runtime auditor; one instance per ``ClusterStore``.

    Writers (store lock held) record flows; ``end_cycle`` (cycle
    thread, store lock held) reconciles and samples; the ``/debug``
    handlers read snapshots.  All shared state below is guarded by
    ``_lock`` — the lock is never held around store/mirror access from
    the read side, so a slow scrape cannot stall the cycle."""

    def __init__(self, sample: Optional[int] = None,
                 ring_capacity: int = DEFAULT_RING,
                 enabled: Optional[bool] = None):
        self.enabled = audit_on() if enabled is None else bool(enabled)
        self.sample = sample_rate() if sample is None else max(int(sample), 1)
        self._lock = threading.Lock()
        self.ledger = ConservationLedger()  # guarded-by: _lock
        self._ring: deque = deque(maxlen=ring_capacity)  # guarded-by: _lock
        self.anomaly_counts: Dict[str, int] = {}  # guarded-by: _lock
        # Census anchor: per-class pod counts at the last reconcile
        # (None until the first), plus the mutation_seq observed then.
        self._census = None  # guarded-by: _lock
        self._census_mut = None  # guarded-by: _lock
        self._reanchor_reason: Optional[str] = None  # guarded-by: _lock
        # Cache sentinels by slot name.  # guarded-by: _lock
        self._sentinels: Dict[str, _Sentinel] = {}
        # Anomalies found mid-cycle (the derive-time aggregate audit),
        # drained into the cycle's end_cycle batch.  # guarded-by: _lock
        self._pending: List[Anomaly] = []
        # id() of the remote-solver client each wire sentinel slot
        # ("wire-mirror" single client, "wire-mirror-<i>" pool
        # replicas) last audited: a replaced client restarts its
        # generation, which must re-anchor, not read as a
        # regression.  # guarded-by: _lock
        self._wire_client: Dict[str, int] = {}
        # Accounting for the bench audit tails / /debug/health.
        self.cycles = 0  # guarded-by: _lock
        self.sampled_cycles = 0  # guarded-by: _lock
        self.reconciles = 0  # guarded-by: _lock
        self.census_skips = 0  # guarded-by: _lock
        self.overhead_ns = 0  # guarded-by: _lock
        self.overhead_max_ns = 0  # guarded-by: _lock
        # SLO tracker (obs/slo.py), attached by the store; internally
        # synchronized, so reads need no auditor lock.
        self.slo = None

    # -------------------------------------------------------------- flows

    def flow(self, reason: str, src: int, dst: int, n: int = 1) -> None:
        """Declare ``n`` pods transitioning ``src`` -> ``dst`` status
        classes (raw TaskStatus ints, or ADDED/GONE at the store edge)."""
        if not self.enabled:
            return
        with self._lock:
            self.ledger.record(reason, src, dst, n)

    def flow_added(self, status: int, reason: str = "pod-added") -> None:
        self.flow(reason, ADDED, status)

    def flow_removed(self, status: int,
                     reason: str = "pod-deleted") -> None:
        self.flow(reason, status, GONE)

    def flow_rows(self, p_status, rows, new_status: int,
                  reason: str) -> None:
        """Bulk transition declaration for the fast path's vectorized
        status writes: call with the OLD ``p_status`` column (before
        the write), the row index array, and the uniform new status."""
        if not self.enabled or not len(rows):
            return
        import numpy as np

        old = np.clip(p_status[rows].astype(np.int64), 0, CENSUS_W - 1)
        vals, counts = np.unique(old, return_counts=True)
        with self._lock:
            for v, c in zip(vals.tolist(), counts.tolist()):
                self.ledger.record(reason, int(v), int(new_status),
                                   int(c))

    def sampling_now(self) -> bool:
        """True when the cycle currently running will be sampled at its
        ``end_cycle`` — lets in-cycle audit hooks (the derive-time
        aggregate verify) share the same amortization schedule."""
        if not self.enabled:
            return False
        with self._lock:
            return (self.cycles + 1) % self.sample == 0

    def audit_aggregates_now(self, m) -> None:
        """Derive-time coherence audit of the persistent
        ``CycleAggregates`` planes — must run right after
        ``CycleAggregates.refresh``, the one point where the planes
        equal mirror truth by construction (by cycle end they
        legitimately lag the cycle's own commits until the next
        derive reconciles them)."""
        if not self.sampling_now():
            return
        t0 = time.perf_counter_ns()
        found: List[Anomaly] = []
        try:
            self._audit_aggregates(m, found)
        except Exception as e:
            found.append(Anomaly("audit-error", {
                "error": type(e).__name__, "message": str(e)[:200],
            }))
        dt = time.perf_counter_ns() - t0
        with self._lock:
            self.overhead_ns += dt
            if dt > self.overhead_max_ns:
                self.overhead_max_ns = dt
            if found:
                self._pending.extend(found)

    def report(self, anomaly: Anomaly) -> None:
        """Out-of-band anomaly intake (the lockdep probe, obs/lockdep.py):
        thread-safe, lands in the same ring/counters the cycle-end
        audits feed, bypassing ``enabled``/sampling — the reporter has
        its own kill switch and must not be silenced by audit
        sampling."""
        with self._lock:
            self._ring.append(anomaly)
            self.anomaly_counts[anomaly.reason] = (
                self.anomaly_counts.get(anomaly.reason, 0) + 1)
        from ..metrics import metrics

        metrics.audit_anomalies.inc(reason=anomaly.reason)

    def reanchor(self, why: str) -> None:
        """Void the next reconcile (bulk resync: the declared-flow
        model can no longer match; re-anchor the census instead of
        reporting a phantom mismatch)."""
        if not self.enabled:
            return
        with self._lock:
            self._reanchor_reason = why

    def set_enabled(self, flag: bool) -> None:
        """Flip the auditor at runtime (the bench overhead A/B).
        Re-enabling re-anchors: mutations while disabled recorded no
        flows, so the first reconcile back must not compare."""
        flag = bool(flag)
        if flag and not self.enabled:
            self.enabled = True
            self.reanchor("re-enabled")
        else:
            self.enabled = flag

    # -------------------------------------------------------------- cycle

    def end_cycle(self, cyc, duration_s: float,
                  err: Optional[BaseException] = None) -> List[Anomaly]:
        """Run the cycle-end audits; returns (and retains) anomalies.
        Called by the cycle thread with the store lock held."""
        if not self.enabled:
            return []
        t0 = time.perf_counter_ns()
        with self._lock:
            self.cycles += 1
            n_cycle = self.cycles
        sampled = (n_cycle % self.sample == 0)
        with self._lock:
            anomalies: List[Anomaly] = self._pending
            self._pending = []
        mode = "reconciled"
        try:
            mode = self._reconcile(cyc.store, cyc.m, anomalies,
                                   force=sampled, failed=err is not None)
            self._audit_ledger(cyc.store, anomalies)
            self._audit_shards(cyc.store, anomalies)
            if sampled:
                self._audit_encode_cache(cyc.store, anomalies)
                self._audit_devincr(cyc.store, anomalies)
                self._audit_wire(cyc.store, anomalies)
            if self.slo is not None:
                idle = cyc.stats.get("dispatched_solve_id") is None
                for breach in self.slo.observe(duration_s, cyc.lanes,
                                               idle=idle):
                    anomalies.append(Anomaly(
                        "slo-budget-exceeded", breach))
        except Exception as e:  # the auditor must never fail the cycle
            anomalies.append(Anomaly("audit-error", {
                "error": type(e).__name__, "message": str(e)[:200],
            }))
        dt = time.perf_counter_ns() - t0
        with self._lock:
            if sampled:
                self.sampled_cycles += 1
            self.overhead_ns += dt
            if dt > self.overhead_max_ns:
                self.overhead_max_ns = dt
            for a in anomalies:
                self._ring.append(a)
                self.anomaly_counts[a.reason] = (
                    self.anomaly_counts.get(a.reason, 0) + 1)
        from ..metrics import metrics

        metrics.audit_cycles.inc(
            mode="sampled" if sampled else mode)
        for a in anomalies:
            metrics.audit_anomalies.inc(reason=a.reason)
        return anomalies

    # -------------------------------------------------- conservation audit

    def _census_now(self, m):
        import numpy as np

        Pn = len(m.p_uid)
        alive = m.p_alive[:Pn]
        st = m.p_status[:Pn][alive]
        return np.bincount(
            np.clip(st.astype(np.int64), 0, CENSUS_W - 1),
            minlength=CENSUS_W,
        )

    def _reconcile(self, store, m, anomalies: List[Anomaly],
                   force: bool, failed: bool) -> str:
        import numpy as np

        with self._lock:
            net = dict(self.ledger.net)
            anchor = self._census
            anchor_mut = self._census_mut
            reanchor = self._reanchor_reason
        mut = m.mutation_seq
        if (anchor is not None and reanchor is None and not net
                and mut == anchor_mut and not force and not failed):
            # Nothing declared, nothing stamped: the census cannot have
            # moved unless a writer bypassed BOTH bookkeeping layers —
            # the sampled cycles still force the census, bounding that
            # detection latency to one sample interval.
            with self._lock:
                self.census_skips += 1
            return "skipped"
        census = self._census_now(m)
        if anchor is not None and reanchor is None and not failed:
            expected = anchor.copy()
            for cls, d in net.items():
                if 0 <= cls < CENSUS_W:
                    expected[cls] += d
            if not np.array_equal(expected, census):
                diff = {}
                for cls in np.flatnonzero(expected != census).tolist():
                    diff[str(cls)] = {
                        "expected": int(expected[cls]),
                        "actual": int(census[cls]),
                    }
                anomalies.append(Anomaly("conservation-mismatch", {
                    "classes": diff,
                    "flows": {k: int(v) for k, v in net.items()},
                }))
        with self._lock:
            self._census = census
            self._census_mut = mut
            self._reanchor_reason = None
            self.ledger.reset_net()
            self.reconciles += 1
        return "reconciled"

    # ------------------------------------------------------- ledger audit

    def _audit_ledger(self, store, anomalies: List[Anomaly]) -> None:
        """Zero-lost-pods: every migration entry whose victim pod is
        gone must have produced its restore (actions/rebalance.py
        ``MigrationLedger.pod_deleted``); an entry stranded without one
        is a pod the eviction machinery lost."""
        ledger = getattr(store, "migrations", None)
        if ledger is None:
            return
        for uid, entry in list(ledger.entries.items()):
            if uid not in store.pods and entry.restored_uid is None:
                anomalies.append(Anomaly("ledger-restore-lost", {
                    "victim": uid,
                    "group": entry.group_uid,
                    "action": entry.action,
                }))

    # ---------------------------------------------------- cross-shard census

    def _audit_shards(self, store, anomalies: List[Anomaly]) -> None:
        """Sharded-control-plane ownership census (shard.py, ISSUE 16):
        every queue must resolve to exactly one IN-RANGE owning shard —
        a steal override naming a shard outside [0, n_shards) would
        orphan its queue (no cycle would ever schedule it), which the
        conservation reconcile above cannot see (an unscheduled queue
        moves no pods).  Runs under the store lock (end_cycle's calling
        contract), which is also the lock guarding the table."""
        table = getattr(store, "shard_table", None)
        if table is None:
            return
        n = table.n_shards
        bad = {
            name: int(owner)
            for name, owner in table._overrides.items()
            if not 0 <= int(owner) < n
        }
        if bad:
            anomalies.append(Anomaly("shard-ownership-violation", {
                "n_shards": n,
                "overrides": bad,
            }))

    # -------------------------------------------------- coherence samples

    def _audit_aggregates(self, m, anomalies: List[Anomaly]) -> None:
        """Sampled re-verify of the persistent CycleAggregates planes
        against a from-scratch ``_build_aggregates`` — the same check
        ``VOLCANO_TPU_INCR_VERIFY=1`` runs every delta derive, here
        amortized to the sample rate and always on."""
        aggr = getattr(m, "_cycle_aggr", None)
        if aggr is None or aggr.n_used is None:
            return
        Pn, Nn = len(m.p_uid), len(m.n_name)
        R = aggr.n_used.shape[1]
        if aggr.key != (m.node_liveness_gen, m.compact_gen, Nn, R) \
                or aggr.Pn != Pn:
            # Planes are stale by key (next derive rebuilds them):
            # nothing coherent to check against.
            return
        try:
            aggr._verify(m, Pn, Nn, R, m.n_alive[:Nn])
        except AssertionError as e:
            anomalies.append(Anomaly("aggregate-divergence", {
                "message": str(e)[:200],
            }))

    def _sentinel_check(self, slot: str, key, arrays,
                        monotonic_key: bool = False) -> Optional[dict]:
        """Advance one slot's sentinel; returns a violation detail dict
        (the caller wraps it in the slot's catalogued Anomaly reason)
        or None when the contract held."""
        with self._lock:
            s = self._sentinels.get(slot)
            if s is None:
                s = self._sentinels[slot] = _Sentinel()
            prev_key, prev_sig = s.key, s.sig
        detail = None
        if monotonic_key and prev_key is not None and key is not None \
                and key < prev_key:
            detail = {
                "slot": slot, "kind": "key-regressed",
                "prev": str(prev_key), "now": str(key),
            }
            sig = _content_sig(arrays) if arrays is not None else None
        elif key is not None and key == prev_key:
            sig = _content_sig(arrays) if arrays is not None else None
            if prev_sig is not None and sig is not None \
                    and sig != prev_sig:
                detail = {
                    "slot": slot, "kind": "content-changed-under-key",
                    "key": str(key),
                }
        else:
            sig = _content_sig(arrays) if arrays is not None else None
        with self._lock:
            s.key = key
            s.sig = sig
        return detail

    def _audit_encode_cache(self, store,
                            anomalies: List[Anomaly]) -> None:
        cached = getattr(store, "_encode_cache", None)
        if not cached:
            with self._lock:
                self._sentinels.pop("encode", None)
            return
        arrays = [cached.get("task_rows"), cached.get("pid"),
                  cached.get("term_key")]
        arrays.extend(cached.get("members") or [])
        detail = self._sentinel_check(
            "encode", (cached.get("key"), cached.get("gen")), arrays)
        if detail is not None:
            anomalies.append(Anomaly("cache-content-mutated", detail))

    def _audit_devincr(self, store, anomalies: List[Anomaly]) -> None:
        dvc = getattr(store, "_devincr_cache", None)
        if dvc is None or dvc._static is None:
            with self._lock:
                self._sentinels.pop("devincr-static", None)
            return
        detail = self._sentinel_check(
            "devincr-static", dvc._static_key, list(dvc._static))
        if detail is not None:
            anomalies.append(Anomaly("cache-content-mutated", detail))

    def _audit_wire(self, store, anomalies: List[Anomaly]) -> None:
        """Client-side wire-mirror invariants (solver_service protocol
        v2): the frame generation only ever grows, and the private
        mirror copies may only change when the generation does — an
        in-place mutation under a held generation means future delta
        frames silently diverge the child's solve inputs.  A solver
        POOL (ISSUE 15) is audited per replica — every member keeps
        its own generation'd mirror, each under its own sentinel slot
        (``wire-mirror-<i>``), so a divergence names the replica."""
        client = getattr(store, "remote_solver", None)
        if client is None:
            with self._lock:
                for slot in [s for s in self._sentinels
                             if s.startswith("wire-mirror")]:
                    self._sentinels.pop(slot, None)
                self._wire_client.clear()
            return
        replicas = getattr(client, "replicas", None)
        if replicas is not None:
            for r in replicas:
                self._audit_wire_client(
                    r.client, f"wire-mirror-{r.index}", anomalies,
                    replica=r.index)
            return
        self._audit_wire_client(client, "wire-mirror", anomalies)

    def _audit_wire_client(self, client, slot: str,
                           anomalies: List[Anomaly],
                           replica: Optional[int] = None) -> None:
        if getattr(client, "_wire", None) is None:
            with self._lock:
                self._sentinels.pop(slot, None)
                self._wire_client.pop(slot, None)
            return
        with self._lock:
            if self._wire_client.get(slot) != id(client):
                # A replaced client (solver failover, endpoint
                # reconfiguration) legitimately restarts its
                # generation at 0 — re-anchor, don't report a
                # regression that never happened.
                self._sentinels.pop(slot, None)
                self._wire_client[slot] = id(client)
        w = client._wire
        arrays = w.arrays if w.arrays is not None else None
        detail = self._sentinel_check(
            slot, int(client._gen), arrays, monotonic_key=True)
        if detail is not None:
            if replica is not None:
                detail["replica"] = replica
            anomalies.append(Anomaly("wire-mirror-divergence", detail))

    # ------------------------------------------------------------- reads

    def anomalies(self, n: Optional[int] = None) -> List[Anomaly]:
        with self._lock:
            ring = list(self._ring)
        if n is None:
            return ring
        n = int(n)
        return ring[-n:] if n > 0 else []

    def total_anomalies(self) -> int:
        with self._lock:
            return sum(self.anomaly_counts.values())

    def audit_stats(self) -> dict:
        """Bench tail block: sampled cycles + measured overhead."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "sample_every": self.sample,
                "cycles": self.cycles,
                "sampled_cycles": self.sampled_cycles,
                "reconciles": self.reconciles,
                "census_skips": self.census_skips,
                "overhead_ms": round(self.overhead_ns / 1e6, 3),
                "overhead_max_ms": round(self.overhead_max_ns / 1e6, 3),
                "anomalies": sum(self.anomaly_counts.values()),
            }

    def health(self) -> dict:
        """The ``/debug/health`` body: audit verdict, armed verifiers,
        SLO state, anomaly summary.  Reads only auditor/SLO state under
        their own locks — never the store lock, so a scrape can never
        block the cycle thread."""
        with self._lock:
            counts = dict(self.anomaly_counts)
            last = self._ring[-1].to_dict() if self._ring else None
            stats = {
                "enabled": self.enabled,
                "sample_every": self.sample,
                "cycles": self.cycles,
                "sampled_cycles": self.sampled_cycles,
                "reconciles": self.reconciles,
                "census_skips": self.census_skips,
                "overhead_ms": round(self.overhead_ns / 1e6, 3),
            }
            flow_totals = dict(self.ledger.totals)
        n_anom = sum(counts.values())
        body = {
            "status": "ok" if n_anom == 0 else "anomalous",
            "anomalies_total": n_anom,
            "anomalies_by_reason": counts,
            "last_anomaly": last,
            "audit": stats,
            "flow_totals": flow_totals,
            "verifiers": armed_verifiers(),
        }
        if self.slo is not None:
            body["slo"] = self.slo.snapshot()
        return body


def armed_verifiers() -> Dict[str, object]:
    """Which runtime verification layers are armed right now — the
    one documented knob family (docs/tuning.md "Runtime verification"):
    per-lane all-or-nothing verify knobs vs the always-on sampled
    audits this module provides."""
    return {
        "host_incr_verify": os.environ.get(
            "VOLCANO_TPU_INCR_VERIFY", "0") == "1",
        "audit": audit_on(),
        "audit_sample_every": sample_rate(),
    }
