"""volcano-tpu: a TPU-native batch scheduling framework.

A ground-up rebuild of the capabilities of Volcano (gang scheduling,
fair-share queues, preemption/reclaim, job lifecycle management) whose
per-cycle allocate/preempt hot loops run as jitted JAX/XLA kernels over dense
cluster arrays on TPU, instead of goroutine-parallel object loops.

See SURVEY.md at the repo root for the structural analysis of the reference
(`/root/reference`, volcano.sh v0.4) this framework is built to match.
"""

__version__ = "0.1.0"
