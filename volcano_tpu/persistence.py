"""Store checkpoint / restore.

The reference keeps all durable state in the API server (etcd) and
rebuilds in-memory caches from informers on restart (``cache.Run`` +
``WaitForCacheSync``, ``pkg/scheduler/cache/cache.go:376-417``); there is
no separate checkpoint subsystem (SURVEY.md section 5.4).  The rebuild's
store is its own system of record, so durability = serializing the spec
objects and replaying them through the event API on load — the informer
resync, replayed from a file instead of a watch stream.

Spec objects are persisted (pods, pod groups, queues, nodes, priority
classes, namespace weights, batch jobs, commands, config maps, secrets,
services, network policies) plus PVC claim records — the one entry with
durable STATUS (phase + provisioned node): claims bind durably in the
reference too (PV controller state in etcd), and replay cannot rebuild
a placement the scheduler chose.  Every derived structure
(JobInfo/NodeInfo, the array mirror, controller caches, the
volume-carrying-pod counter) rebuilds through the normal mutation path.
"""

from __future__ import annotations

import copy
import os
import pickle
import tempfile
from typing import Optional

from .cache import ClusterStore

FORMAT_VERSION = 1

# Derived caches attached to spec objects (mirror feature blobs, resource
# caches).  Their interned indices are only valid for the store that
# created them, so they never enter a checkpoint.
_CACHE_ATTRS = ("_mirror_feat", "_req_cache", "_init_req_cache",
                "_minres_vec")


def _clean(obj):
    o = copy.copy(obj)
    d = getattr(o, "__dict__", None)
    if d is not None:
        for attr in _CACHE_ATTRS:
            d.pop(attr, None)
    return o


def save_store(store: ClusterStore, path: str) -> None:
    """Atomically write a point-in-time snapshot of the store's specs."""
    with store._lock:
        payload = {
            "version": FORMAT_VERSION,
            "nodes": [
                ni.node for ni in store.nodes.values() if ni.node is not None
            ],
            "queues": list(store.raw_queues.values()),
            "pod_groups": [_clean(pg) for pg in store.pod_groups.values()],
            "pods": [_clean(p) for p in store.pods.values()],
            "priority_classes": list(store.priority_classes.values()),
            "namespace_weights": dict(store.namespace_weights),
            "batch_jobs": list(store.batch_jobs.values()),
            "commands": list(store.commands.values()),
            "config_maps": dict(store.config_maps),
            "secrets": dict(store.secrets),
            "services": dict(store.services),
            "network_policies": dict(store.network_policies),
            "pvcs": dict(store.pvcs),
        }
        # Serialize while still holding the lock: the payload holds live
        # object references that scheduler/controller threads mutate.
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".vctpu-ckpt-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_store(path: str, store: Optional[ClusterStore] = None) -> ClusterStore:
    """Rehydrate a store by replaying the snapshot through the event API
    (the informer-replay analog — derived state rebuilds naturally)."""
    with open(path, "rb") as f:
        payload = pickle.load(f)
    if payload.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"unsupported checkpoint version {payload.get('version')!r}"
        )
    store = store or ClusterStore()
    for node in payload["nodes"]:
        store.add_node(node)
    for queue in payload["queues"]:
        store.add_queue(queue)
    for pc in payload["priority_classes"]:
        store.add_priority_class(pc)
    for pg in payload["pod_groups"]:
        store.add_pod_group(pg)
    for pod in payload["pods"]:
        # Replayed pods carry stale feature-cache attrs only if the same
        # object was pickled with them; the mirror recomputes as needed.
        store.add_pod(pod)
    with store._lock:
        store.namespace_weights.update(payload["namespace_weights"])
        for job in payload["batch_jobs"]:
            store.batch_jobs[job.key] = job
        for cmd in payload["commands"]:
            store.commands[cmd.name] = cmd
        store.config_maps.update(payload["config_maps"])
        store.secrets.update(payload["secrets"])
        store.services.update(payload["services"])
        # Added after the initial format; absent in older checkpoints.
        store.network_policies.update(payload.get("network_policies", {}))
        store.pvcs.update(payload.get("pvcs", {}))
    return store
