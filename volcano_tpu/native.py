"""ctypes bridge to the native snapshot serializer (csrc/vcsnap.cc).

The C++ library owns the hot marshalling loops of the snapshot encoder —
CSR bitset packing, CSR resource-slot scatter, padded row gather, and the
epsilon LessEqual row check (resource_info.go:286-320).  When the shared
library is absent it is built on first use with g++ (cached), and if that
fails every entry point falls back to a vectorized NumPy implementation
with identical semantics (cross-checked by tests/test_native.py).

Set VOLCANO_TPU_NO_NATIVE=1 to force the NumPy fallback;
VOLCANO_TPU_VCSNAP=/path/to/libvcsnap.so to use a prebuilt library (e.g.
the ASAN build from `make -C csrc asan`).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger(__name__)

_CSRC = Path(__file__).resolve().parent.parent / "csrc"
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.vcsnap_version.restype = ctypes.c_int
    lib.vcsnap_pack_bits.argtypes = [
        _i32p, _i64p, ctypes.c_int64, ctypes.c_int32, _u32p,
    ]
    lib.vcsnap_scatter_f32.argtypes = [
        _i32p, _f32p, _i64p, ctypes.c_int64, ctypes.c_int32, _f32p,
    ]
    lib.vcsnap_gather_rows_f32.argtypes = [
        _f32p, _i32p, ctypes.c_int64, ctypes.c_int32, _f32p,
    ]
    lib.vcsnap_less_equal.argtypes = [
        _f32p, _f32p, _f32p, _u8p, ctypes.c_int64, ctypes.c_int32, _u8p,
    ]
    # Wire-frame codec (remote-solver snapshot bridge, cache/snapwire.py).
    lib.vcsnap_frame_bytes.restype = ctypes.c_int64
    lib.vcsnap_frame_bytes.argtypes = [
        _u8p, _i64p, ctypes.c_int32, ctypes.c_int64,
    ]
    lib.vcsnap_frame_pack.argtypes = [
        _u8p, _u8p, _i64p, _i64p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)), ctypes.c_int32,
        _u8p, ctypes.c_int64, _u8p,
    ]
    lib.vcsnap_frame_info.restype = ctypes.c_int32
    lib.vcsnap_frame_info.argtypes = [
        _u8p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.vcsnap_frame_unpack.restype = ctypes.c_int32
    lib.vcsnap_frame_unpack.argtypes = [
        _u8p, ctypes.c_int64, _u8p, _u8p, _i64p, _i64p, _i64p,
    ]
    # Delta records (protocol v2 remote-solver frames, ISSUE 10).
    lib.vcsnap_delta_check.restype = ctypes.c_int64
    lib.vcsnap_delta_check.argtypes = [
        _i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.vcsnap_delta_apply.restype = ctypes.c_int32
    lib.vcsnap_delta_apply.argtypes = [
        _u8p, ctypes.c_int64, ctypes.c_int64, _i64p, ctypes.c_int64,
        _u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
    ]
    # Reclaim engine: all stable pointers are captured once into a C-side
    # context; the hot per-reclaimer call takes raw addresses (c_void_p)
    # to keep ctypes marshalling off the 20k-calls-per-cycle path.
    vp = ctypes.c_void_p
    ll = ctypes.c_longlong
    lib.vcreclaim_ctx_new.restype = vp
    lib.vcreclaim_ctx_new.argtypes = (
        [vp] * 20 + [vp, ll] + [vp] * 4 + [ll, ll, ll, ll]
        # batch-mode tail: n_pipelined n_ntasks n_maxtasks pipe_node
        # j_cnt_pending j_waiting j_version q_version Qn j_prio j_rank
        # p_node total_res job_order job_order_len reclaim_gated
        + [vp] * 8 + [ll] + [vp] * 5 + [ll, ll]
    )
    lib.vcreclaim_ctx_free.argtypes = [vp]
    lib.vcreclaim_step.restype = ll
    lib.vcreclaim_step.argtypes = [
        vp, ll, ll,  # ctx prow qid
        vp,  # cursor
        vp, vp, vp, vp,  # anym feas stat slots
        vp, vp, ll,  # out_evicted out_n max
    ]
    lib.vcreclaim_drive_mq.restype = ll
    lib.vcreclaim_drive_mq.argtypes = [
        vp, ll,  # ctx has_pred
        vp, ll,  # qs_ids n_queues
        vp, vp, vp, ll,  # q_create q_uid_rank q_named has_prop
        vp, vp,  # q_overused out_q_dropped
        vp, ll, vp,  # job_ids n_jobs job_qslot
        vp, vp, vp,  # task_ptr task_rows task_cursor
        vp,  # row_maskidx
        ll,  # n_masks
        vp, vp, vp, vp, vp,  # anym feas stat slots initreq ptr arrays
        vp,  # mask_qids
        vp,  # mask_cursors
        vp, vp, ll,  # out_evicted out_n max_ev
        vp, vp, vp,  # out_pipe_rows out_pipe_nodes out_n_pipe
        vp, vp, ll,  # out_touched out_n_touched max_touched
        vp,  # out_yield_job
        vp,  # out_job_dropped
    ]
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("VOLCANO_TPU_NO_NATIVE"):
            return None
        override = os.environ.get("VOLCANO_TPU_VCSNAP")
        candidates = [Path(override)] if override else []
        candidates.append(_CSRC / "libvcsnap.so")
        for path in candidates:
            if path.is_file():
                try:
                    _LIB = _bind(ctypes.CDLL(str(path)))
                    return _LIB
                except (OSError, AttributeError) as err:
                    # AttributeError: stale prebuilt library missing a
                    # newer symbol — fall through to the rebuild.
                    _LIB = None
                    log.warning("vcsnap load failed (%s): %s", path, err)
        # Build on first use.
        try:
            subprocess.run(
                ["make", "-s", "-C", str(_CSRC)],
                check=True, capture_output=True, timeout=120,
            )
            _LIB = _bind(ctypes.CDLL(str(_CSRC / "libvcsnap.so")))
            log.info("built native vcsnap serializer")
        except (OSError, AttributeError, subprocess.SubprocessError) as err:
            _LIB = None
            log.warning("vcsnap build failed, using NumPy fallback: %s", err)
        return _LIB


def native_available() -> bool:
    return _load() is not None


def lib_or_none() -> Optional[ctypes.CDLL]:
    """The bound native library, or None (NumPy fallbacks apply)."""
    return _load()


# --------------------------------------------------------------------- API


def _csr(indices, offsets) -> Tuple[np.ndarray, np.ndarray]:
    idx = np.ascontiguousarray(indices, np.int32)
    off = np.ascontiguousarray(offsets, np.int64)
    return idx, off


def pack_bits_rows(indices, offsets, rows: int, words: int) -> np.ndarray:
    """CSR -> [rows, words] uint32 bitsets."""
    idx, off = _csr(indices, offsets)
    out = np.zeros((rows, words), np.uint32)
    lib = _load()
    if lib is not None and rows:
        lib.vcsnap_pack_bits(idx, off, rows, words, out)
        return out
    if len(idx):
        counts = np.diff(off)
        row_of = np.repeat(np.arange(rows, dtype=np.int64), counts)
        valid = (idx >= 0) & (idx < words * 32)
        r, b = row_of[valid], idx[valid].astype(np.int64)
        np.bitwise_or.at(out, (r, b >> 5), (1 << (b & 31)).astype(np.uint32))
    return out


def scatter_rows_f32(slots, values, offsets, rows: int, width: int) -> np.ndarray:
    """CSR (slot, value) pairs -> [rows, width] float32."""
    slot = np.ascontiguousarray(slots, np.int32)
    val = np.ascontiguousarray(values, np.float32)
    off = np.ascontiguousarray(offsets, np.int64)
    out = np.zeros((rows, width), np.float32)
    lib = _load()
    if lib is not None and rows:
        lib.vcsnap_scatter_f32(slot, val, off, rows, width, out)
        return out
    if len(slot):
        counts = np.diff(off)
        row_of = np.repeat(np.arange(rows, dtype=np.int64), counts)
        valid = (slot >= 0) & (slot < width)
        out[row_of[valid], slot[valid]] = val[valid]
    return out


def gather_rows_f32(src: np.ndarray, order, rows: int) -> np.ndarray:
    """out[i] = src[order[i]] (order < 0 -> zero row), padded to rows."""
    src = np.ascontiguousarray(src, np.float32)
    order = np.ascontiguousarray(order, np.int32)
    if len(order) < rows:  # short order rows are padding (-1 = zero row)
        order = np.concatenate(
            [order, np.full((rows - len(order),), -1, np.int32)]
        )
    width = src.shape[1] if src.ndim == 2 else 1
    out = np.zeros((rows, width), np.float32)
    lib = _load()
    if lib is not None and rows:
        lib.vcsnap_gather_rows_f32(src.reshape(-1), order, rows, width, out)
        return out
    n = min(rows, len(order))
    sel = order[:n]
    ok = sel >= 0
    out[np.arange(n)[ok]] = src[sel[ok]]
    return out


def less_equal_rows(l: np.ndarray, rhs: np.ndarray, eps: np.ndarray,
                    scalar_slot: np.ndarray) -> np.ndarray:
    """Epsilon LessEqual of each row of ``l`` against the single row
    ``rhs`` -> [rows] bool (host-side fit checks at replay/commit time)."""
    l = np.ascontiguousarray(l, np.float32)
    rhs = np.ascontiguousarray(rhs, np.float32)
    eps = np.ascontiguousarray(eps, np.float32)
    ss = np.ascontiguousarray(np.asarray(scalar_slot, bool).view(np.uint8))
    rows = l.shape[0]
    lib = _load()
    if lib is not None and rows:
        out = np.zeros((rows,), np.uint8)
        lib.vcsnap_less_equal(l, rhs, eps, ss, rows, l.shape[1], out)
        return out.astype(bool)
    per = (l < rhs[None, :]) | (np.abs(l - rhs[None, :]) < eps[None, :])
    per |= (np.asarray(scalar_slot, bool)[None, :] & (l <= eps[None, :]))
    return np.all(per, axis=-1)


def reclaim_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library with ``vcreclaim_step`` bound, or None
    (caller falls back to the Python walk in fastpath_evict)."""
    lib = _load()
    if lib is None or not hasattr(lib, "vcreclaim_step"):
        return None
    return lib
