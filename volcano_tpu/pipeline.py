"""Double-buffered scheduler sessions: the in-flight solve handle.

The pipelined cycle (ISSUE 1; Gavel, arxiv 2008.09213 — overlapping the
optimizer solve with state ingestion and commit is where accelerator-
batched schedulers get their throughput) dispatches the device solve for
session N WITHOUT waiting for the result; the device round trip then
runs concurrently with cycle N's close/enqueue and cycle N+1's
derive/order/encode host lanes.  The assignment vectors are fetched and
committed at the TOP of cycle N+1, after a staleness guard re-validates
them against store mutations that landed during the overlap
(``fastpath.FastCycle._commit_inflight``).

``InflightSolve`` is the handle the fast path parks on the store
(``store._inflight_solve``) between the two cycles.  Two payload kinds:

- ``"local"``: a jax ``AllocResult`` whose arrays are still device
  futures (``copy_to_host_async`` already issued); ``fetch()`` is one
  batched ``jax.device_get``.  Covers the single-process and mesh paths.
- ``"remote"``: a ``solver_service.PendingSolve`` — frame N was sent,
  the reply has not been read; ``fetch()`` receives and decodes it.

Validity bookkeeping captured at dispatch time:

- ``mutation_seq``: the mirror's pod/node mutation counter.  Equality at
  fetch time proves nothing moved during the overlap, so the capacity
  re-validation is skipped wholesale (the steady-state case).
- ``epoch``: the mirror's node-table epoch.  A bump means node labels,
  taints, allocatable, or membership changed — the re-validation then
  drops rows whose pods carry node-sensitive constraints (selector,
  node-affinity terms, tolerations) since the solve saw stale planes.
- ``compact_gen``: pod rows are stable for a pod's lifetime (tombstones
  are never reused), so row indices survive every mutation EXCEPT a
  table compaction — a generation bump voids the whole result.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)


class InflightSolve:
    """A dispatched-but-uncommitted device solve (session N's result,
    consumed at the top of session N+1)."""

    __slots__ = (
        "kind", "payload", "solve_jobs", "task_rows", "req_gather",
        "mutation_seq", "epoch", "compact_gen", "n_nodes", "solve_id",
        "fallbacks", "dirty_seq", "devincr_token", "shard", "shard_seq",
    )

    def __init__(self, kind: str, payload, solve_jobs: List[int],
                 task_rows: np.ndarray, req_gather: Tuple,
                 mutation_seq: int, epoch: int, compact_gen: int,
                 n_nodes: int, solve_id: int = 0, dirty_seq: int = 0,
                 devincr_token=None, shard: Optional[int] = None,
                 shard_seq: Optional[Tuple[int, int]] = None):
        self.kind = kind
        self.payload = payload
        self.solve_jobs = solve_jobs
        self.task_rows = task_rows
        # (elem_rows, slot_idx, values) c_req gather over task_rows,
        # prepared at dispatch time so the commit needs no host gather.
        self.req_gather = req_gather
        self.mutation_seq = mutation_seq
        self.epoch = epoch
        self.compact_gen = compact_gen
        self.n_nodes = n_nodes
        # Flow id linking this dispatch's trace span (cycle N) to the
        # fetch/commit spans (cycle N+1); 0 = untracked.
        self.solve_id = solve_id
        # (exhausted, affinity-required) shortlist-fallback rescore
        # counts of the solve, populated by fetch(); the commit folds
        # them into the per-reason counter series.
        self.fallbacks = (0, 0)
        # The mirror's dirty-set event counter at dispatch (ISSUE 8):
        # the incremental derive and this guard must agree on what
        # "changed" means — a dirty_seq advance during the overlap
        # implies a mutation_seq advance (every marking writer also
        # bumps the mutation counter, or epoch/compact_gen), so
        # mutation_seq equality at fetch proves the dirty set recorded
        # no pod-state change either.  ``_commit_inflight`` asserts the
        # implication; tests/test_incremental.py churns it.
        self.dirty_seq = dirty_seq
        # Device-incremental solve-input token captured at dispatch
        # (ISSUE 9): the null-delta skip proof this dispatch would
        # anchor.  Carried on the handle so an abandoned or lost solve
        # demonstrably voids the proof (abandon_inflight below /
        # fastpath's lost-reply handling) — a skipped re-dispatch must
        # never stand in for a result nobody fetched.
        self.devincr_token = devincr_token
        # Sharded control plane (shard.py, ISSUE 16): the dispatching
        # shard's index (None on the single-scheduler path) and the
        # cross-shard gate token captured at dispatch —
        # (mirror.shard_commit_seq, ShardOwnershipTable.epoch).  An
        # advance of either component at fetch time means another
        # shard committed binds (or stole a queue) during the overlap;
        # the re-validation's competing-bind / capacity-taken voids are
        # then attributed as `cross-shard-conflict`.
        self.shard = shard
        self.shard_seq = shard_seq

    # ----------------------------------------------------------- lifecycle

    def fetch(self) -> np.ndarray:
        """Block on the remaining device/remote round trip; return the
        assignment vector ([P] int32, node row or -1) as numpy.  The
        two-phase shortlist-fallback counters ride the same batched
        fetch into ``self.fallbacks``."""
        if self.kind == "remote":
            res = self.payload.fetch()
            if res.fb_exhausted is not None:
                self.fallbacks = (int(res.fb_exhausted),
                                  int(res.fb_affinity))
            return np.asarray(res.assigned)
        import jax

        if self.payload.fb_exhausted is not None:
            assigned, fb_ex, fb_aff = jax.device_get(
                (self.payload.assigned, self.payload.fb_exhausted,
                 self.payload.fb_affinity)
            )
            self.fallbacks = (int(fb_ex), int(fb_aff))
        else:
            (assigned,) = jax.device_get((self.payload.assigned,))
        return np.asarray(assigned)

    def abandon(self) -> None:
        """Drop the pending result without committing it.  The solved
        pods are still Pending store-side, so nothing is lost — the next
        dispatched cycle simply re-places them."""
        if self.kind == "remote":
            try:
                self.payload.abandon()
            except Exception:  # pragma: no cover - best-effort teardown
                log.debug("in-flight remote solve abandon failed",
                          exc_info=True)
        # Local device futures just lose their last reference; the
        # runtime completes and frees them off-thread.
        self.payload = None


def take_inflight(store, shard: Optional[int] = None) -> Optional[InflightSolve]:
    """Pop the store's in-flight solve (None when no dispatch pending).
    ``shard`` selects a sharded cycle's own slot
    (``store._shard_inflight[shard]``); None is the default
    single-scheduler slot.

    The slots are lock-guarded: each cycle thread owns its own between
    dispatch and fetch, but ``store.close()`` and ``Scheduler.stop()``
    pop them from other threads (the RLock makes the cycle-thread
    re-entry free)."""
    with store._lock:
        if shard is None:
            inflight = store._inflight_solve
            if inflight is not None:
                store._inflight_solve = None
        else:
            inflight = getattr(store, "_shard_inflight", {}).pop(shard, None)
    return inflight


def _abandon_one(store, inflight: InflightSolve) -> None:
    log.info("abandoning in-flight solve of %d task rows",
             len(inflight.task_rows))
    # The abandoned solve's result is lost: void the null-delta skip
    # proof its dispatch anchored, or a restarted scheduler facing an
    # unchanged store would skip forever while the pods stay Pending.
    # ``_devincr_cache`` is a guarded store attribute (both callers
    # invoke this helper AFTER releasing the store lock).
    with store._lock:
        dvc = getattr(store, "_devincr_cache", None)
    if dvc is not None and inflight.devincr_token is not None:
        dvc.skip_token = None
    inflight.abandon()


def abandon_inflight(store, shard: Optional[int] = None) -> bool:
    """Drop pending dispatches (scheduler shutdown / restart: the
    solved pods stay Pending and re-place on the next cycle).
    ``shard=None`` drains the default slot AND every per-shard slot
    (store teardown); an integer drains only that shard's slot (one
    shard's Scheduler stopping must not void its siblings' solves).
    Returns True when at least one was abandoned."""
    if shard is not None:
        inflight = take_inflight(store, shard)
        if inflight is None:
            return False
        _abandon_one(store, inflight)
        return True
    pending: List[InflightSolve] = []
    with store._lock:
        if store._inflight_solve is not None:
            pending.append(store._inflight_solve)
            store._inflight_solve = None
        shard_slots = getattr(store, "_shard_inflight", None)
        if shard_slots:
            pending.extend(shard_slots.values())
            shard_slots.clear()
    for inflight in pending:
        _abandon_one(store, inflight)
    return bool(pending)


class InflightPlan:
    """A dispatched-but-uncommitted what-if solve (the plan of cycle N
    — rebalance, preempt or reclaim (``whatif.WhatIfPlan``) — committed
    or voided at the top of cycle N+1).

    The what-if ``solve_wave`` over the hypothetically drained cluster
    rides the same pipelining as the allocate dispatch: the device round
    trip overlaps the dispatching cycle's close and the next cycle's
    host lanes.  Unlike ``InflightSolve``, a stale plan commits NOTHING
    — a whole-cluster what-if has no per-row salvage (partial commit
    would evict victims whose proven outcome no longer holds), so any
    ``mutation_seq``/``epoch``/``compact_gen``/node-count drift voids
    it wholesale (``volcano_whatif_plans_total`` outcome=stale-voided)
    and the planner simply re-plans against fresh state next cycle.
    Nothing is lost either way: a plan only mutates the store at COMMIT
    time.
    """

    __slots__ = (
        "kind", "payload", "plan", "mutation_seq", "epoch",
        "compact_gen", "n_nodes", "plan_id",
    )

    def __init__(self, payload, plan, mutation_seq: int, epoch: int,
                 compact_gen: int, n_nodes: int, plan_id: int = 0,
                 kind: str = "local"):
        # "local": a jax AllocResult (copy_to_host_async already
        # issued).  "remote": a solver_pool.PoolPendingSolve — the
        # plan solve was offloaded to an idle pool replica (ISSUE 15)
        # and its reply is still unread.
        self.kind = kind
        self.payload = payload
        # whatif.WhatIfPlan (host-side wave bookkeeping).
        self.plan = plan
        self.mutation_seq = mutation_seq
        self.epoch = epoch
        self.compact_gen = compact_gen
        self.n_nodes = n_nodes
        self.plan_id = plan_id

    def fetch(self):
        """Block on the remaining round trip; returns (assigned [P],
        never_ready [J]) as numpy."""
        if self.kind == "remote":
            res = self.payload.fetch()
            return (np.asarray(res.assigned),
                    np.asarray(res.never_ready))
        import jax

        assigned, never_ready = jax.device_get(
            (self.payload.assigned, self.payload.never_ready)
        )
        return np.asarray(assigned), np.asarray(never_ready)

    def abandon(self) -> None:
        """Drop the pending plan without committing it (device futures
        lose their last reference — or, offloaded, the replica's
        connection resets its framing; nothing was mutated
        store-side)."""
        if self.kind == "remote" and self.payload is not None:
            try:
                self.payload.abandon()
            except Exception:  # pragma: no cover - best-effort teardown
                log.debug("in-flight plan abandon failed",
                          exc_info=True)
        self.payload = None


def take_inflight_plan(store) -> Optional[InflightPlan]:
    """Pop the store's in-flight rebalance plan (None when no plan is
    pending).  Same locking contract as ``take_inflight``."""
    with store._lock:
        inflight = getattr(store, "_inflight_plan", None)
        if inflight is not None:
            store._inflight_plan = None
    return inflight


def abandon_inflight_plan(store) -> bool:
    """Drop a pending rebalance plan, if any (shutdown / object-path
    fallback: plans mutate nothing until committed, so this is free).
    Returns True when one was abandoned."""
    inflight = take_inflight_plan(store)
    if inflight is None:
        return False
    log.info("abandoning in-flight rebalance plan of %d victims",
             len(inflight.plan.victim_rows))
    inflight.abandon()
    return True
