"""Solver replica pool: hedged dispatch, one-cycle failover, what-if
offload (ISSUE 15; ROADMAP item 5's scale-out control plane).

Protocol v2's per-connection generation'd wire mirrors (ISSUE 10) make
every solver connection self-contained: each ``RemoteSolver`` keeps a
private ``_WireCache`` and monotone frame generation, and the child
keeps the matching mirror + device-incremental context per connection —
so a *pool* of replicas needs no shared wire state at all.  Any replica
can serve any solve; deltas re-engage per replica after its first full
frame (reconnect -> full frame -> deltas is already the healed path the
endurance gate proves).

``SolverPool`` duck-types the ``RemoteSolver`` client surface the fast
path, bench, and auditor consume (``solve`` / ``solve_async`` / ``ping``
/ ``close`` / telemetry counters), so ``store.remote_solver`` may hold
either and the dispatch seams stay unchanged.  Three perf behaviors,
all kill-switched by ``VOLCANO_TPU_SOLVER_POOL`` (default 1 = exactly
the single-connection path — a pool of one adds no machinery to the
wire):

1. **Health-scored routing + one-cycle failover** — each replica keeps
   an EWMA of its fetch latency and a consecutive-failure counter; the
   dispatch target is the healthy replica with the lowest EWMA (lowest
   index tie-break, so fault-free pools route deterministically).  A
   dead replica's in-flight reply surfaces as the existing lost-reply
   path (``FastCycle._commit_inflight``: rows re-place, nothing lost)
   and the NEXT dispatch routes to a healthy replica, whose empty
   mirror makes the first frame full by construction — one cycle's
   re-place, no scheduler stall.  Failed replicas are re-probed with a
   doubling cooldown so a restarted child heals back into rotation.
2. **Hedged dispatch** (the tail-at-scale trick, arxiv 2008.09213's
   redundancy argument applied at the solve transport) — when the
   primary's reply exceeds its rolling p99 x
   ``VOLCANO_TPU_POOL_HEDGE_P99_MULT``, the IDENTICAL frame
   re-dispatches to a second replica and whichever valid reply lands
   first commits.  The byte-frozen frame comes from the dispatching
   replica's wire cache — the private copies of exactly what its child
   received, already paid for by the delta diff — so later in-place
   plane mutations cannot skew the duplicate and the hot path carries
   no extra copy.  Replies are deterministic for identical frames, so
   first-wins is safe; the loser's reply is drained off its connection
   later (never abandoned mid-stream, so its mirror stays coherent via
   ``ack_gen``).
3. **What-if offload** — ``whatif.dispatch_plan`` ships plan-proving
   solves (preempt / reclaim / rebalance) to an idle non-primary
   replica, overlapping the allocate lane instead of contending for
   the store's single inflight slot.  The staleness guard and
   ``InflightPlan`` commit semantics are unchanged; a lost plan reply
   voids the plan (it mutated nothing) and counts
   ``outcome="lost-reply"``.

Threading: every dispatch/fetch runs on the scheduler's cycle thread
(like ``RemoteSolver``); ``close()`` may race it from
``Scheduler.stop()``/test teardown, so the replica table's mutable
health state is guarded by the pool's own ``_lock`` (vclint LOCK_FILES
enforces the annotations below).  The lock is never held across socket
I/O — only across the bookkeeping reads/writes.
"""

from __future__ import annotations

import logging
import os
import select
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .metrics import metrics

log = logging.getLogger(__name__)

# Rolling fetch-latency window per replica (p99 of <= 64 samples is the
# max of the recent window — exactly the "slower than everything recent"
# signal hedging wants).
_LATENCY_WINDOW = 64
# Hedge only once the window carries enough signal.
_HEDGE_MIN_SAMPLES = 5
# EWMA smoothing for the routing score.
_EWMA_ALPHA = 0.2
# A failed replica is re-probed (one ping) after this many dispatches,
# doubling per consecutive failure so a permanently dead endpoint costs
# one cheap probe every 2^k dispatches, not one per cycle.
_PROBE_BASE = 8


def pool_size() -> int:
    """The pool kill switch (docs/tuning.md "Solver replica pool"):
    ``VOLCANO_TPU_SOLVER_POOL=<n>``, default 1 = the single-connection
    path (``service.make_solver_client`` then builds a plain
    ``RemoteSolver``, no pool object at all)."""
    try:
        return max(1, int(os.environ.get("VOLCANO_TPU_SOLVER_POOL", "1")))
    except ValueError:
        return 1


def hedge_p99_mult() -> float:
    """Hedge trigger: the in-flight reply must exceed (rolling p99 x
    this multiplier) before the frame re-dispatches to a second
    replica.  0 disables hedging."""
    try:
        return float(os.environ.get("VOLCANO_TPU_POOL_HEDGE_P99_MULT",
                                    "3.0"))
    except ValueError:
        return 3.0


def hedge_min_ms() -> float:
    """Floor on the hedge deadline: pipelined fetch waits are near zero
    in steady state, so a bare p99 multiple would hedge on scheduler
    jitter; the floor keeps hedges for genuine stragglers."""
    try:
        return float(os.environ.get("VOLCANO_TPU_POOL_HEDGE_MIN_MS",
                                    "25.0"))
    except ValueError:
        return 25.0


class _Replica:
    """One pool member: a ``RemoteSolver`` plus its health state.  All
    mutable fields below are guarded by the owning pool's ``_lock``
    (the client object itself synchronizes internally)."""

    __slots__ = ("index", "client", "ewma_ms", "window", "failures",
                 "since_fail", "busy", "draining", "probing")

    def __init__(self, index: int, client):
        self.index = index
        self.client = client
        self.ewma_ms = 0.0       # guarded-by: _lock
        self.window: List[float] = []  # guarded-by: _lock
        self.failures = 0        # guarded-by: _lock
        self.since_fail = 0      # guarded-by: _lock
        # An outstanding request (allocate pending, hedge, or what-if)
        # owns the connection: strict request/reply allows one.
        self.busy = False        # guarded-by: _lock
        # A hedge loser's unread reply parked for a later drain.
        self.draining = None     # guarded-by: _lock
        # A health probe is in flight on its daemon thread.
        self.probing = False     # guarded-by: _lock


class PoolPendingSolve:
    """A dispatched-but-unread pool solve (the ``InflightSolve`` payload
    for kind "remote").  ``fetch()`` adds the hedging leg on top of the
    plain ``PendingSolve`` receive; ``abandon()`` drops every leg.

    A hedge must re-dispatch the *identical* frame even if the
    scheduler mutated the encode planes in place during the overlap.
    The byte-frozen copy already exists: the dispatching replica's
    ``_WireCache`` holds private copies of exactly the bytes the child
    received (its delta-diff base), so the hedge rebuilds the frame
    from there at hedge time — no per-dispatch copy on the hot path.
    ``hedgeable`` is False when no hedge can ever fire (pool of one,
    hedging disabled); ``wave``/``devincr`` are the scalar dispatch
    params the rebuilt frame needs."""

    __slots__ = ("pool", "replica", "handle", "hedgeable", "wave",
                 "devincr", "kind")

    def __init__(self, pool: "SolverPool", replica: _Replica, handle,
                 hedgeable: bool = False, wave: Optional[int] = None,
                 devincr: Optional[dict] = None, kind: str = "primary"):
        self.pool = pool
        self.replica = replica
        self.handle = handle
        self.hedgeable = hedgeable
        self.wave = wave
        self.devincr = devincr
        self.kind = kind

    def fetch(self):
        return self.pool._fetch(self)

    def abandon(self) -> None:
        self.pool._abandon(self)


class SolverPool:
    """N ``RemoteSolver`` replicas behind one RemoteSolver-shaped
    client (see module docstring).  Construct with one address
    (replicated ``size`` times — N connections to one child still buy
    hedging and what-if offload, since the server threads per
    connection) or one address per replica (real failover)."""

    def __init__(self, addresses: Sequence[str],
                 size: Optional[int] = None, timeout: float = 300.0):
        from .solver_service import RemoteSolver

        addresses = list(addresses)
        if not addresses:
            raise ValueError("solver pool needs at least one address")
        n = max(size or len(addresses), len(addresses))
        while len(addresses) < n:
            addresses.append(addresses[-1])
        self._lock = threading.Lock()
        # The replica table itself is immutable after construction
        # (only each replica's health state mutates); readers may grab
        # the list reference without the lock.
        self.replicas: List[_Replica] = [
            _Replica(i, RemoteSolver(addr, timeout=timeout))
            for i, addr in enumerate(addresses)
        ]
        # Index of the replica serving the allocate stream (the frame
        # the per-replica devincr dirty superset is anchored on).
        self._primary = 0        # guarded-by: _lock
        # Replica that last received an anchored devincr frame: warm
        # tokens are only valid for it (any other replica's child
        # missed the dirty supersets since ITS last frame).
        self._devincr_owner: Optional[int] = None  # guarded-by: _lock
        # Telemetry (bench pool tails + flight recorder).
        self.hedge_dispatches = 0  # guarded-by: _lock
        self.hedge_wins = 0        # guarded-by: _lock
        self.failovers = 0         # guarded-by: _lock
        # Fetch info of the last completed/lost fetch, folded into the
        # cycle's flight record by FastCycle._commit_inflight.
        self.last_fetch_info: Optional[dict] = None  # guarded-by: _lock
        self.last_devincr_mode: Optional[str] = None
        self.last_frame_kind: Optional[str] = None
        from .obs.trace import null_tracer

        self._tracer = null_tracer()
        # Runtime lockdep (obs/lockdep.py): the pool is usually attached
        # to a store AFTER that store's construction-time walk, so it
        # arms itself.  No-op unless VOLCANO_TPU_LOCKDEP enabled it.
        from .obs.lockdep import attach

        attach(self)

    # ------------------------------------------------------- client shims

    @property
    def size(self) -> int:
        return len(self.replicas)

    @property
    def tracer(self):
        return self._tracer

    @tracer.setter
    def tracer(self, t) -> None:
        self._tracer = t
        for r in self.replicas:
            r.client.tracer = t

    def ping(self) -> dict:
        """Ping every replica; returns the first healthy pong.  A pool
        is built to serve degraded — a member that is down at startup
        is marked failed (the doubling-cooldown probe heals it into
        rotation later) instead of aborting the whole service the way
        the single-client path fail-fasts.  Only when EVERY address is
        unreachable does the last error propagate: that is the
        permanently-wrong-config case fail-fast exists for."""
        out = None
        last_err: Optional[BaseException] = None
        for r in self.replicas:
            try:
                pong = r.client.ping()
            except (OSError, ConnectionError, ValueError) as e:
                last_err = e
                self._mark_failure(r)
                log.warning(
                    "solver pool replica %d unreachable at startup "
                    "(%s); serving degraded until it heals", r.index,
                    type(e).__name__)
                continue
            if out is None:
                out = pong
        if out is None:
            raise last_err if last_err is not None else RuntimeError(
                "solver pool has no replicas")
        return out

    def close(self) -> None:
        for r in self.replicas:
            with self._lock:
                r.draining = None
                r.busy = False
            r.client.close()

    # Aggregated telemetry: the bench wire tails and BASELINE overhead
    # table read these off whatever store.remote_solver holds.
    @property
    def requests(self) -> int:
        return sum(r.client.requests for r in self.replicas)

    @property
    def bytes_out(self) -> int:
        return sum(r.client.bytes_out for r in self.replicas)

    @property
    def bytes_in(self) -> int:
        return sum(r.client.bytes_in for r in self.replicas)

    @property
    def frame_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {"full": 0, "delta": 0}
        for r in self.replicas:
            for k, v in r.client.frame_counts.items():
                out[k] = out.get(k, 0) + v
        return out

    @property
    def frame_bytes(self) -> Dict[str, int]:
        out: Dict[str, int] = {"full": 0, "delta": 0}
        for r in self.replicas:
            for k, v in r.client.frame_bytes.items():
                out[k] = out.get(k, 0) + v
        return out

    @property
    def wire_fallbacks(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.replicas:
            for k, v in r.client.wire_fallbacks.items():
                out[k] = out.get(k, 0) + v
        return out

    def per_replica_frames(self) -> List[Dict[str, int]]:
        """Per-replica frame counters (the bench pool tail's proof that
        deltas re-engaged on each member)."""
        return [dict(r.client.frame_counts) for r in self.replicas]

    def health_snapshot(self) -> dict:
        """The /debug/health "solver_pool" block: per-replica EWMA,
        failure counters, busy/draining flags + pool totals.  Reads
        only the pool's own lock — never store state."""
        with self._lock:
            return {
                "size": len(self.replicas),
                "primary": self._primary,
                "hedge_dispatches": self.hedge_dispatches,
                "hedge_wins": self.hedge_wins,
                "failovers": self.failovers,
                "replicas": [
                    {
                        "index": r.index,
                        "address": f"{r.client.host}:{r.client.port}",
                        "ewma_ms": round(r.ewma_ms, 3),
                        "consecutive_failures": r.failures,
                        "busy": r.busy,
                        "draining": r.draining is not None,
                        "frames": dict(r.client.frame_counts),
                    }
                    for r in self.replicas
                ],
            }

    # --------------------------------------------------------- health state

    def _score_gauge_locked(self) -> None:
        # holds: _lock
        for r in self.replicas:
            metrics.solver_pool_replica_health.set(
                1.0 / (1.0 + r.failures), replica=str(r.index))

    def _fold_latency_locked(self, replica: _Replica,
                             wait_ms: float) -> None:
        # holds: _lock
        replica.ewma_ms = (wait_ms if not replica.window
                           else (1 - _EWMA_ALPHA) * replica.ewma_ms
                           + _EWMA_ALPHA * wait_ms)
        replica.window.append(wait_ms)
        if len(replica.window) > _LATENCY_WINDOW:
            del replica.window[0]

    def _mark_success(self, replica: _Replica, wait_ms: float) -> None:
        with self._lock:
            replica.failures = 0
            replica.since_fail = 0
            self._fold_latency_locked(replica, wait_ms)
            self._score_gauge_locked()

    def _mark_failure(self, replica: _Replica) -> None:
        with self._lock:
            replica.failures += 1
            replica.since_fail = 0
            replica.busy = False
            replica.draining = None
            self._score_gauge_locked()

    def _note_latency(self, replica: _Replica, wait_ms: float) -> None:
        """Fold a latency sample into the routing state WITHOUT
        touching the failure counters.  Used for the hedge loser's
        still-in-flight primary: its reply took AT LEAST the elapsed
        wait (a lower bound — the true latency lands later, at drain
        time, untimed), and skipping the sample entirely is what lets
        a persistently-slow-but-not-erroring member keep its stale
        good EWMA and win ``_choose`` forever, paying the hedge
        deadline plus a duplicate solve every cycle."""
        with self._lock:
            self._fold_latency_locked(replica, wait_ms)

    def _p99_ms(self, replica: _Replica) -> Optional[float]:
        """Rolling p99 of the replica's HEALTHY latency class: samples
        past 4x the rolling median are trimmed before the percentile.
        Raw p99 would learn the stragglers (and the first compile
        spike) themselves, ratcheting the hedge deadline above the
        very tail it exists to cut — the classic hedged-request
        feedback loop; excluding known-anomalous samples from the
        estimator is the standard fix (The Tail at Scale).  A replica
        with a thin window (fresh primary after a failover) borrows
        the pool-wide union — replicas serve identical frames, so
        their samples are exchangeable and a failover must not open
        an unhedged window."""
        with self._lock:
            w = sorted(replica.window)
            if len(w) < _HEDGE_MIN_SAMPLES:
                w = sorted(
                    x for r in self.replicas for x in r.window)
        if len(w) < _HEDGE_MIN_SAMPLES:
            return None
        med = w[len(w) // 2]
        clean = [x for x in w if x <= med * 4] or w
        return clean[min(int(0.99 * (len(clean) - 1) + 0.5),
                         len(clean) - 1)]

    def _maybe_probe(self) -> None:
        """Re-probe failed replicas on a doubling cooldown so a
        restarted child heals back into rotation (reconnect -> full
        frame -> deltas re-engage, per replica).  The probe itself
        runs on a daemon thread: a black-holed endpoint (connect
        hangs rather than refusing) must cost the cycle thread
        NOTHING — a recurring 2 s dispatch stall every cooldown lap
        is exactly the p99 spike class the pool exists to cut.  At
        most one probe per replica is in flight (``probing``)."""
        probes = []
        with self._lock:
            for r in self.replicas:
                if r.failures <= 0 or r.probing:
                    continue
                r.since_fail += 1
                if r.since_fail >= _PROBE_BASE * (
                        2 ** min(r.failures - 1, 4)):
                    r.since_fail = 0
                    r.probing = True
                    probes.append(r)
        for r in probes:
            threading.Thread(target=self._probe_replica, args=(r,),
                             daemon=True).start()

    def _probe_replica(self, r: _Replica) -> None:
        """Bounded raw TCP probe, NOT a client ping: a black-holed
        endpoint must cost its probe thread 2 s, not the client's
        full solve timeout, and the probe must not perturb the
        client's own connection state (the next real dispatch
        performs the actual reconnect + full frame)."""
        import socket as _socket

        ok = False
        try:
            s = _socket.create_connection(
                (r.client.host, r.client.port), timeout=2.0)
            s.close()
            ok = True
        except OSError:
            pass
        with self._lock:
            r.probing = False
            if ok and r.failures > 0:
                r.failures = 0
                self._score_gauge_locked()
        if ok:
            log.info("solver pool replica %d healed (probe ok)",
                     r.index)

    def _choose(self, exclude: Tuple[int, ...] = ()) -> Optional[_Replica]:
        """Healthiest free replica: zero-failure members by lowest
        EWMA (index tie-break), else the least-failed member — the
        pool never refuses to dispatch while any replica exists."""
        with self._lock:
            free = [r for r in self.replicas
                    if r.index not in exclude
                    and not r.busy and r.draining is None]
            if not free:
                # Drainable members count as reachable: the caller
                # drains before dispatching.
                free = [r for r in self.replicas
                        if r.index not in exclude and not r.busy]
            if not free:
                return None
            healthy = [r for r in free if r.failures == 0]
            pick = min(healthy or free,
                       key=lambda r: (r.failures, r.ewma_ms, r.index))
            return pick

    # ----------------------------------------------------------- draining

    def _drain(self, replica: _Replica, block: bool) -> None:
        """Consume a hedge loser's parked reply so the connection's
        request/reply framing stays coherent (the decode also verifies
        ``ack_gen``, keeping the replica's wire mirror honest).  The
        reply itself is discarded — it solved a frame whose result
        already committed from the hedge winner."""
        with self._lock:
            handle = replica.draining
            if handle is None:
                return
            if not block and not replica.client.reply_ready(0.0):
                return
            replica.draining = None
        try:
            handle.fetch()
        except Exception:
            # The connection died with the stale reply; the client
            # already closed it (wire cache voided) — the replica's
            # next frame ships full.
            log.debug("pool drain of replica %d failed", replica.index,
                      exc_info=True)
            self._mark_failure(replica)

    def _drain_opportunistic(self) -> None:
        for r in self.replicas:
            self._drain(r, block=False)

    # ------------------------------------------------------------ dispatch

    def _hedge_frame_from_wire(self, client) -> Optional[tuple]:
        """Rebuild the dispatched frame's ``(solve_args, pid,
        profiles)`` from the dispatching replica's wire cache — the
        private byte copies of EXACTLY what its child received (the
        delta-diff base), unreachable by the scheduler's in-place plane
        mutations and stable while the solve is pending (the strict
        request/reply protocol admits no newer frame).  None when the
        cache is off (kill switch, v1 child): the hedge then simply
        does not fire — re-encoding from live planes could ship a
        DIFFERENT frame and break first-wins determinism."""
        w = getattr(client, "_wire", None)
        if w is None or w.arrays is None or w.spec is None:
            return None
        from .cache import snapwire as sw
        from .solver_service import _registry

        return sw.unflatten_tree(w.spec, list(w.arrays), _registry())

    def _strip_devincr(self, replica: _Replica,
                       devincr: Optional[dict]) -> Optional[dict]:
        """Warm-shortlist tokens are only valid for the replica whose
        child consumed every dirty superset since its last frame — the
        devincr owner.  Any other target full-re-ranks (static planes
        are content-keyed and stay valid everywhere)."""
        if devincr is None:
            return None
        with self._lock:
            owner = self._devincr_owner
        if owner is None or owner == replica.index:
            # No anchored frame anywhere yet (every child's caches are
            # empty — the tokens cannot hit) or this replica owns the
            # anchor: ship the manifest untouched.  The None case also
            # keeps a pool of one byte-identical to the single client.
            return devincr
        out = dict(devincr)
        out["warm_key"] = None
        out["dirty_nodes"] = None
        return out

    def _count_dispatch(self, replica: _Replica, kind: str) -> None:
        metrics.solver_pool_dispatch.inc(replica=str(replica.index),
                                         kind=kind)

    def _note_failover(self, chosen: _Replica) -> None:
        with self._lock:
            if chosen.index != self._primary:
                prev = self.replicas[self._primary]
                if prev.failures > 0:
                    self.failovers += 1
                    metrics.solver_pool_failover.inc()
                    log.warning(
                        "solver pool failover: replica %d -> %d",
                        prev.index, chosen.index)
                self._primary = chosen.index

    def _dispatch_with_failover(self, send, devincr: Optional[dict],
                                exclude: Tuple[int, ...] = (),
                                kind: str = "primary"):
        """The ONE dispatch loop every entry point routes through:
        probe failed members, opportunistically drain hedge losers,
        then try replicas healthiest-first — a send failure marks the
        member and moves on, so a dead child never stalls a cycle.
        ``send(replica, dv)`` performs the client call; returns
        ``(replica, send's result)`` or raises the last send error when
        every candidate failed."""
        self._maybe_probe()
        self._drain_opportunistic()
        tried: List[int] = list(exclude)
        last_err: Optional[BaseException] = None
        while True:
            replica = self._choose(exclude=tuple(tried))
            if replica is None:
                break
            self._drain(replica, block=True)
            dv = self._strip_devincr(replica, devincr)
            try:
                out = send(replica, dv)
            except (OSError, ConnectionError, ValueError) as e:
                last_err = e
                tried.append(replica.index)
                self._mark_failure(replica)
                log.warning(
                    "solver pool dispatch to replica %d failed (%s); "
                    "trying next replica", replica.index,
                    type(e).__name__)
                continue
            if kind == "primary":
                self._note_failover(replica)
            with self._lock:
                if dv is not None:
                    self._devincr_owner = replica.index
            self._count_dispatch(replica, kind)
            self.last_frame_kind = replica.client.last_frame_kind
            return replica, out
        raise last_err if last_err is not None else RuntimeError(
            "solver pool has no dispatchable replica")

    def solve_async(self, solve_args: Sequence, pid, profiles,
                    wave: Optional[int] = None,
                    devincr: Optional[dict] = None) -> PoolPendingSolve:
        """Pipelined dispatch on the healthiest replica; a send failure
        fails over to the next replica in the SAME cycle (the frame is
        rebuilt against that replica's own wire cache, full by
        construction after its reconnect)."""
        replica, handle = self._dispatch_with_failover(
            lambda r, dv: r.client.solve_async(
                solve_args, pid, profiles, wave=wave, devincr=dv),
            devincr)
        with self._lock:
            replica.busy = True
        hedgeable = len(self.replicas) > 1 and hedge_p99_mult() > 0
        return PoolPendingSolve(self, replica, handle,
                                hedgeable=hedgeable, wave=wave,
                                devincr=devincr)

    def solve(self, solve_args: Sequence, pid, profiles,
              wave: Optional[int] = None,
              devincr: Optional[dict] = None):
        """Synchronous round trip (the chunked / non-pipelined path):
        routed like ``solve_async``, no hedging (the caller is already
        blocking; failover still applies)."""
        cell = {}

        def send(r, dv):
            cell["t0"] = time.perf_counter()
            return r.client.solve(solve_args, pid, profiles,
                                  wave=wave, devincr=dv)

        replica, res = self._dispatch_with_failover(send, devincr)
        self._mark_success(replica,
                           (time.perf_counter() - cell["t0"]) * 1e3)
        self.last_devincr_mode = replica.client.last_devincr_mode
        return res

    # ------------------------------------------------------ what-if offload

    def whatif_replica_available(self) -> bool:
        """True when a healthy, idle, NON-primary replica can take a
        plan-proving solve without contending with the allocate lane
        (whatif.evict_device_on gates the engine on this)."""
        if len(self.replicas) < 2:
            return False
        with self._lock:
            primary = self._primary
            return any(
                r.index != primary and not r.busy
                and r.draining is None and r.failures == 0
                for r in self.replicas
            )

    def solve_whatif_async(self, solve_args: Sequence, pid,
                           profiles) -> PoolPendingSolve:
        """Dispatch a what-if solve to an idle non-primary replica
        (plan frames carry no devincr section, so they cannot perturb
        any child's incremental caches).  A dead candidate marks its
        failure and the next one is tried; raises when none can take
        the frame — the caller voids the plan, which mutated nothing."""
        with self._lock:
            primary = self._primary
        replica, handle = self._dispatch_with_failover(
            lambda r, dv: r.client.solve_async(solve_args, pid,
                                               profiles),
            None, exclude=(primary,), kind="whatif")
        with self._lock:
            replica.busy = True
        return PoolPendingSolve(self, replica, handle, kind="whatif")

    # --------------------------------------------------------------- fetch

    def _hedge_deadline_s(self, replica: _Replica) -> Optional[float]:
        if hedge_p99_mult() <= 0 or len(self.replicas) < 2:
            return None
        p99 = self._p99_ms(replica)
        if p99 is None:
            return None
        return max(p99 * hedge_p99_mult(), hedge_min_ms()) / 1e3

    def _fetch(self, pending: PoolPendingSolve):
        """Receive the reply, hedging past the primary's rolling-p99
        deadline.  Returns the decoded AllocResult-shaped namedtuple
        (the ``InflightSolve.fetch`` contract); raises the standard
        lost-reply errors when every leg died."""
        replica = pending.replica
        t0 = time.perf_counter()
        info = {"replica": replica.index, "kind": pending.kind,
                "hedged": False, "hedge_won": False}
        try:
            if pending.kind != "primary" or not pending.hedgeable:
                res = pending.handle.fetch()
                self._finish_fetch(pending, replica, res, t0, info)
                return res
            deadline = self._hedge_deadline_s(replica)
            if deadline is None or replica.client.reply_ready(deadline):
                res = pending.handle.fetch()
                self._finish_fetch(pending, replica, res, t0, info)
                return res
            return self._fetch_hedged(pending, t0, info, deadline)
        except Exception as e:
            self._mark_failure(replica)
            with self._lock:
                replica.busy = False
                info["lost"] = type(e).__name__
                self.last_fetch_info = info
            raise

    def _fetch_hedged(self, pending: PoolPendingSolve, t0: float,
                      info: dict, deadline: float):
        """The primary exceeded its hedge deadline: re-dispatch the
        frozen frame to a second replica and commit whichever valid
        reply lands first; the loser's reply parks for a drain."""
        replica = pending.replica
        hedge = self._choose(exclude=(replica.index,))
        frozen = (self._hedge_frame_from_wire(replica.client)
                  if hedge is not None else None)
        hedge_handle = None
        t_hedge = time.perf_counter()
        if hedge is not None and frozen is not None:
            self._drain(hedge, block=True)
            fargs, fpid, fprof = frozen
            dv = self._strip_devincr(hedge, pending.devincr)
            try:
                hedge_handle = hedge.client.solve_async(
                    fargs, fpid, fprof, wave=pending.wave, devincr=dv)
            except (OSError, ConnectionError, ValueError):
                self._mark_failure(hedge)
                hedge_handle = None
            else:
                with self._lock:
                    hedge.busy = True
                    self.hedge_dispatches += 1
                info["hedged"] = True
                self._count_dispatch(hedge, "hedge")
                log.info(
                    "solver pool hedge: replica %d reply past its "
                    "p99 deadline (%.0f ms); re-dispatched to %d",
                    replica.index, deadline * 1e3, hedge.index)
        if hedge_handle is None:
            # No hedge capacity: block on the primary as before.
            res = pending.handle.fetch()
            self._finish_fetch(pending, replica, res, t0, info)
            return res
        # First valid reply wins.  Replies are deterministic for
        # identical frames, so committing either is equivalent; the
        # loser's reply drains later, keeping its mirror coherent.
        winner_is_hedge = self._wait_first(replica, hedge)
        if winner_is_hedge:
            with self._lock:
                replica.draining = pending.handle
                replica.busy = False
            try:
                res = hedge_handle.fetch()
            except Exception:
                # The hedge died at the finish line; fall back to the
                # primary (drain-parked above, still in flight).
                self._mark_failure(hedge)
                with self._lock:
                    replica.draining = None
                    replica.busy = True
                res = pending.handle.fetch()
                self._finish_fetch(pending, replica, res, t0, info)
                return res
            # The primary is still in flight: its reply took AT LEAST
            # this long (the drain discards it untimed later), so fold
            # the lower bound into its routing state — a persistently
            # slow member must lose _choose eventually, not keep its
            # stale good EWMA and force a hedge every cycle.
            self._note_latency(replica,
                               (time.perf_counter() - t0) * 1e3)
            return self._commit_hedge_win(hedge, res, t0, t_hedge,
                                          info)
        # Primary won after all: park the hedge reply for a drain.
        with self._lock:
            hedge.draining = hedge_handle
            hedge.busy = False
        try:
            res = pending.handle.fetch()
        except Exception:
            # Primary died mid-reply with a live hedge outstanding:
            # commit the hedge instead (identical frame).
            with self._lock:
                hedge.draining = None
                hedge.busy = True
            try:
                res = hedge_handle.fetch()
            except Exception:
                # Double fault: BOTH legs died.  Mark the hedge here
                # (clearing its busy flag — a leaked busy=True would
                # silently retire the replica from rotation forever);
                # the primary is marked ONCE, by _fetch's outer
                # lost-reply handler on the re-raise (marking it here
                # too would count one incident as two consecutive
                # failures, doubling its re-probe cooldown).
                self._mark_failure(hedge)
                raise
            self._mark_failure(replica)
            return self._commit_hedge_win(hedge, res, t0, t_hedge,
                                          info)
        self._finish_fetch(pending, replica, res, t0, info)
        return res

    def _commit_hedge_win(self, hedge: _Replica, res, t0: float,
                          t_hedge: float, info: dict):
        """The ONE hedge-win commit sequence (both win paths: hedge
        replied first, or the primary died mid-reply): counted only
        AFTER the hedge reply actually decoded — a hedge that dies at
        the finish line is not a win.  The hedge replica's latency
        sample starts at ITS dispatch, not the primary's — charging it
        the hedge deadline would teach the router the hedge replica is
        slow for having rescued a straggler."""
        with self._lock:
            self.hedge_wins += 1
            info["hedge_won"] = True
            # The record names the replica whose reply COMMITTED (the
            # recorder/tuning docs' contract), not the straggler.
            info["replica"] = hedge.index
            hedge.busy = False
        metrics.solver_pool_hedge_wins.inc()
        self.last_devincr_mode = hedge.client.last_devincr_mode
        self._mark_success(hedge,
                           (time.perf_counter() - t_hedge) * 1e3)
        with self._lock:
            info["wait_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
            self.last_fetch_info = info
        return res

    def _wait_first(self, primary: _Replica, hedge: _Replica) -> bool:
        """Block until either leg's reply starts arriving; True when
        the hedge replica's reply is first.  A dead socket reads as
        ready (its fetch raises promptly, which the caller handles).
        Bounded by the primary client's timeout: if NEITHER leg ever
        replies (both children hung, blackholed network), fall back to
        the primary's blocking fetch, whose socket timeout turns the
        hang into the standard lost-reply OSError — hedging must never
        remove the timeout bound the single-client path has."""
        deadline = time.monotonic() + max(
            float(primary.client.timeout or 0.0), 1.0)
        while time.monotonic() < deadline:
            socks = {}
            for is_hedge, r in ((False, primary), (True, hedge)):
                s = r.client.wire_socket()
                if s is None:
                    return is_hedge
                socks[s] = is_hedge
            ready, _, _ = select.select(list(socks), [], [], 1.0)
            if ready:
                return socks[ready[0]]
        return False

    def _finish_fetch(self, pending: PoolPendingSolve,
                      replica: _Replica, res, t0: float,
                      info: dict) -> None:
        wait_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            replica.busy = False
        self._mark_success(replica, wait_ms)
        self.last_devincr_mode = replica.client.last_devincr_mode
        with self._lock:
            info["wait_ms"] = round(wait_ms, 3)
            self.last_fetch_info = info

    def take_last_fetch_info(self) -> Optional[dict]:
        with self._lock:
            info, self.last_fetch_info = self.last_fetch_info, None
        return info

    def _abandon(self, pending: PoolPendingSolve) -> None:
        """Drop the pending reply (scheduler shutdown / plan void) by
        PARKING it for a drain — the hedge-loser machinery: the reply
        is read and discarded opportunistically, keeping the
        connection framing and the replica's wire cache warm (deltas
        keep flowing), where a client abandon would tear the socket
        down and cost a reconnect + full frame for EVERY stale-voided
        what-if plan.  ``close()`` still tears parked replies down
        with the socket at shutdown."""
        replica = pending.replica
        with self._lock:
            replica.busy = False
            if replica.draining is None:
                replica.draining = pending.handle
                return
        # A reply is already parked (unreachable under the strict
        # request/reply protocol, but never leak a second handle):
        # fall back to the teardown abandon.
        try:
            pending.handle.abandon()
        except Exception:  # pragma: no cover - best-effort teardown
            log.debug("pool abandon failed", exc_info=True)


def make_solver_client(addresses: str, timeout: float = 300.0):
    """Build the store's solver client from a ``host:port[,host:port...]``
    spec honoring ``VOLCANO_TPU_SOLVER_POOL``: a plain ``RemoteSolver``
    for the default single-connection path (bit-for-bit today's wire),
    a ``SolverPool`` when more than one replica is asked for."""
    from .solver_service import RemoteSolver

    addrs = [a.strip() for a in str(addresses).split(",") if a.strip()]
    n = max(pool_size(), len(addrs))
    if n <= 1:
        return RemoteSolver(addrs[0], timeout=timeout)
    return SolverPool(addrs, size=n, timeout=timeout)
