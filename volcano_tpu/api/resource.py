"""Resource arithmetic with Volcano's epsilon-tolerant comparison semantics.

Host-side scalar model. Reproduces the behavior of the reference's
``pkg/scheduler/api/resource_info.go`` (see /root/reference), in particular the
load-bearing epsilon tolerances of ``LessEqual`` (resource_info.go:286-320):
a request "fits" if it is below the target or within the minimum quantum
(10 milli-CPU / 10 MiB memory / 10 milli-units for scalar resources).

The device-array mirror of these semantics lives in
``volcano_tpu.arrays.schema`` (fixed resource-slot vectors) and
``volcano_tpu.ops.resreq`` (vectorized fit kernels); both must stay in exact
agreement with this module — ``tests/test_resource.py`` cross-checks them.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Optional, Tuple

# Minimum quanta (the epsilon tolerances). Mirrors resource_info.go:70-72.
MIN_MILLI_CPU = 10.0
MIN_MILLI_SCALAR = 10.0
MIN_MEMORY = 10.0 * 1024 * 1024

# Well-known resource names.
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
GPU = "nvidia.com/gpu"  # resource_info.go:43-45


class Resource:
    """A multi-dimensional resource quantity.

    ``milli_cpu`` is in milli-cores, ``memory`` in bytes, and ``scalars`` maps
    extended resource names (e.g. ``nvidia.com/gpu``) to milli-units.
    ``max_task_num`` mirrors the pods capacity and is only consulted by
    predicates, never by arithmetic (resource_info.go:36-39).
    """

    __slots__ = ("milli_cpu", "memory", "scalars", "max_task_num")

    def __init__(
        self,
        milli_cpu: float = 0.0,
        memory: float = 0.0,
        scalars: Optional[Dict[str, float]] = None,
        max_task_num: int = 0,
    ):
        self.milli_cpu = float(milli_cpu)
        self.memory = float(memory)
        self.scalars: Optional[Dict[str, float]] = dict(scalars) if scalars else None
        self.max_task_num = max_task_num

    # ------------------------------------------------------------------ build

    @classmethod
    def empty(cls) -> "Resource":
        return cls()

    @classmethod
    def from_resource_list(cls, rl: Dict[str, object]) -> "Resource":
        """Build from a k8s-style resource list.

        Accepts quantities as numbers in *whole units* (cpu cores, memory
        bytes, scalar units) or strings using k8s quantity suffixes
        ("2", "500m", "1Gi", "512Mi").  cpu and extended scalars are stored
        in milli-units.  Mirrors NewResource (resource_info.go:75-93).
        """
        r = cls()
        for name, quant in rl.items():
            if name == CPU:
                r.milli_cpu += parse_milli(quant)
            elif name == MEMORY:
                r.memory += parse_bytes(quant)
            elif name == PODS:
                r.max_task_num += int(parse_count(quant))
            else:
                r.add_scalar(name, parse_milli(quant))
        return r

    def clone(self) -> "Resource":
        r = Resource.__new__(Resource)
        r.milli_cpu = self.milli_cpu
        r.memory = self.memory
        r.scalars = dict(self.scalars) if self.scalars else None
        r.max_task_num = self.max_task_num
        return r

    # ------------------------------------------------------------- predicates

    def is_empty(self) -> bool:
        """True when every dimension is below its minimum quantum."""
        if not (self.milli_cpu < MIN_MILLI_CPU and self.memory < MIN_MEMORY):
            return False
        if self.scalars:
            for quant in self.scalars.values():
                if quant >= MIN_MILLI_SCALAR:
                    return False
        return True

    def is_zero(self, name: str) -> bool:
        if name == CPU:
            return self.milli_cpu < MIN_MILLI_CPU
        if name == MEMORY:
            return self.memory < MIN_MEMORY
        if not self.scalars:
            return True
        if name not in self.scalars:
            raise KeyError(f"unknown resource {name}")
        return self.scalars[name] < MIN_MILLI_SCALAR

    # ------------------------------------------------------------- arithmetic

    def add(self, rr: "Resource") -> "Resource":
        self.milli_cpu += rr.milli_cpu
        self.memory += rr.memory
        if rr.scalars:
            if self.scalars is None:
                self.scalars = {}
            for name, quant in rr.scalars.items():
                self.scalars[name] = self.scalars.get(name, 0.0) + quant
        return self

    def sub(self, rr: "Resource") -> "Resource":
        """Subtract; asserts sufficiency first (resource_info.go:145-159)."""
        assert rr.less_equal(self), (
            f"resource is not sufficient to do operation: <{self}> sub <{rr}>"
        )
        self.milli_cpu -= rr.milli_cpu
        self.memory -= rr.memory
        if rr.scalars:
            if self.scalars is None:
                return self
            for name, quant in rr.scalars.items():
                self.scalars[name] = self.scalars.get(name, 0.0) - quant
        return self

    def set_max_resource(self, rr: "Resource") -> None:
        if rr is None:
            return
        self.milli_cpu = max(self.milli_cpu, rr.milli_cpu)
        self.memory = max(self.memory, rr.memory)
        if rr.scalars:
            if self.scalars is None:
                self.scalars = dict(rr.scalars)
                return
            for name, quant in rr.scalars.items():
                if quant > self.scalars.get(name, 0.0):
                    self.scalars[name] = quant

    def fit_delta(self, rr: "Resource") -> "Resource":
        """Subtract request plus one quantum for each requested dimension.

        A negative field afterwards means that dimension is insufficient
        (resource_info.go:193-213).
        """
        if rr.milli_cpu > 0:
            self.milli_cpu -= rr.milli_cpu + MIN_MILLI_CPU
        if rr.memory > 0:
            self.memory -= rr.memory + MIN_MEMORY
        if rr.scalars:
            if self.scalars is None:
                self.scalars = {}
            for name, quant in rr.scalars.items():
                if quant > 0:
                    self.scalars[name] = (
                        self.scalars.get(name, 0.0) - quant - MIN_MILLI_SCALAR
                    )
        return self

    def multi(self, ratio: float) -> "Resource":
        self.milli_cpu *= ratio
        self.memory *= ratio
        if self.scalars:
            for name in self.scalars:
                self.scalars[name] *= ratio
        return self

    # ------------------------------------------------------------ comparison

    def less(self, rr: "Resource") -> bool:
        """Strict elementwise less-than (resource_info.go:226-261)."""
        if not self.milli_cpu < rr.milli_cpu:
            return False
        if not self.memory < rr.memory:
            return False
        if self.scalars is None:
            if rr.scalars is not None:
                for quant in rr.scalars.values():
                    if quant <= MIN_MILLI_SCALAR:
                        return False
            return True
        if rr.scalars is None:
            return False
        for name, quant in self.scalars.items():
            if not quant < rr.scalars.get(name, 0.0):
                return False
        return True

    def less_equal_strict(self, rr: "Resource") -> bool:
        """Elementwise <= with no epsilon (resource_info.go:264-283)."""
        if not self.milli_cpu <= rr.milli_cpu:
            return False
        if not self.memory <= rr.memory:
            return False
        if self.scalars:
            rs = rr.scalars or {}
            for name, quant in self.scalars.items():
                if not quant <= rs.get(name, 0.0):
                    return False
        return True

    def less_equal(self, rr: "Resource") -> bool:
        """Epsilon-tolerant fit comparison (resource_info.go:286-320).

        Each dimension passes when ``l < r`` or ``|l - r| < quantum``; scalar
        dimensions requesting no more than one quantum always pass.
        """

        def le(l: float, r: float, diff: float) -> bool:
            return l < r or abs(l - r) < diff

        if not le(self.milli_cpu, rr.milli_cpu, MIN_MILLI_CPU):
            return False
        if not le(self.memory, rr.memory, MIN_MEMORY):
            return False
        if self.scalars is None:
            return True
        for name, quant in self.scalars.items():
            if quant <= MIN_MILLI_SCALAR:
                continue
            if rr.scalars is None:
                return False
            if not le(quant, rr.scalars.get(name, 0.0), MIN_MILLI_SCALAR):
                return False
        return True

    def diff(self, rr: "Resource") -> Tuple["Resource", "Resource"]:
        """Return (increased, decreased) vs rr (resource_info.go:323-355)."""
        inc = Resource.empty()
        dec = Resource.empty()
        if self.milli_cpu > rr.milli_cpu:
            inc.milli_cpu += self.milli_cpu - rr.milli_cpu
        else:
            dec.milli_cpu += rr.milli_cpu - self.milli_cpu
        if self.memory > rr.memory:
            inc.memory += self.memory - rr.memory
        else:
            dec.memory += rr.memory - self.memory
        if self.scalars:
            rs = rr.scalars or {}
            for name, quant in self.scalars.items():
                rr_quant = rs.get(name, 0.0)
                if quant > rr_quant:
                    inc.add_scalar(name, quant - rr_quant)
                else:
                    dec.add_scalar(name, rr_quant - quant)
        return inc, dec

    # ---------------------------------------------------------------- access

    def get(self, name: str) -> float:
        if name == CPU:
            return self.milli_cpu
        if name == MEMORY:
            return self.memory
        if self.scalars is None:
            return 0.0
        return self.scalars.get(name, 0.0)

    def resource_names(self) -> Iterable[str]:
        names = [CPU, MEMORY]
        if self.scalars:
            names.extend(self.scalars.keys())
        return names

    def add_scalar(self, name: str, quantity: float) -> None:
        self.set_scalar(name, (self.scalars or {}).get(name, 0.0) + quantity)

    def set_scalar(self, name: str, quantity: float) -> None:
        if self.scalars is None:
            self.scalars = {}
        self.scalars[name] = quantity

    # ----------------------------------------------------------------- misc

    def __repr__(self) -> str:
        s = f"cpu {self.milli_cpu:.2f}, memory {self.memory:.2f}"
        if self.scalars:
            for name, quant in self.scalars.items():
                s += f", {name} {quant:.2f}"
        return s

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resource):
            return NotImplemented
        return (
            self.milli_cpu == other.milli_cpu
            and self.memory == other.memory
            and (self.scalars or {}) == (other.scalars or {})
        )


def res_min(l: Resource, r: Resource) -> Resource:
    """Elementwise minimum (api/helpers/helpers.go:28-44)."""
    res = Resource(min(l.milli_cpu, r.milli_cpu), min(l.memory, r.memory))
    if l.scalars is None or r.scalars is None:
        return res
    res.scalars = {}
    for name, quant in l.scalars.items():
        res.scalars[name] = min(quant, r.scalars.get(name, 0.0))
    return res


def share(l: float, r: float) -> float:
    """Share ratio with 0/0 -> 0 and x/0 -> 1 (api/helpers/helpers.go:46-59)."""
    if r == 0:
        return 0.0 if l == 0 else 1.0
    return l / r


# --------------------------------------------------------------------- parse

_BINARY_SUFFIXES = {
    "Ki": 1024.0,
    "Mi": 1024.0**2,
    "Gi": 1024.0**3,
    "Ti": 1024.0**4,
    "Pi": 1024.0**5,
    "Ei": 1024.0**6,
}
_DECIMAL_SUFFIXES = {
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}


def parse_quantity(q: object) -> float:
    """Parse a k8s quantity string (or pass through a number) to a float."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    for suf, mult in _BINARY_SUFFIXES.items():
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    for suf, mult in _DECIMAL_SUFFIXES.items():
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    return float(s)


def parse_milli(q: object) -> float:
    """Quantity -> milli-units (k8s Quantity.MilliValue: rounded UP to
    an integral milli count).  Integrality is load-bearing beyond
    parity with the reference: the incremental cycle aggregates
    (fastpath_incr.py) rely on requests being exact in float64 so the
    subtract-old/add-new delta planes stay bit-for-bit with a full
    rebuild — a fractional milli value would accrue ulp drift."""
    if isinstance(q, (int, float)):
        # Numbers are whole units (e.g. cpu: 2 -> 2000 milli); a
        # fractional number (cpu: 0.0001) rounds up like the reference.
        return float(math.ceil(float(q) * 1000.0))
    return float(math.ceil(parse_quantity(q) * 1000.0))


def parse_bytes(q: object) -> float:
    """Quantity -> bytes (k8s Quantity.Value: rounded UP to an integral
    byte count; same integrality contract as parse_milli)."""
    if isinstance(q, (int, float)):
        return float(math.ceil(float(q)))
    return float(math.ceil(parse_quantity(q)))


def parse_count(q: object) -> float:
    return parse_quantity(q)
