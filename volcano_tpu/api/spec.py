"""Framework-native spec records: Pod, Node, PodGroup, Queue.

These replace the Kubernetes objects the reference schedules
(v1.Pod / v1.Node, PodGroup and Queue CRDs from
``pkg/apis/scheduling/v1beta1/types.go:142-281``).  They are plain records in
the framework's own store (``volcano_tpu.cache``); the scheduler and
controllers communicate only through that store, mirroring how the
reference's planes communicate only through the API server.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .resource import Resource
from .types import PodGroupPhase, QueueState, TaskStatus

# Annotation key binding a pod to its PodGroup, mirroring
# scheduling.k8s.io/group-name (v1beta1/types.go KubeGroupNameAnnotationKey).
GROUP_NAME_ANNOTATION = "scheduling.volcano-tpu/group-name"

# Per-gang fabric-topology constraint (PodGroup.topology equivalent for
# annotation-driven workloads): "prefer-contiguous" folds the selected
# fabric block into node ordering; "require-contiguous" refuses to bind
# the gang scattered across blocks (drop reason ``topology-infeasible``).
TOPOLOGY_ANNOTATION = "scheduling.volcano-tpu/topology"

# Fabric coordinate label keys, coarse -> fine.  ``rack`` and ``slice``
# define a contiguous placement block (an ICI slice / NVLink island
# within a rack); ``host`` rides along for forensics.  Canonical here so
# the wire schema (arrays.NodeArrays.fabric), the mirror planes
# (ops/topology), and synth all agree on the order.
FABRIC_RACK = "fabric.volcano-tpu/rack"
FABRIC_SLICE = "fabric.volcano-tpu/slice"
FABRIC_HOST = "fabric.volcano-tpu/host"
FABRIC_LEVELS: Tuple[str, ...] = (FABRIC_RACK, FABRIC_SLICE, FABRIC_HOST)
FABRIC_L = len(FABRIC_LEVELS)
TOPOLOGY_NONE = 0
TOPOLOGY_PREFER = 1
TOPOLOGY_REQUIRE = 2
_TOPOLOGY_CODES = {
    "": TOPOLOGY_NONE,
    "prefer-contiguous": TOPOLOGY_PREFER,
    "require-contiguous": TOPOLOGY_REQUIRE,
}


def topology_code(pg: "PodGroup") -> int:
    """Resolve a PodGroup's fabric constraint to its int code.  The
    explicit field wins; the annotation is the CRD-compatible fallback.
    Unknown values degrade to no-constraint (never block a bind on a
    typo)."""
    raw = pg.topology or pg.annotations.get(TOPOLOGY_ANNOTATION, "")
    return _TOPOLOGY_CODES.get(raw or "", TOPOLOGY_NONE)

# Critical-pod exemption set (conformance.go:44-66): system priority
# classes and the system namespace.  Canonical here — the conformance
# plugin, the evict machinery, and the mirror's p_critical column all
# consume these.
SYSTEM_CLUSTER_CRITICAL = "system-cluster-critical"
SYSTEM_NODE_CRITICAL = "system-node-critical"
SYSTEM_NAMESPACE = "kube-system"

_uid_counter = itertools.count(1)
_ts_counter = itertools.count(1)


def new_uid(prefix: str = "obj") -> str:
    return f"{prefix}-{next(_uid_counter)}"


def new_timestamp() -> float:
    """Monotonic logical creation timestamp for orderings."""
    return float(next(_ts_counter))


class PodPhase(str):
    Pending = "Pending"
    Running = "Running"
    Succeeded = "Succeeded"
    Failed = "Failed"
    Unknown = "Unknown"


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class AffinityTerm:
    """One pod-(anti)affinity term: select pods by labels within a topology
    domain (predicates.go:272-291 wraps the upstream equivalent)."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    topology_key: str = "kubernetes.io/hostname"
    namespaces: List[str] = field(default_factory=list)  # empty = pod's own


@dataclass
class Pod:
    """The schedulable unit (equivalent of v1.Pod for the scheduler)."""

    name: str
    namespace: str = "default"
    uid: str = ""
    # Resource lists: name -> quantity (see Resource.from_resource_list).
    containers: List[Dict[str, object]] = field(default_factory=list)
    init_containers: List[Dict[str, object]] = field(default_factory=list)
    node_name: Optional[str] = None
    phase: str = PodPhase.Pending
    deleting: bool = False
    priority: Optional[int] = None
    priority_class: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Toleration] = field(default_factory=list)
    host_ports: List[int] = field(default_factory=list)
    affinity: List[AffinityTerm] = field(default_factory=list)
    anti_affinity: List[AffinityTerm] = field(default_factory=list)
    preferred_node_affinity: List[Tuple[Dict[str, str], int]] = field(
        default_factory=list
    )  # (required labels, weight) soft terms
    required_node_affinity: List[Dict[str, str]] = field(default_factory=list)
    # Soft inter-pod terms (upstream preferredDuringScheduling...): scored,
    # not gating (nodeorder.go:217-235 InterPodAffinity analog).
    preferred_affinity: List[Tuple["AffinityTerm", int]] = field(
        default_factory=list
    )
    preferred_anti_affinity: List[Tuple["AffinityTerm", int]] = field(
        default_factory=list
    )
    # Topology spread: (topology_key, weight) — softly prefer domains with
    # fewer pods of this pod's own job/PodGroup.
    topology_spread: List[Tuple[str, int]] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    # (claim_name, mount_path) pairs wired by the job controller from the
    # Job's VolumeSpecs (job_controller_util.go:56-78); the volume binder
    # gates the pod's bind on these claims.
    volumes: List[Tuple[str, str]] = field(default_factory=list)
    exit_code: int = 0
    creation_timestamp: float = 0.0
    # Batch-job bookkeeping (set by the job controller):
    owner_job: str = ""
    task_name: str = ""

    def __post_init__(self):
        if not self.uid:
            self.uid = new_uid("pod")
        if not self.creation_timestamp:
            self.creation_timestamp = new_timestamp()

    # ---------------------------------------------------------------- joins

    def job_id(self) -> str:
        """Job (PodGroup) this pod belongs to (job_info.go:56-64)."""
        gn = self.annotations.get(GROUP_NAME_ANNOTATION, "")
        if gn:
            return f"{self.namespace}/{gn}"
        return ""

    # ------------------------------------------------------------- resources

    def resource_request(self) -> Resource:
        """Sum of container requests (GetPodResourceWithoutInitContainers).

        Cached per Pod object: container lists are treated as immutable
        (updates replace the Pod), and callers clone() before mutating."""
        cached = getattr(self, "_req_cache", None)
        if cached is None:
            cached = Resource.empty()
            for c in self.containers:
                cached.add(Resource.from_resource_list(c))
            self._req_cache = cached
        return cached

    def init_resource_request(self) -> Resource:
        """max(max(init containers), sum(containers))
        (GetPodResourceRequest in pod_info.go).  Cached like
        resource_request."""
        cached = getattr(self, "_init_req_cache", None)
        if cached is None:
            cached = self.resource_request().clone()
            for ic in self.init_containers:
                cached.set_max_resource(Resource.from_resource_list(ic))
            self._init_req_cache = cached
        return cached

    def task_status(self) -> TaskStatus:
        """Map pod phase to TaskStatus (pod_info.go getTaskStatus)."""
        if self.phase == PodPhase.Running:
            return TaskStatus.Releasing if self.deleting else TaskStatus.Running
        if self.phase == PodPhase.Pending:
            if self.deleting:
                return TaskStatus.Releasing
            if self.node_name:
                return TaskStatus.Bound
            return TaskStatus.Pending
        if self.phase == PodPhase.Unknown:
            return TaskStatus.Unknown
        if self.phase == PodPhase.Succeeded:
            return TaskStatus.Succeeded
        if self.phase == PodPhase.Failed:
            return TaskStatus.Failed
        return TaskStatus.Unknown


@dataclass
class Node:
    """A worker node (equivalent of v1.Node)."""

    name: str
    allocatable: Dict[str, object] = field(default_factory=dict)
    capacity: Dict[str, object] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    ready: bool = True
    unschedulable: bool = False
    # TPU-native: slice topology coordinates used by placement scoring.
    topology: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        if not self.capacity:
            self.capacity = dict(self.allocatable)
        if self.topology:
            # Topology coordinates are labels (as on Kubernetes nodes), so
            # selectors, (anti)affinity, and spread resolve them through
            # the same machinery; explicit labels win on key collision.
            self.labels = {**self.topology, **self.labels}

    def allocatable_resource(self) -> Resource:
        return Resource.from_resource_list(self.allocatable)

    def capacity_resource(self) -> Resource:
        return Resource.from_resource_list(self.capacity)


@dataclass
class PodGroupCondition:
    type: str
    status: str
    transition_id: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class PodGroupStatus:
    phase: str = PodGroupPhase.Pending.value
    conditions: List[PodGroupCondition] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class PodGroup:
    """Gang unit (v1beta1/types.go:142-207)."""

    name: str
    namespace: str = "default"
    min_member: int = 0
    queue: str = "default"
    priority_class: str = ""
    min_resources: Optional[Dict[str, object]] = None
    status: PodGroupStatus = field(default_factory=PodGroupStatus)
    creation_timestamp: float = 0.0
    owner_job: str = ""
    # Disruption budget for the rebalance lane (PDB max_unavailable
    # equivalent): max members a migration wave may evict at once.
    # None -> the VOLCANO_TPU_REBALANCE_MAX_UNAVAIL default.
    max_unavailable: Optional[int] = None
    # Fabric-topology constraint: "" (none), "prefer-contiguous", or
    # "require-contiguous"; the TOPOLOGY_ANNOTATION key is the
    # annotation-driven equivalent (see topology_code()).
    topology: str = ""
    annotations: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        if not self.creation_timestamp:
            self.creation_timestamp = new_timestamp()

    @property
    def uid(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Queue:
    """Fair-share queue (v1beta1/types.go:228-281)."""

    name: str
    weight: int = 1
    capability: Dict[str, object] = field(default_factory=dict)
    reclaimable: bool = True
    state: str = QueueState.Open.value
    creation_timestamp: float = 0.0

    def __post_init__(self):
        if not self.creation_timestamp:
            self.creation_timestamp = new_timestamp()


@dataclass
class PriorityClass:
    name: str
    value: int = 0
    preemptable: bool = True


@dataclass
class ResourceQuota:
    """Namespace quota; carries the namespace weight annotation
    (api/namespace_info.go:33-37)."""

    name: str
    namespace: str = "default"
    annotations: Dict[str, str] = field(default_factory=dict)


NAMESPACE_WEIGHT_KEY = "volcano-tpu/namespace.weight"
