"""Core enums and callback type conventions.

Mirrors the reference's ``pkg/scheduler/api/types.go`` (TaskStatus bit values,
NodePhase) and ``pkg/apis/scheduling/v1beta1/types.go`` (PodGroup/Queue
phases).  Status values are kept identical to the Go iota bit-shifts so that
snapshots/int8 encodings are stable and comparable in tests.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Optional


class TaskStatus(enum.IntEnum):
    """Status of a task/pod (types.go:26-58)."""

    Pending = 1 << 0
    Allocated = 1 << 1
    Pipelined = 1 << 2
    Binding = 1 << 3
    Bound = 1 << 4
    Running = 1 << 5
    Releasing = 1 << 6
    Succeeded = 1 << 7
    Failed = 1 << 8
    Unknown = 1 << 9


def allocated_status(status: TaskStatus) -> bool:
    """True for statuses that hold node resources (api/helpers.go:64-71)."""
    return status in (
        TaskStatus.Bound,
        TaskStatus.Binding,
        TaskStatus.Running,
        TaskStatus.Allocated,
    )


class NodePhase(enum.IntEnum):
    """Phase of a node (types.go:86-93)."""

    Ready = 1 << 0
    NotReady = 1 << 1


class PodGroupPhase(str, enum.Enum):
    """Phase of a PodGroup (apis/scheduling/v1beta1/types.go:42-57)."""

    Pending = "Pending"
    Running = "Running"
    Unknown = "Unknown"
    Inqueue = "Inqueue"


class QueueState(str, enum.Enum):
    """State of a Queue (apis/scheduling/v1beta1/types.go:30-39)."""

    Open = "Open"
    Closed = "Closed"
    Closing = "Closing"
    Unknown = "Unknown"


@dataclass
class ValidateResult:
    """Result of an extended validation (types.go:121-125)."""

    pass_: bool
    reason: str = ""
    message: str = ""


# Reasons mirrored from apis/scheduling/v1beta1 constants.
NOT_ENOUGH_PODS_REASON = "NotEnoughPods"
NOT_ENOUGH_RESOURCES_REASON = "NotEnoughResources"
POD_GROUP_NOT_READY = "pod group is not ready"

# Fit error messages (api/unschedule_info.go).
NODE_RESOURCE_FIT_FAILED = "node(s) resource fit failed"
ALL_NODES_UNAVAILABLE = "all nodes are unavailable"


class FitError(Exception):
    """A task failed to fit on a node."""

    def __init__(self, task_name: str, node_name: str, reason: str):
        self.task_name = task_name
        self.node_name = node_name
        self.reason = reason
        super().__init__(f"task {task_name} on node {node_name}: {reason}")


@dataclass
class FitErrors:
    """Aggregation of per-node fit errors (api/unschedule_info.go:22-110)."""

    nodes: Dict[str, str] = field(default_factory=dict)
    error: Optional[str] = None

    def set_node_error(self, node_name: str, err: object) -> None:
        self.nodes[node_name] = str(err)

    def set_error(self, msg: str) -> None:
        self.error = msg

    def __str__(self) -> str:
        if self.error:
            return self.error
        # Histogram of reasons, like FitErrors.Error().
        reasons: Dict[str, int] = {}
        for msg in self.nodes.values():
            reasons[msg] = reasons.get(msg, 0) + 1
        sorted_reasons = sorted(reasons.items(), key=lambda kv: -kv[1])
        return ", ".join(f"{cnt} {msg}" for msg, cnt in sorted_reasons)
