"""Host-side scheduling data model: Task/Job/Node/Queue/Namespace infos.

Mirrors the semantics of the reference's ``pkg/scheduler/api`` (job_info.go,
node_info.go, queue_info.go, namespace_info.go, cluster_info.go) on top of the
framework's own spec records (``volcano_tpu.api.spec``), with no Kubernetes
dependency.  These objects are the authoritative system of record; the dense
device arrays (``volcano_tpu.arrays``) are derived views.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .resource import Resource
from .spec import Pod, PodGroup, Queue
from .types import (
    FitErrors,
    NodePhase,
    PodGroupPhase,
    QueueState,
    TaskStatus,
    allocated_status,
)

DEFAULT_NAMESPACE_WEIGHT = 1  # api/namespace_info.go:28-31


def pod_key(pod: Pod) -> str:
    return f"{pod.namespace}/{pod.name}"


class TaskInfo:
    """All scheduler-facing info about one task (job_info.go:36-114)."""

    __slots__ = (
        "uid",
        "job",
        "name",
        "namespace",
        "resreq",
        "init_resreq",
        "node_name",
        "status",
        "priority",
        "volume_ready",
        "pod",
    )

    def __init__(self, pod: Pod):
        self.uid: str = pod.uid
        self.job: str = pod.job_id()
        self.name: str = pod.name
        self.namespace: str = pod.namespace
        # Resreq: run-time request; InitResreq: launch-time request (includes
        # init containers).  job_info.go:67-84.
        self.resreq: Resource = pod.resource_request().clone()
        self.init_resreq: Resource = pod.init_resource_request().clone()
        self.node_name: str = pod.node_name or ""
        self.status: TaskStatus = pod.task_status()
        self.priority: int = pod.priority if pod.priority is not None else 1
        self.volume_ready: bool = False
        self.pod: Pod = pod

    def clone(self) -> "TaskInfo":
        t = TaskInfo.__new__(TaskInfo)
        t.uid = self.uid
        t.job = self.job
        t.name = self.name
        t.namespace = self.namespace
        t.resreq = self.resreq.clone()
        t.init_resreq = self.init_resreq.clone()
        t.node_name = self.node_name
        t.status = self.status
        t.priority = self.priority
        t.volume_ready = self.volume_ready
        t.pod = self.pod
        return t

    def __repr__(self) -> str:
        return (
            f"Task ({self.uid}:{self.namespace}/{self.name}): job {self.job}, "
            f"status {self.status.name}, pri {self.priority}, resreq {self.resreq}"
        )


class JobInfo:
    """All scheduler-facing info about one job/PodGroup (job_info.go:125-389)."""

    def __init__(self, uid: str, *tasks: TaskInfo):
        self.uid: str = uid
        self.name: str = ""
        self.namespace: str = ""
        self.queue: str = ""
        self.priority: int = 0
        self.min_available: int = 0
        self.nodes_fit_delta: Dict[str, Resource] = {}
        self.job_fit_errors: str = ""
        self.nodes_fit_errors: Dict[str, FitErrors] = {}
        # status -> {task uid -> TaskInfo}
        self.task_status_index: Dict[TaskStatus, Dict[str, TaskInfo]] = {}
        # Incremental count of Pending tasks with empty InitResreq (they
        # count as "ready" in job_info.go:329-348); keeping it live makes
        # ready_task_num O(statuses) instead of O(tasks) — it sits inside
        # every job-order heap comparison.
        self._empty_pending: int = 0
        self.tasks: Dict[str, TaskInfo] = {}
        self.allocated: Resource = Resource.empty()
        self.total_request: Resource = Resource.empty()
        self.creation_timestamp: float = 0.0
        self.pod_group: Optional[PodGroup] = None
        for task in tasks:
            self.add_task_info(task)

    # ------------------------------------------------------------- pod group

    def set_pod_group(self, pg: PodGroup) -> None:
        self.name = pg.name
        self.namespace = pg.namespace
        self.min_available = pg.min_member
        self.queue = pg.queue
        self.creation_timestamp = pg.creation_timestamp
        self.pod_group = pg

    def unset_pod_group(self) -> None:
        self.pod_group = None

    # ----------------------------------------------------------------- tasks

    def _add_task_index(self, ti: TaskInfo) -> None:
        self.task_status_index.setdefault(ti.status, {})[ti.uid] = ti
        if ti.status == TaskStatus.Pending and ti.init_resreq.is_empty():
            self._empty_pending += 1

    def _delete_task_index(self, ti: TaskInfo) -> None:
        tasks = self.task_status_index.get(ti.status)
        if tasks is not None:
            removed = tasks.pop(ti.uid, None)
            if not tasks:
                del self.task_status_index[ti.status]
            if (
                removed is not None
                and ti.status == TaskStatus.Pending
                and removed.init_resreq.is_empty()
            ):
                self._empty_pending -= 1

    def add_task_info(self, ti: TaskInfo) -> None:
        self.tasks[ti.uid] = ti
        self._add_task_index(ti)
        self.total_request.add(ti.resreq)
        if allocated_status(ti.status):
            self.allocated.add(ti.resreq)

    def delete_task_info(self, ti: TaskInfo) -> None:
        task = self.tasks.get(ti.uid)
        if task is None:
            raise KeyError(
                f"failed to find task <{ti.namespace}/{ti.name}> "
                f"in job <{self.namespace}/{self.name}>"
            )
        self.total_request.sub(task.resreq)
        if allocated_status(task.status):
            self.allocated.sub(task.resreq)
        del self.tasks[task.uid]
        self._delete_task_index(task)

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        """Move a task to a new status (job_info.go:214-231)."""
        if task.uid in self.tasks:
            self.delete_task_info(task)
        task.status = status
        self.add_task_info(task)

    def clone(self) -> "JobInfo":
        info = JobInfo(self.uid)
        info.name = self.name
        info.namespace = self.namespace
        info.queue = self.queue
        info.priority = self.priority
        info.min_available = self.min_available
        info.pod_group = self.pod_group
        info.creation_timestamp = self.creation_timestamp
        for task in self.tasks.values():
            info.add_task_info(task.clone())
        return info

    # ------------------------------------------------------------- readiness

    def ready_task_num(self) -> int:
        """Tasks holding resources, succeeded, or zero-request pending
        (job_info.go:329-348)."""
        occupied = self._empty_pending
        for status, tasks in self.task_status_index.items():
            if allocated_status(status) or status == TaskStatus.Succeeded:
                occupied += len(tasks)
        return occupied

    def waiting_task_num(self) -> int:
        return len(self.task_status_index.get(TaskStatus.Pipelined, {}))

    def valid_task_num(self) -> int:
        occupied = 0
        for status, tasks in self.task_status_index.items():
            if (
                allocated_status(status)
                or status == TaskStatus.Succeeded
                or status == TaskStatus.Pipelined
                or status == TaskStatus.Pending
            ):
                occupied += len(tasks)
        return occupied

    def ready(self) -> bool:
        return self.ready_task_num() >= self.min_available

    def pipelined(self) -> bool:
        return self.waiting_task_num() + self.ready_task_num() >= self.min_available

    def fit_error(self) -> str:
        """Histogram message of task statuses (job_info.go:309-326)."""
        reasons: Dict[str, int] = {}
        for status, tasks in self.task_status_index.items():
            reasons[status.name] = reasons.get(status.name, 0) + len(tasks)
        reasons["minAvailable"] = self.min_available
        parts = sorted(f"{v} {k}" for k, v in reasons.items())
        return f"pod group is not ready, {', '.join(parts)}."

    def __repr__(self) -> str:
        return (
            f"Job ({self.uid}): namespace {self.namespace} ({self.queue}), "
            f"name {self.name}, minAvailable {self.min_available}"
        )


@dataclass
class NodeState:
    phase: NodePhase = NodePhase.NotReady
    reason: str = ""


class NodeInfo:
    """Node-level aggregated information (node_info.go:27-316)."""

    def __init__(self, node=None):
        from .spec import Node  # local import to avoid cycle in typing

        self.name: str = ""
        self.node: Optional[Node] = None
        self.state: NodeState = NodeState()
        self.releasing: Resource = Resource.empty()
        self.pipelined: Resource = Resource.empty()
        self.idle: Resource = Resource.empty()
        self.used: Resource = Resource.empty()
        self.allocatable: Resource = Resource.empty()
        self.capability: Resource = Resource.empty()
        self.tasks: Dict[str, TaskInfo] = {}
        self.others: Dict[str, object] = {}
        if node is not None:
            self.name = node.name
            self.node = node
            self.idle = node.allocatable_resource().clone()
            self.allocatable = node.allocatable_resource().clone()
            self.capability = node.capacity_resource().clone()
        self._set_node_state(node)

    def future_idle(self) -> Resource:
        """Idle + releasing - pipelined (node_info.go:53-58)."""
        return self.idle.clone().add(self.releasing).sub(self.pipelined)

    def ready(self) -> bool:
        return self.state.phase == NodePhase.Ready

    def _set_node_state(self, node) -> None:
        if node is None:
            self.state = NodeState(NodePhase.NotReady, "UnInitialized")
            return
        if not self.used.less_equal(node.allocatable_resource()):
            self.state = NodeState(NodePhase.NotReady, "OutOfSync")
            return
        if not node.ready:
            self.state = NodeState(NodePhase.NotReady, "NotReady")
            return
        self.state = NodeState(NodePhase.Ready, "")

    def set_node(self, node) -> None:
        """Re-point at a (possibly updated) node spec and re-derive resource
        accounting from resident tasks (node_info.go:158-190)."""
        self._set_node_state(node)
        if not self.ready():
            return
        self.name = node.name
        self.node = node
        self.allocatable = node.allocatable_resource().clone()
        self.capability = node.capacity_resource().clone()
        self.releasing = Resource.empty()
        self.pipelined = Resource.empty()
        self.idle = node.allocatable_resource().clone()
        self.used = Resource.empty()
        for ti in self.tasks.values():
            if ti.status == TaskStatus.Releasing:
                self.idle.sub(ti.resreq)
                self.releasing.add(ti.resreq)
                self.used.add(ti.resreq)
            elif ti.status == TaskStatus.Pipelined:
                self.pipelined.add(ti.resreq)
            else:
                self.idle.sub(ti.resreq)
                self.used.add(ti.resreq)

    def _allocate_idle(self, ti: TaskInfo) -> None:
        if not ti.resreq.less_equal(self.idle):
            raise ValueError("selected node NotReady")
        self.idle.sub(ti.resreq)

    def add_task(self, task: TaskInfo) -> None:
        """Add a task (a defensive copy) to this node (node_info.go:201-244)."""
        if task.node_name and self.name and task.node_name != self.name:
            raise ValueError(
                f"task <{task.namespace}/{task.name}> already on different "
                f"node <{task.node_name}>"
            )
        key = pod_key(task.pod)
        if key in self.tasks:
            raise ValueError(
                f"task <{task.namespace}/{task.name}> already on node <{self.name}>"
            )
        ti = task.clone()
        if self.node is not None:
            if ti.status == TaskStatus.Releasing:
                self._allocate_idle(ti)
                self.releasing.add(ti.resreq)
                self.used.add(ti.resreq)
            elif ti.status == TaskStatus.Pipelined:
                self.pipelined.add(ti.resreq)
            else:
                self._allocate_idle(ti)
                self.used.add(ti.resreq)
        task.node_name = self.name
        ti.node_name = self.name
        self.tasks[key] = ti

    def remove_task(self, ti: TaskInfo) -> None:
        key = pod_key(ti.pod)
        task = self.tasks.get(key)
        if task is None:
            raise KeyError(
                f"failed to find task <{ti.namespace}/{ti.name}> "
                f"on host <{self.name}>"
            )
        if self.node is not None:
            if task.status == TaskStatus.Releasing:
                self.releasing.sub(task.resreq)
                self.idle.add(task.resreq)
                self.used.sub(task.resreq)
            elif task.status == TaskStatus.Pipelined:
                self.pipelined.sub(task.resreq)
            else:
                self.idle.add(task.resreq)
                self.used.sub(task.resreq)
        del self.tasks[key]

    def update_task(self, ti: TaskInfo) -> None:
        self.remove_task(ti)
        self.add_task(ti)

    def clone(self) -> "NodeInfo":
        res = NodeInfo(self.node)
        res.name = self.name  # placeholder nodes (node is None) keep the name
        for task in self.tasks.values():
            t = task.clone()
            t.node_name = ""  # allow re-add to the clone
            res.add_task(t)
        res.others = self.others
        return res

    def pods(self) -> List[Pod]:
        return [t.pod for t in self.tasks.values()]

    def __repr__(self) -> str:
        return (
            f"Node ({self.name}): idle <{self.idle}>, used <{self.used}>, "
            f"releasing <{self.releasing}>, state <{self.state.phase.name}>"
        )


class QueueInfo:
    """Queue info (queue_info.go)."""

    def __init__(self, queue: Queue):
        self.uid: str = queue.name
        self.name: str = queue.name
        self.weight: int = queue.weight
        self.queue: Queue = queue

    def reclaimable(self) -> bool:
        return self.queue.reclaimable

    def clone(self) -> "QueueInfo":
        return QueueInfo(self.queue)


class NamespaceInfo:
    """Namespace weight info (api/namespace_info.go)."""

    def __init__(self, name: str, weight: int = DEFAULT_NAMESPACE_WEIGHT):
        self.name = name
        self.weight = weight

    def get_weight(self) -> int:
        if self.weight < 1:
            return DEFAULT_NAMESPACE_WEIGHT
        return self.weight


@dataclass
class ClusterInfo:
    """A deep-copied snapshot of cluster state (cluster_info.go)."""

    jobs: Dict[str, JobInfo] = field(default_factory=dict)
    nodes: Dict[str, NodeInfo] = field(default_factory=dict)
    queues: Dict[str, QueueInfo] = field(default_factory=dict)
    namespace_info: Dict[str, NamespaceInfo] = field(default_factory=dict)
