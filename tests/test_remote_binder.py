"""Side effects crossing a real process boundary (PARITY deviation 5
proof).

The reference scheduler's binds, evictions, and status updates are RPCs
to the API server (cache.go:492-554 Bind, :439-491 Evict, :556-599
status) with errTasks backoff on bind failure (:627-649).  These tests
run a RemoteBindService in a SECOND PROCESS and drive the store's three
side-effect interfaces through the Http* drop-ins: success lands
server-side; injected failures exercise BindFailure -> Pending revert ->
backoff -> retry and EvictFailure -> Running revert -> retry end to end
across the boundary.
"""

import subprocess
import sys
import time
import urllib.request

import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    PriorityClass,
    Queue,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.cache.remote import (
    HttpBinder,
    HttpEvictor,
    HttpStatusUpdater,
    RemoteBindService,
)
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster

EVICT_CONF = """
actions: "enqueue, allocate, preempt, reclaim, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


@pytest.fixture()
def remote_binder_process():
    proc = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu.cache.remote", "--port", "0"],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    try:
        line = proc.stdout.readline()  # "remote-binder listening on h:p"
        assert "listening" in line, line
        port = int(line.rsplit(":", 1)[1])
        url = f"http://127.0.0.1:{port}"
        # Healthz across the boundary.
        with urllib.request.urlopen(f"{url}/healthz", timeout=5) as r:
            assert r.status == 200
        yield url
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def _store_with_remote(url, **kw) -> ClusterStore:
    store = synthetic_cluster(**kw)
    store.binder = HttpBinder(url)
    store.async_bind = True
    return store


def test_binds_cross_process_boundary(remote_binder_process):
    url = remote_binder_process
    store = _store_with_remote(url, n_nodes=8, n_pods=24, gang_size=4)
    sched = Scheduler(store)
    sched.run_once()
    assert store.flush_binds(timeout=30)
    binds = HttpBinder(url).binds()
    assert len(binds) == 24
    # Server-side placements agree with the store's pod records.
    for pod in store.pods.values():
        assert binds[f"{pod.namespace}/{pod.name}"] == pod.node_name
    store.close()


def test_remote_failure_exercises_backoff(remote_binder_process,
                                          monkeypatch):
    from volcano_tpu.cache import bindqueue

    monkeypatch.setattr(bindqueue, "BACKOFF_BASE", 0.1)
    url = remote_binder_process
    store = _store_with_remote(url, n_nodes=8, n_pods=16, gang_size=1)
    client = HttpBinder(url)
    client.chaos_fail_next(1)  # the next batch fails wholesale

    sched = Scheduler(store)
    sched.run_once()
    assert store.flush_binds(timeout=30)
    assert not client.binds()  # nothing landed remotely

    # Drain: every pod back to Pending with a backoff window.
    sched.run_once()
    assert len(store.bind_backoff) == 16
    assert all(p.node_name is None for p in store.pods.values())

    # Window expires -> re-solve -> binds land across the boundary.
    time.sleep(0.25)
    sched.run_once()
    assert store.flush_binds(timeout=30)
    assert len(client.binds()) == 16
    assert all(p.node_name for p in store.pods.values())
    store.close()


def _oversubscribed_store() -> ClusterStore:
    """One full node of low-priority victims + a pending high-priority
    gang that only fits by evicting (the config-4 shape, miniature)."""
    store = ClusterStore()
    store.add_priority_class(PriorityClass(name="low", value=100))
    store.add_priority_class(PriorityClass(name="high", value=10000))
    store.add_queue(Queue(name="victim", weight=1))
    store.add_queue(Queue(name="premium", weight=9))
    store.add_node(Node(name="n0",
                        allocatable={"cpu": "16", "memory": "32Gi"}))
    for k in range(2):
        pg = PodGroup(name=f"fill-{k}", min_member=1, queue="victim")
        store.add_pod_group(pg)
        store.add_pod(Pod(
            name=f"fill-{k}-0",
            annotations={GROUP_NAME_ANNOTATION: pg.name},
            containers=[{"cpu": "8", "memory": "16Gi"}],
            phase=PodPhase.Running, node_name="n0",
            priority_class="low", priority=100,
        ))
    store.add_pod_group(PodGroup(name="hi", min_member=1,
                                 queue="premium"))
    store.add_pod(Pod(
        name="hi-0",
        annotations={GROUP_NAME_ANNOTATION: "hi"},
        containers=[{"cpu": "12", "memory": "8Gi"}],
        priority_class="high", priority=10000,
    ))
    return store


def test_evictions_cross_process_boundary(remote_binder_process):
    """A preempt/reclaim cycle whose evictions land in a second OS
    process (cache.go:439-491 as a real RPC)."""
    url = remote_binder_process
    store = _oversubscribed_store()
    store.evictor = HttpEvictor(url)
    Scheduler(store, conf_str=EVICT_CONF).run_once()
    remote_evicts = HttpEvictor(url).evicts()
    assert remote_evicts, "no evictions crossed the boundary"
    # Remote channel agrees with local terminating pods.
    deleting = {f"{p.namespace}/{p.name}"
                for p in store.pods.values() if p.deleting}
    assert set(remote_evicts) == deleting
    store.close()


def test_remote_evict_failure_reverts_and_retries(remote_binder_process):
    """EvictFailure -> victims revert to Running (not terminating) ->
    the next cycle re-selects and the evictions land remotely."""
    url = remote_binder_process
    store = _oversubscribed_store()
    client = HttpEvictor(url)
    store.evictor = client
    client.chaos_fail_next(1)  # the next evict batch fails wholesale

    sched = Scheduler(store, conf_str=EVICT_CONF)
    sched.run_once()
    assert not client.evicts()  # nothing landed remotely
    assert not any(p.deleting for p in store.pods.values())
    # The failure is user-visible on the victims' event trails.
    assert any(
        ev["reason"] == "EvictFailed"
        for p in store.pods.values()
        for ev in store.events_for(f"Pod/{p.namespace}/{p.name}")
    )

    sched.run_once()  # retry cycle: chaos exhausted
    remote_evicts = client.evicts()
    assert remote_evicts
    deleting = {f"{p.namespace}/{p.name}"
                for p in store.pods.values() if p.deleting}
    assert set(remote_evicts) == deleting
    store.close()


def test_object_path_remote_evict_failure_reverts(remote_binder_process,
                                                  monkeypatch):
    """The object session's per-pod evict takes the same revert path
    (store.evict catches EvictFailure)."""
    monkeypatch.setenv("VOLCANO_TPU_FASTPATH", "0")
    url = remote_binder_process
    store = _oversubscribed_store()
    client = HttpEvictor(url)
    store.evictor = client
    client.chaos_fail_next(10)  # per-pod requests: fail several batches
    Scheduler(store, conf_str=EVICT_CONF).run_once()
    assert not client.evicts()
    assert not any(p.deleting for p in store.pods.values())
    running = [p for p in store.pods.values()
               if p.phase == PodPhase.Running and not p.deleting]
    assert len(running) == 2
    store.close()


def test_podgroup_status_crosses_process_boundary(remote_binder_process):
    """Session-close PodGroup status write-back lands in the second
    process (cache.go:556-599 as a real RPC)."""
    url = remote_binder_process
    store = synthetic_cluster(n_nodes=4, n_pods=8, gang_size=4)
    store.status_updater = HttpStatusUpdater(url)
    Scheduler(store).run_once()
    remote = HttpStatusUpdater(url).pod_groups()
    assert remote, "no PodGroup status crossed the boundary"
    for uid, g in remote.items():
        pg = store.pod_groups[uid]
        assert g["phase"] == pg.status.phase
        assert g["running"] == pg.status.running
    # Every live PodGroup's latest status is what the remote holds.
    assert set(remote) == set(store.pod_groups)
    store.close()


def test_remote_evictor_transport_error_reverts(remote_binder_process):
    """A transport-level failure (server gone mid-flight) is handled
    like EvictFailure: per-key re-drive, then revert to Running — the
    indeterminate-batch handling the binder documents, applied to
    evictions."""
    url = remote_binder_process
    store = _oversubscribed_store()
    client = HttpEvictor(url)

    class Dying(HttpEvictor):
        def evict_keys(self, keys, reason="preempted"):
            raise OSError("connection reset by peer")

        def evict(self, pod):
            raise OSError("connection reset by peer")

    store.evictor = Dying(url)
    Scheduler(store, conf_str=EVICT_CONF).run_once()
    assert not client.evicts()
    assert not any(p.deleting for p in store.pods.values())
    # Swap in a healthy evictor: next cycle lands the evictions.
    store.evictor = client
    Scheduler(store, conf_str=EVICT_CONF).run_once()
    assert client.evicts()
    store.close()


def test_service_wires_remote_evictor_and_status(remote_binder_process):
    """--remote-evictor / --remote-status-updater install the drop-ins
    (with the same fail-fast healthz probe as the binder)."""
    from volcano_tpu.service import Service

    with pytest.raises(OSError):
        Service(remote_evictor="http://127.0.0.1:9")
    with pytest.raises(OSError):
        Service(remote_status_updater="http://127.0.0.1:9")
    store = ClusterStore()
    svc = Service(store=store,
                  remote_evictor=remote_binder_process,
                  remote_status_updater=remote_binder_process)
    assert isinstance(store.evictor, HttpEvictor)
    assert isinstance(store.status_updater, HttpStatusUpdater)
    svc.stop()


def test_remote_pod_conditions_land(remote_binder_process):
    """update_pod_condition posts to /podconditions (taskUnschedulable
    analog, cache.go:556-575)."""
    from types import SimpleNamespace

    url = remote_binder_process
    up = HttpStatusUpdater(url)
    pod = SimpleNamespace(namespace="default", name="p0")
    cond = SimpleNamespace(type="PodScheduled", status="False")
    up.update_pod_condition(pod, cond)
    conds = up.pod_conditions()
    assert {"key": "default/p0", "type": "PodScheduled",
            "status": "False"} in conds


def test_in_process_service_object_for_unit_use():
    """RemoteBindService is also usable in-process (thread) for tests
    that don't need the boundary."""
    svc = RemoteBindService(port=0)
    import threading

    t = threading.Thread(target=svc.serve_forever, daemon=True)
    t.start()
    try:
        b = HttpBinder(f"http://127.0.0.1:{svc.port}")
        b.bind_keys(["default/a", "default/b"], ["n0", "n1"])
        assert b.binds() == {"default/a": "n0", "default/b": "n1"}
        # Idempotent re-drive lands on the same host, no error.
        b.bind_keys(["default/a"], ["n0"])
        assert b.binds()["default/a"] == "n0"
    finally:
        svc.shutdown()


def test_service_remote_binder_startup_validation(remote_binder_process):
    """--remote-binder fails fast on a dead URL, applies to caller-passed
    stores, and probes /healthz at startup."""
    from volcano_tpu.service import Service
    from volcano_tpu.cache.remote import HttpBinder

    # Dead URL: startup raises instead of looping Pending forever
    # (urllib's URLError subclasses OSError).
    with pytest.raises(OSError):
        Service(remote_binder="http://127.0.0.1:9")
    # A caller-passed store is rewired, not silently left on the fake.
    store = ClusterStore()
    svc = Service(store=store, remote_binder=remote_binder_process)
    assert isinstance(store.binder, HttpBinder)
    svc.stop()


def test_service_rewires_already_dispatched_store(remote_binder_process):
    """A store whose BindDispatcher already ran captured the OLD binder;
    Service(remote_binder=...) must reset it so later async binds reach
    the remote process."""
    from volcano_tpu.service import Service
    from volcano_tpu.cache.remote import HttpBinder

    store = synthetic_cluster(n_nodes=4, n_pods=4, gang_size=1)
    store.async_bind = True
    Scheduler(store).run_once()
    assert store.flush_binds(timeout=10)
    assert len(store.binder.binds) == 4  # landed on the in-process fake

    svc = Service(store=store, remote_binder=remote_binder_process)
    # New pods bind through the remote service now.
    from volcano_tpu.api import GROUP_NAME_ANNOTATION, Pod, PodGroup
    store.add_pod_group(PodGroup(name="late", min_member=1))
    store.add_pod(Pod(name="late-0",
                      annotations={GROUP_NAME_ANNOTATION: "late"},
                      containers=[{"cpu": "1", "memory": "1Gi"}]))
    Scheduler(store).run_once()
    assert store.flush_binds(timeout=30)
    remote = HttpBinder(remote_binder_process).binds()
    assert "default/late-0" in remote
    svc.stop()
