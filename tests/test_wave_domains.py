"""Exactness guards for the wave solver's domain machinery.

Round-4 rewrote the per-attempt count lookup as an MXU matmul against a
domain-membership one-hot and added wave-disjoint term detection that
skips the global count write-back.  Both are claimed EXACT; these tests
pin that claim:

- matmul path vs gather path produce identical placements
  (``DOM_MM_MAX_MB`` forced to 0 switches back to the gather);
- multi-wave solves with terms SHARED across waves (disjoint detection
  off) still agree with the single-wave solve;
- the sub-round filter's tightened gate changes nothing observable.

jax caches compiled programs per (shape, static args), so each variant
clears the jit caches after monkeypatching the module constants.
"""

import jax
import numpy as np
import pytest

import volcano_tpu.ops.wave as wave_mod
from volcano_tpu.api import GROUP_NAME_ANNOTATION
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster


def affinity_store(seed=0, n_nodes=24, n_pods=96):
    return synthetic_cluster(
        n_nodes=n_nodes, n_pods=n_pods, gang_size=4, zones=3,
        affinity_fraction=0.25, anti_affinity_fraction=0.15,
        spread_fraction=0.15, seed=seed,
    )


def placements(store):
    return {f"{p.namespace}/{p.name}": p.node_name
            for p in store.pods.values()}


def solve(store):
    Scheduler(store).run_once()
    return placements(store)


def test_dom_matmul_matches_gather_path(monkeypatch):
    """cnt @ dom_oh must equal the per-element gather bit-for-bit in
    every consumed form (feasibility classification + soft score →
    identical placements)."""
    base = solve(affinity_store(seed=7))
    assert any(v for v in base.values())
    monkeypatch.setattr(wave_mod, "DOM_MM_MAX_MB", 0)  # force gather
    jax.clear_caches()
    try:
        gather = solve(affinity_store(seed=7))
    finally:
        jax.clear_caches()
    assert base == gather


def test_multiwave_shared_terms_match_single_wave(monkeypatch):
    """Multi-wave solves where gangs STRADDLE wave boundaries (gang 5
    over wave 24), so their terms appear in several waves: the disjoint
    detection must turn OFF and the cross-wave count flow must place
    the same task count as the single-wave solve.  Drives solve_wave
    directly with an explicit wave= (the scheduler always uses the
    default wave size; monkeypatching the module constant cannot reach
    the def-time default)."""
    from volcano_tpu.synth import solve_args_from_store

    def term_store():
        return synthetic_cluster(
            n_nodes=24, n_pods=120, gang_size=5, zones=3,
            affinity_fraction=0.3, anti_affinity_fraction=0.2,
            spread_fraction=0.1, seed=11,
        )

    args, _ = solve_args_from_store(term_store())
    single = np.asarray(wave_mod.solve_wave(*args).assigned)

    seen_flags = []
    orig = wave_mod._term_windows

    def spy(*a, **k):
        out = orig(*a, **k)
        seen_flags.append(out[5])
        return out

    monkeypatch.setattr(wave_mod, "_term_windows", spy)
    args2, _ = solve_args_from_store(term_store())
    multi = np.asarray(wave_mod.solve_wave(*args2, wave=24).assigned)

    assert seen_flags and seen_flags[-1] is False, (
        f"gangs of 5 straddling wave-24 boundaries must defeat the "
        f"disjoint detection: {seen_flags}"
    )
    # Cross-shard/cross-wave reduction order may flip score near-ties;
    # placement COUNT parity plus per-solve validity are the invariants.
    assert int((multi >= 0).sum()) == int((single >= 0).sum())
    # Capacity validity: charged requests never exceed allocatable.
    tasks = args2[1]
    nodes = args2[0]
    req = np.asarray(tasks.req)
    alloc = np.asarray(nodes.allocatable)
    used = np.zeros_like(alloc)
    placed = np.flatnonzero(multi[:len(req)] >= 0)
    np.add.at(used, multi[placed], req[placed])
    assert not (used > alloc + 1e-3).any()


def test_forced_nondisjoint_write_back_roundtrip(monkeypatch):
    """Explicitly force the non-disjoint (write-back) compile path on a
    normal store and assert placements match the disjoint path — the
    write-back must be a semantic no-op when terms don't actually
    cross waves."""
    base = solve(affinity_store(seed=13))
    orig = wave_mod._term_windows

    def force_nondisjoint(*a, **k):
        out = orig(*a, **k)
        return (*out[:5], False)

    monkeypatch.setattr(wave_mod, "_term_windows", force_nondisjoint)
    jax.clear_caches()
    try:
        forced = solve(affinity_store(seed=13))
    finally:
        jax.clear_caches()
    assert base == forced


def test_conflict_compaction_overflow_parity(monkeypatch):
    """More than GCAP (256) anti-affinity givers in one wave force the
    full-scatter/full-gather fallback branches: placements must match
    the object path exactly either way."""
    from volcano_tpu.api import AffinityTerm, Node, Pod, PodGroup
    from volcano_tpu.cache import ClusterStore

    # Env guard: the overflow precondition (300 givers in ONE wave,
    # > GCAP = min(256, W)) requires the default wave size; a smaller
    # VOLCANO_TPU_WAVE would make this test silently cover only the
    # compact branch.
    assert wave_mod.DEFAULT_WAVE >= 300, wave_mod.DEFAULT_WAVE

    def build():
        s = ClusterStore()
        for i in range(40):
            s.add_node(Node(name=f"n{i:02d}",
                            allocatable={"cpu": "64", "memory": "128Gi",
                                         "pods": 256}))
        # 300 single-pod anti-affinity jobs sharing ONE app label: every
        # pod is simultaneously a giver and an anti requirer of the same
        # term, so the sub-round conflict machinery sees ~300 giver rows
        # (> GCAP) while capacity forces multi-attempt resolution.
        for j in range(300):
            pg = PodGroup(name=f"anti-{j:03d}", min_member=1)
            s.add_pod_group(pg)
            s.add_pod(Pod(
                name=f"anti-{j:03d}-0",
                labels={"app": "shared"},
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[{"cpu": "1", "memory": "1Gi"}],
                anti_affinity=[AffinityTerm(
                    match_labels={"app": "shared"},
                    topology_key="kubernetes.io/hostname",
                )],
            ))
        return s

    res = {}
    for mode, env in (("fast", "1"), ("object", "0")):
        monkeypatch.setenv("VOLCANO_TPU_FASTPATH", env)
        store = build()
        Scheduler(store).run_once()
        res[mode] = placements(store)
    # Anti-affinity against a shared label: at most one pod per node,
    # 40 nodes -> exactly 40 placed, and the full PLACEMENTS agree.
    assert res["fast"] == res["object"]
    placed = [v for v in res["fast"].values() if v]
    assert len(placed) == 40
    assert len(set(placed)) == len(placed)  # one per node


def test_count_update_overflow_parity(monkeypatch):
    """More than GCAP (256) ACCEPTED matching tasks in one sub-round
    force the count-update full-scatter fallback (soft spread terms:
    every pod matches its job's term and places immediately on roomy
    nodes).  Placements and scores must match the object path."""
    from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup
    from volcano_tpu.cache import ClusterStore

    assert wave_mod.DEFAULT_WAVE >= 300, wave_mod.DEFAULT_WAVE

    def build():
        s = ClusterStore()
        for i in range(8):
            s.add_node(Node(
                name=f"n{i}",
                allocatable={"cpu": "64", "memory": "128Gi",
                             "pods": 256},
                topology={"zone": f"z{i % 4}"},
            ))
        # One shared spread job of 300 pods: every pod matches the
        # job's soft term, capacity accepts all in the first waves.
        pg = PodGroup(name="spread", min_member=300)
        s.add_pod_group(pg)
        for j in range(300):
            s.add_pod(Pod(
                name=f"spread-{j:03d}",
                labels={"app": "spread"},
                annotations={GROUP_NAME_ANNOTATION: pg.name},
                containers=[{"cpu": "1", "memory": "1Gi"}],
                topology_spread=[("zone", 10)],
            ))
        return s

    res = {}
    for mode, env in (("fast", "1"), ("object", "0")):
        monkeypatch.setenv("VOLCANO_TPU_FASTPATH", env)
        store = build()
        Scheduler(store).run_once()
        res[mode] = placements(store)
    assert all(v for v in res["fast"].values())
    assert res["fast"] == res["object"]
