"""Worker payload for the rendezvous e2e (the rebuild's MPI-hello-world
moment, test/e2e/mpi.go:27 analog): consume the env the svc/env job
plugins injected into the bound pod and complete a real
``jax.distributed.initialize`` handshake with the other workers.

Launched as its own OS process per pod by tests/test_rendezvous_e2e.py
(the test plays the kubelet, as kind's node containers do for the
reference's e2e).
"""

import json
import os
import sys


def main() -> None:
    count = int(os.environ["VC_PROCESS_COUNT"])
    pid = int(os.environ["VC_PROCESS_ID"])
    addr = os.environ["VC_COORDINATOR_ADDRESS"]
    host, _, port = addr.rpartition(":")
    # Production resolves the headless-service DNS name
    # (job-task-0.job); this single-host e2e loops back — exactly what
    # kind's cluster DNS does for the reference's MPI example.
    addr = f"127.0.0.1:{port}"

    import jax

    # The CI harness force-selects its accelerator platform regardless of
    # JAX_PLATFORMS; pin CPU through the config API so both workers hold
    # one local CPU device each.
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=count, process_id=pid
    )
    assert jax.process_count() == count, jax.process_count()
    global_devices = len(jax.devices())
    local_devices = len(jax.local_devices())
    print(json.dumps({
        "process_id": pid,
        "process_count": jax.process_count(),
        "global_devices": global_devices,
        "local_devices": local_devices,
        "coordinator": addr,
    }), flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    sys.exit(main())
