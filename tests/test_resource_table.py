"""Resource arithmetic tables (the resource_info_test.go shape, 574 LoC
in the reference — every comparison/arithmetic rule as an asserting
case, including the scalar-dict edge semantics the fit decisions load-
bear on: epsilon quanta, nil-vs-empty scalar dicts, sub's early return,
and the MIN_MILLI_SCALAR pass in less())."""

import pytest

from volcano_tpu.api.resource import (
    MIN_MEMORY,
    MIN_MILLI_CPU,
    MIN_MILLI_SCALAR,
    Resource,
    parse_quantity,
    res_min,
    share,
)

GPU = "nvidia.com/gpu"
Mi = 1024.0 * 1024.0


def R(cpu=0.0, mem=0.0, **scalars):
    r = Resource(cpu, mem)
    for k, v in scalars.items():
        r.set_scalar(k.replace("__", "/").replace("_", "."), v)
    return r


def G(cpu=0.0, mem=0.0, gpu=None):
    r = Resource(cpu, mem)
    if gpu is not None:
        r.set_scalar(GPU, gpu)
    return r


# ---- less_equal (epsilon-tolerant fit, resource_info.go:286-320) ----

LESS_EQUAL_CASES = [
    ("equal", G(4000, 4000), G(4000, 4000), True),
    ("all-below", G(3000, 3000), G(4000, 4000), True),
    ("cpu-above", G(5000, 3000), G(4000, 4000), False),
    ("mem-above", G(3000, 5000 * Mi), G(4000, 4000 * Mi), False),
    ("cpu-within-quantum", G(4000 + MIN_MILLI_CPU / 2, 4000),
     G(4000, 4000), True),
    ("cpu-at-quantum", G(4000 + MIN_MILLI_CPU, 4000),
     G(4000, 4000), False),
    ("mem-within-quantum", G(4000, 4000 + MIN_MEMORY / 2),
     G(4000, 4000), True),
    ("mem-at-quantum", G(4000, 4000 + MIN_MEMORY), G(4000, 4000), False),
    ("gpu-below", G(1000, 1000, gpu=2), G(4000, 4000, gpu=4), True),
    ("gpu-above", G(1000, 1000, gpu=8000), G(4000, 4000, gpu=4000), False),
    # A scalar request of at most one quantum always fits.
    ("gpu-single-quantum-fits-nothing",
     G(1000, 1000, gpu=MIN_MILLI_SCALAR), G(4000, 4000), True),
    ("gpu-missing-on-right", G(1000, 1000, gpu=2 * MIN_MILLI_SCALAR),
     G(4000, 4000), False),
    ("zero-fits-zero", G(), G(), True),
]


@pytest.mark.parametrize("name,l,r,want", LESS_EQUAL_CASES,
                         ids=[c[0] for c in LESS_EQUAL_CASES])
def test_less_equal(name, l, r, want):
    assert l.less_equal(r) is want


# ---- less (strict, resource_info.go:226-261) ----

LESS_CASES = [
    ("all-strictly-below", G(3000, 3000), G(4000, 4000), True),
    ("equal-not-less", G(4000, 4000), G(4000, 4000), False),
    ("cpu-equal-blocks", G(4000, 3000), G(4000, 4000 * Mi), False),
    # nil self scalars vs rhs scalars above the quantum: allowed.
    ("nil-self-scalars-rhs-large", G(1, 1), G(2, 2, gpu=100), True),
    # rhs scalar at/below one quantum blocks the nil-self branch.
    ("nil-self-scalars-rhs-quantum", G(1, 1),
     G(2, 2, gpu=MIN_MILLI_SCALAR), False),
    ("self-scalars-rhs-nil", G(1, 1, gpu=1), G(2, 2), False),
    ("scalar-strictly-below", G(1, 1, gpu=1), G(2, 2, gpu=2), True),
    ("scalar-equal-blocks", G(1, 1, gpu=2), G(2, 2, gpu=2), False),
    # Missing key on rhs reads as 0.
    ("scalar-missing-on-rhs", R(1, 1, a__b=1),
     R(2, 2, c__d=5), False),
]


@pytest.mark.parametrize("name,l,r,want", LESS_CASES,
                         ids=[c[0] for c in LESS_CASES])
def test_less(name, l, r, want):
    assert l.less(r) is want


# ---- less_equal_strict (no epsilon, resource_info.go:264-283) ----

LES_CASES = [
    ("equal", G(4000, 4000), G(4000, 4000), True),
    ("cpu-above-by-epsilon", G(4000 + 1, 4000), G(4000, 4000), False),
    ("scalar-equal", G(1, 1, gpu=2), G(1, 1, gpu=2), True),
    ("scalar-above", G(1, 1, gpu=3), G(1, 1, gpu=2), False),
    ("self-scalar-vs-missing", G(1, 1, gpu=1), G(1, 1), False),
    ("zero-scalar-entry-vs-missing", G(1, 1, gpu=0), G(1, 1), True),
]


@pytest.mark.parametrize("name,l,r,want", LES_CASES,
                         ids=[c[0] for c in LES_CASES])
def test_less_equal_strict(name, l, r, want):
    assert l.less_equal_strict(r) is want


# ---- add / sub (resource_info.go:118-159) ----

def test_add_merges_scalars():
    a = G(1000, 1000, gpu=1)
    b = Resource(2000, 2000)
    b.set_scalar("gpu.x", 3)
    a.add(b)
    assert a.milli_cpu == 3000 and a.memory == 3000
    assert a.scalars[GPU] == 1 and a.scalars["gpu.x"] == 3


def test_add_into_nil_scalars():
    a = G(1000, 1000)
    a.add(G(1, 1, gpu=2))
    assert a.scalars == {GPU: 2}


def test_sub_keeps_zeroed_entries():
    a = G(4000, 4000, gpu=2)
    a.sub(G(1000, 1000, gpu=2))
    # The zeroed entry STAYS in the dict — load-bearing for less()'s
    # nil-vs-empty branch (proportion reclaim semantics).
    assert a.scalars == {GPU: 0.0}


def test_sub_on_nil_scalars_early_returns():
    # sub with self.scalars None skips scalar subtraction entirely
    # (resource.py:132-134) — the subtrahend's scalars must be within
    # epsilon for the sufficiency assert to pass.
    a = G(4000, 4000)
    a.sub(G(1000, 1000, gpu=MIN_MILLI_SCALAR / 2))
    assert a.scalars is None
    assert a.milli_cpu == 3000


def test_sub_asserts_sufficiency():
    a = G(1000, 1000)
    with pytest.raises(AssertionError):
        a.sub(G(2000, 1000))


def test_sub_adds_missing_keys():
    a = G(4000, 4000, gpu=2)
    b = R(0, 0, other_res=0.0)
    a.sub(b)
    assert a.scalars["other.res"] == 0.0


# ---- is_empty / is_zero (resource_info.go:92-116) ----

def test_is_empty_quantum_tolerance():
    assert G(MIN_MILLI_CPU / 2, MIN_MEMORY / 2,
             gpu=MIN_MILLI_SCALAR / 2).is_empty()
    assert not G(MIN_MILLI_CPU * 2, 0).is_empty()
    assert not G(0, 0, gpu=MIN_MILLI_SCALAR).is_empty()


def test_is_zero_per_dimension():
    r = G(MIN_MILLI_CPU / 2, MIN_MEMORY * 2, gpu=MIN_MILLI_SCALAR / 2)
    assert r.is_zero("cpu")
    assert not r.is_zero("memory")
    assert r.is_zero(GPU)
    # Unknown scalar name counts as zero (no entry).
    assert G(0, 0).is_zero(GPU)


# ---- set_max_resource / diff / fit_delta / multi / res_min / share ----

def test_set_max_resource():
    a = G(1000, 4000, gpu=1)
    a.set_max_resource(G(2000, 3000, gpu=4))
    assert (a.milli_cpu, a.memory, a.scalars[GPU]) == (2000, 4000, 4)


def test_diff_splits_increase_and_decrease():
    a = G(3000, 1000, gpu=4)
    b = G(1000, 2000, gpu=1)
    inc, dec = a.diff(b)
    assert inc.milli_cpu == 2000 and inc.memory == 0
    assert inc.scalars[GPU] == 3
    assert dec.milli_cpu == 0 and dec.memory == 1000


def test_multi_scales_everything():
    a = G(1000, 2000, gpu=2).multi(2.5)
    assert (a.milli_cpu, a.memory, a.scalars[GPU]) == (2500, 5000, 5)


def test_res_min():
    m = res_min(G(1000, 4000, gpu=3), G(2000, 3000, gpu=1))
    assert (m.milli_cpu, m.memory, m.scalars[GPU]) == (1000, 3000, 1)


def test_share_zero_denominator():
    assert share(0.0, 0.0) == 0.0
    assert share(5.0, 0.0) == 1.0
    assert share(5.0, 10.0) == 0.5


# ---- parsing (kube resource.Quantity grammar subset) ----

PARSE_CASES = [
    ("1", 1.0),
    ("100m", 0.1),
    ("1500m", 1.5),
    ("1Gi", float(1024 ** 3)),
    ("512Mi", 512 * Mi),
    ("1G", 1e9),
    ("2.5", 2.5),
    (3, 3.0),
    (2.5, 2.5),
]


@pytest.mark.parametrize("q,want", PARSE_CASES,
                         ids=[str(c[0]) for c in PARSE_CASES])
def test_parse_quantity(q, want):
    assert parse_quantity(q) == pytest.approx(want)
