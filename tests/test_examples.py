"""The shipped examples parse and run end-to-end."""

import pathlib

import yaml

from volcano_tpu.framework import parse_scheduler_conf
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.service import job_from_dict

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


def test_job_yaml_runs():
    from volcano_tpu.api import Node
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.controllers import ControllerManager

    data = yaml.safe_load((EXAMPLES / "job.yaml").read_text())
    job = job_from_dict(data)
    assert job.min_available == 3
    assert job.tasks[0].replicas == 6
    store = ClusterStore()
    for i in range(3):
        store.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "4", "memory": "8Gi"}))
    cm = ControllerManager(store)
    store.add_batch_job(job)
    sched = Scheduler(store)
    for _ in range(6):
        cm.process()
        sched.run_once()
    assert len(store.binder.binds) == 6


def test_dist_job_parses():
    data = yaml.safe_load((EXAMPLES / "tensorflow-dist.yaml").read_text())
    job = job_from_dict(data)
    assert {t.name for t in job.tasks} == {"ps", "worker"}
    assert "svc" in job.plugins


def test_dist_job_runs_to_completion():
    """The PS/worker example runs end-to-end: rendezvous env injected,
    TaskCompleted on the workers completes the job (its task-level
    policy), as the reference's distributed-MNIST e2e does
    (test/e2e/tensorflow.go:30)."""
    from volcano_tpu.controllers import ControllerManager
    from volcano_tpu.sim import ClusterSimulator
    from volcano_tpu.api import Node
    from volcano_tpu.cache import ClusterStore

    data = yaml.safe_load((EXAMPLES / "tensorflow-dist.yaml").read_text())
    job = job_from_dict(data)
    store = ClusterStore()
    for i in range(3):
        store.add_node(Node(name=f"n{i}",
                            allocatable={"cpu": "4", "memory": "8Gi",
                                         "pods": 16}))
    cm = ControllerManager(store)
    sched = Scheduler(store)
    sim = ClusterSimulator(store)
    store.add_batch_job(job)
    for _ in range(4):
        cm.process()
        sched.run_once()
        sim.step()
        cm.process()
    pods = [p for p in store.pods.values()
            if p.owner_job == "default/dist-mnist"]
    assert len(pods) == 3
    worker = next(p for p in pods if p.task_name == "worker")
    assert worker.env["WORKER_NUM"] == "2"
    assert "PS_HOSTS" in worker.env
    assert "VC_PROCESS_ID" in worker.env
    # Workers complete -> TaskCompleted task policy -> CompleteJob.
    for _ in range(6):
        cm.process()
        sched.run_once()
        sim.step(complete=lambda p: 0 if p.task_name == "worker"
                 else None)
        cm.process()
    assert store.batch_jobs["default/dist-mnist"].status.state.phase == \
        "Completed"


def test_scheduler_confs_parse():
    for name in ("scheduler-conf.yaml", "preempt-conf.yaml"):
        conf = parse_scheduler_conf((EXAMPLES / name).read_text())
        assert conf.actions
        assert conf.tiers
    conf = parse_scheduler_conf(
        (EXAMPLES / "scheduler-conf.yaml").read_text()
    )
    binpack = [
        o for t in conf.tiers for o in t.plugins if o.name == "binpack"
    ][0]
    assert binpack.arguments["binpack.weight"] == "10"


def test_remote_boundary_example_runs():
    """examples/remote_boundary.py is a runnable demo of the three
    remote side-effect drop-ins; it asserts its own outcomes."""
    import runpy

    runpy.run_path(str(EXAMPLES / "remote_boundary.py"),
                   run_name="__main__")
