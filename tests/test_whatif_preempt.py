"""Device-native preempt + reclaim on the extracted what-if engine
(ISSUE 11, docs/preempt_reclaim.md): victim kernel <-> oracle parity,
the plan-prove-commit acceptance e2e under the pipelined AND mesh
configurations, host-walk parity behind VOLCANO_TPU_EVICT_DEVICE=0,
cross-action budget/ledger interplay, and the lifted rebalance mesh
carve-out.

The legacy suites assert the reference host walk (conftest pins
VOLCANO_TPU_EVICT_DEVICE=0 for them); every device-lane test here opts
in explicitly.
"""

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodGroupPhase,
    PodPhase,
    PriorityClass,
    Queue,
)
from volcano_tpu.cache import ClusterStore, FakeBinder, FakeEvictor
from volcano_tpu.metrics import metrics
from volcano_tpu.oracle import oracle_preempt, oracle_reclaim
from volcano_tpu.ops import victim as vk
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.sim import ClusterSimulator

PREEMPT_CONF = """
actions: "enqueue, allocate, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

RECLAIM_CONF = PREEMPT_CONF.replace("preempt", "reclaim")

MIXED_CONF = """
actions: "enqueue, allocate, backfill, preempt, rebalance"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
  - name: binpack
"""


def _whatif_count(action, outcome):
    key = (("action", action), ("outcome", outcome))
    return metrics.whatif_plans.data.get(key, 0.0)


def running_pod(name, group, cpu, node, prio=None, ns="default"):
    return Pod(
        name=name, namespace=ns,
        annotations={GROUP_NAME_ANNOTATION: group},
        containers=[{"cpu": cpu, "memory": "1Gi"}],
        phase=PodPhase.Running, node_name=node, priority=prio,
    )


def pending_pod(name, group, cpu, prio=None, ns="default"):
    return Pod(
        name=name, namespace=ns,
        annotations={GROUP_NAME_ANNOTATION: group},
        containers=[{"cpu": cpu, "memory": "1Gi"}], priority=prio,
    )


# ------------------------------------------------- kernel/oracle parity


def _random_wave(seed, mode):
    """One randomized victim-plane snapshot, kernel+greedy vs oracle."""
    import jax

    rng = np.random.RandomState(seed)
    V, N, Q, R, U, J = 32, 8, 4, 3, 2, 6
    v_ok = rng.rand(V) > 0.2
    v_jprio = rng.randint(0, 4, V).astype(np.int32)
    v_crank = np.argsort(np.argsort(rng.rand(V))).astype(np.int32)
    v_tie = np.arange(V, dtype=np.int32)
    v_queue = rng.randint(0, Q, V).astype(np.int32)
    v_node = rng.randint(0, N, V).astype(np.int32)
    v_req = (rng.uniform(0.0, 3.0, (V, R))).astype(np.float32)
    v_req[rng.rand(V, R) < 0.2] = 0.0
    p_prio = np.int32(rng.randint(1, 5))
    p_queue = np.int32(rng.randint(0, Q))
    q_alloc = rng.uniform(0.0, 8.0, (Q, R)).astype(np.float32)
    q_des = rng.uniform(1.0, 6.0, (Q, R)).astype(np.float32)
    q_des[rng.rand(Q, R) < 0.3] = 3.0e38  # uncapped slots
    q_rec = rng.rand(Q) > 0.3
    idle = rng.uniform(0.0, 4.0, (N, R)).astype(np.float32)
    prof_req = rng.uniform(0.5, 4.0, (U, R)).astype(np.float32)
    prof_req[rng.rand(U, R) < 0.3] = 0.0
    eps = np.full(R, 1e-3, np.float32)
    need = int(rng.randint(1, 5))
    v_job = rng.randint(0, J, V).astype(np.int64)
    v_group = [f"g{j % 4}" for j in v_job]
    j_ready = rng.randint(0, 4, J).astype(np.int64)
    j_minav = rng.randint(1, 3, J).astype(np.int64)
    budget_left = {f"g{i}": int(rng.randint(0, 5)) for i in range(4)}
    cap = int(rng.randint(1, V))

    planes = vk.victim_scores(
        v_ok, v_jprio, v_crank, v_tie, v_queue, v_node, v_req,
        p_prio, p_queue, q_alloc, q_des, q_rec,
        np.int32(mode), np.zeros((N, R), np.float32))
    eligible, order, evictable, q_share = jax.device_get(
        (planes.eligible, planes.order, planes.evictable,
         planes.q_share))
    qa = q_alloc if mode == vk.RECLAIM else None
    qd = q_des if mode == vk.RECLAIM else None
    sel = vk.select_victims(
        order, eligible, v_node, v_req, v_job, v_group, v_queue,
        need, idle, evictable, prof_req, eps, j_ready, j_minav,
        dict(budget_left), cap, q_alloc=qa, q_deserved=qd)

    oracle_fn = oracle_preempt if mode == vk.PREEMPT else oracle_reclaim
    ref = oracle_fn(
        v_ok, v_jprio, v_crank, v_tie, v_queue, v_node, v_req,
        p_prio, p_queue, q_alloc, q_des, q_rec, idle, prof_req, eps,
        need, v_job, v_group, j_ready, j_minav, dict(budget_left), cap)

    np.testing.assert_array_equal(eligible, ref.eligible,
                                  err_msg=f"seed {seed} eligibility")
    np.testing.assert_array_equal(order, ref.order,
                                  err_msg=f"seed {seed} order")
    np.testing.assert_allclose(q_share, ref.q_share, rtol=1e-6,
                               err_msg=f"seed {seed} q_share")
    assert sel.feasible == ref.feasible, f"seed {seed}"
    assert sel.budget_blocked == ref.budget_blocked, f"seed {seed}"
    assert sel.gain == ref.gain, f"seed {seed}"
    assert list(sel.chosen) == ref.chosen.tolist(), f"seed {seed}"
    return sel.feasible


def test_victim_kernel_oracle_parity_preempt():
    """Eligibility, eviction order, queue shares and the greedy
    selection agree exactly with the Go-shaped oracle on seeded
    fragmented snapshots (preempt tier gating)."""
    feasible_any = False
    for seed in range(8):
        feasible_any |= _random_wave(seed, vk.PREEMPT)
    assert feasible_any, "no seed exercised a feasible wave"


def test_victim_kernel_oracle_parity_reclaim():
    """Same parity under reclaim gating (cross-queue, Reclaimable,
    overused, never below deserved)."""
    for seed in range(8):
        _random_wave(100 + seed, vk.RECLAIM)


# -------------------------------------------------------- acceptance e2e


def _priority_cluster(pipeline=False, mesh=False, workers=4, gang=2):
    store = ClusterStore(evictor=FakeEvictor(), binder=FakeBinder())
    if pipeline:
        store.pipeline = True
    if mesh:
        from volcano_tpu.parallel import make_mesh

        store.solve_mesh = make_mesh(4)
    ClusterSimulator.priority_tier_workload(
        store, workers=workers, serving_tasks=gang)
    return store


def _drive_to_bound(store, sched, sim, name_prefix, count, cycles=16):
    bound = 0
    for _ in range(cycles):
        sched.run_once()
        sim.step()
        bound = sum(1 for p in store.pods.values()
                    if p.name.startswith(name_prefix) and p.node_name)
        if bound >= count:
            break
    return bound


@pytest.mark.parametrize("mesh", [False, True],
                         ids=["pipelined", "mesh"])
def test_preempt_acceptance_e2e(monkeypatch, mesh):
    """Acceptance e2e: a starved high-priority serving gang binds after
    ONE preempt plan cycle plus the eviction grace window, under both
    the pipelined and the mesh (virtual multi-device) configurations —
    victims planned by the jitted kernel, proven by the what-if solve,
    evicted atomically, restored as Pending (zero lost pods), budgets
    never exceeded."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "1")
    committed_before = _whatif_count("preempt", "committed")
    store = _priority_cluster(pipeline=True, mesh=mesh)
    n_logical = len(store.pods)
    sched = Scheduler(store, conf_str=PREEMPT_CONF)
    sim = ClusterSimulator(store, grace_steps=2)

    bound = _drive_to_bound(store, sched, sim, "serving-", 2)
    assert bound >= 2, "serving gang did not bind"
    ledger = store.migrations
    assert ledger is not None and ledger.committed_plans >= 1
    assert _whatif_count("preempt", "committed") > committed_before
    # Zero lost pods: every evicted batch pod restored as Pending and
    # re-entered the store (the ledger's restore hook).
    assert len(store.pods) == n_logical
    restored = [p for p in store.pods.values() if "-mig" in p.uid]
    assert len(restored) >= 2
    assert all(p.phase == "Pending" or p.node_name is None or True
               for p in restored)
    # Budgets: single-member groups with the default max_unavailable=1
    # never see 2 disruptions.
    for uid in {e.group_uid for e in ledger.entries.values()} | {
            f"default/batch{i}" for i in range(4)}:
        assert ledger.disrupted(store, uid) <= 1
    # The ledger entries carry the action + beneficiary gang.
    for e in ledger.entries.values():
        assert e.action == "preempt"
        assert e.for_gang == "default/serving"
    store.close()


def test_preempt_rejects_when_budget_zero(monkeypatch):
    """Atomicity's rejection half: with every batch group's disruption
    budget at 0, the lane plans nothing and mutates NOTHING — no
    evictions, no Releasing pods, outcome counted as rejected-budget."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "1")
    before = _whatif_count("preempt", "rejected-budget")
    store = ClusterStore(evictor=FakeEvictor(), binder=FakeBinder())
    ClusterSimulator.priority_tier_workload(store, workers=2,
                                            serving_tasks=1)
    for i in range(2):
        store.pod_groups[f"default/batch{i}"].max_unavailable = 0
    sched = Scheduler(store, conf_str=PREEMPT_CONF)
    sched.run_once()
    assert not any(p.deleting for p in store.pods.values())
    assert not any(p.phase == "Releasing" for p in store.pods.values())
    assert store.migrations is None or not store.migrations.entries
    assert _whatif_count("preempt", "rejected-budget") == before + 1
    store.close()


def test_pipelined_preempt_stale_plan_voids(monkeypatch):
    """A parked preempt plan voids wholesale when the store mutates
    during the overlap — the old plan never commits, nothing is
    evicted by it."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "1")
    before = _whatif_count("preempt", "stale-voided")
    store = _priority_cluster(pipeline=True)
    sched = Scheduler(store, conf_str=PREEMPT_CONF)
    # Pipelined starvation streak: the plan forms on the second starved
    # pass and parks on the store.
    sched.run_once()
    sched.run_once()
    parked = store._inflight_plan
    assert parked is not None, "plan did not park"
    assert parked.plan.action == "preempt"
    store.add_pod(pending_pod("intruder", "batch0", "1"))
    sched.run_once()
    assert store._inflight_plan is not parked
    assert _whatif_count("preempt", "stale-voided") >= before + 1
    store.close()


def test_reclaim_device_e2e(monkeypatch):
    """Cross-queue reclaim on the engine: a gang in an under-deserved
    queue drains an overused Reclaimable queue down to (never below)
    its deserved share; the gang binds; the victim restores."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "1")
    store = ClusterStore(evictor=FakeEvictor(), binder=FakeBinder())
    store.add_node(Node(name="n1", allocatable={
        "cpu": "4", "memory": "8Gi", "pods": 110}))
    store.add_queue(Queue(name="qa", weight=1, reclaimable=True))
    store.add_queue(Queue(name="qb", weight=1))
    store.add_pod_group(PodGroup(name="ga", min_member=1, queue="qa",
                                 max_unavailable=2))
    store.pod_groups["default/ga"].status.phase = \
        PodGroupPhase.Running.value
    store.add_pod(running_pod("a-0", "ga", "2", "n1"))
    store.add_pod(running_pod("a-1", "ga", "2", "n1"))
    store.add_pod_group(PodGroup(name="gb", min_member=1, queue="qb"))
    store.add_pod(pending_pod("b-0", "gb", "2"))
    sched = Scheduler(store, conf_str=RECLAIM_CONF)
    sim = ClusterSimulator(store, grace_steps=2)
    bound = _drive_to_bound(store, sched, sim, "b-", 1)
    assert bound >= 1, "reclaimer did not bind"
    # Exactly ONE victim: a second eviction would push qa below its
    # deserved share (proportion tier).
    a_pods = [p for p in store.pods.values() if p.name.startswith("a-")]
    assert sum(1 for p in a_pods if p.node_name) == 1
    assert sum(1 for p in a_pods if "-mig" in p.uid) == 1
    ledger = store.migrations
    assert ledger is not None
    assert all(e.action == "reclaim" for e in ledger.entries.values())
    store.close()


# --------------------------------------------------- host-walk parity


def test_host_walk_parity_with_device_off(monkeypatch):
    """VOLCANO_TPU_EVICT_DEVICE=0 keeps the host victim walk
    bind-for-bind with the object-session reference path: identical
    eviction sets and identical surviving pod placements."""

    def build():
        evictor = FakeEvictor()
        store = ClusterStore(evictor=evictor, binder=FakeBinder())
        store.add_node(Node(name="n1", allocatable={
            "cpu": "4", "memory": "8Gi", "pods": 110}))
        store.add_priority_class(PriorityClass(name="high", value=100))
        store.add_priority_class(PriorityClass(name="low", value=1))
        store.add_pod_group(PodGroup(name="lo", min_member=1,
                                     priority_class="low"))
        store.pod_groups["default/lo"].status.phase = \
            PodGroupPhase.Running.value
        store.add_pod(running_pod("lo-0", "lo", "2", "n1", prio=1))
        store.add_pod(running_pod("lo-1", "lo", "2", "n1", prio=1))
        store.add_pod_group(PodGroup(name="hi", min_member=1,
                                     priority_class="high"))
        store.add_pod(pending_pod("hi-0", "hi", "2", prio=100))
        return store, evictor

    monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "0")
    fast_store, fast_ev = build()
    Scheduler(fast_store, conf_str=PREEMPT_CONF).run_once()

    monkeypatch.setenv("VOLCANO_TPU_FASTPATH", "0")
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "always")
    obj_store, obj_ev = build()
    Scheduler(obj_store, conf_str=PREEMPT_CONF).run_once()

    assert sorted(fast_ev.evicts) == sorted(obj_ev.evicts)
    fast_state = sorted((p.name, p.node_name, str(p.phase))
                        for p in fast_store.pods.values())
    obj_state = sorted((p.name, p.node_name, str(p.phase))
                       for p in obj_store.pods.values())
    assert fast_state == obj_state
    # The host walk never touches the what-if machinery.
    assert fast_store.migrations is None
    fast_store.close()
    obj_store.close()


# --------------------------------------- cross-action budget interplay


def test_cross_action_budget_and_ledger_interplay(monkeypatch):
    """Preempt and rebalance active in the same store share ONE
    disruption-budget pool and ONE MigrationLedger: under randomized
    churn no PodGroup's disrupted count ever exceeds its
    max_unavailable — across BOTH actions — and every evicted pod
    either rebinds or is restored (zero lost pods)."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "1")
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "8")
    rng = np.random.RandomState(7)
    store = ClusterStore(evictor=FakeEvictor(), binder=FakeBinder())
    store.add_priority_class(PriorityClass(name="serve", value=1000))
    store.add_priority_class(PriorityClass(name="batch", value=10))
    # 6 x 4cpu worker nodes occupied by 3cpu fillers of ONE shared
    # group (budget 2), plus 6 x 3cpu spill nodes for migrations.
    for i in range(6):
        store.add_node(Node(name=f"w{i}", allocatable={
            "cpu": "4", "memory": "16Gi", "pods": 110}))
        store.add_node(Node(name=f"s{i}", allocatable={
            "cpu": "3", "memory": "16Gi", "pods": 110}))
    store.add_pod_group(PodGroup(name="fill", min_member=1,
                                 max_unavailable=2,
                                 priority_class="batch"))
    for i in range(6):
        store.add_pod(running_pod(f"fill{i}", "fill", "3", f"w{i}",
                                  prio=10))
    # A high-priority serving gang (preempt target) and a default-
    # priority whole-node gang (rebalance target).
    store.add_pod_group(PodGroup(name="serving", min_member=2,
                                 priority_class="serve"))
    for i in range(2):
        store.add_pod(pending_pod(f"serving-{i}", "serving", "4",
                                  prio=1000))
    store.add_pod_group(PodGroup(name="big", min_member=2))
    for i in range(2):
        store.add_pod(pending_pod(f"big-{i}", "big", "4"))
    sched = Scheduler(store, conf_str=MIXED_CONF)
    sim = ClusterSimulator(store, grace_steps=1)

    from volcano_tpu.actions.rebalance import max_unavailable_of

    max_seen = 0
    actions_seen = set()
    churn_seq = 0
    for step in range(24):
        sched.run_once()
        ledger = store.migrations
        if ledger is not None:
            actions_seen |= {e.action for e in ledger.entries.values()}
            d = ledger.disrupted(store, "default/fill")
            max_seen = max(max_seen, d)
            pg = store.pod_groups.get("default/fill")
            assert d <= max_unavailable_of(pg), \
                f"step {step}: budget exceeded across actions ({d})"
        sim.step()
        # Randomized churn: unrelated pods come and go.
        if rng.rand() < 0.4:
            churn_seq += 1
            store.add_pod_group(PodGroup(name=f"c{churn_seq}",
                                         min_member=1))
            store.add_pod(pending_pod(f"churn-{churn_seq}",
                                      f"c{churn_seq}", "1"))
        elif churn_seq and rng.rand() < 0.5:
            gone = [p for p in store.pods.values()
                    if p.name.startswith("churn-")]
            if gone:
                store.delete_pod(gone[0])
    assert max_seen > 0, "no wave ever disrupted the shared group"
    assert "preempt" in actions_seen, "preempt never used the ledger"
    # Zero lost pods: every filler is either the original (bound or
    # terminating) or a restored successor present in the store.
    fillers = [p for p in store.pods.values()
               if p.name.startswith("fill")]
    assert len(fillers) == 6
    serving = [p for p in store.pods.values()
               if p.name.startswith("serving-")]
    assert sum(1 for p in serving if p.node_name) >= 2, \
        "serving gang did not bind"
    store.close()


# ------------------------------------------------- rebalance on engine


def test_rebalance_mesh_carveout_lifted(monkeypatch):
    """Rebalance rides the mesh-aware engine now: with
    ``store.solve_mesh`` set (virtual 4-device) the fragmented-cluster
    migration commits and converges — the ISSUE 7 single-device
    carve-out is gone."""
    monkeypatch.setenv("VOLCANO_TPU_REBALANCE_DRAIN_CAP", "8")
    from volcano_tpu.framework import REBALANCE_SCHEDULER_CONF
    from volcano_tpu.parallel import make_mesh

    store = ClusterStore(binder=FakeBinder())
    store.solve_mesh = make_mesh(4)
    store.add_priority_class(PriorityClass(name="high", value=1000))
    for i in range(4):
        store.add_node(Node(name=f"w{i}", allocatable={
            "cpu": "4", "memory": "16Gi", "pods": 110}))
        store.add_node(Node(name=f"s{i}", allocatable={
            "cpu": "3", "memory": "16Gi", "pods": 110}))
    for i in range(4):
        store.add_pod_group(PodGroup(name=f"f{i}", min_member=1))
        store.add_pod(Pod(
            name=f"fill{i}", namespace="default",
            annotations={GROUP_NAME_ANNOTATION: f"f{i}"},
            containers=[{"cpu": "3", "memory": "1Gi"}],
        ))
    sched = Scheduler(store, conf_str=REBALANCE_SCHEDULER_CONF)
    sim = ClusterSimulator(store, grace_steps=1)
    sched.run_once()
    sim.step()
    store.add_pod_group(PodGroup(name="gang", min_member=2,
                                 priority_class="high"))
    for i in range(2):
        store.add_pod(Pod(
            name=f"g{i}", namespace="default",
            annotations={GROUP_NAME_ANNOTATION: "gang"},
            containers=[{"cpu": "4", "memory": "1Gi"}],
        ))
    bound = _drive_to_bound(store, sched, sim, "g", 2)
    assert bound >= 2, "gang did not bind under the mesh"
    ledger = store.migrations
    assert ledger is not None and ledger.committed_plans >= 1
    store.close()


def test_evict_device_kill_switch(monkeypatch):
    """VOLCANO_TPU_EVICT_DEVICE=0 runs the host walk: evictions happen
    without the what-if engine (no ledger, no whatif plan counts)."""
    monkeypatch.setenv("VOLCANO_TPU_EVICT_DEVICE", "0")
    before = dict(metrics.whatif_plans.data)
    store = _priority_cluster()
    evictor = store.evictor
    sched = Scheduler(store, conf_str=PREEMPT_CONF)
    sched.run_once()
    assert evictor.evicts, "host walk did not evict"
    assert store.migrations is None
    preempt_after = {k: v for k, v in metrics.whatif_plans.data.items()
                     if k[0][1] == "preempt"}
    preempt_before = {k: v for k, v in before.items()
                      if k[0][1] == "preempt"}
    assert preempt_after == preempt_before
    store.close()
