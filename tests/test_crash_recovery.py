"""Mid-solve TPU-crash recovery (the hyperscale-affinity failure mode).

BASELINE.md documents an intermittent remote-TPU-worker crash at
50k x 500k with inter-pod affinity.  The cycle must not be lost to it:
the allocate action catches runtime-crash errors, halves the affinity
chunk budget, re-probes the device, and resumes the cycle with the
remaining pending work — completing degraded instead of failing.  These
tests inject the crash through a fake solver wrapper (the fake-backend
injection VERDICT r3 #4 prescribes).
"""

import numpy as np
import pytest

import volcano_tpu.ops.wave as wave_mod
from volcano_tpu.fastpath import FastCycle
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.synth import synthetic_cluster


def crashing_once(real_fn, crashes, message="TPU worker process crashed"):
    """Wrap the solver: the first ``crashes`` calls raise a runtime
    crash; later calls delegate."""
    state = {"left": crashes, "calls": 0}

    def fn(*args, **kw):
        state["calls"] += 1
        if state["left"] > 0:
            state["left"] -= 1
            raise RuntimeError(message)
        return real_fn(*args, **kw)

    return fn, state


def affinity_store(seed=0):
    return synthetic_cluster(
        n_nodes=48, n_pods=192, gang_size=4, zones=4,
        affinity_fraction=0.2, anti_affinity_fraction=0.1,
        spread_fraction=0.1, seed=seed,
    )


def test_cycle_completes_after_injected_crash(monkeypatch):
    store = affinity_store()
    real = wave_mod.solve_wave
    fake, state = crashing_once(real, crashes=1)
    monkeypatch.setattr(wave_mod, "solve_wave", fake)
    Scheduler(store).run_once()
    assert state["calls"] >= 2  # crashed once, then resumed
    bound = [p for p in store.pods.values() if p.node_name]
    assert len(bound) == len(store.pods)  # cycle completed degraded
    # Budget degraded and the recovery is user-visible.
    assert store._aff_budget_scale == 0.5
    evs = store.events_for("Scheduler/device")
    assert any(e["reason"] == "DeviceCrashRecovered" for e in evs)


def test_repeated_crashes_eventually_propagate(monkeypatch):
    """More than 3 crashes in one cycle give up (health machinery takes
    over) instead of looping forever."""
    store = affinity_store()
    real = wave_mod.solve_wave
    fake, state = crashing_once(real, crashes=99)
    monkeypatch.setattr(wave_mod, "solve_wave", fake)
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "never")
    with pytest.raises(RuntimeError, match="TPU worker"):
        Scheduler(store).run_once()
    assert store._aff_budget_scale <= 0.25


def test_programming_errors_are_not_swallowed(monkeypatch):
    """Only runtime-crash signatures trigger recovery; a genuine bug
    propagates immediately (no silent degradation)."""
    store = affinity_store()
    real = wave_mod.solve_wave
    fake, state = crashing_once(real, crashes=1,
                                message="name 'x' is not defined")
    monkeypatch.setattr(wave_mod, "solve_wave", fake)
    monkeypatch.setenv("VOLCANO_TPU_FALLBACK", "never")
    with pytest.raises(RuntimeError, match="not defined"):
        Scheduler(store).run_once()
    assert getattr(store, "_aff_budget_scale", 1.0) == 1.0


def test_budget_scale_recovers_after_clean_cycles(monkeypatch):
    from volcano_tpu.api import GROUP_NAME_ANNOTATION, Pod, PodGroup

    store = affinity_store()
    real = wave_mod.solve_wave
    fake, state = crashing_once(real, crashes=1)
    monkeypatch.setattr(wave_mod, "solve_wave", fake)
    sched = Scheduler(store)
    sched.run_once()
    assert store._aff_budget_scale == 0.5
    # Fresh pending AFFINITY work each cycle: only affinity-bearing
    # solves count toward walking the degraded budget back up.
    for i in range(FastCycle._SCALE_RECOVER_AFTER):
        pg = PodGroup(name=f"late-{i}", min_member=1)
        store.add_pod_group(pg)
        store.add_pod(Pod(
            name=f"late-{i}-0",
            annotations={GROUP_NAME_ANNOTATION: pg.name},
            containers=[{"cpu": "1", "memory": "1Gi"}],
            topology_spread=[("zone", 10)],
        ))
        sched.run_once()
    # The degraded budget walked back up after the clean streak.
    assert store._aff_budget_scale == 1.0


def test_crash_marker_classification():
    assert FastCycle._is_device_crash(
        RuntimeError("DATA_LOSS: TPU worker process crashed"))
    assert FastCycle._is_device_crash(
        RuntimeError("UNAVAILABLE: Socket closed"))
    assert not FastCycle._is_device_crash(RuntimeError("divide by zero"))
    assert not FastCycle._is_device_crash(
        KeyboardInterrupt("UNAVAILABLE"))
