"""Admission validator tests (table-driven, mirroring
admit_job_test.go) + service/CLI end-to-end."""

import json
import urllib.request

import pytest

from volcano_tpu.api import GROUP_NAME_ANNOTATION, Node, Pod, Queue, QueueState
from volcano_tpu.cache import ClusterStore
from volcano_tpu.controllers import Action, Event, Job, LifecyclePolicy, TaskSpec
from volcano_tpu.webhooks import (
    AdmissionError,
    AdmittedStore,
    validate_job_create,
    validate_job_update,
    validate_queue_delete,
)


def ok_job(**kw):
    defaults = dict(
        name="j1",
        min_available=2,
        tasks=[TaskSpec(name="worker", replicas=2,
                        containers=[{"cpu": "1", "memory": "1Gi"}])],
    )
    defaults.update(kw)
    return Job(**defaults)


@pytest.fixture
def store():
    return ClusterStore()


class TestJobValidation:
    def test_valid_job_passes(self, store):
        validate_job_create(ok_job(), store)

    def test_min_available_zero(self, store):
        with pytest.raises(AdmissionError, match="minAvailable"):
            validate_job_create(ok_job(min_available=0), store)

    def test_min_available_exceeds_replicas(self, store):
        with pytest.raises(AdmissionError, match="total replicas"):
            validate_job_create(ok_job(min_available=5), store)

    def test_duplicate_task_names(self, store):
        tasks = [
            TaskSpec(name="worker", replicas=1,
                     containers=[{"cpu": "1", "memory": "1Gi"}]),
            TaskSpec(name="worker", replicas=1,
                     containers=[{"cpu": "1", "memory": "1Gi"}]),
        ]
        with pytest.raises(AdmissionError, match="duplicated task name"):
            validate_job_create(ok_job(tasks=tasks, min_available=1), store)

    def test_invalid_task_name(self, store):
        tasks = [TaskSpec(name="Not_DNS", replicas=2,
                          containers=[{"cpu": "1", "memory": "1Gi"}])]
        with pytest.raises(AdmissionError, match="DNS-1123"):
            validate_job_create(ok_job(tasks=tasks), store)

    def test_no_tasks(self, store):
        with pytest.raises(AdmissionError, match="No task"):
            validate_job_create(ok_job(tasks=[]), store)

    def test_negative_max_retry(self, store):
        with pytest.raises(AdmissionError, match="maxRetry"):
            validate_job_create(ok_job(max_retry=-1), store)

    def test_policy_event_and_exitcode_exclusive(self, store):
        job = ok_job(policies=[
            LifecyclePolicy(action=Action.RestartJob.value,
                            event=Event.PodFailed.value, exit_code=3)
        ])
        with pytest.raises(AdmissionError, match="simultaneously"):
            validate_job_create(job, store)

    def test_policy_exit_code_zero(self, store):
        job = ok_job(policies=[
            LifecyclePolicy(action=Action.AbortJob.value, exit_code=0)
        ])
        with pytest.raises(AdmissionError, match="not a valid error code"):
            validate_job_create(job, store)

    def test_policy_internal_event_rejected(self, store):
        job = ok_job(policies=[
            LifecyclePolicy(action=Action.RestartJob.value,
                            event=Event.OutOfSync.value)
        ])
        with pytest.raises(AdmissionError, match="invalid policy event"):
            validate_job_create(job, store)

    def test_duplicate_policy_events(self, store):
        job = ok_job(policies=[
            LifecyclePolicy(action=Action.RestartJob.value,
                            event=Event.PodFailed.value),
            LifecyclePolicy(action=Action.AbortJob.value,
                            event=Event.PodFailed.value),
        ])
        with pytest.raises(AdmissionError, match="duplicate event"):
            validate_job_create(job, store)

    def test_unknown_queue(self, store):
        with pytest.raises(AdmissionError, match="queue"):
            validate_job_create(ok_job(queue="nope"), store)

    def test_closed_queue(self, store):
        store.add_queue(Queue(name="closed", state=QueueState.Closed.value))
        with pytest.raises(AdmissionError, match="Open"):
            validate_job_create(ok_job(queue="closed"), store)

    def test_unknown_plugin(self, store):
        with pytest.raises(AdmissionError, match="job plugin"):
            validate_job_create(ok_job(plugins={"nope": []}), store)

    def test_update_replicas_allowed(self):
        old, new = ok_job(), ok_job()
        new.tasks[0].replicas = 4
        validate_job_update(old, new)

    def test_update_task_add_rejected(self):
        old, new = ok_job(), ok_job()
        new.tasks = new.tasks + [
            TaskSpec(name="x", replicas=1,
                     containers=[{"cpu": "1", "memory": "1Gi"}])
        ]
        with pytest.raises(AdmissionError, match="add or remove"):
            validate_job_update(old, new)

    def test_update_queue_change_rejected(self):
        old, new = ok_job(), ok_job()
        new.queue = "other"
        with pytest.raises(AdmissionError, match="may not change"):
            validate_job_update(old, new)


class TestQueueAndPodAdmission:
    def test_default_queue_undeletable(self):
        with pytest.raises(AdmissionError, match="can not be deleted"):
            validate_queue_delete("default")

    def test_pod_gated_until_podgroup_leaves_pending(self, store):
        from volcano_tpu.api import PodGroup

        admitted = AdmittedStore(store)
        store.add_pod_group(PodGroup(name="pg1", min_member=1))
        pod = Pod(name="p0", annotations={GROUP_NAME_ANNOTATION: "pg1"},
                  containers=[{"cpu": "1", "memory": "1Gi"}])
        with pytest.raises(AdmissionError, match="podgroup phase"):
            admitted.add_pod(pod)
        store.pod_groups["default/pg1"].status.phase = "Inqueue"
        admitted.add_pod(pod)  # passes now


class TestServiceAndCli:
    @pytest.fixture
    def service(self):
        from volcano_tpu.service import Service

        svc = Service(simulate=True, schedule_period=0.05,
                      controller_period=0.05)
        svc.store.add_node(
            Node(name="n1", allocatable={"cpu": "8", "memory": "16Gi",
                                         "pods": 110})
        )
        port = svc.start(http_port=0)
        yield svc, f"http://127.0.0.1:{port}"
        svc.stop()

    def test_submit_job_over_http_and_cli_flow(self, service):
        import time

        from volcano_tpu.cli.main import main

        svc, server = service
        # Submit via CLI.
        assert main(["--server", server, "job", "run", "--name", "cj",
                     "--replicas", "2", "--min-available", "2"]) == 0
        # Wait for it to run.
        deadline = time.time() + 10
        while time.time() < deadline:
            job = svc.store.batch_jobs.get("default/cj")
            if job and job.status.state.phase == "Running":
                break
            time.sleep(0.1)
        assert svc.store.batch_jobs["default/cj"].status.state.phase == "Running"
        # job list / view via CLI (stdout not asserted, must not raise).
        assert main(["--server", server, "job", "list"]) == 0
        assert main(["--server", server, "job", "view", "--name", "cj"]) == 0
        # Suspend -> Aborted.
        assert main(["--server", server, "job", "suspend",
                     "--name", "cj"]) == 0
        deadline = time.time() + 10
        while time.time() < deadline:
            if (svc.store.batch_jobs["default/cj"].status.state.phase
                    == "Aborted"):
                break
            time.sleep(0.1)
        assert (svc.store.batch_jobs["default/cj"].status.state.phase
                == "Aborted")

    def test_queue_cli(self, service):
        from volcano_tpu.cli.main import main

        svc, server = service
        assert main(["--server", server, "queue", "create", "--name", "q9",
                     "--weight", "4"]) == 0
        assert "q9" in svc.store.raw_queues
        assert main(["--server", server, "queue", "list"]) == 0
        assert main(["--server", server, "queue", "operate", "--name", "q9",
                     "-a", "close"]) == 0
        assert main(["--server", server, "queue", "delete",
                     "--name", "q9"]) == 0
        assert "q9" not in svc.store.raw_queues

    def test_rejected_job_returns_error(self, service):
        svc, server = service
        req = urllib.request.Request(
            server + "/apis/jobs",
            data=json.dumps({"name": "bad", "minAvailable": 0,
                             "tasks": []}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req)
        assert err.value.code == 400

    def test_metrics_and_healthz(self, service):
        svc, server = service
        with urllib.request.urlopen(server + "/healthz") as r:
            assert r.read() == b"ok"
        with urllib.request.urlopen(server + "/metrics") as r:
            text = r.read().decode()
        assert "volcano_e2e_scheduling_latency_milliseconds" in text


import urllib.error  # noqa: E402


class TestClientLib:
    """volcano_tpu.client: the thin client lib + in-memory fake
    (SURVEY.md 2.3, pkg/client analog)."""

    @pytest.fixture
    def service(self):
        from volcano_tpu.service import Service

        svc = Service(simulate=True, schedule_period=0.05,
                      controller_period=0.05)
        port = svc.start(http_port=0)
        yield svc, f"http://127.0.0.1:{port}"
        svc.stop()

    def test_client_against_live_service(self, service):
        import time

        from volcano_tpu.client import ApiError, Client

        svc, server = service
        c = Client(server)
        assert c.healthz()
        c.add_node("cn-0", {"cpu": "8", "memory": "16Gi", "pods": 64},
                   topology={"volcano-tpu/slice": "s0"})
        c.create_queue("cq", weight=3)
        assert any(q["name"] == "cq" and q["weight"] == 3
                   for q in c.queues())
        c.create_job({"name": "cjob", "minAvailable": 2, "queue": "cq",
                      "tasks": [{"name": "w", "replicas": 2,
                                 "containers": [{"cpu": "1",
                                                 "memory": "1Gi"}]}]})
        deadline = time.time() + 10
        while time.time() < deadline:
            if c.get_job("cjob")["status"]["phase"] == "Running":
                break
            time.sleep(0.1)
        assert c.get_job("cjob")["status"]["phase"] == "Running"
        assert any(j["name"] == "cjob" for j in c.jobs("default"))
        c.suspend_job("cjob")
        deadline = time.time() + 10
        while time.time() < deadline:
            if c.get_job("cjob")["status"]["phase"] == "Aborted":
                break
            time.sleep(0.1)
        assert c.get_job("cjob")["status"]["phase"] == "Aborted"
        c.delete_job("cjob")
        with pytest.raises(ApiError) as err:
            c.get_job("cjob")
        assert err.value.status == 404
        assert "volcano" in c.metrics_text()

    def test_fake_client_mirrors_client_surface(self):
        from volcano_tpu.client import ApiError, Client, FakeClient

        fc = FakeClient()
        # Same public surface as the HTTP client.
        public = {n for n in dir(Client) if not n.startswith("_")}
        assert public <= {n for n in dir(FakeClient)
                          if not n.startswith("_")}
        fc.add_node("n0", {"cpu": "4", "memory": "8Gi"})
        fc.create_queue("fq", weight=2)
        out = fc.create_job({
            "name": "fj", "minAvailable": 1, "queue": "fq",
            "tasks": [{"name": "w", "replicas": 1,
                       "containers": [{"cpu": "1", "memory": "1Gi"}]}],
        })
        assert out["name"] == "fj"
        assert fc.get_job("fj")["queue"] == "fq"
        fc.delete_job("fj")
        with pytest.raises(ApiError):
            fc.get_job("fj")
