"""Mirror churn fuzz: randomized interleavings of store mutations must
leave the struct-of-arrays mirror equivalent to the object model.

The mirror (cache/mirror.py, the incremental snapshot serializer) is
maintained through every add/update/delete/bind/evict path plus
compaction; any drift between it and the pod records silently corrupts
the fast path's whole view of the cluster.  This harness drives random
mutation sequences and asserts full equivalence after every burst, then
checks that scheduling the churned store matches scheduling a FRESH
store built from the surviving state (the strongest end-to-end
equivalence: the mirror's dense state is the only input the solver
sees)."""

import copy

import numpy as np
import pytest

from volcano_tpu.api import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PodPhase,
    TaskStatus,
)
from volcano_tpu.cache import ClusterStore
from volcano_tpu.scheduler import Scheduler


def check_mirror_equivalence(store: ClusterStore) -> None:
    """The mirror's live rows must agree with the pod records."""
    m = store.mirror
    live = {}
    for uid, row in m.p_row.items():
        assert m.p_uid[row] == uid
        live[uid] = row
    # Every stored pod has a row; every live row has a stored pod.
    for uid, pod in store.pods.items():
        assert uid in live, f"pod {uid} missing from mirror"
        row = live[uid]
        assert m.p_key[row] == f"{pod.namespace}/{pod.name}"
        st = int(m.p_status[row])
        if pod.deleting:
            assert st == int(TaskStatus.Releasing), (uid, st)
        elif pod.phase == PodPhase.Succeeded:
            assert st == int(TaskStatus.Succeeded)
        elif pod.phase == PodPhase.Failed:
            assert st == int(TaskStatus.Failed)
        elif pod.node_name is None:
            assert st == int(TaskStatus.Pending), (uid, st)
    extra = set(live) - set(store.pods)
    assert not extra, f"mirror rows with no pod: {extra}"


def rebuild_from_survivors(store: ClusterStore) -> ClusterStore:
    fresh = ClusterStore()
    for q in store.raw_queues.values():
        if q.name != "default":
            fresh.add_queue(q)
    for name, ni in store.nodes.items():
        if ni.node is not None:
            fresh.add_node(ni.node)
    for pg in store.pod_groups.values():
        pg2 = copy.deepcopy(pg)
        pg2.status.phase = "Pending"
        pg2.status.conditions = []
        fresh.add_pod_group(pg2)
    for pod in store.pods.values():
        if pod.deleting:
            continue
        p2 = copy.copy(pod)
        p2.env = dict(pod.env)
        fresh.add_pod(p2)
    return fresh


@pytest.mark.parametrize("seed", range(6))
def test_churn_keeps_mirror_equivalent(seed):
    rng = np.random.default_rng(seed)
    store = ClusterStore()
    n_nodes = int(rng.integers(3, 8))
    for i in range(n_nodes):
        store.add_node(Node(
            name=f"n{i}", allocatable={"cpu": "16", "memory": "32Gi"},
        ))
    next_id = [0]
    pods: list = []

    def add_gang():
        g = next_id[0]
        next_id[0] += 1
        size = int(rng.integers(1, 4))
        store.add_pod_group(PodGroup(
            name=f"g{g}", min_member=int(rng.integers(1, size + 1)),
        ))
        for k in range(size):
            p = Pod(
                name=f"g{g}-{k}",
                annotations={GROUP_NAME_ANNOTATION: f"g{g}"},
                containers=[{"cpu": str(int(rng.integers(1, 4))),
                             "memory": "1Gi"}],
            )
            store.add_pod(p)
            pods.append(p.uid)

    def delete_some():
        if not pods:
            return
        for _ in range(min(len(pods), int(rng.integers(1, 5)))):
            uid = pods.pop(int(rng.integers(0, len(pods))))
            pod = store.pods.get(uid)
            if pod is not None:
                store.delete_pod(pod)

    def finish_some():
        running = [p for p in store.pods.values()
                   if p.node_name and not p.deleting]
        for pod in running[: int(rng.integers(0, 3))]:
            p2 = copy.copy(pod)
            p2.phase = (PodPhase.Succeeded if rng.random() < 0.5
                        else PodPhase.Failed)
            store.update_pod(p2)

    for burst in range(6):
        for _ in range(int(rng.integers(1, 5))):
            op = rng.random()
            if op < 0.5:
                add_gang()
            elif op < 0.8:
                delete_some()
            else:
                finish_some()
        Scheduler(store).run_once()
        store.mirror.maybe_compact()
        check_mirror_equivalence(store)

    # Strongest check: one more cycle on the CHURNED store (whose solver
    # input is the incrementally-maintained mirror) must place exactly
    # like a FRESH store rebuilt from the surviving spec state (whose
    # mirror was built in one shot).
    fresh = rebuild_from_survivors(store)
    Scheduler(store).run_once()
    Scheduler(fresh).run_once()
    a = {f"{p.namespace}/{p.name}": p.node_name
         for p in store.pods.values() if not p.deleting}
    b = {f"{p.namespace}/{p.name}": p.node_name
         for p in fresh.pods.values()}
    assert a == b
