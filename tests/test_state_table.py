"""Every job state x action transition cell, table-driven.

The shape of ``pkg/controllers/job/job_state_test.go`` (1,295 LoC — the
reference's largest test file), tightened: each row pins down which
controller verb the state dispatches (sync_job vs kill_job), the
pod-retain set, the resulting phase given a status-count scenario, and
the retry-count delta.  Transition logic cites
``pkg/controllers/job/state/*.go`` per state class in
``volcano_tpu/controllers/state.py``.
"""

import pytest

from volcano_tpu.controllers.apis import (
    Action,
    Job,
    JobPhase,
    JobStatus,
    TaskSpec,
)
from volcano_tpu.controllers.state import (
    POD_RETAIN_PHASE_NONE,
    POD_RETAIN_PHASE_SOFT,
    new_state,
)


class RecordingCtrl:
    """Stands in for the JobController: records the dispatched verb and
    retain set, then applies the transition closure to the scenario's
    status counts — exactly what sync_job/kill_job do after reconciling
    pods (job_controller.py)."""

    def __init__(self):
        self.verb = None
        self.retain = None

    def sync_job(self, job, update_status):
        self.verb = "sync"
        if update_status is not None:
            update_status(job.status)

    def kill_job(self, job, retain_phases, update_status):
        self.verb = "kill"
        self.retain = set(retain_phases)
        if update_status is not None:
            update_status(job.status)


def make_job(phase, *, replicas=3, min_available=2, max_retry=3,
             retry_count=0, pending=0, running=0, succeeded=0, failed=0,
             terminating=0):
    job = Job(
        name="t",
        min_available=min_available,
        max_retry=max_retry,
        tasks=[TaskSpec(name="w", replicas=replicas,
                        containers=[{"cpu": "1"}])],
    )
    job.status = JobStatus(
        pending=pending, running=running, succeeded=succeeded,
        failed=failed, terminating=terminating,
        retry_count=retry_count, min_available=min_available,
    )
    job.status.state.phase = phase.value
    return job


SYNC = ("sync", None)
KILL_NONE = ("kill", POD_RETAIN_PHASE_NONE)
KILL_SOFT = ("kill", POD_RETAIN_PHASE_SOFT)

# Each row: (name, phase, action, job kwargs, expected (verb, retain),
#            expected phase, expected retry delta)
CELLS = [
    # ---------------- Pending (state/pending.go) ----------------
    ("pending-restart", JobPhase.Pending, Action.RestartJob, {},
     KILL_NONE, JobPhase.Restarting, 1),
    ("pending-abort", JobPhase.Pending, Action.AbortJob, {},
     KILL_SOFT, JobPhase.Aborting, 0),
    ("pending-complete", JobPhase.Pending, Action.CompleteJob, {},
     KILL_SOFT, JobPhase.Completing, 0),
    ("pending-terminate", JobPhase.Pending, Action.TerminateJob, {},
     KILL_SOFT, JobPhase.Terminating, 0),
    ("pending-sync-below-minavailable", JobPhase.Pending, Action.SyncJob,
     dict(running=1), SYNC, JobPhase.Pending, 0),
    ("pending-sync-reaches-minavailable", JobPhase.Pending,
     Action.SyncJob, dict(running=2), SYNC, JobPhase.Running, 0),
    ("pending-sync-minavailable-mixed-counts", JobPhase.Pending,
     Action.SyncJob, dict(running=1, succeeded=1), SYNC,
     JobPhase.Running, 0),
    ("pending-resume-falls-to-sync", JobPhase.Pending, Action.ResumeJob,
     dict(running=0), SYNC, JobPhase.Pending, 0),
    # ---------------- Running (state/running.go) ----------------
    ("running-restart", JobPhase.Running, Action.RestartJob,
     dict(running=3), KILL_NONE, JobPhase.Restarting, 1),
    ("running-abort", JobPhase.Running, Action.AbortJob, dict(running=3),
     KILL_SOFT, JobPhase.Aborting, 0),
    ("running-terminate", JobPhase.Running, Action.TerminateJob,
     dict(running=3), KILL_SOFT, JobPhase.Terminating, 0),
    ("running-complete", JobPhase.Running, Action.CompleteJob,
     dict(running=3), KILL_SOFT, JobPhase.Completing, 0),
    ("running-sync-still-running", JobPhase.Running, Action.SyncJob,
     dict(running=3), SYNC, JobPhase.Running, 0),
    ("running-sync-all-done-enough-succeeded", JobPhase.Running,
     Action.SyncJob, dict(succeeded=2, failed=1), SYNC,
     JobPhase.Completed, 0),
    ("running-sync-all-done-too-few-succeeded", JobPhase.Running,
     Action.SyncJob, dict(succeeded=1, failed=2), SYNC,
     JobPhase.Failed, 0),
    ("running-sync-partial-done", JobPhase.Running, Action.SyncJob,
     dict(running=1, succeeded=2), SYNC, JobPhase.Running, 0),
    # ---------------- Restarting (state/restarting.go) ----------------
    # Any action: the state machine is already mid-restart.
    ("restarting-retries-exhausted", JobPhase.Restarting, Action.SyncJob,
     dict(retry_count=3), KILL_NONE, JobPhase.Failed, 0),
    ("restarting-pods-gone-to-pending", JobPhase.Restarting,
     Action.SyncJob, dict(retry_count=1, terminating=1), KILL_NONE,
     JobPhase.Pending, 0),
    ("restarting-waiting-on-terminating", JobPhase.Restarting,
     Action.SyncJob, dict(retry_count=1, terminating=2), KILL_NONE,
     JobPhase.Restarting, 0),
    ("restarting-ignores-restart-action", JobPhase.Restarting,
     Action.RestartJob, dict(retry_count=1, terminating=1), KILL_NONE,
     JobPhase.Pending, 0),
    # ---------------- Aborting (state/aborting.go) ----------------
    ("aborting-resume", JobPhase.Aborting, Action.ResumeJob, {},
     KILL_SOFT, JobPhase.Restarting, 1),
    ("aborting-waits-for-pods", JobPhase.Aborting, Action.SyncJob,
     dict(terminating=1), KILL_SOFT, JobPhase.Aborting, 0),
    ("aborting-pods-gone", JobPhase.Aborting, Action.SyncJob, {},
     KILL_SOFT, JobPhase.Aborted, 0),
    ("aborting-abort-again-noop", JobPhase.Aborting, Action.AbortJob,
     dict(running=1), KILL_SOFT, JobPhase.Aborting, 0),
    # ---------------- Aborted (state/aborted.go) ----------------
    ("aborted-resume", JobPhase.Aborted, Action.ResumeJob, {},
     KILL_SOFT, JobPhase.Restarting, 1),
    ("aborted-other-stays", JobPhase.Aborted, Action.RestartJob, {},
     KILL_SOFT, JobPhase.Aborted, 0),
    ("aborted-sync-stays", JobPhase.Aborted, Action.SyncJob, {},
     KILL_SOFT, JobPhase.Aborted, 0),
    # ---------------- Terminating (state/terminating.go) ----------------
    ("terminating-waits-for-pods", JobPhase.Terminating, Action.SyncJob,
     dict(pending=1), KILL_SOFT, JobPhase.Terminating, 0),
    ("terminating-pods-gone", JobPhase.Terminating, Action.SyncJob, {},
     KILL_SOFT, JobPhase.Terminated, 0),
    ("terminating-ignores-resume", JobPhase.Terminating,
     Action.ResumeJob, {}, KILL_SOFT, JobPhase.Terminated, 0),
    # ---------------- Completing (state/completing.go) ----------------
    ("completing-waits-for-pods", JobPhase.Completing, Action.SyncJob,
     dict(running=1), KILL_SOFT, JobPhase.Completing, 0),
    ("completing-pods-gone", JobPhase.Completing, Action.SyncJob,
     dict(succeeded=3), KILL_SOFT, JobPhase.Completed, 0),
    # ---------------- Finished (state/finished.go) ----------------
    ("completed-any-action-stays", JobPhase.Completed, Action.RestartJob,
     {}, KILL_SOFT, JobPhase.Completed, 0),
    ("failed-any-action-stays", JobPhase.Failed, Action.ResumeJob, {},
     KILL_SOFT, JobPhase.Failed, 0),
    ("terminated-sync-stays", JobPhase.Terminated, Action.SyncJob, {},
     KILL_SOFT, JobPhase.Terminated, 0),
]


@pytest.mark.parametrize(
    "name,phase,action,kw,expected_call,expected_phase,retry_delta",
    CELLS, ids=[c[0] for c in CELLS])
def test_state_action_cell(name, phase, action, kw, expected_call,
                           expected_phase, retry_delta):
    job = make_job(phase, **kw)
    before_retry = job.status.retry_count
    ctrl = RecordingCtrl()
    new_state(ctrl, job).execute(action.value)
    verb, retain = expected_call
    assert ctrl.verb == verb, f"{name}: dispatched {ctrl.verb}"
    if retain is not None:
        assert ctrl.retain == retain
    assert job.status.state.phase == expected_phase.value
    assert job.status.retry_count - before_retry == retry_delta


def test_factory_maps_every_phase():
    """state/factory.go NewState: each phase resolves to its state class,
    unknown/terminal phases fall through to Finished semantics."""
    from volcano_tpu.controllers import state as st

    expected = {
        JobPhase.Pending: st.PendingState,
        JobPhase.Running: st.RunningState,
        JobPhase.Restarting: st.RestartingState,
        JobPhase.Aborting: st.AbortingState,
        JobPhase.Aborted: st.AbortedState,
        JobPhase.Terminating: st.TerminatingState,
        JobPhase.Completing: st.CompletingState,
        JobPhase.Completed: st.FinishedState,
        JobPhase.Terminated: st.FinishedState,
        JobPhase.Failed: st.FinishedState,
    }
    for phase, cls in expected.items():
        job = make_job(phase)
        assert isinstance(st.new_state(RecordingCtrl(), job), cls), phase
    # Empty phase (fresh job) is Pending.
    job = make_job(JobPhase.Pending)
    job.status.state.phase = ""
    assert isinstance(st.new_state(RecordingCtrl(), job), st.PendingState)


def test_default_max_retry_applies_when_zero():
    """RestartingState falls back to DEFAULT_MAX_RETRY when the spec's
    maxRetry is 0 (restarting.go)."""
    job = make_job(JobPhase.Restarting, max_retry=0, retry_count=3)
    ctrl = RecordingCtrl()
    new_state(ctrl, job).execute(Action.SyncJob.value)
    assert job.status.state.phase == JobPhase.Failed.value


# ---------------------------------------------------------------------------
# Queue 5-state machine (pkg/controllers/queue/state/{factory,open,closed,
# closing,unknown}.go), table-driven like the job table above.  "" is Open
# (factory.go NewState: `case "", v1beta1.QueueStateOpen`).
# ---------------------------------------------------------------------------

def _queue_env(state, n_pgs):
    from volcano_tpu.api import PodGroup, Queue
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.controllers.queue_controller import QueueController

    store = ClusterStore()
    qc = QueueController(store)
    q = Queue(name="q")
    q.state = state
    store.add_queue(q)
    for i in range(n_pgs):
        store.add_pod_group(PodGroup(name=f"pg-{i}", queue="q"))
    qc.queue.clear()  # table rows drive _handle_queue directly
    return store, qc, q


# (state, action, n_pgs) -> expected resulting state.  Every cell of the
# reference machine, including the v0.4 quirk: a plain Sync on Closing or
# Unknown re-derives to Unknown (closing.go/unknown.go default branch —
# the recorded state is neither Open nor Closed).
QUEUE_TABLE = [
    ("", "OpenQueue", 0, "Open"),
    ("", "CloseQueue", 0, "Closed"),
    ("", "CloseQueue", 2, "Closing"),
    ("", "SyncQueue", 2, "Open"),
    ("Open", "OpenQueue", 2, "Open"),
    ("Open", "CloseQueue", 0, "Closed"),
    ("Open", "CloseQueue", 2, "Closing"),
    ("Open", "SyncQueue", 2, "Open"),
    ("Closed", "OpenQueue", 0, "Open"),
    ("Closed", "CloseQueue", 0, "Closed"),
    ("Closed", "CloseQueue", 2, "Closed"),  # closed.go: Sync(state=Closed)
    ("Closed", "SyncQueue", 2, "Closed"),
    ("Closing", "OpenQueue", 2, "Open"),
    ("Closing", "CloseQueue", 0, "Closed"),
    ("Closing", "CloseQueue", 2, "Closing"),
    ("Closing", "SyncQueue", 2, "Unknown"),
    ("Unknown", "OpenQueue", 2, "Open"),
    ("Unknown", "CloseQueue", 0, "Closed"),
    ("Unknown", "CloseQueue", 2, "Closing"),
    ("Unknown", "SyncQueue", 2, "Unknown"),
]


@pytest.mark.parametrize("state,action,n_pgs,expected", QUEUE_TABLE)
def test_queue_state_table(state, action, n_pgs, expected):
    store, qc, q = _queue_env(state, n_pgs)
    qc._handle_queue(action, "q")
    assert q.state == expected, (state, action, n_pgs)


def test_queue_open_close_events_on_transition():
    """openQueue/closeQueue record events only on an actual state change
    (queue_controller_action.go recorder.Event calls)."""
    store, qc, q = _queue_env("Open", 1)
    qc._handle_queue("CloseQueue", "q")
    evs = store.events_for("Queue/q")
    assert any(e["reason"] == "CloseQueue"
               and "Close queue succeed" in e["message"] for e in evs)
    qc._handle_queue("OpenQueue", "q")
    evs = store.events_for("Queue/q")
    assert any(e["reason"] == "OpenQueue"
               and "Open queue succeed" in e["message"] for e in evs)
    # Re-opening an Open queue records nothing new (openQueue early
    # return when the state already matches).
    before = len(store.events_for("Queue/q"))
    qc._handle_queue("OpenQueue", "q")
    assert len(store.events_for("Queue/q")) == before


def test_queue_status_phase_counts():
    """syncQueue tallies PodGroup phases into the status
    (queue_controller_action.go:34-82)."""
    from volcano_tpu.api import PodGroupPhase

    store, qc, q = _queue_env("Open", 4)
    pgs = [store.pod_groups[f"default/pg-{i}"] for i in range(4)]
    pgs[0].status.phase = PodGroupPhase.Running.value
    pgs[1].status.phase = PodGroupPhase.Inqueue.value
    pgs[2].status.phase = PodGroupPhase.Unknown.value
    qc._handle_queue("SyncQueue", "q")
    st = qc.status["q"]
    assert (st.pending, st.running, st.unknown, st.inqueue) == (1, 1, 1, 1)
    assert st.state == "Open"


def test_queue_request_retry_then_drop_records_event(monkeypatch):
    """A persistently-failing request retries MAX_RETRIES times, then is
    dropped with a Warning event naming the action
    (queue_controller.go handleQueueErr -> recordEventsForQueue)."""
    from volcano_tpu.controllers import queue_controller as qcm

    store, qc, q = _queue_env("Open", 0)
    calls = {"n": 0}

    def boom(action, name):
        calls["n"] += 1
        raise RuntimeError("induced sync failure")

    monkeypatch.setattr(qc, "_handle_queue", boom)
    qc.queue.append(("SyncQueue", "q"))
    for _ in range(qcm.MAX_RETRIES + 2):
        qc.process_all()
    assert calls["n"] == qcm.MAX_RETRIES + 1  # first try + retries
    assert not qc.queue
    evs = store.events_for("Queue/q")
    assert any("failed" in e["message"] for e in evs)


def test_queue_pg_index_incremental():
    """The queue->PodGroup index updates from watch events, not scans
    (queue_controller_handler.go addPodGroup/deletePodGroup)."""
    from volcano_tpu.api import PodGroup

    store, qc, q = _queue_env("Open", 1)
    assert qc.pod_groups["q"] == {"default/pg-0"}
    store.add_pod_group(PodGroup(name="pg-x", queue="q"))
    assert qc.pod_groups["q"] == {"default/pg-0", "default/pg-x"}
    store.delete_pod_group("default/pg-0")
    assert qc.pod_groups["q"] == {"default/pg-x"}
    # Closing drains to Closed via an explicit CloseQueue once empty.
    store.delete_pod_group("default/pg-x")
    qc._handle_queue("CloseQueue", "q")
    assert q.state == "Closed"


def test_queue_sync_not_self_driven_by_own_writebacks():
    """The controller's own update_queue write-backs must not enqueue
    syncs (updateQueue is a no-op handler in the reference) — otherwise
    closing a non-empty queue self-drives Closing -> Unknown with no
    external event."""
    from volcano_tpu.controllers import Command

    store, qc, q = _queue_env("Open", 2)
    store.add_command(Command(action="CloseQueue", target_kind="Queue",
                              target_name="q"))
    qc.process_all()
    assert q.state == "Closing"
    # Further empty process passes leave the state alone: no self-syncs.
    qc.process_all()
    qc.process_all()
    assert q.state == "Closing"


def test_queue_pg_move_updates_both_indexes():
    """A PodGroup that moves queues leaves the old queue's index (the
    reference's updatePodGroup handles the phase path; the rebuild also
    covers Spec.Queue moves so the old queue can drain)."""
    from volcano_tpu.api import PodGroup

    store, qc, q2 = _queue_env("Open", 1)
    from volcano_tpu.api import Queue

    store.add_queue(Queue(name="q2"))
    pg = store.pod_groups["default/pg-0"]
    pg.queue = "q2"
    store.update_pod_group(pg)
    assert qc.pod_groups["q"] == set()
    assert qc.pod_groups["q2"] == {"default/pg-0"}
    qc.process_all()
    # The vacated queue can now drain to Closed.
    qc._handle_queue("CloseQueue", "q")
    assert store.raw_queues["q"].state == "Closed"


def test_queue_pg_index_survives_sync_before_queue_exists():
    """Watch ordering across kinds is not guaranteed: a PodGroup (and its
    SyncQueue) can arrive before its Queue object.  The NotFound sync must
    not wipe the incrementally-built index (the reference's handleQueue
    touches neither podGroups nor queueStatus on NotFound) — otherwise the
    late-created queue permanently reports zero PodGroups."""
    from volcano_tpu.api import PodGroup, Queue
    from volcano_tpu.cache import ClusterStore
    from volcano_tpu.controllers.queue_controller import QueueController

    store = ClusterStore()
    qc = QueueController(store)
    store.add_pod_group(PodGroup(name="pg-0", queue="late"))
    qc.process_all()  # SyncQueue("late") -> NotFound
    assert qc.pod_groups.get("late") == {"default/pg-0"}
    q = Queue(name="late")
    store.add_queue(q)
    qc.process_all()
    assert qc.status["late"].pending == 1
    # CloseQueue on the non-empty queue drains to Closing, not Closed.
    qc._handle_queue("CloseQueue", "late")
    assert q.state == "Closing"


def test_queue_spec_only_pg_update_does_not_resync():
    """updatePodGroup re-enqueues a sync only on a phase change
    ("oldPG.Status.Phase != newPG.Status.Phase",
    queue_controller_handler.go).  A spec-only update must not sync — a
    Sync on a Closing queue derives Unknown (the v0.4 quirk), so a no-op
    update would corrupt the state."""
    store, qc, q = _queue_env("Open", 1)
    qc._handle_queue("CloseQueue", "q")
    assert q.state == "Closing"
    pg = store.pod_groups["default/pg-0"]
    store.update_pod_group(pg)  # same queue, same phase
    qc.process_all()
    assert q.state == "Closing"
    # A real phase change still syncs (and Closing re-derives Unknown).
    pg.status.phase = "Running"
    store.update_pod_group(pg)
    qc.process_all()
    assert q.state == "Unknown"
    assert qc.status["q"].running == 1
