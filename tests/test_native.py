"""Native serializer (csrc/vcsnap.cc) vs NumPy fallback equivalence.

Every vcsnap entry point must produce bit-identical output to the fallback
path; the snapshot encoder must produce the same ClusterArrays either way.
"""

import importlib

import numpy as np
import pytest

from volcano_tpu import native


requires_native = pytest.mark.skipif(
    not native.native_available(), reason="libvcsnap.so not built"
)


def _fallback(fn, *args, **kwargs):
    """Call a native.py entry point with the library disabled."""
    saved_lib, saved_tried = native._LIB, native._TRIED
    native._LIB, native._TRIED = None, True
    try:
        return fn(*args, **kwargs)
    finally:
        native._LIB, native._TRIED = saved_lib, saved_tried


@requires_native
@pytest.mark.parametrize("seed", range(5))
def test_pack_bits_matches_fallback(seed):
    rng = np.random.default_rng(seed)
    rows = int(rng.integers(1, 200))
    words = int(rng.integers(1, 5))
    counts = rng.integers(0, 8, size=rows)
    idx = rng.integers(0, words * 32, size=int(counts.sum())).astype(np.int32)
    off = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    got = native.pack_bits_rows(idx, off, rows, words)
    want = _fallback(native.pack_bits_rows, idx, off, rows, words)
    np.testing.assert_array_equal(got, want)


@requires_native
@pytest.mark.parametrize("seed", range(5))
def test_scatter_matches_fallback(seed):
    rng = np.random.default_rng(seed)
    rows = int(rng.integers(1, 200))
    width = int(rng.integers(2, 9))
    counts = rng.integers(0, width, size=rows)
    n = int(counts.sum())
    # Unique slots per row so duplicate-resolution order cannot differ.
    slot = np.concatenate(
        [rng.permutation(width)[:c] for c in counts]
    ).astype(np.int32) if n else np.zeros((0,), np.int32)
    val = rng.random(n).astype(np.float32)
    off = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
    got = native.scatter_rows_f32(slot, val, off, rows, width)
    want = _fallback(native.scatter_rows_f32, slot, val, off, rows, width)
    np.testing.assert_array_equal(got, want)


@requires_native
def test_gather_matches_fallback():
    rng = np.random.default_rng(0)
    src = rng.random((50, 4)).astype(np.float32)
    order = np.array([3, -1, 49, 0, 7, -1, 12], np.int32)
    got = native.gather_rows_f32(src, order, 10)
    want = _fallback(native.gather_rows_f32, src, order, 10)
    np.testing.assert_array_equal(got, want)


@requires_native
@pytest.mark.parametrize("seed", range(3))
def test_less_equal_matches_fallback_and_host(seed):
    from volcano_tpu.api import Resource

    rng = np.random.default_rng(seed)
    rows, r = 64, 3
    eps = np.array([10.0, 10.0 * (1 << 20), 10.0], np.float32)
    scalar = np.array([False, False, True])
    l = (rng.random((rows, r)) * 100).astype(np.float32)
    rhs = (rng.random((r,)) * 100).astype(np.float32)
    got = native.less_equal_rows(l, rhs, eps, scalar)
    want = _fallback(native.less_equal_rows, l, rhs, eps, scalar)
    np.testing.assert_array_equal(got, want)


@requires_native
def test_encode_cluster_native_vs_fallback():
    from volcano_tpu.arrays import encode_cluster
    from volcano_tpu.api import TaskStatus
    from volcano_tpu.synth import synthetic_cluster

    store = synthetic_cluster(n_nodes=32, n_pods=64, gang_size=4, n_queues=2)
    snap = store.snapshot()
    job_ids = sorted(snap.jobs.keys())
    pending = []
    for jid in job_ids:
        pending.extend(
            sorted(
                snap.jobs[jid].task_status_index.get(
                    TaskStatus.Pending, {}
                ).values(),
                key=lambda t: t.name,
            )
        )
    a1, _ = encode_cluster(snap, pending, job_ids)
    a2, _ = _fallback(encode_cluster, snap, pending, job_ids)
    for grp1, grp2 in zip(a1, a2):
        if isinstance(grp1, np.ndarray):
            np.testing.assert_array_equal(grp1, grp2)
            continue
        for f1, f2 in zip(grp1, grp2):
            np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2))
